"""Keras model import.

Reference: deeplearning4j-modelimport —
org.deeplearning4j.nn.modelimport.keras.KerasModelImport.
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasModelImport,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
)

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
]

"""Model import: Keras configs/weights and TF frozen GraphDefs.

Reference: deeplearning4j-modelimport —
org.deeplearning4j.nn.modelimport.keras.KerasModelImport — and nd4j-api
org.nd4j.imports.graphmapper.tf.TFGraphMapper.
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasModelImport,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
)
from deeplearning4j_tpu.modelimport.tensorflow import (
    TFGraphMapper,
    TFImportException,
    importFrozenTF,
)

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
    "TFGraphMapper",
    "TFImportException",
    "importFrozenTF",
]

"""Model import: Keras configs/weights, TF frozen GraphDefs, ONNX models.

Reference: deeplearning4j-modelimport —
org.deeplearning4j.nn.modelimport.keras.KerasModelImport — and nd4j-api
org.nd4j.imports.graphmapper.tf.TFGraphMapper /
org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper.
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasModelImport,
    InvalidKerasConfigurationException,
    UnsupportedKerasConfigurationException,
)
from deeplearning4j_tpu.modelimport.tensorflow import (
    TFGraphMapper,
    TFImportException,
    importFrozenTF,
)
from deeplearning4j_tpu.modelimport.onnx import (
    OnnxGraphMapper,
    ONNXImportException,
    importOnnx,
)

__all__ = [
    "KerasModelImport",
    "InvalidKerasConfigurationException",
    "UnsupportedKerasConfigurationException",
    "TFGraphMapper",
    "TFImportException",
    "importFrozenTF",
    "OnnxGraphMapper",
    "ONNXImportException",
    "importOnnx",
]

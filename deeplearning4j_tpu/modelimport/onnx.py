"""ONNX model import into SameDiff.

Reference: nd4j-api org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper —
the reference's third model-import path next to Keras
(modelimport/keras.py) and TF frozen graphs (modelimport/tensorflow.py).
Same TPU-first design as those two: the ONNX graph maps onto SameDiff
ops, so the imported model traces to ONE jitted XLA computation and
behaves exactly like a natively-built graph (jit, grad, serialization).

Layout: ONNX is NCHW/OIHW. The mapper keeps every tensor in its ONNX
layout and brackets conv/pool ops with `permute` pairs into the
framework's NHWC/HWIO kernels; XLA cancels back-to-back transposes
between consecutive spatial ops, so chains cost one layout change at
each end, not one per op. Weights arriving as initializers are
constants, so their permutes fold at compile time.

Parsing uses modelimport/onnx_wire.py (a dependency-free protobuf wire
codec for the onnx.proto subset) — the `onnx` package is not required.

Scope (the pragmatic inference-graph subset): Conv (incl. groups/
dilations/auto_pad), ConvTranspose, MaxPool/AveragePool/GlobalAverage-
Pool/GlobalMaxPool, BatchNormalization (inference), Gemm, MatMul,
elementwise +-*/ Pow Min Max, Relu/LeakyRelu/PRelu/Elu/Selu/Sigmoid/
HardSigmoid/Tanh/Softplus/Softsign/Erf/Clip, Softmax (both pre- and
post-opset-13 semantics), Reshape/Flatten/Transpose/Squeeze/Unsqueeze/
Concat/Pad/Slice basics, ReduceMean/Sum/Max/Min, Gather, Cast, Constant,
Dropout/Identity. Anything else raises ONNXImportException naming the
node and op type.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.modelimport import onnx_wire as wire
from deeplearning4j_tpu.modelimport.tensorflow import _same_pads


class ONNXImportException(ValueError):
    pass


# TensorProto.DataType enum -> numpy dtype
_DT = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
       6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
       11: np.float64, 12: np.uint32, 13: np.uint64,
       16: ml_dtypes.bfloat16}

# TensorProto typed-field fallbacks (when raw_data is absent); note
# float16/bfloat16 ship in int32_data as raw bit patterns per onnx.proto
_TYPED_FIELD = {1: "float_data", 6: "int32_data", 7: "int64_data",
                9: "int32_data", 11: "double_data", 2: "int32_data",
                3: "int32_data", 4: "int32_data", 5: "int32_data",
                12: "uint64_data", 13: "uint64_data"}


def tensor_to_ndarray(tp):
    """TensorProto -> numpy array."""
    dtype = _DT.get(tp.data_type)
    if dtype is None:
        raise ONNXImportException(
            f"tensor '{tp.name}': unsupported ONNX dtype {tp.data_type}")
    shape = tuple(int(d) for d in tp.dims)
    if tp.raw_data:
        return np.frombuffer(tp.raw_data, dtype=dtype).reshape(shape).copy()
    if tp.data_type in (10, 16):  # fp16/bf16 bit patterns in int32_data
        bits = np.asarray(tp.int32_data, np.uint16)
        return bits.view(dtype).reshape(shape).copy()
    field = _TYPED_FIELD.get(tp.data_type)
    if field is None:
        raise ONNXImportException(
            f"tensor '{tp.name}': no data field for dtype {tp.data_type}")
    return np.asarray(getattr(tp, field), dtype=dtype).reshape(shape)


def _model_from(source):
    """Accept a ModelProto Message, serialized bytes, or a .onnx path."""
    if isinstance(source, wire.Message):
        if source._type == "ModelProto":
            return source
        raise ONNXImportException(
            f"expected ModelProto, got {source._type}")
    if isinstance(source, (bytes, bytearray)):
        return wire.decode("ModelProto", bytes(source))
    with open(str(source), "rb") as f:
        return wire.decode("ModelProto", f.read())


def _attrs(node):
    return {a.name: a for a in node.attribute}


def _attr_i(attrs, name, default=None):
    return int(attrs[name].i) if name in attrs else default


def _attr_f(attrs, name, default=None):
    return float(attrs[name].f) if name in attrs else default


def _attr_s(attrs, name, default=None):
    return attrs[name].s.decode("utf-8") if name in attrs else default


def _attr_ints(attrs, name, default=None):
    return [int(v) for v in attrs[name].ints] if name in attrs else default


_NHWC = (0, 2, 3, 1)   # NCHW -> NHWC
_NCHW = (0, 3, 1, 2)   # NHWC -> NCHW
_HWIO = (2, 3, 1, 0)   # OIHW -> HWIO (also correct per-group)


def _auto_pads(auto_pad, in_hw, k, s, d, node_name):
    """auto_pad SAME_UPPER/SAME_LOWER/VALID -> explicit ((lo,hi),(lo,hi))."""
    if auto_pad in ("", "NOTSET", None):
        return None
    if auto_pad == "VALID":
        return ((0, 0), (0, 0))
    if auto_pad not in ("SAME_UPPER", "SAME_LOWER"):
        raise ONNXImportException(
            f"node '{node_name}': unsupported auto_pad {auto_pad!r}")
    return _same_pads(in_hw[0], in_hw[1], k, s, d,
                      lower=auto_pad == "SAME_LOWER")


def _pads_2d(attrs, node_name):
    p = _attr_ints(attrs, "pads")
    if p is None:
        return ((0, 0), (0, 0))
    if len(p) != 4:
        raise ONNXImportException(
            f"node '{node_name}': only 2-spatial-dim pads supported, "
            f"got pads={p}")
    return ((p[0], p[2]), (p[1], p[3]))  # [hb, wb, he, we]


class OnnxGraphMapper:
    """importGraph(ModelProto | bytes | path) -> SameDiff.

    Reference: OnnxGraphMapper.importGraph (nd4j-api onnx import)."""

    @staticmethod
    def importGraph(source, inputShapes=None):
        """`inputShapes`: {inputName: shape tuple} overriding/filling
        symbolic dims (ONNX inputs routinely have batch as a dim_param;
        XLA needs static shapes)."""
        import jax

        from deeplearning4j_tpu.autodiff.ops_impl import OPS

        model = _model_from(source)
        graph = model.graph
        if graph is None:
            raise ONNXImportException("ModelProto has no graph")
        opset = 17
        for osi in model.opset_import:
            if osi.domain in ("", "ai.onnx"):
                opset = int(osi.version) or opset
        sd = SameDiff.create()
        vars_ = {}   # ONNX tensor name -> SDVariable
        consts = {}  # ONNX tensor name -> numpy (initializers + Constants)
        meta = {}    # SDVariable name -> ShapeDtypeStruct (incremental)

        def emit(opName, inputs, kwargs=None):
            v = sd._op(opName, inputs, kwargs)
            try:
                structs = [meta[i.name] for i in inputs]
                out = jax.eval_shape(
                    lambda *a: OPS[opName](*a, **(kwargs or {})), *structs)
                meta[v.name] = out[0] if isinstance(out, (list, tuple)) else out
            except Exception:
                pass  # best-effort; shape_of falls back to the variable
            return v

        def bind(tname, arr):
            arr = np.asarray(arr)
            v = sd.constant(arr, None)  # ONNX names may collide with sd ids
            vars_[tname] = v
            consts[tname] = arr
            meta[v.name] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
            return v

        def get(tname):
            if tname not in vars_:
                raise ONNXImportException(
                    f"reference to unknown tensor '{tname}' (graph inputs, "
                    "initializers and prior node outputs are resolvable)")
            return vars_[tname]

        def const_value(tname):
            if tname in consts:
                return consts[tname]
            v = get(tname)
            arr = sd._arrays.get(v.name)
            if arr is None:
                raise ONNXImportException(
                    f"'{tname}' must be a constant/initializer here "
                    "(structural argument)")
            return np.asarray(arr)

        def shape_of(tname):
            m = meta.get(vars_[tname].name) if tname in vars_ else None
            if m is not None:
                return tuple(m.shape)
            return tuple(get(tname).shape)

        def rank_of(tname):
            return len(shape_of(tname))

        def dtype_of(tname):
            m = meta.get(vars_[tname].name) if tname in vars_ else None
            return np.dtype(m.dtype) if m is not None else np.dtype(np.float32)

        def scalar(tname, ref, value):
            """Bind a helper scalar in `ref`'s dtype — a float32 literal
            would silently promote fp16/bf16 graphs under jax rules
            (ONNX: a node's output dtype equals its input's)."""
            return bind(tname, np.asarray(value, dtype_of(ref)))

        for init in graph.initializer:
            bind(init.name, tensor_to_ndarray(init))

        for vi in graph.input:
            if vi.name in vars_:  # initializers may be re-listed as inputs
                continue
            shape = None
            tt = vi.type.tensor_type if vi.type is not None else None
            if inputShapes and vi.name in inputShapes:
                shape = tuple(int(x) for x in inputShapes[vi.name])
            elif tt is not None and tt.shape is not None:
                dims = []
                for d in tt.shape.dim:
                    dims.append(int(d.dim_value) if not d.dim_param
                                and d.dim_value > 0 else -1)
                shape = tuple(dims)
            if shape is None or any(s < 0 for s in shape):
                raise ONNXImportException(
                    f"input '{vi.name}' has symbolic/unknown dims {shape}; "
                    f"pass inputShapes={{'{vi.name}': (...)}} (XLA needs "
                    "static shapes)")
            dt = _DT.get(tt.elem_type, np.float32) if tt is not None \
                else np.float32
            v = sd.placeHolder(vi.name, dt, *shape)
            vars_[vi.name] = v
            meta[v.name] = jax.ShapeDtypeStruct(shape, np.dtype(dt))

        def spatial_op(node, x_name, kernel_from_w=None):
            """Common conv/pool geometry: returns (strides, dilations,
            explicit pads) honoring auto_pad, all in (H, W) order."""
            attrs = _attrs(node)
            if rank_of(x_name) != 4:
                raise ONNXImportException(
                    f"node '{node.name}' ({node.op_type}): only 4-D NCHW "
                    f"inputs supported, got rank {rank_of(x_name)}")
            k = kernel_from_w or tuple(_attr_ints(attrs, "kernel_shape"))
            s = tuple(_attr_ints(attrs, "strides", [1, 1]))
            d = tuple(_attr_ints(attrs, "dilations", [1, 1]))
            if len(k) != 2:
                raise ONNXImportException(
                    f"node '{node.name}': only 2 spatial dims supported "
                    f"(kernel {k})")
            in_hw = shape_of(x_name)[2:4]
            pads = _auto_pads(_attr_s(attrs, "auto_pad"), in_hw, k, s, d,
                              node.name)
            if pads is None:
                pads = _pads_2d(attrs, node.name)
            return k, s, d, pads

        def to_nhwc(v):
            return emit("permute", [v], {"dimensions": _NHWC})

        def to_nchw(v):
            return emit("permute", [v], {"dimensions": _NCHW})

        for node in graph.node:
            op = node.op_type
            attrs = _attrs(node)
            ins = list(node.input)
            out = node.output[0] if node.output else None

            if op == "Constant":
                if "value" in attrs:
                    bind(out, tensor_to_ndarray(attrs["value"].t))
                elif "value_float" in attrs:
                    bind(out, np.float32(attrs["value_float"].f))
                elif "value_int" in attrs:
                    bind(out, np.int64(attrs["value_int"].i))
                elif "value_floats" in attrs:
                    bind(out, np.asarray(attrs["value_floats"].floats,
                                         np.float32))
                elif "value_ints" in attrs:
                    bind(out, np.asarray(attrs["value_ints"].ints, np.int64))
                else:
                    raise ONNXImportException(
                        f"Constant node '{node.name}' has no supported "
                        "value attribute")
                continue

            if op in ("Identity", "Dropout"):
                # Dropout at inference is identity; the optional mask
                # output is not materialized (an error surfaces naturally
                # if a downstream node references it)
                vars_[out] = emit("identity", [get(ins[0])])
                # structural arguments (Reshape shapes, Clip bounds, …)
                # are routinely routed through Identity by exporters and
                # graph optimizers — keep their const-ness visible
                if ins[0] in consts:
                    consts[out] = consts[ins[0]]
                continue

            if op in ("Add", "Sub", "Mul", "Div", "Pow"):
                name = {"Add": "add", "Sub": "sub", "Mul": "mul",
                        "Div": "div", "Pow": "pow"}[op]
                vars_[out] = emit(name, [get(ins[0]), get(ins[1])])
                continue

            if op in ("Max", "Min"):  # n-ary
                name = "maximum" if op == "Max" else "minimum"
                acc = get(ins[0])
                for extra in ins[1:]:
                    acc = emit(name, [acc, get(extra)])
                vars_[out] = acc
                continue

            _UNARY = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                      "Exp": "exp", "Log": "log", "Sqrt": "sqrt",
                      "Neg": "neg", "Abs": "abs", "Erf": "erf",
                      "Floor": "floor", "Ceil": "ceil", "Round": "round",
                      "Reciprocal": "reciprocal", "Softplus": "softplus",
                      "Softsign": "softsign", "Sign": "sign",
                      "Not": "not"}
            if op in _UNARY:
                vars_[out] = emit(_UNARY[op], [get(ins[0])])
                continue

            if op == "LeakyRelu":
                vars_[out] = emit("leakyRelu", [get(ins[0])],
                                  {"alpha": _attr_f(attrs, "alpha", 0.01)})
                continue

            if op == "Elu":
                alpha = _attr_f(attrs, "alpha", 1.0)
                x = get(ins[0])
                if alpha == 1.0:
                    vars_[out] = emit("elu", [x])
                else:
                    zero = scalar(f"__{out}_zero", ins[0], 0.0)
                    a = scalar(f"__{out}_alpha", ins[0], alpha)
                    one = scalar(f"__{out}_one", ins[0], 1.0)
                    em1 = emit("sub", [emit("exp", [x]), one])
                    vars_[out] = emit(
                        "where", [emit("gt", [x, zero]), x,
                                  emit("mul", [a, em1])])
                continue

            if op == "Selu":
                vars_[out] = emit("selu", [get(ins[0])])
                continue

            if op == "HardSigmoid":
                # ONNX: max(0, min(1, alpha*x + beta)), defaults .2/.5
                alpha = _attr_f(attrs, "alpha", 0.2)
                beta = _attr_f(attrs, "beta", 0.5)
                x = get(ins[0])
                a = scalar(f"__{out}_a", ins[0], alpha)
                b = scalar(f"__{out}_b", ins[0], beta)
                y = emit("add", [emit("mul", [x, a]), b])
                vars_[out] = emit("clipByValue", [y], {"clipValueMin": 0.0,
                                                       "clipValueMax": 1.0})
                continue

            if op == "PRelu":
                x, slope = get(ins[0]), get(ins[1])
                zero = scalar(f"__{out}_zero", ins[0], 0.0)
                vars_[out] = emit(
                    "where", [emit("gt", [x, zero]), x,
                              emit("mul", [x, slope])])
                continue

            if op == "Clip":
                x = get(ins[0])
                if opset >= 11:
                    lo = (float(np.asarray(const_value(ins[1])).ravel()[0])
                          if len(ins) > 1 and ins[1] else None)
                    hi = (float(np.asarray(const_value(ins[2])).ravel()[0])
                          if len(ins) > 2 and ins[2] else None)
                else:
                    lo = _attr_f(attrs, "min")
                    hi = _attr_f(attrs, "max")
                # both bounds are optional per spec (clamp_min exports
                # Clip with no max); clipByValue needs both
                if lo is not None and hi is not None:
                    vars_[out] = emit("clipByValue", [x],
                                      {"clipValueMin": lo,
                                       "clipValueMax": hi})
                elif lo is not None:
                    vars_[out] = emit(
                        "maximum", [x, scalar(f"__{out}_lo", ins[0], lo)])
                elif hi is not None:
                    vars_[out] = emit(
                        "minimum", [x, scalar(f"__{out}_hi", ins[0], hi)])
                else:
                    vars_[out] = emit("identity", [x])
                continue

            if op == "Gemm":
                alpha = _attr_f(attrs, "alpha", 1.0)
                beta = _attr_f(attrs, "beta", 1.0)
                y = emit("mmul", [get(ins[0]), get(ins[1])],
                         {"transposeA": bool(_attr_i(attrs, "transA", 0)),
                          "transposeB": bool(_attr_i(attrs, "transB", 0))})
                if alpha != 1.0:
                    y = emit("mul", [y, scalar(f"__{out}_alpha",
                                               ins[0], alpha)])
                if len(ins) > 2 and ins[2]:
                    c = get(ins[2])
                    if beta != 1.0:
                        c = emit("mul", [c, scalar(f"__{out}_beta",
                                                   ins[2], beta)])
                    y = emit("add", [y, c])
                vars_[out] = y
                continue

            if op == "MatMul":
                vars_[out] = emit("mmul", [get(ins[0]), get(ins[1])])
                continue

            if op == "Conv":
                x, w = ins[0], ins[1]
                wshape = shape_of(w)  # OIHW: (M, C/g, kH, kW)
                groups = _attr_i(attrs, "group", 1)
                k, s, d, pads = spatial_op(node, x,
                                           kernel_from_w=wshape[2:4])
                conv_ins = [to_nhwc(get(x)),
                            emit("permute", [get(w)],
                                 {"dimensions": _HWIO})]
                if len(ins) > 2 and ins[2]:
                    conv_ins.append(get(ins[2]))
                y = emit("conv2d", conv_ins,
                         {"stride": s, "padding": pads, "dilation": d,
                          "groups": groups})
                vars_[out] = to_nchw(y)
                continue

            if op == "ConvTranspose":
                x, w = ins[0], ins[1]
                wshape = shape_of(w)  # (C, M/g, kH, kW)
                if _attr_i(attrs, "group", 1) != 1:
                    raise ONNXImportException(
                        f"node '{node.name}': grouped ConvTranspose is not "
                        "supported")
                if _attr_ints(attrs, "output_padding"):
                    if any(_attr_ints(attrs, "output_padding")):
                        raise ONNXImportException(
                            f"node '{node.name}': output_padding is not "
                            "supported")
                k, s, d, pads = spatial_op(node, x,
                                           kernel_from_w=wshape[2:4])
                ap = _attr_s(attrs, "auto_pad")
                if ap in ("SAME_UPPER", "SAME_LOWER"):
                    # ConvTranspose SAME is NOT forward-conv SAME: spec
                    # fixes output = input*stride, so per axis
                    # total_pad = eff_kernel - stride (clamped at 0) —
                    # spatial_op's _same_pads math would over-pad
                    pads = []
                    for kk, ss, dd in zip(k, s, d):
                        eff = (kk - 1) * dd + 1
                        tot = max(eff - ss, 0)
                        lo = (tot // 2 if ap == "SAME_UPPER"
                              else tot - tot // 2)
                        pads.append((lo, tot - lo))
                    pads = tuple(pads)
                # ONNX ConvTranspose pads REMOVE output (out = (in-1)*s
                # + eff_k - lo - hi); lax.conv_transpose padding pads
                # the lhs-dilated input (out = (in-1)*s + 1 + lo + hi +
                # eff_k - 2k + ...). The conversion per side is
                # lax_pad = (k-1)*d - onnx_pad.
                pads = tuple(
                    ((kk - 1) * dd - lo, (kk - 1) * dd - hi)
                    for (lo, hi), kk, dd in zip(pads, k, d))
                # ONNX/torch ConvTranspose is the TRUE transpose of a
                # forward conv (scatter form => correlation with the
                # spatially-flipped kernel); deconv2d does not flip, so
                # reverse kH/kW, then (Cin, M, kH, kW) -> (kH, kW, Cin, M)
                wf = emit("reverse", [get(w)], {"dimensions": (2, 3)})
                conv_ins = [to_nhwc(get(x)),
                            emit("permute", [wf],
                                 {"dimensions": (2, 3, 0, 1)})]
                if len(ins) > 2 and ins[2]:
                    conv_ins.append(get(ins[2]))
                y = emit("deconv2d", conv_ins,
                         {"stride": s, "padding": pads, "dilation": d})
                vars_[out] = to_nchw(y)
                continue

            if op in ("MaxPool", "AveragePool"):
                if _attr_i(attrs, "ceil_mode", 0):
                    raise ONNXImportException(
                        f"node '{node.name}': ceil_mode=1 is not supported")
                k, s, d, pads = spatial_op(node, ins[0])
                if d != (1, 1):
                    raise ONNXImportException(
                        f"node '{node.name}': dilated pooling is not "
                        "supported")
                kw = {"kernel": k, "stride": s, "padding": pads}
                if op == "MaxPool":
                    # maxPooling2d's reduce_window init is -inf, matching
                    # ONNX's pad-with--inf semantics for explicit pads
                    y = emit("maxPooling2d", [to_nhwc(get(ins[0]))], kw)
                else:
                    kw["count_include_pad"] = bool(
                        _attr_i(attrs, "count_include_pad", 0))
                    y = emit("avgPooling2d", [to_nhwc(get(ins[0]))], kw)
                vars_[out] = to_nchw(y)
                continue

            if op in ("GlobalAveragePool", "GlobalMaxPool"):
                # spec: reduce over ALL spatial dims (rank-agnostic:
                # NCW, NCHW, NCDHW all legal)
                r = rank_of(ins[0])
                if r < 3:
                    raise ONNXImportException(
                        f"node '{node.name}' ({op}): input rank {r} has "
                        "no spatial dims")
                red = "mean" if op == "GlobalAveragePool" else "max"
                vars_[out] = emit(red, [get(ins[0])],
                                  {"dimensions": list(range(2, r)),
                                   "keepDims": True})
                continue

            if op == "BatchNormalization":
                if _attr_i(attrs, "training_mode", 0):
                    raise ONNXImportException(
                        f"node '{node.name}': training_mode=1 "
                        "BatchNormalization is not supported (export for "
                        "inference)")
                eps = _attr_f(attrs, "epsilon", 1e-5)
                x, scale, b, mean, var = (get(ins[0]), get(ins[1]),
                                          get(ins[2]), get(ins[3]),
                                          get(ins[4]))
                vars_[out] = emit("batchNorm", [x, mean, var, scale, b],
                                  {"epsilon": eps, "axis": 1})
                continue

            if op == "Softmax":
                axis = _attr_i(attrs, "axis", -1 if opset >= 13 else 1)
                x = get(ins[0])
                if opset >= 13:
                    vars_[out] = emit("softmax", [x], {"dimension": axis})
                else:
                    # pre-13 semantics: coerce to 2-D at `axis`, softmax
                    # over the flattened trailing block, restore shape
                    shp = shape_of(ins[0])
                    ax = axis % len(shp)
                    lead = int(np.prod(shp[:ax])) if ax else 1
                    trail = int(np.prod(shp[ax:]))
                    y = emit("reshape", [x], {"shape": [lead, trail]})
                    y = emit("softmax", [y], {"dimension": -1})
                    vars_[out] = emit("reshape", [y],
                                      {"shape": list(shp)})
                continue

            if op == "Reshape":
                shp = [int(v) for v in const_value(ins[1])]
                in_shape = shape_of(ins[0])
                if not _attr_i(attrs, "allowzero", 0):
                    shp = [in_shape[i] if v == 0 else v
                           for i, v in enumerate(shp)]
                vars_[out] = emit("reshape", [get(ins[0])], {"shape": shp})
                continue

            if op == "Flatten":
                axis = _attr_i(attrs, "axis", 1)
                shp = shape_of(ins[0])
                # spec: negative axis means rank+axis (axis in [-r, r])
                ax = axis if axis >= 0 else axis + len(shp)
                lead = int(np.prod(shp[:ax])) if ax else 1
                vars_[out] = emit("reshape", [get(ins[0])],
                                  {"shape": [lead, -1]})
                continue

            if op == "Transpose":
                perm = _attr_ints(attrs, "perm")
                if perm is None:
                    perm = list(range(rank_of(ins[0])))[::-1]
                vars_[out] = emit("permute", [get(ins[0])],
                                  {"dimensions": tuple(perm)})
                continue

            if op == "Concat":
                axis = _attr_i(attrs, "axis")
                if axis is None:
                    raise ONNXImportException(
                        f"node '{node.name}': Concat requires axis")
                vars_[out] = emit("concat", [get(i) for i in ins],
                                  {"dimension": axis})
                continue

            if op in ("Squeeze", "Unsqueeze"):
                if opset >= 13:
                    axes = ([int(v) for v in const_value(ins[1])]
                            if len(ins) > 1 and ins[1] else None)
                else:
                    axes = _attr_ints(attrs, "axes")
                x = get(ins[0])
                if op == "Squeeze":
                    ax = (tuple(a % rank_of(ins[0]) for a in axes)
                          if axes else None)
                    vars_[out] = emit("squeeze", [x], {"axis": ax})
                else:
                    if axes is None:
                        raise ONNXImportException(
                            f"node '{node.name}': Unsqueeze requires axes")
                    r = rank_of(ins[0]) + len(axes)
                    for a in sorted(ax % r for ax in axes):
                        x = emit("expandDims", [x], {"axis": a})
                    vars_[out] = x
                continue

            if op == "Pad":
                mode = _attr_s(attrs, "mode", "constant")
                if mode not in ("constant", "reflect", "edge"):
                    raise ONNXImportException(
                        f"node '{node.name}': unsupported Pad mode {mode!r}")
                if opset >= 11:
                    pads = [int(v) for v in const_value(ins[1])]
                    cval = (float(np.asarray(const_value(ins[2])).ravel()[0])
                            if len(ins) > 2 and ins[2] else 0.0)
                else:
                    pads = _attr_ints(attrs, "pads")
                    cval = _attr_f(attrs, "value", 0.0)
                rank = rank_of(ins[0])
                if len(ins) > 3 and ins[3]:
                    # opset 18+: pads bind to the listed axes only
                    axes = [int(a) % rank for a in const_value(ins[3])]
                else:
                    axes = list(range(rank))
                if len(pads) != 2 * len(axes):
                    raise ONNXImportException(
                        f"node '{node.name}': Pad expects "
                        f"{2 * len(axes)} pad values for {len(axes)} "
                        f"axes, got {len(pads)}")
                n = len(axes)
                full = [(0, 0)] * rank
                for j, a in enumerate(axes):
                    full[a] = (pads[j], pads[j + n])
                padding = tuple(full)
                kw = {"padding": padding,
                      "mode": {"constant": "CONSTANT", "reflect": "REFLECT",
                               "edge": "EDGE"}[mode]}
                if mode == "constant":
                    kw["constant"] = cval
                vars_[out] = emit("pad", [get(ins[0])], kw)
                continue

            _REDUCE = {"ReduceMean": "mean", "ReduceSum": "sum",
                       "ReduceMax": "max", "ReduceMin": "min",
                       "ReduceProd": "prod"}
            if op in _REDUCE:
                # axes moved from attr to input at opset 13 (ReduceSum)
                # and 18 (the rest); accept either
                if len(ins) > 1 and ins[1]:
                    axes = [int(v) for v in np.atleast_1d(
                        const_value(ins[1]))]
                else:
                    axes = _attr_ints(attrs, "axes")
                kd = bool(_attr_i(attrs, "keepdims", 1))
                if not axes and _attr_i(attrs, "noop_with_empty_axes", 0):
                    # spec: empty axes + noop flag -> identity
                    vars_[out] = emit("identity", [get(ins[0])])
                else:
                    vars_[out] = emit(_REDUCE[op], [get(ins[0])],
                                      {"dimensions": axes, "keepDims": kd})
                continue

            if op == "Gather":
                axis = _attr_i(attrs, "axis", 0)
                dim = shape_of(ins[0])[axis]
                ids = get(ins[1])
                if ins[1] in consts:
                    # spec: negative indices wrap from the end —
                    # normalize constant indices at import time
                    arr = np.asarray(consts[ins[1]])
                    if (arr < 0).any():
                        ids = bind(f"__{out}_ids", arr % dim)
                else:
                    # jnp.mod wraps negatives Python-style, exactly the
                    # spec's semantics for in-range indices
                    ids = emit("mod", [ids, scalar(f"__{out}_dim",
                                                   ins[1], dim)])
                vars_[out] = emit("gather", [get(ins[0]), ids],
                                  {"axis": axis})
                continue

            if op == "Cast":
                dt = _DT.get(_attr_i(attrs, "to"))
                if dt is None:
                    raise ONNXImportException(
                        f"node '{node.name}': unsupported Cast target "
                        f"{_attr_i(attrs, 'to')}")
                vars_[out] = emit("cast", [get(ins[0])],
                                  {"dtype": str(np.dtype(dt))})
                continue

            if op == "Slice":
                if opset < 10:
                    starts = _attr_ints(attrs, "starts")
                    ends = _attr_ints(attrs, "ends")
                    axes = _attr_ints(attrs, "axes")
                    steps = None
                else:
                    starts = [int(v) for v in const_value(ins[1])]
                    ends = [int(v) for v in const_value(ins[2])]
                    axes = ([int(v) for v in const_value(ins[3])]
                            if len(ins) > 3 and ins[3] else None)
                    steps = ([int(v) for v in const_value(ins[4])]
                             if len(ins) > 4 and ins[4] else None)
                shp = shape_of(ins[0])
                r = len(shp)
                if axes is None:
                    axes = list(range(len(starts)))
                if steps is None:
                    steps = [1] * len(starts)
                begin, end, stride = ([0] * r), list(shp), ([1] * r)
                for a, st, en, sp in zip(axes, starts, ends, steps):
                    a %= r
                    if sp <= 0:
                        raise ONNXImportException(
                            f"node '{node.name}': non-positive Slice steps "
                            "are not supported")
                    # spec: negatives wrap once, then CLAMP into
                    # [0, dim] — Python's slice() would re-wrap
                    # out-of-range negatives a second time
                    begin[a] = max(0, min(st if st >= 0 else st + shp[a],
                                          shp[a]))
                    end[a] = max(0, min(en if en >= 0 else en + shp[a],
                                        shp[a]))
                    stride[a] = sp
                vars_[out] = emit("stridedSlice", [get(ins[0])],
                                  {"begin": begin, "end": end,
                                   "strides": stride})
                continue

            raise ONNXImportException(
                f"unsupported ONNX op '{op}' (node '{node.name}'); the "
                "supported subset is documented in modelimport.onnx")

        missing = [vo.name for vo in graph.output if vo.name not in vars_]
        if missing:
            raise ONNXImportException(
                f"graph outputs {missing} were never produced by any node")
        sd._onnx_vars = vars_  # ONNX tensor name -> SDVariable
        sd._onnx_outputs = [vo.name for vo in graph.output]
        return sd

    @staticmethod
    def outputVariable(sd, onnxName):
        """The SDVariable for an ONNX tensor name in an imported graph."""
        return sd._onnx_vars[onnxName]


def importOnnx(source, inputShapes=None):
    """Convenience wrapper (reference: OnnxGraphMapper.importGraph)."""
    return OnnxGraphMapper.importGraph(source, inputShapes=inputShapes)

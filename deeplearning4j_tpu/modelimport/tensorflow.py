"""TF frozen-graph (GraphDef) import into SameDiff.

Reference: nd4j-api org.nd4j.imports.graphmapper.tf.TFGraphMapper — maps a
frozen TensorFlow GraphDef's nodes onto SameDiff ops. Same idea here,
TPU-first: the imported SameDiff graph traces to ONE jitted XLA
computation (no per-node interpretation), so an imported model runs
exactly like a natively-built one — jit, grad, training, serialization.

Scope (the pragmatic op subset frozen inference CNN/MLP graphs use):
Placeholder, Const, Identity/StopGradient, Conv2D, DepthwiseConv2dNative,
BiasAdd, FusedBatchNorm(V2/V3), Relu, Relu6, LeakyRelu, Sigmoid, Tanh,
Softmax, MaxPool, AvgPool, Mean, MatMul, Add/AddV2/AddN, Sub, Mul,
RealDiv, Maximum, Minimum, Pow, Rsqrt, Sqrt, Exp, Log, Neg, Square, Abs,
Reshape, Squeeze, Pad, ConcatV2, Cast. NHWC data format only
(TF's CPU default; NCHW graphs raise). Anything else raises with the node
name and op type.

Parsing: GraphDef protobuf classes come from the installed tensorflow
package (gated import — parsing wire format by hand would duplicate the
schema). Everything downstream of the parsed proto is this framework.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff


class TFImportException(ValueError):
    pass


def _graph_def_from(source):
    """Accept a GraphDef message, serialized bytes, or a .pb path."""
    try:
        from tensorflow.core.framework import graph_pb2
    except ImportError as e:  # pragma: no cover - tf is baked into the image
        raise TFImportException(
            "TF GraphDef import needs the tensorflow package for the "
            "protobuf schema (tensorflow.core.framework.graph_pb2); "
            "it is not importable here") from e
    if isinstance(source, graph_pb2.GraphDef):
        return source
    gd = graph_pb2.GraphDef()
    if isinstance(source, bytes):
        gd.ParseFromString(source)
        return gd
    path = str(source)
    with open(path, "rb") as f:
        data = f.read()
    if path.endswith((".pbtxt", ".pbtext")):
        from google.protobuf import text_format

        text_format.Parse(data.decode(), gd)
    else:
        gd.ParseFromString(data)
    return gd


_DT = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
       6: np.int8, 9: np.int64, 10: np.bool_, 19: np.float16,
       14: ml_dtypes.bfloat16}  # 14 = DT_BFLOAT16 (NOT fp16 — different layout)


def _tensor_to_ndarray(tp):
    """TensorProto -> numpy (the fields frozen graphs actually use)."""
    shape = tuple(d.size for d in tp.tensor_shape.dim)
    dtype = _DT.get(tp.dtype)
    if dtype is None:
        raise TFImportException(f"unsupported TensorProto dtype {tp.dtype}")
    if tp.tensor_content:
        return np.frombuffer(tp.tensor_content, dtype=dtype).reshape(shape).copy()
    for field in ("float_val", "double_val", "int_val", "int64_val",
                  "bool_val", "half_val"):
        vals = list(getattr(tp, field, []))
        if vals:
            if field == "half_val":
                # half_val holds RAW BIT PATTERNS (uint16) for both
                # DT_HALF and DT_BFLOAT16, not numeric values
                arr = np.asarray(vals, np.uint16).view(dtype)
            else:
                arr = np.asarray(vals, dtype=dtype)
            if shape and arr.size == 1:
                arr = np.full(shape, arr[0], dtype=dtype)
            return arr.reshape(shape) if shape else arr.reshape(())
    return np.zeros(shape, dtype=dtype)


def _attr(node, name, default=None):
    if name in node.attr:
        return node.attr[name]
    return default


def _require_attr(node, name):
    """Attrs a node is meaningless without (a graph serialized with
    strip_default_attrs can legitimately omit default-VALUED attrs, but
    strides/ksize/value have no defaults)."""
    a = _attr(node, name)
    if a is None:
        raise TFImportException(
            f"node '{node.name}' ({node.op}) is missing required "
            f"attribute '{name}'")
    return a


def _require_nhwc(node):
    a = _attr(node, "data_format")
    fmt = a.s.decode() if (a is not None and a.s) else "NHWC"
    if fmt != "NHWC":
        raise TFImportException(
            f"node '{node.name}' ({node.op}) uses data_format={fmt}; only "
            "NHWC graphs are supported (TF's CPU freezing default)")


def _same_pads(in_h, in_w, k, s, d=(1, 1), lower=False):
    """SAME padding -> explicit ((lo,hi),(lo,hi)) for static shapes.

    `lower=False` puts the odd pad at the end (TF SAME / ONNX
    SAME_UPPER); `lower=True` at the start (ONNX SAME_LOWER). Shared
    with modelimport/onnx.py — one copy of the geometry math."""
    pads = []
    for size, kk, ss, dd in ((in_h, k[0], s[0], d[0]), (in_w, k[1], s[1], d[1])):
        eff = (kk - 1) * dd + 1
        out = -(-size // ss)
        tot = max((out - 1) * ss + eff - size, 0)
        lo = tot - tot // 2 if lower else tot // 2
        pads.append((lo, tot - lo))
    return tuple(pads)


def _conv_padding(node, xshape, k, s, d=(1, 1)):
    a = _attr(node, "padding")
    p = a.s.decode() if (a is not None and a.s) else "VALID"
    if p == "VALID":
        return ((0, 0), (0, 0))
    if p == "SAME":
        return _same_pads(xshape[1], xshape[2], k, s, d)
    if p == "EXPLICIT":
        ep = list(_require_attr(node, "explicit_paddings").list.i)
        return ((ep[2], ep[3]), (ep[4], ep[5]))  # NHWC: [b,b,h,h,w,w,c,c]
    raise TFImportException(f"node '{node.name}': unsupported padding {p!r}")


def _hw(list_attr):
    v = list(list_attr.list.i)
    return (v[1], v[2])  # NHWC [1, h, w, 1]


class TFGraphMapper:
    """importGraph(frozen GraphDef) -> SameDiff (reference: TFGraphMapper)."""

    @staticmethod
    def importGraph(source, inputShapes=None):
        """`inputShapes`: {placeholderName: shape tuple} overriding/filling
        unknown dims (TF placeholders routinely have batch=-1; XLA needs
        static shapes)."""
        import jax

        from deeplearning4j_tpu.autodiff.ops_impl import OPS

        gd = _graph_def_from(source)
        sd = SameDiff.create()
        vars_ = {}  # tf tensor name (output 0, no ":0") -> SDVariable
        # Static shape/dtype per variable, tracked INCREMENTALLY with a
        # single-op jax.eval_shape per node — SDVariable.shape re-traces
        # the whole prefix graph, which is O(n^2) over a deep import.
        meta = {}

        def emit(opName, inputs, kwargs=None):
            v = sd._op(opName, inputs, kwargs)
            try:
                structs = [meta[i.name] for i in inputs]
                out = jax.eval_shape(
                    lambda *a: OPS[opName](*a, **(kwargs or {})), *structs)
                meta[v.name] = out[0] if isinstance(out, (list, tuple)) else out
            except Exception:
                pass  # best-effort: shape_of falls back to graph eval
            return v

        def shape_of(v):
            m = meta.get(v.name)
            return tuple(m.shape) if m is not None else tuple(v.shape)

        def get(ref):
            name = ref.lstrip("^")
            if ":" in name:
                base, idx = name.rsplit(":", 1)
                if idx not in ("0",):
                    raise TFImportException(
                        f"reference '{ref}': only output 0 of multi-output "
                        "nodes is supported (FusedBatchNorm etc. expose y)")
                name = base
            if name not in vars_:
                raise TFImportException(f"reference to unknown node '{name}'")
            return vars_[name]

        def const_value(ref):
            v = get(ref)
            arr = sd._arrays.get(v.name)
            if arr is None:
                raise TFImportException(
                    f"'{ref}' must be a Const (structural argument)")
            return np.asarray(arr)

        for node in gd.node:
            op = node.op
            ins = [i for i in node.input if not i.startswith("^")]
            if op == "NoOp":
                continue
            if op == "Placeholder":
                shape = None
                if inputShapes and node.name in inputShapes:
                    shape = tuple(int(x) for x in inputShapes[node.name])
                else:
                    a = _attr(node, "shape")
                    if a is not None and not a.shape.unknown_rank:
                        shape = tuple(d.size for d in a.shape.dim)
                if shape is None or any(s < 0 for s in shape):
                    raise TFImportException(
                        f"placeholder '{node.name}' has unknown dims "
                        f"{shape}; pass inputShapes={{'{node.name}': "
                        "(...)}} (XLA needs static shapes)")
                da = _attr(node, "dtype")
                dt = _DT.get(da.type, np.float32) if da is not None \
                    else np.float32
                vars_[node.name] = sd.placeHolder(node.name, dt, *shape)
                meta[node.name] = jax.ShapeDtypeStruct(shape, np.dtype(dt))
                continue
            if op == "Const":
                arr = _tensor_to_ndarray(_require_attr(node, "value").tensor)
                vars_[node.name] = sd.constant(arr, node.name)
                meta[node.name] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
                continue
            if op in ("Identity", "StopGradient"):
                vars_[node.name] = emit("identity", [get(ins[0])])
                continue
            if op == "Conv2D":
                _require_nhwc(node)
                x, w = get(ins[0]), get(ins[1])
                s = _hw(_require_attr(node, "strides"))
                dil_a = _attr(node, "dilations")
                d = _hw(dil_a) if dil_a is not None else (1, 1)
                kshp = shape_of(w)
                pad = _conv_padding(node, shape_of(x), (kshp[0], kshp[1]), s, d)
                vars_[node.name] = emit("conv2d", [x, w], {
                    "stride": s, "padding": pad, "dilation": d})
                continue
            if op == "DepthwiseConv2dNative":
                _require_nhwc(node)
                x, w = get(ins[0]), get(ins[1])
                s = _hw(_require_attr(node, "strides"))
                dil_a = _attr(node, "dilations")
                d = _hw(dil_a) if dil_a is not None else (1, 1)
                kh, kw, cin, mult = shape_of(w)
                pad = _conv_padding(node, shape_of(x), (kh, kw), s, d)
                # TF stores (kh,kw,Cin,mult); grouped-conv layout is
                # (kh,kw,1,Cin*mult) with groups=Cin
                wg = emit("reshape", [w], {"shape": [kh, kw, 1, cin * mult]})
                vars_[node.name] = emit("conv2d", [x, wg], {
                    "stride": s, "padding": pad, "dilation": d,
                    "groups": int(cin)})
                continue
            if op == "BiasAdd":
                _require_nhwc(node)
                vars_[node.name] = emit("add", [get(ins[0]), get(ins[1])])
                continue
            if op in ("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3"):
                _require_nhwc(node)
                t = _attr(node, "is_training")
                if t is not None and t.b:
                    raise TFImportException(
                        f"node '{node.name}': is_training=true — freeze the "
                        "graph for inference import")
                ea = _attr(node, "epsilon")
                eps = float(ea.f) if ea is not None else 1e-4  # proto default
                x, gamma, beta, mean, var = (get(i) for i in ins[:5])
                vars_[node.name] = emit(
                    "batchNorm", [x, mean, var, gamma, beta],
                    {"epsilon": eps, "axis": -1})
                continue
            if op in ("MaxPool", "AvgPool"):
                _require_nhwc(node)
                x = get(ins[0])
                k = _hw(_require_attr(node, "ksize"))
                s = _hw(_require_attr(node, "strides"))
                pad = _conv_padding(node, shape_of(x), k, s)
                kw = {"kernel": k, "stride": s, "padding": pad}
                if op == "AvgPool":
                    # TF's AvgPool divides border windows by the VALID
                    # cell count (excludes SAME/EXPLICIT padding)
                    kw["count_include_pad"] = False
                vars_[node.name] = emit(
                    "maxPooling2d" if op == "MaxPool" else "avgPooling2d",
                    [x], kw)
                continue
            if op == "MatMul":
                ta = _attr(node, "transpose_a")
                tb = _attr(node, "transpose_b")
                vars_[node.name] = emit(
                    "mmul", [get(ins[0]), get(ins[1])],
                    {"transposeA": bool(ta.b) if ta else False,
                     "transposeB": bool(tb.b) if tb else False})
                continue
            if op in ("Add", "AddV2"):
                vars_[node.name] = emit("add", [get(ins[0]), get(ins[1])])
                continue
            if op == "AddN":
                acc = get(ins[0])
                for r in ins[1:]:
                    acc = emit("add", [acc, get(r)])
                vars_[node.name] = emit("identity", [acc])
                continue
            if op in ("Sub", "Mul", "RealDiv", "Maximum", "Minimum", "Pow"):
                nm = {"Sub": "sub", "Mul": "mul", "RealDiv": "div",
                      "Maximum": "maximum", "Minimum": "minimum",
                      "Pow": "pow"}[op]
                vars_[node.name] = emit(nm, [get(ins[0]), get(ins[1])])
                continue
            if op in ("Rsqrt", "Sqrt", "Exp", "Log", "Neg", "Square", "Abs"):
                # Keras-3 freezing decomposes inference BatchNorm into
                # Rsqrt/Mul/Sub/AddV2 chains — these unaries make those
                # graphs (and general math tails) importable
                vars_[node.name] = emit(op.lower(), [get(ins[0])])
                continue
            if op in ("Relu", "Sigmoid", "Tanh", "Softmax"):
                vars_[node.name] = emit(op.lower(), [get(ins[0])])
                continue
            if op == "Relu6":
                vars_[node.name] = emit(
                    "clipByValue", [get(ins[0])],
                    {"clipValueMin": 0.0, "clipValueMax": 6.0})
                continue
            if op == "LeakyRelu":
                a = _attr(node, "alpha")
                vars_[node.name] = emit(
                    "leakyRelu", [get(ins[0])],
                    {"alpha": float(a.f) if a else 0.2})
                continue
            if op == "Reshape":
                shape = [int(v) for v in const_value(ins[1])]
                vars_[node.name] = emit("reshape", [get(ins[0])],
                                          {"shape": shape})
                continue
            if op == "Squeeze":
                sa = _attr(node, "squeeze_dims")
                dims = list(sa.list.i) if sa is not None else []
                vars_[node.name] = emit(
                    "squeeze", [get(ins[0])],
                    {"axis": tuple(int(d) for d in dims) if dims else None})
                continue
            if op in ("Pad", "PadV2"):
                pads = const_value(ins[1]).tolist()
                kw = {"padding": pads}
                if op == "PadV2" and len(ins) > 2:
                    kw["constant"] = float(const_value(ins[2]))
                vars_[node.name] = emit("pad", [get(ins[0])], kw)
                continue
            if op == "ConcatV2":
                axis = int(const_value(ins[-1]))
                vars_[node.name] = emit(
                    "concat", [get(i) for i in ins[:-1]], {"dimension": axis})
                continue
            if op == "Mean":
                axes = np.atleast_1d(const_value(ins[1])).tolist()
                kd = _attr(node, "keep_dims")
                vars_[node.name] = emit(
                    "mean", [get(ins[0])],
                    {"dimensions": [int(a) for a in axes],
                     "keepDims": bool(kd.b) if kd else False})
                continue
            if op == "Cast":
                dt = _DT.get(_require_attr(node, "DstT").type)
                if dt is None:
                    raise TFImportException(
                        f"node '{node.name}': unsupported Cast target")
                vars_[node.name] = emit(
                    "cast", [get(ins[0])], {"dtype": str(np.dtype(dt))})
                continue
            raise TFImportException(
                f"unsupported TF op '{op}' (node '{node.name}'); supported "
                "subset is documented in modelimport.tensorflow")
        sd._tf_vars = vars_  # tf node name -> SDVariable (introspection)
        return sd

    @staticmethod
    def outputVariable(sd, tfName):
        """The SDVariable for a TF node name in an imported graph."""
        return sd._tf_vars[tfName.split(":")[0]]


def importFrozenTF(source, inputShapes=None):
    """Convenience wrapper (reference: TFGraphMapper.importGraph)."""
    return TFGraphMapper.importGraph(source, inputShapes=inputShapes)

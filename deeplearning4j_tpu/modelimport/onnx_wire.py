"""Minimal ONNX protobuf wire codec (reader AND writer), no dependencies.

Reference: nd4j's ONNX import path (nd4j-api
org.nd4j.imports.graphmapper.onnx.OnnxGraphMapper + onnx.proto under
nd4j-backends) parses ONNX ModelProto files via generated protobuf
classes. Neither the `onnx` package nor its generated classes are
available in this image, so this module speaks the protobuf wire format
directly for the subset of onnx.proto that inference model files use:
ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
ValueInfoProto / TypeProto / TensorShapeProto / OperatorSetIdProto.

Field numbers follow the public onnx.proto schema (onnx/onnx.proto in
the ONNX repo — stable since IR version 3; proto field numbers are
frozen by protobuf compatibility rules). Unknown fields are skipped on
read, so files produced by any ONNX exporter parse as long as they only
*use* ops the mapper supports. The writer exists so tests can assemble
real ONNX files (and users can round-trip graphs) without the onnx
package; reader and writer share one schema table, and the tests
cross-check the codec against byte sequences hand-assembled from the
wire-format spec.
"""

from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# schema: message name -> {field number: (field name, kind)}
# kinds:  int        signed 64-bit varint
#         str        length-delimited utf-8
#         bytes      length-delimited raw
#         float      fixed32
#         rep_int    repeated int64 (accepts packed or unpacked; writes packed)
#         rep_uint   repeated uint64 (same, but no sign reinterpretation)
#         rep_float  repeated float (same)
#         rep_double repeated double (same)
#         rep_str    repeated string
#         rep_bytes  repeated bytes
#         Name       embedded message
#         rep_Name   repeated embedded message
# ---------------------------------------------------------------------------

SCHEMA = {
    "ModelProto": {
        1: ("ir_version", "int"),
        2: ("producer_name", "str"),
        3: ("producer_version", "str"),
        4: ("domain", "str"),
        5: ("model_version", "int"),
        6: ("doc_string", "str"),
        7: ("graph", "GraphProto"),
        8: ("opset_import", "rep_OperatorSetIdProto"),
    },
    "OperatorSetIdProto": {
        1: ("domain", "str"),
        2: ("version", "int"),
    },
    "GraphProto": {
        1: ("node", "rep_NodeProto"),
        2: ("name", "str"),
        5: ("initializer", "rep_TensorProto"),
        10: ("doc_string", "str"),
        11: ("input", "rep_ValueInfoProto"),
        12: ("output", "rep_ValueInfoProto"),
        13: ("value_info", "rep_ValueInfoProto"),
    },
    "NodeProto": {
        1: ("input", "rep_str"),
        2: ("output", "rep_str"),
        3: ("name", "str"),
        4: ("op_type", "str"),
        5: ("attribute", "rep_AttributeProto"),
        6: ("doc_string", "str"),
        7: ("domain", "str"),
    },
    "AttributeProto": {
        1: ("name", "str"),
        2: ("f", "float"),
        3: ("i", "int"),
        4: ("s", "bytes"),
        5: ("t", "TensorProto"),
        6: ("g", "GraphProto"),
        7: ("floats", "rep_float"),
        8: ("ints", "rep_int"),
        9: ("strings", "rep_bytes"),
        10: ("tensors", "rep_TensorProto"),
        20: ("type", "int"),
    },
    "TensorProto": {
        1: ("dims", "rep_int"),
        2: ("data_type", "int"),
        4: ("float_data", "rep_float"),
        5: ("int32_data", "rep_int"),
        6: ("string_data", "rep_bytes"),
        7: ("int64_data", "rep_int"),
        8: ("name", "str"),
        9: ("raw_data", "bytes"),
        10: ("double_data", "rep_double"),
        11: ("uint64_data", "rep_uint"),
    },
    "ValueInfoProto": {
        1: ("name", "str"),
        2: ("type", "TypeProto"),
        3: ("doc_string", "str"),
    },
    "TypeProto": {
        1: ("tensor_type", "TypeProto.Tensor"),
    },
    "TypeProto.Tensor": {
        1: ("elem_type", "int"),
        2: ("shape", "TensorShapeProto"),
    },
    "TensorShapeProto": {
        1: ("dim", "rep_TensorShapeProto.Dimension"),
    },
    "TensorShapeProto.Dimension": {
        1: ("dim_value", "int"),
        2: ("dim_param", "str"),
    },
}

# AttributeProto.AttributeType values (onnx.proto enum)
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR, ATTR_GRAPH = 1, 2, 3, 4, 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS, ATTR_TENSORS = 6, 7, 8, 9


class Message:
    """A decoded protobuf message: fields as attributes, repeated -> list."""

    def __init__(self, type_name, **fields):
        if type_name not in SCHEMA:
            raise ValueError(f"unknown ONNX message type {type_name!r}")
        self._type = type_name
        for _num, (fname, kind) in SCHEMA[type_name].items():
            if kind.startswith("rep_"):
                setattr(self, fname, [])
            elif kind in ("int", "float"):
                setattr(self, fname, 0)
            elif kind == "str":
                setattr(self, fname, "")
            elif kind == "bytes":
                setattr(self, fname, b"")
            else:  # embedded message: absent until set
                setattr(self, fname, None)
        for k, v in fields.items():
            if not hasattr(self, k):
                raise ValueError(f"{type_name} has no field {k!r}")
            setattr(self, k, v)

    def __repr__(self):
        set_fields = {k: v for k, v in vars(self).items()
                      if not k.startswith("_") and v not in (None, [], "", b"", 0)}
        return f"{self._type}({', '.join(f'{k}={v!r}' for k, v in set_fields.items())})"


# ---------------------------------------------------------------------------
# varint / wire primitives
# ---------------------------------------------------------------------------

_MASK64 = (1 << 64) - 1


def _write_varint(out, value):
    value &= _MASK64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data, pos):
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & _MASK64, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed(value):
    return value - (1 << 64) if value >= (1 << 63) else value


def _tag(field_num, wire_type):
    return (field_num << 3) | wire_type


# ---------------------------------------------------------------------------
# encoder
# ---------------------------------------------------------------------------

def encode(msg):
    """Message -> wire bytes."""
    out = bytearray()
    for num, (fname, kind) in sorted(SCHEMA[msg._type].items()):
        val = getattr(msg, fname)
        if kind == "int":
            if val:
                _write_varint(out, _tag(num, 0))
                _write_varint(out, val)
        elif kind == "float":
            if val:
                _write_varint(out, _tag(num, 5))
                out += struct.pack("<f", val)
        elif kind == "str":
            if val:
                _emit_len(out, num, val.encode("utf-8"))
        elif kind == "bytes":
            if val:
                _emit_len(out, num, bytes(val))
        elif kind in ("rep_int", "rep_uint"):
            if val:
                packed = bytearray()
                for v in val:
                    _write_varint(packed, int(v))
                _emit_len(out, num, bytes(packed))
        elif kind == "rep_float":
            if val:
                _emit_len(out, num, struct.pack(f"<{len(val)}f", *val))
        elif kind == "rep_double":
            if val:
                _emit_len(out, num, struct.pack(f"<{len(val)}d", *val))
        elif kind == "rep_str":
            for v in val:
                _emit_len(out, num, v.encode("utf-8"))
        elif kind == "rep_bytes":
            for v in val:
                _emit_len(out, num, bytes(v))
        elif kind.startswith("rep_"):
            for v in val:
                _emit_len(out, num, encode(v))
        else:  # embedded message
            if val is not None:
                _emit_len(out, num, encode(val))
    return bytes(out)


def _emit_len(out, num, payload):
    _write_varint(out, _tag(num, 2))
    _write_varint(out, len(payload))
    out += payload


# ---------------------------------------------------------------------------
# decoder
# ---------------------------------------------------------------------------

def decode(type_name, data):
    """wire bytes -> Message (unknown fields skipped)."""
    msg = Message(type_name)
    fields = SCHEMA[type_name]
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        num, wt = key >> 3, key & 0x7
        spec = fields.get(num)
        if spec is None:
            pos = _skip(data, pos, wt)
            continue
        fname, kind = spec
        if wt == 0:  # varint
            raw, pos = _read_varint(data, pos)
            if kind == "int":
                setattr(msg, fname, _signed(raw))
            elif kind == "rep_int":
                getattr(msg, fname).append(_signed(raw))
            elif kind == "rep_uint":
                getattr(msg, fname).append(raw)
            elif kind == "float":  # malformed; tolerate as int bits
                setattr(msg, fname, float(raw))
            else:
                pass  # wrong wire type for field: ignore
        elif wt == 5:  # fixed32
            if pos + 4 > len(data):
                raise ValueError("truncated fixed32")
            raw = struct.unpack_from("<f", data, pos)[0]
            pos += 4
            if kind == "float":
                setattr(msg, fname, raw)
            elif kind == "rep_float":
                getattr(msg, fname).append(raw)
        elif wt == 1:  # fixed64
            if pos + 8 > len(data):
                raise ValueError("truncated fixed64")
            raw = struct.unpack_from("<d", data, pos)[0]
            pos += 8
            if kind == "rep_double":
                getattr(msg, fname).append(raw)
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(data, pos)
            if pos + ln > len(data):
                raise ValueError(f"truncated field {fname} ({ln} bytes)")
            payload = data[pos:pos + ln]
            pos += ln
            if kind == "str":
                setattr(msg, fname, payload.decode("utf-8"))
            elif kind == "bytes":
                setattr(msg, fname, bytes(payload))
            elif kind == "rep_str":
                getattr(msg, fname).append(payload.decode("utf-8"))
            elif kind == "rep_bytes":
                getattr(msg, fname).append(bytes(payload))
            elif kind in ("rep_int", "rep_uint"):  # packed
                p = 0
                dst = getattr(msg, fname)
                signed = kind == "rep_int"
                while p < len(payload):
                    v, p = _read_varint(payload, p)
                    dst.append(_signed(v) if signed else v)
            elif kind == "rep_float":  # packed
                getattr(msg, fname).extend(
                    struct.unpack(f"<{len(payload) // 4}f", payload))
            elif kind == "rep_double":
                getattr(msg, fname).extend(
                    struct.unpack(f"<{len(payload) // 8}d", payload))
            elif kind.startswith("rep_"):
                getattr(msg, fname).append(decode(kind[4:], payload))
            elif kind in ("int", "float"):
                pass  # wrong wire type: ignore
            else:  # embedded message
                setattr(msg, fname, decode(kind, payload))
        else:
            raise ValueError(f"unsupported wire type {wt} in {type_name}")
    return msg


def _skip(data, pos, wire_type):
    if wire_type == 0:
        _, pos = _read_varint(data, pos)
        return pos
    if wire_type == 1:
        return pos + 8
    if wire_type == 5:
        return pos + 4
    if wire_type == 2:
        ln, pos = _read_varint(data, pos)
        return pos + ln
    raise ValueError(f"cannot skip wire type {wire_type}")


# ---------------------------------------------------------------------------
# builder helpers (mirror onnx.helper's make_* API so test/export code reads
# like standard ONNX assembly)
# ---------------------------------------------------------------------------

# numpy dtype -> TensorProto.DataType enum
NP_TO_ONNX = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}


def make_tensor(name, array):
    """numpy array -> TensorProto (raw_data encoding, little-endian)."""
    import numpy as np

    arr = np.ascontiguousarray(array)
    dt = NP_TO_ONNX.get(arr.dtype.name)
    if dt is None:
        raise ValueError(f"no ONNX dtype for numpy {arr.dtype}")
    if arr.dtype.byteorder == ">":
        arr = arr.astype(arr.dtype.newbyteorder("<"))
    return Message("TensorProto", name=name, dims=list(arr.shape),
                   data_type=dt, raw_data=arr.tobytes())


def make_attribute(name, value):
    """Python value -> AttributeProto, dispatching on type like onnx.helper."""
    import numpy as np

    a = Message("AttributeProto", name=name)
    if isinstance(value, float):
        a.f, a.type = value, ATTR_FLOAT
    elif isinstance(value, bool):
        a.i, a.type = int(value), ATTR_INT
    elif isinstance(value, int):
        a.i, a.type = value, ATTR_INT
    elif isinstance(value, str):
        a.s, a.type = value.encode("utf-8"), ATTR_STRING
    elif isinstance(value, bytes):
        a.s, a.type = value, ATTR_STRING
    elif isinstance(value, np.ndarray):
        a.t, a.type = make_tensor(name, value), ATTR_TENSOR
    elif isinstance(value, Message) and value._type == "TensorProto":
        a.t, a.type = value, ATTR_TENSOR
    elif isinstance(value, (list, tuple)):
        vals = list(value)
        if all(isinstance(v, (int, np.integer)) for v in vals):
            a.ints, a.type = [int(v) for v in vals], ATTR_INTS
        elif all(isinstance(v, (int, float, np.floating)) for v in vals):
            a.floats, a.type = [float(v) for v in vals], ATTR_FLOATS
        elif all(isinstance(v, str) for v in vals):
            a.strings = [v.encode("utf-8") for v in vals]
            a.type = ATTR_STRINGS
        else:
            raise ValueError(f"mixed-type attribute list for {name!r}")
    else:
        raise ValueError(f"cannot infer attribute type for {name!r}: "
                         f"{type(value).__name__}")
    return a


def make_node(op_type, inputs, outputs, name="", **attrs):
    return Message(
        "NodeProto", op_type=op_type, input=list(inputs),
        output=list(outputs), name=name or f"{op_type}_{outputs[0]}",
        attribute=[make_attribute(k, v) for k, v in attrs.items()])


def make_value_info(name, dtype, shape):
    """name + numpy dtype + shape tuple -> ValueInfoProto (None dim -> dim_param)."""
    import numpy as np

    dims = []
    for i, d in enumerate(shape):
        if d is None or (isinstance(d, int) and d < 0):
            dims.append(Message("TensorShapeProto.Dimension",
                                dim_param=f"dyn_{i}"))
        else:
            dims.append(Message("TensorShapeProto.Dimension",
                                dim_value=int(d)))
    tt = Message("TypeProto.Tensor",
                 elem_type=NP_TO_ONNX[np.dtype(dtype).name],
                 shape=Message("TensorShapeProto", dim=dims))
    return Message("ValueInfoProto", name=name,
                   type=Message("TypeProto", tensor_type=tt))


def make_graph(nodes, name, inputs, outputs, initializers=()):
    return Message("GraphProto", node=list(nodes), name=name,
                   input=list(inputs), output=list(outputs),
                   initializer=list(initializers))


def make_model(graph, opset=17, producer="deeplearning4j_tpu"):
    return Message(
        "ModelProto", ir_version=8, producer_name=producer, graph=graph,
        opset_import=[Message("OperatorSetIdProto", domain="",
                              version=int(opset))])

"""Keras model import: config JSON (+ optional weights) → native networks.

Reference: org.deeplearning4j.nn.modelimport.keras.KerasModelImport /
KerasSequentialModel / KerasLayer subclasses. The reference parses Keras 1/2
model JSON and HDF5 weights into DL4J configurations; this importer parses
Keras 2 (tf.keras legacy) and Keras 3 `model.to_json()` output into
MultiLayerConfiguration (Sequential) or ComputationGraphConfiguration
(Functional), with weights from a legacy Keras HDF5 file, a full legacy
HDF5 model, or a {layerName: [arrays...]} mapping (e.g. collected from
`layer.get_weights()`).

Data-format note: imported networks use THIS framework's API conventions —
CNN inputs NCHW, recurrent inputs NCW [B, F, T] — regardless of Keras'
channels_last/time-major layout. Weight layouts happen to agree for Dense
(in,out) and Conv2D (HWIO); LSTM gate columns are reordered from Keras
[i,f,g,o] to the native [i,f,o,g].
"""

from __future__ import annotations

import json
import re

import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf import recurrent as R
from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph


class InvalidKerasConfigurationException(ValueError):
    pass


class UnsupportedKerasConfigurationException(ValueError):
    pass


_ACTIVATIONS = {
    "relu": "relu", "sigmoid": "sigmoid", "tanh": "tanh", "softmax": "softmax",
    "linear": "identity", "elu": "elu", "selu": "selu", "softplus": "softplus",
    "softsign": "softsign", "swish": "swish", "silu": "swish", "gelu": "gelu",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
    "mish": "mish",
}


def _act(name):
    if name is None:
        return "identity"
    try:
        return _ACTIVATIONS[str(name)]
    except KeyError:
        raise UnsupportedKerasConfigurationException(
            f"unsupported Keras activation '{name}'") from None


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _leaky(alpha):
    """Exact-alpha leaky-relu closure (activations.get accepts
    callables): Keras slopes are arbitrary and rarely match the
    registry's leakyrelu(0.01). Callable activations don't serialize —
    re-export such imports via Keras, not ModelSerializer."""
    import jax.nn as _jnn

    return lambda x: _jnn.leaky_relu(x, alpha)


def _conv_mode(padding):
    p = str(padding).lower()
    if p == "valid":
        return "truncate"
    if p == "same":
        return "same"
    raise UnsupportedKerasConfigurationException(f"unsupported padding '{padding}'")


def _input_type_from_shape(shape):
    """Keras shape tuple (batch dim stripped) → InputType. channels_last:
    (H,W,C) → CNN; (T,F) → recurrent [F,T] (T may be None = variable);
    (N,) → feedForward. Rank is judged with None dims INCLUDED — (None, F)
    is a variable-length sequence, not flat features."""
    dims = list(shape)
    if len(dims) == 4:
        d, h, w, c = dims
        if None in (d, h, w, c):
            raise UnsupportedKerasConfigurationException(
                f"variable spatial dims not supported for 3D-CNN input "
                f"{shape} (XLA needs static shapes)")
        return InputType.convolutional3D(d, h, w, c)
    if len(dims) == 3:
        h, w, c = dims
        if h is None or w is None or c is None:
            raise UnsupportedKerasConfigurationException(
                f"variable spatial dims not supported for CNN input {shape} "
                "(XLA needs static shapes)")
        return InputType.convolutional(h, w, c)
    if len(dims) == 2:
        t, f = dims
        if f is None:
            raise UnsupportedKerasConfigurationException(
                f"variable feature dim in recurrent input {shape}")
        return InputType.recurrent(f, t)
    if len(dims) == 1:
        if dims[0] is None:
            raise UnsupportedKerasConfigurationException(
                f"variable feature dim in input {shape}")
        return InputType.feedForward(dims[0])
    raise UnsupportedKerasConfigurationException(f"unsupported input shape {shape}")


class KerasReshapeLayer(L.Layer):
    """Keras Reshape(target_shape), per example. Valid because Keras'
    channels_last layout and the internal NHWC layout agree elementwise:
    a row-major reshape means the same thing on both sides. Targets:
    [features] (flatten) or [h, w, c]."""

    def __init__(self, targetShape, **kw):
        super().__init__(**kw)
        self.targetShape = tuple(int(v) for v in targetShape)

    def hasParams(self):
        return False

    def _resolve(self, inputType):
        """Resolve one -1 wildcard (Keras allows it; Reshape((-1,)) is
        the common flatten idiom) against the input's element count."""
        t = list(self.targetShape)
        if t.count(-1) > 1 or any(v < 1 and v != -1 for v in t):
            raise InvalidKerasConfigurationException(
                f"Reshape target {tuple(t)} invalid: at most one -1 "
                "wildcard, all other dims positive")
        if -1 in t:
            total = inputType.arrayElementsPerExample()
            known = 1
            for v in t:
                if v != -1:
                    known *= v
            if total % known:
                raise InvalidKerasConfigurationException(
                    f"Reshape target {tuple(t)}: {total} elements per "
                    f"example not divisible by {known}")
            t[t.index(-1)] = total // known
        return tuple(t)

    def getOutputType(self, inputType):
        t = self._resolved = self._resolve(inputType)
        if len(t) == 1:
            return InputType.feedForward(t[0])
        h, w, c = t
        return InputType.convolutional(h, w, c)

    def forward(self, params, state, x, train, key, mask=None):
        # -1 resolution happened during shape inference (getOutputType
        # always runs at build); fall back to the raw target otherwise
        t = getattr(self, "_resolved", self.targetShape)
        return x.reshape((x.shape[0],) + t), state


class _KerasLayerSpec:
    """One parsed Keras layer: class name, config, inbound names."""

    def __init__(self, raw):
        self.className = raw.get("class_name")
        self.config = raw.get("config", {})
        self.name = self.config.get("name") or raw.get("name")
        self.inbound = []
        for node in raw.get("inbound_nodes", []):
            if isinstance(node, dict):  # Keras 3: {"args": [...]} history refs
                for a in _walk_keras3_history(node):
                    self.inbound.append(a)
            elif isinstance(node, list):  # Keras 2: [[name, idx, tensor_idx, {}]...]
                for ref in node:
                    self.inbound.append(ref[0])

    def inputShape(self):
        for k in ("batch_input_shape", "batch_shape"):
            if self.config.get(k):
                return self.config[k][1:]
        return None


def _walk_keras3_history(node):
    """Extract inbound layer names from a Keras-3 serialized call node."""
    out = []

    def rec(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                hist = obj.get("config", {}).get("keras_history")
                if hist:
                    out.append(hist[0])
            else:
                for v in obj.values():
                    rec(v)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                rec(v)

    rec(node.get("args", []))
    rec(node.get("kwargs", {}))
    return out


# ---------------------------------------------------------------------------
# layer conversion
# ---------------------------------------------------------------------------

def _normalization_guards(cfg, name):
    """Shared keras.layers.Normalization support checks (channels-last
    stats, no invert) for both the adapt-mode BN mapping and the
    constructor-mode vertex mapping."""
    axis = cfg.get("axis", -1)
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    if axis not in ((-1,), (3,)):
        raise UnsupportedKerasConfigurationException(
            f"Normalization over axis {axis} not supported "
            f"(channels-last only; layer '{name}')")
    if cfg.get("invert", False):
        raise UnsupportedKerasConfigurationException(
            f"Normalization(invert=True) not supported (layer '{name}')")


def _convert_layer(spec: _KerasLayerSpec, is_last: bool):
    """Keras layer spec → (native layer | None, activation carried)."""
    cn, cfg = spec.className, spec.config
    name = spec.name

    if cn == "InputLayer":
        return None
    if cn == "Dense":
        act = _act(cfg.get("activation"))
        units = int(cfg["units"])
        bias = bool(cfg.get("use_bias", True))
        if is_last:
            loss = {"softmax": "mcxent", "sigmoid": "xent"}.get(act, "mse")
            return L.OutputLayer(nOut=units, activation=act, hasBias=bias,
                                 lossFunction=loss, name=name)
        return L.DenseLayer(nOut=units, activation=act, hasBias=bias, name=name)
    if cn == "Conv2D":
        return L.ConvolutionLayer(
            nOut=int(cfg["filters"]), kernelSize=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            convolutionMode=_conv_mode(cfg.get("padding", "valid")),
            hasBias=bool(cfg.get("use_bias", True)),
            activation=_act(cfg.get("activation")), name=name)
    if cn == "DepthwiseConv2D":
        return L.DepthwiseConvolution2D(
            depthMultiplier=int(cfg.get("depth_multiplier", 1)),
            kernelSize=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            convolutionMode=_conv_mode(cfg.get("padding", "valid")),
            hasBias=bool(cfg.get("use_bias", True)),
            activation=_act(cfg.get("activation")), name=name)
    if cn in ("MaxPooling2D", "AveragePooling2D"):
        return L.SubsamplingLayer(
            poolingType="max" if cn.startswith("Max") else "avg",
            kernelSize=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            convolutionMode=_conv_mode(cfg.get("padding", "valid")), name=name)
    if cn in ("MaxPooling1D", "AveragePooling1D"):
        if str(cfg.get("padding", "valid")).lower() == "same":
            raise UnsupportedKerasConfigurationException(
                f"{cn} padding='same' not supported (layer '{name}'); "
                "pad explicitly with ZeroPadding1D")
        return L.Subsampling1DLayer(
            poolingType="max" if cn.startswith("Max") else "avg",
            kernelSize=cfg.get("pool_size", 2),
            stride=cfg.get("strides") or cfg.get("pool_size", 2),
            name=name)
    if cn == "ZeroPadding1D":
        return L.ZeroPadding1DLayer(padding=cfg.get("padding", 1), name=name)
    if cn == "Cropping1D":
        return L.Cropping1D(cropping=cfg.get("cropping", 0), name=name)
    if cn in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
              "GlobalMaxPooling1D", "GlobalAveragePooling1D",
              "GlobalMaxPooling3D", "GlobalAveragePooling3D"):
        return L.GlobalPoolingLayer(
            poolingType="max" if "Max" in cn else "avg",
            # keepdims=True (MobileNet heads) = upstream's
            # collapseDimensions(false): pooled dims stay as size 1
            collapseDimensions=not cfg.get("keepdims", False), name=name)
    if cn == "Flatten":
        return None  # our shape inference auto-inserts CnnToFeedForward
    if cn == "Dropout":
        return L.DropoutLayer(dropOut=1.0 - float(cfg.get("rate", 0.5)), name=name)
    if cn in ("SpatialDropout1D", "SpatialDropout2D", "SpatialDropout3D"):
        from deeplearning4j_tpu.nn.conf.dropout import SpatialDropout
        return L.DropoutLayer(
            dropOut=SpatialDropout(1.0 - float(cfg.get("rate", 0.5))), name=name)
    if cn == "GaussianDropout":
        from deeplearning4j_tpu.nn.conf.dropout import GaussianDropout
        return L.DropoutLayer(dropOut=GaussianDropout(float(cfg.get("rate", 0.5))),
                              name=name)
    if cn == "GaussianNoise":
        from deeplearning4j_tpu.nn.conf.dropout import GaussianNoise
        return L.DropoutLayer(dropOut=GaussianNoise(float(cfg.get("stddev", 0.1))),
                              name=name)
    if cn == "AlphaDropout":
        from deeplearning4j_tpu.nn.conf.dropout import AlphaDropout
        return L.DropoutLayer(dropOut=AlphaDropout(1.0 - float(cfg.get("rate", 0.5))),
                              name=name)
    if cn == "PReLU":
        # Keras shared_axes are 1-based over the NHWC input's (H, W, C) =
        # (1, 2, 3); native sharedAxes use the reference's (C, H, W) order.
        # Only the 2D-CNN axis set is supported (a 3D-CNN PReLU would need
        # NDHWC axes 1-4).
        shared = cfg.get("shared_axes") or ()
        if any(int(a) not in (1, 2, 3) for a in shared):
            raise UnsupportedKerasConfigurationException(
                f"PReLU shared_axes {list(shared)} not supported "
                f"(only 2D-CNN axes 1-3; layer '{name}')")
        mapped = tuple({1: 2, 2: 3, 3: 1}[int(a)] for a in shared) or None
        return L.PReLULayer(sharedAxes=mapped, name=name)
    if cn == "Conv3D":
        t3 = lambda v: (v, v, v) if isinstance(v, int) else tuple(v)
        return L.Convolution3D(
            nOut=int(cfg["filters"]), kernelSize=t3(cfg["kernel_size"]),
            stride=t3(cfg.get("strides", 1)),
            dilation=t3(cfg.get("dilation_rate", 1)),
            convolutionMode=_conv_mode(cfg.get("padding", "valid")),
            hasBias=bool(cfg.get("use_bias", True)),
            activation=_act(cfg.get("activation")), name=name)
    if cn == "SeparableConv2D":
        return L.SeparableConvolution2D(
            nOut=int(cfg["filters"]),
            depthMultiplier=int(cfg.get("depth_multiplier", 1)),
            kernelSize=_pair(cfg["kernel_size"]),
            stride=_pair(cfg.get("strides", 1)),
            dilation=_pair(cfg.get("dilation_rate", 1)),
            convolutionMode=_conv_mode(cfg.get("padding", "valid")),
            hasBias=bool(cfg.get("use_bias", True)),
            activation=_act(cfg.get("activation")), name=name)
    if cn == "Cropping2D":
        crop = cfg.get("cropping", 0)
        if isinstance(crop, int):
            crop = (crop, crop, crop, crop)
        elif crop and isinstance(crop[0], (list, tuple)):
            (t, b), (l, r) = crop
            crop = (t, b, l, r)
        return L.Cropping2D(cropping=tuple(int(v) for v in crop), name=name)
    if cn == "UpSampling1D":
        return L.Upsampling1D(size=int(cfg.get("size", 2)), name=name)
    if cn == "UpSampling3D":
        s = cfg.get("size", 2)
        return L.Upsampling3D(size=s if isinstance(s, int) else tuple(s),
                              name=name)
    if cn == "Activation":
        return L.ActivationLayer(activation=_act(cfg.get("activation")), name=name)
    if cn == "ReLU":
        # standalone ReLU layer (MobileNet-family configs): plain,
        # capped (relu6), or leaky — reject other parameterisations
        max_v = cfg.get("max_value")
        slope = float(cfg.get("negative_slope") or 0.0)
        thresh = float(cfg.get("threshold") or 0.0)
        if thresh != 0.0:
            raise UnsupportedKerasConfigurationException(
                f"ReLU threshold={thresh} not supported (layer '{name}')")
        if max_v is not None and slope != 0.0:
            raise UnsupportedKerasConfigurationException(
                f"ReLU with both max_value and negative_slope not "
                f"supported (layer '{name}')")
        if max_v is not None:
            if float(max_v) != 6.0:
                raise UnsupportedKerasConfigurationException(
                    f"ReLU max_value={max_v} not supported (only 6.0 — "
                    f"relu6; layer '{name}')")
            return L.ActivationLayer(activation="relu6", name=name)
        if slope != 0.0:
            return L.ActivationLayer(activation=_leaky(slope), name=name)
        return L.ActivationLayer(activation="relu", name=name)
    if cn == "LeakyReLU":
        # Keras 3 serializes "negative_slope"; Keras 2 used "alpha".
        # No `or` fallback: an explicit 0.0 means plain relu, not 0.3.
        alpha = cfg.get("negative_slope", cfg.get("alpha", 0.3))
        return L.ActivationLayer(activation=_leaky(float(alpha)), name=name)
    if cn == "BatchNormalization":
        bn = L.BatchNormalization(
            decay=float(cfg.get("momentum", 0.99)),
            eps=float(cfg.get("epsilon", 1e-3)),
            # per-param locking: a Keras BN with scale=False keeps a trainable
            # beta but NO gamma — creating a trainable identity gamma would
            # add degrees of freedom Keras omitted and diverge on fine-tune
            lockGamma=not cfg.get("scale", True),
            lockBeta=not cfg.get("center", True),
            name=name)
        return bn
    if cn == "Normalization":
        # keras.layers.Normalization (e.g. the EfficientNet stem):
        # (x - mean) / sqrt(var). ADAPT mode (mean/var stored as
        # weights) is exactly a frozen no-gamma/no-beta
        # BatchNormalization in inference mode (eps=0: Keras guards
        # sqrt(var) with epsilon(), ~1e-7, invisible at
        # image-statistics variance scales); the BN weight mapper reads
        # [mean, variance, (count ignored)] as-is. CONSTRUCTOR mode
        # (mean/var in the config, NO weights) is intercepted by the
        # functional importer as Shift/Scale vertices before reaching
        # here.
        _normalization_guards(cfg, name)
        if cfg.get("mean") is not None:
            raise UnsupportedKerasConfigurationException(
                f"Normalization with constructor mean/variance is only "
                f"supported in Functional models (layer '{name}')")
        # eps=1e-14 ~ Keras's maximum(sqrt(var), epsilon()) clamp: equal
        # at var=0, invisible at real-statistics variance scales
        bn = L.BatchNormalization(eps=1e-14, lockGammaBeta=True, name=name)
        bn.frozen = True  # stats are dataset constants, never updated
        return bn
    if cn == "ZeroPadding2D":
        pad = cfg.get("padding", 1)
        if isinstance(pad, (list, tuple)) and pad and isinstance(pad[0], (list, tuple)):
            # ((top, bottom), (left, right)) incl. asymmetric (MobileNet
            # stride-2 blocks pad (0,1)); ZeroPaddingLayer's native
            # 4-tuple order is (top, bottom, left, right)
            (t, b), (l, r) = pad
            return L.ZeroPaddingLayer(padding=(int(t), int(b), int(l),
                                               int(r)), name=name)
        return L.ZeroPaddingLayer(padding=_pair(pad), name=name)
    if cn == "Reshape":
        target = tuple(int(v) for v in cfg.get("target_shape", ()))
        if len(target) not in (1, 3):
            raise UnsupportedKerasConfigurationException(
                f"Reshape to {target} not supported (only [features] or "
                f"[h, w, c]; layer '{name}')")
        return KerasReshapeLayer(target, name=name)
    if cn == "UpSampling2D":
        size = _pair(cfg.get("size", 2))
        if size[0] != size[1]:
            raise UnsupportedKerasConfigurationException(
                f"non-square UpSampling2D {size} not supported (layer '{name}')")
        return L.Upsampling2D(size=size[0], name=name)
    if cn == "Embedding":
        return L.EmbeddingSequenceLayer(
            nIn=int(cfg["input_dim"]), nOut=int(cfg["output_dim"]), name=name)
    if cn in ("LSTM", "SimpleRNN", "GRU"):
        cls = {"LSTM": R.LSTM, "SimpleRNN": R.SimpleRnn, "GRU": R.GRU}[cn]
        inner = cls(nOut=int(cfg["units"]), activation=_act(cfg.get("activation")),
                    name=name)
        if cn == "LSTM":
            inner.gateActivationFn = _act(cfg.get("recurrent_activation", "sigmoid"))
        if not cfg.get("return_sequences", False):
            return R.LastTimeStep(inner)
        return inner
    if cn == "Bidirectional":
        inner_spec = _KerasLayerSpec(cfg["layer"])
        inner = _convert_layer(inner_spec, False)
        mode = {"concat": "concat", "sum": "add", "ave": "average", "mul": "mul"}[
            cfg.get("merge_mode", "concat")]
        return R.Bidirectional(layer=inner, mode=mode, name=name)
    raise UnsupportedKerasConfigurationException(
        f"unsupported Keras layer '{cn}' (layer '{name}')")


# ---------------------------------------------------------------------------
# weight conversion
# ---------------------------------------------------------------------------

def _flatten_reorder(kernel, h, w, c):
    """Dense kernel rows after a Keras Flatten are in (h,w,c) order; our
    CnnToFeedForward flattens (c,h,w). Permute rows accordingly."""
    out = kernel.shape[1]
    return kernel.reshape(h, w, c, out).transpose(2, 0, 1, 3).reshape(h * w * c, out)


def _lstm_reorder(k, H):
    """Keras gate columns [i, f, g, o] → native [i, f, o, g]."""
    i, f, g, o = k[..., :H], k[..., H:2 * H], k[..., 2 * H:3 * H], k[..., 3 * H:]
    return np.concatenate([i, f, o, g], axis=-1)


def _apply_weights(layer, weights, params, state):
    """Write Keras weight arrays into a native layer's param/state dicts.
    Returns updated (params, state)."""
    import jax.numpy as jnp

    cn = type(layer).__name__
    p = dict(params)
    s = dict(state)

    def put(key, arr):
        tgt = p[key]
        arr = np.asarray(arr)
        if tuple(tgt.shape) != tuple(arr.shape):
            raise InvalidKerasConfigurationException(
                f"weight shape mismatch for {cn}.{key}: "
                f"model {tuple(tgt.shape)} vs h5 {tuple(arr.shape)}")
        p[key] = jnp.asarray(arr, tgt.dtype)

    if isinstance(layer, R.LastTimeStep):
        return _apply_weights(layer.layer, weights, params, state)
    if isinstance(layer, L.DepthwiseConvolution2D):
        # Keras (kh,kw,nIn,mult) → native grouped layout (kh,kw,1,nIn*mult);
        # channel-major grouping is identical, so reshape suffices
        k = np.asarray(weights[0])
        kh, kw, nin, mult = k.shape
        put("W", k.reshape(kh, kw, 1, nin * mult))
        if len(weights) > 1 and "b" in p:
            put("b", weights[1])
        return p, s
    if isinstance(layer, L.SeparableConvolution2D):
        # Keras: depthwise (kh,kw,nIn,mult) + pointwise (1,1,nIn*mult,out)
        k = np.asarray(weights[0])
        kh, kw, nin, mult = k.shape
        put("W", k.reshape(kh, kw, 1, nin * mult))
        put("pW", weights[1])
        if len(weights) > 2 and "b" in p:
            put("b", weights[2])
        return p, s
    if isinstance(layer, L.PReLULayer):
        put("alpha", weights[0])
        return p, s
    if isinstance(layer, (L.DenseLayer, L.BaseOutputLayer, L.ConvolutionLayer,
                          L.Convolution3D)) \
            and not isinstance(layer, L.Convolution1DLayer):
        put("W", weights[0])
        if len(weights) > 1 and "b" in p:
            put("b", weights[1])
        return p, s
    if isinstance(layer, (L.EmbeddingLayer, L.EmbeddingSequenceLayer)):
        put("W", weights[0])
        return p, s
    if isinstance(layer, L.BatchNormalization):
        # Keras omits gamma when scale=False and beta when center=False;
        # lockGamma/lockBeta mirror those flags exactly (set at conversion),
        # so the weight-list layout follows from them
        has_gamma = not (layer.lockGammaBeta or layer.lockGamma)
        has_beta = not (layer.lockGammaBeta or layer.lockBeta)
        idx = 0
        if has_gamma and "gamma" in p:
            put("gamma", weights[idx])
        idx += 1 if has_gamma else 0
        if has_beta and "beta" in p:
            put("beta", weights[idx])
        idx += 1 if has_beta else 0
        s["mean"] = jnp.asarray(np.asarray(weights[idx]), jnp.float32)
        s["var"] = jnp.asarray(np.asarray(weights[idx + 1]), jnp.float32)
        return p, s
    if isinstance(layer, R.LSTM):
        H = layer.nOut
        put("W", _lstm_reorder(np.asarray(weights[0]), H))
        put("RW", _lstm_reorder(np.asarray(weights[1]), H))
        if len(weights) > 2:
            b = np.asarray(weights[2])
            if b.ndim == 2:  # CuDNN-fused double bias
                b = b.sum(0)
            put("b", _lstm_reorder(b, H))
        return p, s
    if isinstance(layer, R.SimpleRnn):
        put("W", weights[0])
        put("RW", weights[1])
        if len(weights) > 2:
            put("b", weights[2])
        return p, s
    from deeplearning4j_tpu.nn.conf.attention import AttentionVertex as _AV
    if isinstance(layer, _AV):
        # Keras MHA weight order: query/kernel [E,H,hs] (+bias [H,hs]),
        # key/kernel, value/kernel, attention_output/kernel [H,hs,E] (+bias
        # [E]); our projections are flat [E, H*hs] / [H*hs, E]
        has_b = layer.hasBias
        step = 2 if has_b else 1
        qk, kk, vk, ok = (np.asarray(weights[i * step]) for i in range(4))
        put("Wq", qk.reshape(qk.shape[0], -1))
        put("Wk", kk.reshape(kk.shape[0], -1))
        put("Wv", vk.reshape(vk.shape[0], -1))
        put("Wo", ok.reshape(-1, ok.shape[-1]))
        if has_b:
            put("bq", np.asarray(weights[1]).reshape(-1))
            put("bk", np.asarray(weights[3]).reshape(-1))
            put("bv", np.asarray(weights[5]).reshape(-1))
            put("bo", np.asarray(weights[7]).reshape(-1))
        return p, s
    raise UnsupportedKerasConfigurationException(
        f"weight import not supported for layer type {cn}")


def _load_h5_weights(path):
    """Legacy Keras HDF5 → {layerName: [np.ndarray, ...]} in weight_names
    order. Works for both full-model files (model_weights group) and
    save_weights files (layers at the root)."""
    import h5py

    out = {}
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        for lname in root:
            g = root[lname]
            wnames = [n.decode() if isinstance(n, bytes) else n
                      for n in g.attrs.get("weight_names", [])]
            arrs = [np.asarray(g[w]) for w in wnames]
            if arrs:
                out[lname.split("/")[0]] = arrs
    return out


def _keras3_group_name(class_name, counters):
    """Keras-3 weight-group name: to_snake_case(class) + per-class
    counter in layer order (verified against keras 3.13 saving_lib)."""
    n = re.sub(r"\W+", "", class_name)
    n = re.sub("(.)([A-Z][a-z]+)", r"\1_\2", n)
    n = re.sub("([a-z])([A-Z])", r"\1_\2", n).lower()
    c = counters.get(class_name, 0)
    counters[class_name] = c + 1
    return n if c == 0 else f"{n}_{c}"


def _keras3_subtree_has_data(grp):
    import h5py

    for k in grp:
        item = grp[k]
        if isinstance(item, h5py.Group):
            if _keras3_subtree_has_data(item):
                return True
        else:
            return True
    return False


def _load_keras3_archive(path, config_only=False):
    """Keras-3 `.keras` zip -> (config dict, {configLayerName: [arrays]}
    or None). model.weights.h5 stores variables under
    layers/<snake_case(class)[_k]>/vars/<i> with NO name mapping back to
    the config — group names are RECOMPUTED from the config's layer
    order here and looked up BY NAME: h5py iterates groups
    alphabetically (dense_10 sorts before dense_2), so order-based
    collection would silently permute weights on models with 11+
    same-class layers or non-alphabetical class order."""
    import io
    import zipfile

    import h5py

    with zipfile.ZipFile(str(path)) as z:
        cfg = json.loads(z.read("config.json"))
        if config_only or "model.weights.h5" not in z.namelist():
            return cfg, None
        blob = io.BytesIO(z.read("model.weights.h5"))
    layers_cfg = cfg.get("config", {})
    if isinstance(layers_cfg, dict):
        layers_cfg = layers_cfg.get("layers", [])
    counters, wmap = {}, {}
    with h5py.File(blob, "r") as f:
        root = f.get("layers")
        if root is None:
            return cfg, None
        for lc in layers_cfg:
            cls = lc.get("class_name", "")
            gname = _keras3_group_name(cls, counters)
            if gname not in root:
                continue  # var-less layers (Dropout, Flatten, Input)
            g = root[gname]
            if "vars" in g and len(g["vars"]):
                src = g["vars"]
            elif "cell" in g and "vars" in g["cell"] and len(g["cell"]["vars"]):
                src = g["cell"]["vars"]  # recurrent layers nest under cell
            elif _keras3_subtree_has_data(g):
                raise UnsupportedKerasConfigurationException(
                    f".keras archive layer "
                    f"'{lc.get('config', {}).get('name')}' stores variables "
                    "in nested containers (wrapper layers); re-save the "
                    "weights as a legacy h5 for import")
            else:
                continue  # var-less layers (empty vars groups included)
            lname = lc.get("config", {}).get("name")
            wmap[lname] = [np.asarray(src[str(i)]) for i in range(len(src))]
    return cfg, (wmap or None)


# ---------------------------------------------------------------------------
# the importer
# ---------------------------------------------------------------------------

class KerasModelImport:
    @staticmethod
    def _parse_config(source) -> dict:
        if isinstance(source, dict):
            return source
        text = str(source)
        if text.lstrip().startswith("{"):
            return json.loads(text)
        if text.endswith(".keras"):
            return _load_keras3_archive(text, config_only=True)[0]
        if text.endswith((".h5", ".hdf5")):
            import h5py

            with h5py.File(text, "r") as f:
                raw = f.attrs.get("model_config")
                if raw is None:
                    raise InvalidKerasConfigurationException(
                        f"{text} has no model_config attribute")
                if isinstance(raw, bytes):
                    raw = raw.decode()
                return json.loads(raw)
        with open(text) as fh:
            return json.loads(fh.read())

    # ----- Sequential ------------------------------------------------
    @staticmethod
    def importKerasSequentialModelAndWeights(configSource, weights=None,
                                             enforceTrainingConfig=False):
        """Sequential config (+ optional weights) → MultiLayerNetwork.
        `weights`: legacy-H5 path or {layerName: [arrays...]} dict.
        (reference: KerasModelImport.importKerasSequentialModelAndWeights)"""
        if (not isinstance(configSource, dict) and weights is None
                and str(configSource).endswith(".keras")):
            # one-file Keras-3 archive: config + weights together,
            # mirroring the upstream single-h5 convention
            configSource, weights = _load_keras3_archive(configSource)
        cfg = KerasModelImport._parse_config(configSource)
        if cfg.get("class_name") != "Sequential":
            raise InvalidKerasConfigurationException(
                f"expected a Sequential model, got {cfg.get('class_name')}")
        layer_cfgs = cfg.get("config", {})
        if isinstance(layer_cfgs, dict):
            layer_cfgs = layer_cfgs.get("layers", [])
        specs = [_KerasLayerSpec(rl) for rl in layer_cfgs]

        input_type = None
        for sp in specs:
            shape = sp.inputShape()
            if shape is not None:
                input_type = _input_type_from_shape(shape)
                break
        if input_type is None:
            raise InvalidKerasConfigurationException(
                "no input shape found (batch_input_shape/batch_shape)")

        lb = NeuralNetConfiguration.Builder().list()
        native_specs = []  # (spec, native_layer) for weight mapping
        _NOT_OUTPUT = ("InputLayer", "Flatten", "Dropout", "Activation",
                       "SpatialDropout1D", "SpatialDropout2D",
                       "SpatialDropout3D", "GaussianDropout", "GaussianNoise",
                       "AlphaDropout")
        last_real = max((i for i, sp in enumerate(specs)
                         if sp.className not in _NOT_OUTPUT),
                        default=len(specs) - 1)
        # fold a trailing Activation into the output layer: Dense(10) +
        # Activation('softmax') must train as softmax+mcxent, not as an
        # identity OutputLayer (mse) with a layer dangling after it
        folded = set()
        for j in range(last_real + 1, len(specs)):
            if specs[j].className == "Activation":
                specs[last_real].config["activation"] = \
                    specs[j].config.get("activation")
                folded.add(j)
            elif specs[j].className in _NOT_OUTPUT and \
                    specs[j].className != "InputLayer":
                # trailing train-time noise after the output head has no
                # DL4J representation (loss attaches to the output layer);
                # inference is unchanged, so drop it loudly
                import warnings

                warnings.warn(
                    f"dropping trailing {specs[j].className} layer "
                    f"'{specs[j].name}' (after the output head; "
                    "inference-equivalent)", stacklevel=2)
                folded.add(j)
        for i, sp in enumerate(specs):
            if i in folded:
                continue
            nl = _convert_layer(sp, is_last=(i == last_real))
            if nl is None:
                continue
            lb.layer(nl)
            native_specs.append((sp, nl))
        lb.setInputType(input_type)
        conf = lb.build()
        net = MultiLayerNetwork(conf).init()

        if weights is not None:
            from deeplearning4j_tpu.nn.conf.preprocessors import (
                CnnToFeedForwardPreProcessor,
            )

            wmap = weights if isinstance(weights, dict) \
                else _load_h5_weights(weights)
            for li, (sp, nl) in enumerate(native_specs):
                if sp.name in wmap:
                    w = list(wmap[sp.name])
                    pp = conf.preprocessors.get(li)
                    if (isinstance(pp, CnnToFeedForwardPreProcessor)
                            and isinstance(nl, (L.DenseLayer, L.BaseOutputLayer))):
                        # Keras flattened (h,w,c); our preprocessor flattens
                        # (c,h,w) — permute the kernel rows to match
                        w[0] = _flatten_reorder(np.asarray(w[0]), pp.inputHeight,
                                                pp.inputWidth, pp.numChannels)
                    net._params[li], net._states[li] = _apply_weights(
                        nl, w, net._params[li], net._states[li])
                elif nl.hasParams() and net._params[li]:
                    raise InvalidKerasConfigurationException(
                        f"no weights found for layer '{sp.name}'")
        return net

    @staticmethod
    def importKerasModelConfiguration(configSource):
        """Config-only Sequential import (reference:
        KerasModelImport.importKerasSequentialConfiguration)."""
        return KerasModelImport.importKerasSequentialModelAndWeights(configSource).conf

    # ----- Functional ------------------------------------------------
    @staticmethod
    def importKerasModelAndWeights(configSource, weights=None,
                                   enforceTrainingConfig=False):
        """Functional-API config (+ optional weights) → ComputationGraph.
        Supports layer nodes plus Add/Concatenate merge vertices.
        (reference: KerasModelImport.importKerasModelAndWeights)"""
        from deeplearning4j_tpu.nn.conf.graph import (
            ElementWiseVertex, MergeVertex,
        )

        if (not isinstance(configSource, dict) and weights is None
                and str(configSource).endswith(".keras")):
            configSource, weights = _load_keras3_archive(configSource)
        cfg = KerasModelImport._parse_config(configSource)
        if cfg.get("class_name") not in ("Model", "Functional"):
            raise InvalidKerasConfigurationException(
                f"expected a Functional model, got {cfg.get('class_name')}")
        mc = cfg["config"]
        specs = [_KerasLayerSpec(rl) for rl in mc["layers"]]
        by_name = {sp.name: sp for sp in specs}

        def _refs(v):
            """input_layers/output_layers: ["name", 0, 0] for a single ref,
            or a list of such refs / of bare names."""
            if not v:
                return []
            if isinstance(v[0], str):
                return [v[0]]
            return [ref[0] if isinstance(ref, (list, tuple)) else ref for ref in v]

        input_names = _refs(mc.get("input_layers", []))
        output_names = _refs(mc.get("output_layers", []))

        gb = NeuralNetConfiguration.Builder().graphBuilder()
        gb.addInputs(*input_names)
        in_types = []
        for n in input_names:
            shape = by_name[n].inputShape()
            if shape is None:
                raise InvalidKerasConfigurationException(f"input '{n}' has no shape")
            in_types.append(_input_type_from_shape(shape))
        gb.setInputTypes(*in_types)

        native_by_name = {}
        for sp in specs:
            if sp.name in input_names:
                continue
            inputs = sp.inbound
            if sp.className in ("Add", "Concatenate", "Average", "Maximum",
                                "Subtract", "Multiply"):
                vtx = {"Add": ElementWiseVertex("add"),
                       "Subtract": ElementWiseVertex("subtract"),
                       "Multiply": ElementWiseVertex("product"),
                       "Average": ElementWiseVertex("average"),
                       "Maximum": ElementWiseVertex("max"),
                       "Concatenate": MergeVertex()}[sp.className]
                gb.addVertex(sp.name, vtx, *inputs)
                continue
            if (sp.className == "Normalization"
                    and sp.config.get("mean") is not None):
                # constructor-mode Normalization: mean/variance are
                # config constants (no weights) -> (x - mean)/sqrt(var)
                # as chained Shift/Scale vertices
                from deeplearning4j_tpu.nn.conf.graph import (ScaleVertex,
                                                              ShiftVertex)

                _normalization_guards(sp.config, sp.name)
                mean = np.asarray(sp.config["mean"], np.float32).reshape(-1)
                # Keras clamps the denominator at epsilon() ~1e-7;
                # clamping variance at its square keeps a zero-variance
                # channel finite with the same result
                var = np.maximum(np.asarray(sp.config["variance"],
                                            np.float32).reshape(-1), 1e-14)
                gb.addVertex(sp.name + "_kshift",
                             ShiftVertex(-mean), *inputs)
                gb.addVertex(sp.name, ScaleVertex(1.0 / np.sqrt(var)),
                             sp.name + "_kshift")
                continue
            if sp.className == "Rescaling":
                # keras.layers.Rescaling: x*scale + offset with config
                # constants (no weights) -> chained Scale/Shift vertices
                # (the reference's ScaleVertex/ShiftVertex, extended to
                # per-channel factors)
                from deeplearning4j_tpu.nn.conf.graph import (ScaleVertex,
                                                              ShiftVertex)

                c = sp.config
                gb.addVertex(sp.name + "_kscale",
                             ScaleVertex(c.get("scale", 1.0)), *inputs)
                gb.addVertex(sp.name, ShiftVertex(c.get("offset", 0.0)),
                             sp.name + "_kscale")
                continue
            if sp.className == "MultiHeadAttention":
                from deeplearning4j_tpu.nn.conf.attention import AttentionVertex

                c = sp.config
                if c.get("value_dim") not in (None, c.get("key_dim")):
                    raise UnsupportedKerasConfigurationException(
                        f"MultiHeadAttention with value_dim != key_dim not "
                        f"supported (layer '{sp.name}')")
                if c.get("output_shape") is not None:
                    raise UnsupportedKerasConfigurationException(
                        f"MultiHeadAttention custom output_shape not supported "
                        f"(layer '{sp.name}')")
                av = AttentionVertex(
                    nHeads=int(c["num_heads"]), headSize=int(c["key_dim"]),
                    hasBias=bool(c.get("use_bias", True)), name=sp.name)
                # Keras call order is (query, value[, key]); the vertex wants
                # (query[, keys[, values]])
                if len(inputs) == 3:
                    inputs = [inputs[0], inputs[2], inputs[1]]
                gb.addVertex(sp.name, av, *inputs)
                native_by_name[sp.name] = av
                continue
            is_out = sp.name in output_names
            nl = _convert_layer(sp, is_last=is_out)
            if nl is None:  # Flatten/InputLayer: identity vertex via ActivationLayer
                nl = L.ActivationLayer(activation="identity", name=sp.name)
            gb.addLayer(sp.name, nl, *inputs)
            native_by_name[sp.name] = nl
        gb.setOutputs(*output_names)
        graph = ComputationGraph(gb.build()).init()

        if weights is not None:
            from deeplearning4j_tpu.nn.conf.preprocessors import (
                CnnToFeedForwardPreProcessor,
            )

            wmap = weights if isinstance(weights, dict) \
                else _load_h5_weights(weights)
            for lname, nl in native_by_name.items():
                if lname in wmap:
                    w = list(wmap[lname])
                    pp = graph.conf.nodes[lname].preprocessor
                    if (isinstance(pp, CnnToFeedForwardPreProcessor)
                            and isinstance(nl, (L.DenseLayer, L.BaseOutputLayer))):
                        # same flatten-order permutation as the Sequential path
                        w[0] = _flatten_reorder(np.asarray(w[0]), pp.inputHeight,
                                                pp.inputWidth, pp.numChannels)
                    graph._params[lname], graph._states[lname] = _apply_weights(
                        nl, w, graph._params[lname], graph._states[lname])
        return graph

"""Training dashboard: static report rendering + live HTTP server.

Reference: deeplearning4j-ui — `UIServer.getInstance().attach(storage)`
serves a live play-framework dashboard fed by StatsListener. The TPU
build keeps that shape with zero new dependencies: (a) the StatsListener
JSONL stream, (b) render_report(), which turns that stream into a
single self-contained HTML report (inline SVG, no external assets) —
the artifact you keep from a run — and (c) UIServer.start(), a stdlib
http.server endpoint that serves the live-rendered report with
auto-refresh plus a JSONL polling route (`/train/updates?since=N`) for
external dashboards, standing in for the reference's Play/Vertx server.
"""

from __future__ import annotations

import html
import json
import math
import time
import urllib.parse

from deeplearning4j_tpu.util.httpserve import HttpServerOwner, JsonHandler


def _read_records(logFile):
    recs = []
    with open(logFile) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                continue  # torn write at the tail of a live file
    return recs


def _svg_line_chart(points, title, width=640, height=220, fmt="{:.4g}"):
    """One series as an inline SVG polyline with min/max axis labels."""
    if len(points) < 2:
        return (f"<div class='chart'><h3>{html.escape(title)}</h3>"
                f"<p class='empty'>not enough data</p></div>")
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    xr = (x1 - x0) or 1.0
    yr = (y1 - y0) or 1.0
    pad, w, h = 8, width, height
    pts = " ".join(
        f"{pad + (x - x0) / xr * (w - 2 * pad):.1f},"
        f"{h - pad - (y - y0) / yr * (h - 2 * pad):.1f}"
        for x, y in points)
    return f"""<div class='chart'><h3>{html.escape(title)}</h3>
<svg viewBox='0 0 {w} {h}' width='{w}' height='{h}'
     style='background:#fafafa;border:1px solid #ddd'>
  <polyline fill='none' stroke='#2b6cb0' stroke-width='1.5' points='{pts}'/>
  <text x='{pad}' y='{h - 2}' font-size='10' fill='#666'>{fmt.format(y0)} … {fmt.format(y1)}</text>
  <text x='{w - 140}' y='{h - 2}' font-size='10' fill='#666'>iter {int(x0)} … {int(x1)}</text>
</svg></div>"""


def render_report(logFile, outFile=None, title="Training report"):
    """StatsListener JSONL -> self-contained HTML. Returns the HTML; if
    outFile is given, also writes it there."""
    recs = _read_records(logFile)
    stats = [r for r in recs if r.get("type") == "stats"
             and r.get("score") is not None]
    epochs = [r for r in recs if r.get("type") == "epochEnd"]

    # A diverged run writes NaN/inf scores — exactly when the report gets
    # read. Non-finite points would poison min/max and every polyline
    # coordinate; drop them and say how many were dropped.
    score_pts = [(r["iteration"], float(r["score"])) for r in stats
                 if math.isfinite(float(r["score"]))]
    dropped = len(stats) - len(score_pts)
    rate_pts = [(r["iteration"], float(r["iterationsPerSec"]))
                for r in stats if "iterationsPerSec" in r
                and math.isfinite(float(r["iterationsPerSec"]))]
    pmean_pts = [(r["iteration"], float(r["paramMeanAbs"]))
                 for r in stats if "paramMeanAbs" in r
                 and math.isfinite(float(r["paramMeanAbs"]))]

    rows = []
    if dropped:
        rows.append(("non-finite scores dropped",
                     f"{dropped} (run diverged?)"))
    if score_pts:
        rows.append(("final score", f"{score_pts[-1][1]:.6g}"))
        rows.append(("best score", f"{min(p[1] for p in score_pts):.6g}"))
        rows.append(("iterations", str(int(score_pts[-1][0]))))
    if rate_pts:
        rows.append(("mean iterations/sec",
                     f"{sum(p[1] for p in rate_pts) / len(rate_pts):.3g}"))
    if epochs:
        rows.append(("epochs", str(len(epochs))))
    if stats and "time" in stats[0] and "time" in stats[-1]:
        rows.append(("wall time",
                     f"{stats[-1]['time'] - stats[0]['time']:.1f} s"))

    table = "".join(f"<tr><td>{html.escape(k)}</td><td>{html.escape(v)}</td></tr>"
                    for k, v in rows)
    charts = _svg_line_chart(score_pts, "score vs iteration")
    if rate_pts:
        charts += _svg_line_chart(rate_pts, "iterations/sec")
    if pmean_pts:
        charts += _svg_line_chart(pmean_pts, "mean |param|")

    doc = f"""<!doctype html><html><head><meta charset='utf-8'>
<title>{html.escape(title)}</title>
<style>body{{font:14px system-ui,sans-serif;margin:2em;color:#222}}
table{{border-collapse:collapse;margin:1em 0}}
td{{border:1px solid #ddd;padding:4px 12px}}
.chart{{margin:1.2em 0}} .empty{{color:#999}}</style></head><body>
<h1>{html.escape(title)}</h1>
<p>generated {time.strftime('%Y-%m-%d %H:%M:%S')} from
{html.escape(str(logFile))} ({len(stats)} stat records)</p>
<table>{table}</table>
{charts}
</body></html>"""
    if outFile is not None:
        with open(outFile, "w") as fh:
            fh.write(doc)
    return doc


class UIServer(HttpServerOwner):
    """The reference's UIServer singleton, TPU-build edition.

    attach() takes a StatsListener (or a JSONL path); render() produces
    the HTML report for every attached source; start(port) serves the
    live report over HTTP (stdlib http.server — see module docstring):

      GET /                       report for source 0, auto-refreshing
      GET /train/<i>              report for source i
      GET /train/<i>/updates?since=N   JSONL records from line N on,
                                  as {"records": [...], "next": M}
      GET /sources                attached source paths

    The handler re-reads the JSONL on every request, so a dashboard
    open during training updates as the listener appends.
    """

    _instance = None

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self._sources = []

    def attach(self, source):
        path = getattr(source, "logFile", source)
        if path is None:
            raise ValueError(
                "StatsListener has no logFile — construct it with "
                "StatsListener(logFile=...) to collect a report")
        self._sources.append(str(path))
        return self

    def detach(self, source):
        path = str(getattr(source, "logFile", source))
        self._sources = [s for s in self._sources if s != path]

    def render(self, outFile=None, title="Training report"):
        """Render all attached sources; returns a list of HTML strings
        (or writes `outFile` / numbered siblings when given)."""
        docs = []
        for i, src in enumerate(self._sources):
            out = None
            if outFile is not None:
                out = str(outFile) if len(self._sources) == 1 else \
                    f"{outFile}.{i}.html"
            docs.append(render_report(src, out, title=title))
        return docs

    # ----- live server (reference: UIServer.getInstance() web UI) -----
    def start(self, port=9000, refreshSec=5, requestDeadline=None):
        """Serve the live dashboard on 127.0.0.1:<port>; returns self.
        Daemon-threaded, so it never keeps a training process alive.
        GET /healthz answers readiness; requestDeadline (seconds) turns
        a stuck handler into a 503 instead of a hung client — see
        util.httpserve."""
        ui = self

        class Handler(JsonHandler):
            def handle_GET(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                try:
                    if parsed.path == "/sources":
                        return self._json({"sources": list(ui._sources)})
                    if not parts or parts[0] == "train":
                        # /train/updates == /train/0/updates (the docs'
                        # short form for the single-source case)
                        if len(parts) > 1 and parts[1] == "updates":
                            parts = [parts[0], "0"] + parts[1:]
                        idx = int(parts[1]) if len(parts) > 1 else 0
                        if not (0 <= idx < len(ui._sources)):
                            return self._json(
                                {"error": f"no source {idx} attached"}, 404)
                        src = ui._sources[idx]
                        if len(parts) > 2 and parts[2] == "updates":
                            q = urllib.parse.parse_qs(parsed.query)
                            since = int(q.get("since", ["0"])[0])
                            recs = _read_records(src)
                            return self._json({"records": recs[since:],
                                               "next": len(recs)})
                        doc = render_report(src, title=f"Training (live) — {src}")
                        doc = doc.replace(
                            "<meta charset='utf-8'>",
                            "<meta charset='utf-8'>"
                            f"<meta http-equiv='refresh' content='{refreshSec}'>",
                            1)
                        return self._send(200, doc, "text/html")
                    return self._json({"error": "unknown route"}, 404)
                except ValueError as e:
                    # malformed index/since is the CLIENT's error
                    return self._json({"error": f"{type(e).__name__}: {e}"},
                                      400)
                except OSError as e:  # source file unreadable: ours
                    return self._json({"error": f"{type(e).__name__}: {e}"},
                                      500)

        return self._serve(Handler, port, requestDeadline=requestDeadline)

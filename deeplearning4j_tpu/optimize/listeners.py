"""Training listeners.

Reference: org.deeplearning4j.optimize.api.TrainingListener and the impls in
org.deeplearning4j.optimize.listeners (ScoreIterationListener,
PerformanceListener, EvaluativeListener, CheckpointListener,
CollectScoresListener, TimeIterationListener) plus the UI StatsListener
(deeplearning4j-ui). TPU note: `model.score()` reads the last device loss —
a host sync — so listeners that only need it every N iterations stay off the
hot path and XLA keeps steps pipelined in between.
"""

from __future__ import annotations

import json
import math
import os
import time


class TrainingListener:
    """No-op base. Subclasses override what they need
    (reference: optimize.api.BaseTrainingListener)."""

    def iterationDone(self, model, iteration: int, epoch: int) -> None:
        pass

    def onEpochStart(self, model) -> None:
        pass

    def onEpochEnd(self, model) -> None:
        pass

    # ----- staged-epoch hook (fitDataSet / ResilientFit blocks) -------
    def onSyncBoundary(self, model, iteration: int, scores) -> None:
        """fitDataSet(stepsPerSync=k) finished one k-step device block:
        `scores` is the block's per-step loss vector (numpy, length k),
        already replayed through iterationDone. The ONLY point inside a
        staged epoch where host-side state is fresh — per-iteration
        hooks between sync boundaries observe scores replayed from the
        block's k-vector, not a live device fetch."""

    # ----- resilience hooks (runtime.resilience.ResilientFit) ---------
    def onStepSkipped(self, model, iteration: int, epoch: int,
                      loss: float) -> None:
        """A step produced non-finite loss/params and was NOT applied."""

    def onCheckpointSaved(self, model, path: str, iteration: int) -> None:
        pass

    def onCheckpointRestored(self, model, path: str,
                             iteration: int) -> None:
        """Training resumed from `path` (preemption recovery)."""


class ScoreIterationListener(TrainingListener):
    """Print score every `printIterations` iterations
    (reference: listeners.ScoreIterationListener)."""

    def __init__(self, printIterations: int = 10):
        self.printIterations = max(1, int(printIterations))

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.printIterations == 0:
            print(f"Score at iteration {iteration} is {model.score()}")


class PerformanceListener(TrainingListener):
    """Throughput reporting: iterations/sec, examples/sec
    (reference: listeners.PerformanceListener).

    Batch size is read from the model's last-fit minibatch (`model.batchSize()`
    if present) so examples/sec covers the real data rate into the chip.
    """

    def __init__(self, frequency: int = 10, reportScore: bool = False):
        self.frequency = max(1, int(frequency))
        self.reportScore = reportScore
        self._last_time = None
        self._last_iter = None

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            ips = iters / dt if dt > 0 else float("inf")
            bs = getattr(model, "batchSize", lambda: None)()
            msg = f"iteration {iteration}: {ips:.2f} iterations/sec"
            if bs:
                msg += f", {ips * bs:.1f} examples/sec"
            if self.reportScore:
                msg += f", score {model.score()}"
            print(msg)
        self._last_time = now
        self._last_iter = iteration


class EvaluativeListener(TrainingListener):
    """Run an evaluation on a held-out iterator every `frequency` iterations
    or at each epoch end (reference: listeners.EvaluativeListener)."""

    ITERATION = "iteration"
    EPOCH = "epoch"

    def __init__(self, iterator, frequency: int = 100, invocationType: str = ITERATION,
                 evaluation=None):
        self.iterator = iterator
        self.frequency = max(1, int(frequency))
        self.invocationType = invocationType
        self.evaluation = evaluation
        self.callback = None  # called with the filled evaluation object

    def _invoke(self, model):
        from deeplearning4j_tpu.evaluation.evaluation import Evaluation

        e = self.evaluation if self.evaluation is not None else Evaluation()
        e.reset()
        self.iterator.reset()
        while self.iterator.hasNext():
            ds = self.iterator.next()
            out = model.output(ds.getFeatures())
            e.eval(ds.getLabels(), out, mask=ds.getLabelsMaskArray())
        if self.callback is not None:
            self.callback(e)
        else:
            print(e.stats())

    def iterationDone(self, model, iteration, epoch):
        if self.invocationType == self.ITERATION and iteration % self.frequency == 0:
            self._invoke(model)

    def onEpochEnd(self, model):
        if self.invocationType == self.EPOCH:
            self._invoke(model)


class CheckpointListener(TrainingListener):
    """Periodic model checkpoints with rotation
    (reference: listeners.CheckpointListener.Builder — saveEveryNIterations /
    saveEveryNEpochs / keepLast)."""

    def __init__(self, modelSaveDir, saveEveryNIterations=None,
                 saveEveryNEpochs=None, keepLast: int = 0, saveUpdater: bool = True):
        if saveEveryNIterations is None and saveEveryNEpochs is None:
            raise ValueError("set saveEveryNIterations and/or saveEveryNEpochs")
        self.dir = str(modelSaveDir)
        os.makedirs(self.dir, exist_ok=True)
        self.everyIter = saveEveryNIterations
        self.everyEpoch = saveEveryNEpochs
        self.keepLast = int(keepLast)
        self.saveUpdater = saveUpdater
        self._saved = []  # paths, oldest first

    def _save(self, model, tag: str):
        from deeplearning4j_tpu.util.serializer import ModelSerializer

        path = os.path.join(self.dir, f"checkpoint_{tag}.npz")
        ModelSerializer.writeModel(model, path, saveUpdater=self.saveUpdater)
        self._saved.append(path)
        if self.keepLast > 0:
            while len(self._saved) > self.keepLast:
                old = self._saved.pop(0)
                try:
                    os.remove(old)
                except OSError:
                    pass

    def lastCheckpoint(self):
        return self._saved[-1] if self._saved else None

    def iterationDone(self, model, iteration, epoch):
        if self.everyIter and iteration % self.everyIter == 0:
            self._save(model, f"iter_{iteration}")

    def onEpochEnd(self, model):
        ep = model.getEpochCount() if hasattr(model, "getEpochCount") else 0
        if self.everyEpoch and (ep + 1) % self.everyEpoch == 0:
            self._save(model, f"epoch_{ep}")


class CollectScoresListener(TrainingListener):
    """Collect (iteration, score) pairs in memory
    (reference: listeners.CollectScoresListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self.iterations = []
        self.scores = []

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            self.iterations.append(iteration)
            self.scores.append(model.score())


class TimeIterationListener(TrainingListener):
    """Estimate remaining training time from iteration rate
    (reference: listeners.TimeIterationListener)."""

    def __init__(self, iterationCount: int, frequency: int = 50):
        self.total = int(iterationCount)
        self.frequency = max(1, int(frequency))
        self._start = None

    def iterationDone(self, model, iteration, epoch):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = iteration / elapsed
            remaining = max(0.0, (self.total - iteration) / rate) if rate > 0 else 0.0
            print(f"iteration {iteration}/{self.total}, ETA {remaining:.1f}s")


class StatsListener(TrainingListener):
    """Training telemetry to a JSONL file + periodic terminal summary.

    TPU-native stand-in for the reference's UI server StatsListener
    (deeplearning4j-ui StatsListener → play-framework dashboard): one JSON
    object per record with score, rates, and parameter/gradient summary
    stats; any dashboard can tail the file.
    """

    def __init__(self, logFile=None, frequency: int = 10, collectHistograms: bool = False):
        self.frequency = max(1, int(frequency))
        self.logFile = str(logFile) if logFile is not None else None
        self.collectHistograms = collectHistograms
        self._last_time = None
        self._last_iter = None

    def _write(self, rec: dict):
        # append-per-record: no held file descriptor to leak, and records
        # are durable the moment they're written
        if self.logFile is None:
            return
        with open(self.logFile, "a") as fh:
            fh.write(json.dumps(rec) + "\n")

    def _param_stats(self, model):
        import numpy as np

        stats = {}
        params = getattr(model, "_params", None)
        if params is None:
            return stats
        try:
            import jax

            leaves = jax.tree_util.tree_leaves(params)
            if leaves:
                means = [float(abs(x).mean()) for x in leaves]
                stats["paramMeanAbs"] = float(np.mean(means))
        except Exception:
            pass
        return stats

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        score = model.score()
        rec = {"type": "stats", "iteration": iteration, "epoch": epoch,
               "score": score, "time": time.time()}
        if self._last_time is not None and iteration > self._last_iter:
            rec["iterationsPerSec"] = (iteration - self._last_iter) / (now - self._last_time)
        if self.collectHistograms:
            rec.update(self._param_stats(model))
        self._write(rec)
        self._last_time, self._last_iter = now, iteration

    def onEpochEnd(self, model):
        self._write({"type": "epochEnd", "epoch": model.getEpochCount(),
                     "score": model.score(), "time": time.time()})

    def summary(self) -> str:
        if self.logFile is None or not os.path.exists(self.logFile):
            return "no stats recorded"
        scores = []
        with open(self.logFile) as fh:
            for line in fh:
                rec = json.loads(line)
                if rec.get("type") == "stats":
                    scores.append((rec["iteration"], rec["score"]))
        if not scores:
            return "no stats recorded"
        first, last = scores[0], scores[-1]
        return (f"{len(scores)} records; score {first[1]:.6f} @ iter {first[0]} "
                f"→ {last[1]:.6f} @ iter {last[0]}")


class MetricsListener(TrainingListener):
    """Bridge the TrainingListener event stream into the process-wide
    metrics registry (runtime.telemetry, docs/OBSERVABILITY.md): the
    scrape-able twin of ScoreIterationListener/ResilienceListener.

    Instruments (all under the registry the InferenceServer's
    /metrics endpoint exposes):

    * ``dl4j_train_iterations_total``        — iterationDone count
    * ``dl4j_train_score``                   — last host-visible score
      (read from the model's already-fetched loss: NO device sync)
    * ``dl4j_train_epochs_total``            — onEpochEnd count
    * ``dl4j_train_sync_boundaries_total``   — fitDataSet k-blocks
    * ``dl4j_train_steps_skipped_total``     — non-finite skipped steps
    * ``dl4j_checkpoints_saved_total`` / ``dl4j_checkpoints_restored_total``

    Counting stays OFF the hot path: every hook fires from host-side
    loop code that already holds the fetched loss. Attach once per
    process per training run; counters are cumulative process-wide.
    """

    def __init__(self, registry=None):
        from deeplearning4j_tpu.runtime import telemetry

        reg = registry if registry is not None \
            else telemetry.get_registry()
        self.registry = reg
        self._iters = reg.counter(
            "dl4j_train_iterations_total",
            "training iterations seen by the listener chain")
        self._score = reg.gauge(
            "dl4j_train_score",
            "last host-visible training score (loss)")
        self._epochs = reg.counter(
            "dl4j_train_epochs_total", "training epochs completed")
        self._syncs = reg.counter(
            "dl4j_train_sync_boundaries_total",
            "fitDataSet k-block sync boundaries")
        self._skips = reg.counter(
            "dl4j_train_steps_skipped_total",
            "steps skipped by the non-finite guard")
        self._saves = reg.counter(
            "dl4j_checkpoints_saved_total", "checkpoints written")
        self._restores = reg.counter(
            "dl4j_checkpoints_restored_total",
            "checkpoints restored (preemption recovery)")

    def iterationDone(self, model, iteration, epoch):
        self._iters.inc()
        # _score is the loop's already-fetched host float — reading it
        # costs nothing; model.score() on these models returns it as-is
        s = getattr(model, "_score", None)
        if s is not None:
            self._score.set(float(s))

    def onEpochEnd(self, model):
        self._epochs.inc()

    def onSyncBoundary(self, model, iteration, scores):
        self._syncs.inc()

    def onStepSkipped(self, model, iteration, epoch, loss):
        self._skips.inc()

    def onCheckpointSaved(self, model, path, iteration):
        self._saves.inc()

    def onCheckpointRestored(self, model, path, iteration):
        self._restores.inc()


class ResilienceListener(TrainingListener):
    """Collects the resilience event stream (skipped steps, checkpoint
    saves, restores) in memory — the assertion surface for the fault
    matrix, and a cheap ops signal ('how often does this run skip?').
    Events are (kind, iteration, detail) tuples, oldest first."""

    def __init__(self):
        self.events = []
        self.skippedSteps = 0
        self.saves = 0
        self.restores = 0

    def onStepSkipped(self, model, iteration, epoch, loss):
        self.skippedSteps += 1
        self.events.append(("skip", iteration, loss))

    def onCheckpointSaved(self, model, path, iteration):
        self.saves += 1
        self.events.append(("save", iteration, path))

    def onCheckpointRestored(self, model, path, iteration):
        self.restores += 1
        self.events.append(("restore", iteration, path))


class NanScoreWatcher(TrainingListener):
    """Failure detection: raise as soon as the loss goes NaN/Inf
    (reference analogue: FailureTestingListener / the workspace NaN panics).
    Catches divergence at the iteration it happens instead of after a full
    wasted epoch."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))

    def iterationDone(self, model, iteration, epoch):
        if iteration % self.frequency == 0:
            s = model.score()
            if not math.isfinite(s):
                raise FloatingPointError(
                    f"non-finite training score {s} at iteration {iteration} "
                    f"(epoch {epoch})")

"""Training-loop orchestration: listeners and early stopping.

Reference: org.deeplearning4j.optimize (listeners, Solver) and
org.deeplearning4j.earlystopping.
"""

from deeplearning4j_tpu.optimize.listeners import (
    TrainingListener,
    ScoreIterationListener,
    PerformanceListener,
    EvaluativeListener,
    CheckpointListener,
    CollectScoresListener,
    TimeIterationListener,
    StatsListener,
    NanScoreWatcher,
    ResilienceListener,
)
from deeplearning4j_tpu.optimize.ui import UIServer, render_report
from deeplearning4j_tpu.optimize.earlystopping import (
    EarlyStoppingParallelTrainer,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    EarlyStoppingGraphTrainer,
    EarlyStoppingResult,
    TerminationReason,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    BestScoreEpochTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    DataSetLossCalculator,
    InMemoryModelSaver,
    LocalFileModelSaver,
)

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "EvaluativeListener", "CheckpointListener", "CollectScoresListener",
    "TimeIterationListener", "StatsListener", "NanScoreWatcher",
    "ResilienceListener",
    "EarlyStoppingConfiguration", "EarlyStoppingTrainer",
    "EarlyStoppingGraphTrainer", "EarlyStoppingResult", "TerminationReason",
    "MaxEpochsTerminationCondition", "ScoreImprovementEpochTerminationCondition",
    "BestScoreEpochTerminationCondition", "MaxScoreIterationTerminationCondition",
    "MaxTimeIterationTerminationCondition", "DataSetLossCalculator",
    "InMemoryModelSaver", "LocalFileModelSaver",
    "UIServer", "render_report", "EarlyStoppingParallelTrainer",
]

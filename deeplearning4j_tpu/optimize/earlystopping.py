"""Early stopping.

Reference: org.deeplearning4j.earlystopping — EarlyStoppingConfiguration,
EarlyStoppingTrainer / EarlyStoppingGraphTrainer, termination conditions
(MaxEpochsTerminationCondition, ScoreImprovementEpochTerminationCondition,
BestScoreEpochTerminationCondition, MaxScoreIterationTerminationCondition,
MaxTimeIterationTerminationCondition), ScoreCalculator
(DataSetLossCalculator), and EarlyStoppingModelSaver
(InMemoryModelSaver / LocalFileModelSaver).

TPU note: model "snapshots" are cheap — params are immutable jax pytrees, so
saving the best model is keeping references, no host copy.
"""

from __future__ import annotations

import enum
import os
import time


class TerminationReason(enum.Enum):
    EpochTerminationCondition = "EpochTerminationCondition"
    IterationTerminationCondition = "IterationTerminationCondition"
    Error = "Error"


# ---------------------------------------------------------------------------
# termination conditions
# ---------------------------------------------------------------------------

class MaxEpochsTerminationCondition:
    def __init__(self, maxEpochs: int):
        self.maxEpochs = int(maxEpochs)

    def initialize(self):
        pass

    def terminate(self, epochNum: int, score: float, minimize: bool) -> bool:
        return epochNum + 1 >= self.maxEpochs

    def __str__(self):
        return f"MaxEpochsTerminationCondition({self.maxEpochs})"


class ScoreImprovementEpochTerminationCondition:
    """Stop when no score improvement for N consecutive epochs."""

    def __init__(self, maxEpochsWithNoImprovement: int, minImprovement: float = 0.0):
        self.maxEpochs = int(maxEpochsWithNoImprovement)
        self.minImprovement = float(minImprovement)
        self._best = None
        self._noImprove = 0

    def initialize(self):
        self._best = None
        self._noImprove = 0

    def terminate(self, epochNum, score, minimize):
        if self._best is None:
            self._best = score
            return False
        improvement = (self._best - score) if minimize else (score - self._best)
        if improvement > self.minImprovement:
            self._best = score
            self._noImprove = 0
        else:
            self._noImprove += 1
        return self._noImprove >= self.maxEpochs

    def __str__(self):
        return (f"ScoreImprovementEpochTerminationCondition({self.maxEpochs}, "
                f"minImprovement={self.minImprovement})")


class BestScoreEpochTerminationCondition:
    """Stop once the score is at least as good as a target value."""

    def __init__(self, bestExpectedScore: float):
        self.bestExpectedScore = float(bestExpectedScore)

    def initialize(self):
        pass

    def terminate(self, epochNum, score, minimize):
        return score <= self.bestExpectedScore if minimize else score >= self.bestExpectedScore

    def __str__(self):
        return f"BestScoreEpochTerminationCondition({self.bestExpectedScore})"


class MaxScoreIterationTerminationCondition:
    """Abort mid-epoch if the score explodes past a ceiling."""

    def __init__(self, maxScore: float):
        self.maxScore = float(maxScore)

    def initialize(self):
        pass

    def terminate(self, lastMiniBatchScore: float) -> bool:
        import math

        return lastMiniBatchScore > self.maxScore or not math.isfinite(lastMiniBatchScore)

    def __str__(self):
        return f"MaxScoreIterationTerminationCondition({self.maxScore})"


class MaxTimeIterationTerminationCondition:
    def __init__(self, maxTime: float, unit: str = "seconds"):
        mult = {"seconds": 1.0, "minutes": 60.0, "hours": 3600.0}[unit]
        self.maxSeconds = float(maxTime) * mult
        self._start = None

    def initialize(self):
        self._start = time.perf_counter()

    def terminate(self, lastMiniBatchScore: float) -> bool:
        return (time.perf_counter() - self._start) >= self.maxSeconds

    def __str__(self):
        return f"MaxTimeIterationTerminationCondition({self.maxSeconds}s)"


# ---------------------------------------------------------------------------
# score calculators
# ---------------------------------------------------------------------------

class DataSetLossCalculator:
    """Held-out loss, averaged over the iterator, weighted by batch size
    (reference: earlystopping.scorecalc.DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculateScore(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        while self.iterator.hasNext():
            ds = self.iterator.next()
            bs = ds.numExamples()
            total += model.score(ds) * bs
            n += bs
        if n == 0:
            return float("nan")
        return total / n if self.average else total

    def minimizeScore(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# model savers
# ---------------------------------------------------------------------------

class InMemoryModelSaver:
    """Keep the best/latest model in memory. Snapshots are DEVICE copies
    (`jnp.copy`, HBM→HBM, no host round-trip): the train step donates its
    param/state buffers to XLA, so bare references would be invalidated by
    the next fit iteration on TPU."""

    def __init__(self):
        self._best = None
        self._latest = None

    @staticmethod
    def _snapshot(model):
        from deeplearning4j_tpu.util.pytree import device_copy_tree as cp

        return {
            "params": cp(model._params),
            "upd_states": cp(model._upd_states),
            "states": cp(model._states),
            "iteration": model._iteration,
            "epoch": model._epoch,
        }

    @staticmethod
    def _restore(model, snap):
        model._params = snap["params"]
        model._upd_states = snap["upd_states"]
        model._states = snap["states"]
        model._iteration = snap["iteration"]
        model._epoch = snap["epoch"]
        return model

    def saveBestModel(self, model, score):
        self._best = (self._snapshot(model), model)

    def saveLatestModel(self, model, score):
        self._latest = (self._snapshot(model), model)

    def getBestModel(self):
        if self._best is None:
            return None
        snap, model = self._best
        import copy

        restored = copy.copy(model)
        return self._restore(restored, snap)

    def getLatestModel(self):
        if self._latest is None:
            return None
        snap, model = self._latest
        import copy

        restored = copy.copy(model)
        return self._restore(restored, snap)


class LocalFileModelSaver:
    """Persist best/latest model zips under a directory
    (reference: earlystopping.saver.LocalFileModelSaver)."""

    def __init__(self, directory):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.directory, name)

    def saveBestModel(self, model, score):
        from deeplearning4j_tpu.util.serializer import ModelSerializer

        ModelSerializer.writeModel(model, self._path("bestModel.npz"), saveUpdater=True)

    def saveLatestModel(self, model, score):
        from deeplearning4j_tpu.util.serializer import ModelSerializer

        ModelSerializer.writeModel(model, self._path("latestModel.npz"), saveUpdater=True)

    def _restore(self, name):
        from deeplearning4j_tpu.util.serializer import ModelSerializer

        path = self._path(name)
        if not os.path.exists(path):
            return None
        try:
            return ModelSerializer.restoreMultiLayerNetwork(path)
        except Exception:
            return ModelSerializer.restoreComputationGraph(path)

    def getBestModel(self):
        return self._restore("bestModel.npz")

    def getLatestModel(self):
        return self._restore("latestModel.npz")


# ---------------------------------------------------------------------------
# configuration + result
# ---------------------------------------------------------------------------

class EarlyStoppingConfiguration:
    """Builder-style config (reference:
    earlystopping.EarlyStoppingConfiguration.Builder)."""

    class Builder:
        def __init__(self):
            self._epochConds = []
            self._iterConds = []
            self._scoreCalc = None
            self._saver = InMemoryModelSaver()
            self._evalEveryN = 1
            self._saveLastModel = False

        def epochTerminationConditions(self, *conds):
            self._epochConds = list(conds)
            return self

        def iterationTerminationConditions(self, *conds):
            self._iterConds = list(conds)
            return self

        def scoreCalculator(self, calc):
            self._scoreCalc = calc
            return self

        def modelSaver(self, saver):
            self._saver = saver
            return self

        def evaluateEveryNEpochs(self, n: int):
            self._evalEveryN = max(1, int(n))
            return self

        def saveLastModel(self, save: bool = True):
            self._saveLastModel = save
            return self

        def build(self):
            return EarlyStoppingConfiguration(self)

    def __init__(self, b: "EarlyStoppingConfiguration.Builder"):
        self.epochTerminationConditions = b._epochConds
        self.iterationTerminationConditions = b._iterConds
        self.scoreCalculator = b._scoreCalc
        self.modelSaver = b._saver
        self.evaluateEveryNEpochs = b._evalEveryN
        self.saveLastModel = b._saveLastModel


class EarlyStoppingResult:
    def __init__(self, terminationReason, terminationDetails, scoreVsEpoch,
                 bestModelEpoch, bestModelScore, totalEpochs, bestModel):
        self.terminationReason = terminationReason
        self.terminationDetails = terminationDetails
        self.scoreVsEpoch = scoreVsEpoch
        self.bestModelEpoch = bestModelEpoch
        self.bestModelScore = bestModelScore
        self.totalEpochs = totalEpochs
        self._bestModel = bestModel

    def getBestModel(self):
        return self._bestModel

    def __str__(self):
        return (f"EarlyStoppingResult(reason={self.terminationReason.value}, "
                f"details={self.terminationDetails}, epochs={self.totalEpochs}, "
                f"bestEpoch={self.bestModelEpoch}, bestScore={self.bestModelScore})")


# ---------------------------------------------------------------------------
# trainer
# ---------------------------------------------------------------------------

class _IterationGuard:
    """Listener bridging per-iteration termination conditions into fit()."""

    class Halt(Exception):
        def __init__(self, cond):
            self.cond = cond

    def __init__(self, conds):
        self.conds = conds

    def iterationDone(self, model, iteration, epoch):
        score = model.score()
        for c in self.conds:
            if c.terminate(score):
                raise _IterationGuard.Halt(c)

    def onEpochStart(self, model):
        pass

    def onEpochEnd(self, model):
        pass


class EarlyStoppingTrainer:
    """Epoch loop with score-based model selection
    (reference: earlystopping.trainer.EarlyStoppingTrainer).

    Works for MultiLayerNetwork and ComputationGraph alike — both expose
    fit(iterator)/score(ds); EarlyStoppingGraphTrainer is an alias kept for
    reference API parity.
    """

    def __init__(self, earlyStoppingConfiguration, model, trainData):
        self.conf = earlyStoppingConfiguration
        self.model = model
        self.trainData = trainData

    def fit(self) -> EarlyStoppingResult:
        conf = self.conf
        for c in conf.epochTerminationConditions:
            c.initialize()
        for c in conf.iterationTerminationConditions:
            c.initialize()

        minimize = (conf.scoreCalculator.minimizeScore()
                    if conf.scoreCalculator is not None else True)
        scoreVsEpoch = {}
        best_score, best_epoch = None, -1
        last_val_score = None
        epoch = 0
        reason, details = None, None

        guard = _IterationGuard(conf.iterationTerminationConditions)
        self.model.addListeners(guard)
        try:
            while True:
                try:
                    self.model.fit(self.trainData)
                except _IterationGuard.Halt as h:
                    reason = TerminationReason.IterationTerminationCondition
                    details = str(h.cond)
                    halt_cond = h.cond
                    break

                scored = True
                if conf.scoreCalculator is not None:
                    if epoch % conf.evaluateEveryNEpochs == 0:
                        score = conf.scoreCalculator.calculateScore(self.model)
                        scoreVsEpoch[epoch] = score
                        last_val_score = score
                        better = (best_score is None or
                                  (score < best_score if minimize else score > best_score))
                        if better:
                            best_score, best_epoch = score, epoch
                            conf.modelSaver.saveBestModel(self.model, score)
                    else:
                        # skipped-evaluation epoch: no new validation score.
                        # Carry the last one forward for reporting, but treat
                        # the epoch as unscored — the training minibatch loss
                        # is a different metric, and re-feeding a stale score
                        # would count fake no-improvement epochs.
                        score = last_val_score
                        scored = False
                else:
                    score = self.model.score()
                    scoreVsEpoch[epoch] = score

                if conf.saveLastModel:
                    conf.modelSaver.saveLatestModel(self.model, score)

                stop = None
                for c in conf.epochTerminationConditions:
                    # score-comparing conditions only run on epochs that
                    # produced a fresh score; epoch-count conditions always run
                    if not scored and not isinstance(c, MaxEpochsTerminationCondition):
                        continue
                    if c.terminate(epoch, score, minimize):
                        stop = c
                        break
                if stop is not None:
                    reason = TerminationReason.EpochTerminationCondition
                    details = str(stop)
                    break
                epoch += 1
        finally:
            # detach the guard so the model is reusable afterwards
            self.model._listeners = [l for l in self.model._listeners if l is not guard]

        if best_score is None:
            if reason == TerminationReason.IterationTerminationCondition and (
                    conf.scoreCalculator is not None
                    or isinstance(halt_cond, MaxScoreIterationTerminationCondition)):
                # halted on divergence/NaN before any validation pass: the
                # final state is the exploded one that triggered the halt —
                # never save it as "best". A pure time-budget halt
                # (MaxTimeIterationTerminationCondition) without a score
                # calculator is benign: fall through and keep the final model.
                return EarlyStoppingResult(reason, details, scoreVsEpoch, -1,
                                           None, epoch + 1, None)
            # no score calculator, epoch-condition or time-budget stop:
            # best = final
            conf.modelSaver.saveBestModel(self.model, scoreVsEpoch.get(epoch))
            best_epoch = epoch
            best_score = scoreVsEpoch.get(epoch)
        best = conf.modelSaver.getBestModel() or self.model
        return EarlyStoppingResult(reason, details, scoreVsEpoch, best_epoch,
                                   best_score, epoch + 1, best)


class EarlyStoppingGraphTrainer(EarlyStoppingTrainer):
    """Reference API parity alias (earlystopping.trainer.EarlyStoppingGraphTrainer)."""


class _ParallelModelFacade:
    """Model-shaped view of a parallel trainer: fit() dispatches the
    sharded step, every other attribute (score, listeners, params,
    snapshot state) comes from the wrapped network, which the wrapper
    keeps replicated across the mesh."""

    def __init__(self, wrapper):
        object.__setattr__(self, "_wrapper", wrapper)
        object.__setattr__(self, "_net", wrapper.net)

    def fit(self, data, *a, **kw):
        return self._wrapper.fit(data, *a, **kw)

    def __getattr__(self, name):
        if name in ("_net", "_wrapper"):
            # copy/pickle can materialize the facade without __init__;
            # a bare lookup must fail instead of recursing
            raise AttributeError(name)
        return getattr(self._net, name)

    def __setattr__(self, name, value):
        # writes must reach the real net too (model savers restore
        # _params/_states onto "the model", trainers reset _listeners);
        # a facade-local write would leave methods reading live weights
        if name in ("_net", "_wrapper"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._net, name, value)

    def __copy__(self):
        # model savers copy.copy "the model" and restore a snapshot onto
        # the copy; unwrap so that lands on a detached net copy, not on
        # the live net shared through the facade
        import copy

        return copy.copy(self._net)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    """Early stopping over a data-parallel trainer (reference:
    org.deeplearning4j.parallelism.EarlyStoppingParallelTrainer — there a
    ParallelWrapper of per-GPU replicas, here one mesh-sharded SPMD step).

    Pass an existing ParallelWrapper/SharedTrainingMaster as `wrapper`,
    or let it build a dense ParallelWrapper over `mesh`/all devices.
    """

    def __init__(self, earlyStoppingConfiguration, model, trainData,
                 wrapper=None, mesh=None, **wrapper_kw):
        from deeplearning4j_tpu.parallel import ParallelWrapper

        if wrapper is None:
            wrapper = ParallelWrapper(model, mesh=mesh, **wrapper_kw)
        elif wrapper.net is not model:
            raise ValueError("wrapper must wrap the same model instance")
        super().__init__(earlyStoppingConfiguration,
                         _ParallelModelFacade(wrapper), trainData)

"""Batch-sharded SPMD inference.

Reference: org.deeplearning4j.parallelism.ParallelInference — upstream
wraps a model per GPU behind a worker queue and round-robins incoming
batches (INPLACE/BATCHED modes, observables for async callers). The
queue exists because each cuda device needs its own host thread and
model replica. TPU-native design: ONE jitted forward whose input is
sharded over the mesh's data axis — XLA splits the batch across chips,
weights stay replicated, and there is no per-device host thread to
tune. The `workers(n)` knob becomes the mesh size.

The upstream modes map onto two dispatch disciplines:

* ``INPLACE`` / ``SEQUENTIAL`` — synchronous: every ``output()`` call
  is one SPMD dispatch (padded to its batch bucket when
  ``batchBuckets`` is set).
* ``BATCHED`` — queued-batched: concurrent ``output()`` callers feed a
  bounded request queue (``queueLimit``) and a dynamic micro-batcher
  (serving.queue.MicroBatcher) coalesces them into ONE padded,
  mesh-sharded dispatch per micro-batch — the continuous-batching
  serving discipline (docs/SERVING.md). Queue overflow raises
  ``QueueFullError`` (backpressure), never a hang.

Weight-only int8 (``int8=True``) consumes nn/quantize: weights are
quantized once at construction and dequantized in-graph, so the
resident/streamed weight bytes are the int8 buffers (the PR-5
bandwidth story applied to serving).
"""

from __future__ import annotations

import threading

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.parallel.mesh import build_mesh, DATA_AXIS

#: upstream InferenceMode names -> dispatch discipline (module
#: docstring); anything else is rejected loudly at construction
INFERENCE_MODES = ("INPLACE", "SEQUENTIAL", "BATCHED")


def _unwrap(x):
    return x.jax() if isinstance(x, INDArray) else np.asarray(x)


class ParallelInference:
    """output() over all devices of a (data-axis) mesh.

    model: an initialized MultiLayerNetwork or ComputationGraph.
    mesh:  jax.sharding.Mesh with a "data" axis (default: all devices).
    batchLimit: optional max examples per dispatch; larger inputs are
        chunked host-side (reference: ParallelInference.batchLimit).
    batchBuckets: padding-bucket executable cache sizes (see below).
    inferenceMode: INPLACE/SEQUENTIAL (sync) or BATCHED (queued
        micro-batching); unknown modes raise.
    queueLimit: BATCHED-mode bound on waiting requests (overflow ->
        serving.QueueFullError, the HTTP tier's 429).
    maxWaitMs: BATCHED-mode micro-batch hold time (latency/occupancy
        knob).
    int8: weight-only int8 serving (nn/quantize) — weights quantized
        once here, dequantized in-graph per dispatch.
    clock: injectable clock for the BATCHED queue (tests).
    """

    def __init__(self, model, mesh=None, batchLimit=0, batchBuckets=None,
                 inferenceMode="INPLACE", queueLimit=64, maxWaitMs=2.0,
                 int8=False, clock=None, metricsName=None):
        model._require_init()
        mode = str(inferenceMode).upper()
        if mode not in INFERENCE_MODES:
            raise ValueError(
                f"unknown inferenceMode {inferenceMode!r}: supported "
                f"modes are {INFERENCE_MODES} (INPLACE/SEQUENTIAL = one "
                "sync SPMD dispatch per output() call, BATCHED = queued "
                "dynamic micro-batching)")
        if int(queueLimit) < 1:
            raise ValueError(f"queueLimit must be >= 1, got {queueLimit}")
        self.model = model
        self.mesh = mesh if mesh is not None else \
            build_mesh({DATA_AXIS: len(jax.devices())})
        self.batchLimit = int(batchLimit)
        self.inferenceMode = mode
        self.queueLimit = int(queueLimit)
        self.maxWaitMs = float(maxWaitMs)
        self._clock = clock
        # the `model` label on the BATCHED queue's telemetry instruments
        # (serving.host passes "name:vN"; None = per-instance default)
        self.metricsName = metricsName
        self._batcher = None
        self._batcher_lock = threading.Lock()
        self._closed = False
        self._n = self.mesh.shape[DATA_AXIS]
        # padding-bucket executable cache: request batches are padded UP
        # to the nearest bucket so the serving tier compiles one
        # executable per bucket, never one per request size (the retrace
        # budget is len(buckets) — aot.sentinel_budget). None keeps the
        # legacy exact-size dispatch (one compile per distinct B) —
        # except in BATCHED mode, where unbounded per-coalesced-size
        # compiles would defeat the whole tier, so the default bucket
        # set applies.
        from deeplearning4j_tpu.runtime import aot

        if batchBuckets is None and mode == "BATCHED":
            batchBuckets = aot.DEFAULT_BATCH_BUCKETS
        self.batchBuckets = None if batchBuckets is None else \
            tuple(sorted(int(b) for b in batchBuckets))
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(DATA_AXIS))
        # prefix-pytree shardings: params/states replicated, batch
        # sharded; compiled through the AOT executable cache so a warm
        # process serves its first request without paying XLA
        self._int8 = bool(int8)
        if self._int8:
            from deeplearning4j_tpu.nn import quantize as _q

            self._qp, self._sc = _q.quantize_params_int8(model._params)
            compute_dtype = model._compute_dtype

            def _fwd_int8(qp, sc, states, x):
                p = _q.dequantize_params(qp, sc, compute_dtype)
                return model._forward_infer(p, states, x)

            self._jit = aot.cached_jit(
                _fwd_int8, owner=model,
                entry="parallel_inference_int8",
                extra=f"|pi[mesh={sorted(dict(self.mesh.shape).items())}]",
                in_shardings=(rep, rep, rep, shard),
                out_shardings=shard)
        else:
            self._jit = aot.cached_jit(
                model._forward_infer, owner=model,
                entry="parallel_inference",
                extra=f"|pi[mesh={sorted(dict(self.mesh.shape).items())}]",
                in_shardings=(rep, rep, shard),
                out_shardings=shard)

    def _head_args(self):
        """The non-batch dispatch arguments (params/states — plus the
        int8 pair when quantized). Scales/quantized weights are runtime
        args, not baked constants, so equal-config models share one
        executable."""
        if self._int8:
            return (self._qp, self._sc, self.model._states)
        return (self.model._params, self.model._states)

    # upstream builder-pattern compatibility --------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._batchLimit = 0
            self._batchBuckets = None
            self._inferenceMode = "INPLACE"
            self._queueLimit = 64

        def workers(self, n):
            self._mesh = build_mesh({DATA_AXIS: int(n)})
            return self

        def batchLimit(self, n):
            self._batchLimit = int(n)
            return self

        def batchBuckets(self, *sizes):
            self._batchBuckets = tuple(int(s) for s in sizes)
            return self

        def inferenceMode(self, mode):
            # validated in ParallelInference.__init__ (unknown modes
            # raise there, loudly)
            self._inferenceMode = mode
            return self

        def queueLimit(self, n):
            self._queueLimit = int(n)
            return self

        def build(self):
            return ParallelInference(self._model, mesh=self._mesh,
                                     batchLimit=self._batchLimit,
                                     batchBuckets=self._batchBuckets,
                                     inferenceMode=self._inferenceMode,
                                     queueLimit=self._queueLimit)

    # -----------------------------------------------------------------
    def _target_batch(self, B):
        """The dispatch batch for B requested rows: bucket-canonicalised
        (when batchBuckets is set), then rounded up to a multiple of the
        mesh size (XLA needs equal shards)."""
        if self.batchBuckets:
            from deeplearning4j_tpu.runtime.aot import bucket_batch

            B = bucket_batch(B, self.batchBuckets)
        return B + ((-B) % self._n)

    def _pad(self, a, B):
        """Pad the batch axis up to _target_batch(B); surplus rows are
        sliced off after the dispatch."""
        from deeplearning4j_tpu.runtime.aot import pad_batch

        return pad_batch(a, self._target_batch(B))

    def _place(self, a):
        """Explicit mesh placement of a padded batch (shard_batch): the
        micro-batch spans the mesh before the dispatch is issued, and —
        because precompile() warms with the SAME placed signature —
        placement can never demote a warm bucket executable to a fresh
        compile."""
        from deeplearning4j_tpu.parallel.sharding import shard_batch

        return shard_batch(np.asarray(a), self.mesh)

    def precompile(self, batchSizes=None, featuresShape=None,
                   cache=None):
        """AOT warm-start of the sharded forward for every batch bucket
        (or the given batchSizes): a serving process hits its first
        request with a hot executable. featuresShape: per-example shape
        override (derived from the model conf's InputType otherwise).
        Returns {batch: {key, status, seconds}}."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import shape_for_input_type

        sizes = tuple(batchSizes) if batchSizes is not None else \
            (self.batchBuckets or ())
        if not sizes:
            raise ValueError(
                "precompile needs batchSizes=... or batchBuckets set at "
                "construction")
        if isinstance(self.model, ComputationGraph) \
                and len(self.model.conf.networkInputs) != 1:
            # output() serves multi-input graphs fine, but there is no
            # canonical single example feed to warm with — fail HERE
            # with intent, not mid-trace with a KeyError
            raise ValueError(
                "precompile supports single-input ComputationGraphs; "
                "warm a multi-input graph by running one real batch "
                "through output()")
        report = {}
        for B in sizes:
            Bt = self._target_batch(int(B))
            if featuresShape is not None:
                shape = (Bt,) + tuple(featuresShape)
                x = np.zeros(shape, np.float32)
            elif isinstance(self.model, ComputationGraph):
                name = self.model.conf.networkInputs[0]
                it = self.model.conf.inputTypes.get(name)
                x = np.zeros(shape_for_input_type(it, Bt), np.float32)
            else:
                x = np.zeros(shape_for_input_type(
                    self.model.conf.inputType, Bt), np.float32)
            if isinstance(self.model, ComputationGraph):
                feed = {self.model.conf.networkInputs[0]: self._place(x)}
            else:
                feed = self._place(x)
            k_, status, secs = self._jit.warm(
                *self._head_args(), feed, cache=cache)
            if status is not None:
                report[int(B)] = {"key": k_, "status": status,
                                  "seconds": round(secs, 3)}
        return report

    def example_shape(self):
        """Per-example (trailing) feature shape from the model conf's
        InputType, or None when it cannot be derived (multi-input
        graphs) — the request-validation contract the serving queue
        enforces at submit time."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import shape_for_input_type

        try:
            if isinstance(self.model, ComputationGraph):
                if len(self.model.conf.networkInputs) != 1:
                    return None
                it = self.model.conf.inputTypes.get(
                    self.model.conf.networkInputs[0])
            else:
                it = self.model.conf.inputType
            return tuple(shape_for_input_type(it, 1)[1:])
        except Exception:  # fault-ok[FLT01]: None IS the classification — "no static shape known" routes the caller to the dynamic-shape path; any config family may legitimately lack input types
            return None

    def _run(self, inputs, B):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            feed = {n: self._place(self._pad(np.asarray(a), B))
                    for n, a in inputs.items()}
            outs = self._jit(*self._head_args(), feed)
            outs = [np.asarray(o)[:B] for o in outs]
            return outs
        x = self._place(self._pad(np.asarray(inputs), B))
        out = self._jit(*self._head_args(), x)
        return [np.asarray(out)[:B]]

    # -- BATCHED mode ---------------------------------------------------
    def _dispatch_coalesced(self, feats):
        """ONE padded, bucketed, mesh-sharded dispatch for a
        host-coalesced batch — the request-path hot function of the
        serving tier (the MicroBatcher's dispatch callable). Returns
        the per-row outputs (list for multi-output graphs)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            ins = self.model.conf.networkInputs
            if len(ins) != 1:
                raise ValueError(
                    "queued-batched dispatch coalesces on one batch "
                    "axis and supports single-input graphs; serve "
                    "multi-input graphs in INPLACE mode")
            outs = self._run({ins[0]: feats}, feats.shape[0])
        else:
            outs = self._run(feats, feats.shape[0])
        return outs if len(outs) > 1 else outs[0]

    def _ensure_batcher(self):
        # double-checked lazy init (the PR 8 race, fixed by the lock
        # below; the lock-free fast path is the benign half): racing
        # first requests must all land on ONE batcher
        b = self._batcher  # thread-ok[THR01]: atomic reference read — the double-checked fast path; a stale None just falls through to the locked slow path
        if b is not None:
            return b
        with self._batcher_lock:
            b = self._batcher
            if b is not None:
                return b
            from deeplearning4j_tpu.serving.queue import (
                MicroBatcher, ServingClosedError)

            if self._closed:
                # a first request racing close() must not resurrect a
                # fresh batcher on a swapped-out instance — fail like a
                # closed queue so the host's swap re-route handles it
                raise ServingClosedError(
                    "ParallelInference is closed")

            b = MicroBatcher(
                self._dispatch_coalesced,
                max_rows=max(self.batchBuckets),
                queue_limit=self.queueLimit,
                max_wait=self.maxWaitMs / 1000.0,
                bucket_for=self._target_batch,
                trailing_shape=self.example_shape(),
                # precompile() warms float32 feeds; pinning the queue to
                # the same dtype means a stray f64 request can never
                # change the coalesced signature and force a
                # request-path compile
                feature_dtype=np.float32,
                clock=self._clock,
                start_thread=self._clock is None,
                name=self.metricsName)
            self._batcher = b
        return b

    def close(self, drain=True):
        """Stop the BATCHED-mode queue (sync modes keep working). Taken
        under the batcher lock so a racing first request can never
        install a fresh batcher after close() looked."""
        with self._batcher_lock:
            self._closed = True
            b = self._batcher
        if b is not None:
            b.close(drain=drain)
        return self

    def _single_array(self, features):
        """features as ONE coalescable [rows, ...] array, or None when
        the feed is not queue-batchable (dicts / multi-input graphs)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(features, dict):
            return None
        if isinstance(self.model, ComputationGraph):
            if len(self.model.conf.networkInputs) != 1:
                return None
            inputs = self.model._coerce_inputs(features)
            return np.asarray(next(iter(inputs.values())))
        return _unwrap(features)

    def output(self, features):
        """Run inference with the batch split across the mesh. Accepts a
        single array (MultiLayerNetwork) or an array / list-of-arrays /
        dict for ComputationGraph inputs. Returns INDArray (or a list
        for multi-output graphs).

        In BATCHED mode the call is queued and coalesced with
        concurrent callers into one micro-batch dispatch; results are
        sliced back per caller and are bitwise-identical to the sync
        path (same bucket executables). May raise
        serving.QueueFullError under backpressure."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if self.inferenceMode == "BATCHED":
            arr = self._single_array(features)
            if arr is not None:
                res = self._ensure_batcher().submit(arr)
                outs = [INDArray(o) for o in
                        (res if isinstance(res, list) else [res])]
                return outs[0] if len(outs) == 1 else outs
            # non-coalescable feed (dict / multi-input): sync dispatch

        if isinstance(self.model, ComputationGraph):
            if isinstance(features, dict):
                inputs = {n: _unwrap(a) for n, a in features.items()}
            else:
                inputs = self.model._coerce_inputs(features)
                inputs = {n: np.asarray(a) for n, a in inputs.items()}
            B = next(iter(inputs.values())).shape[0]
        else:
            inputs = _unwrap(features)
            B = inputs.shape[0]

        if self.batchLimit and B > self.batchLimit:
            chunks = []
            for s in range(0, B, self.batchLimit):
                e = min(B, s + self.batchLimit)
                sub = ({n: a[s:e] for n, a in inputs.items()}
                       if isinstance(inputs, dict) else inputs[s:e])
                chunks.append(self._run(sub, e - s))
            outs = [np.concatenate([c[i] for c in chunks], axis=0)
                    for i in range(len(chunks[0]))]
        else:
            outs = self._run(inputs, B)
        outs = [INDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

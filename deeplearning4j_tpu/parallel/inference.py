"""Batch-sharded SPMD inference.

Reference: org.deeplearning4j.parallelism.ParallelInference — upstream
wraps a model per GPU behind a worker queue and round-robins incoming
batches (INPLACE/BATCHED modes, observables for async callers). The
queue exists because each cuda device needs its own host thread and
model replica. TPU-native design: ONE jitted forward whose input is
sharded over the mesh's data axis — XLA splits the batch across chips,
weights stay replicated, and there is no host-side queue to tune. The
`workers(n)` knob becomes the mesh size; INPLACE vs BATCHED collapses
into the single SPMD dispatch.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.parallel.mesh import build_mesh, DATA_AXIS


def _unwrap(x):
    return x.jax() if isinstance(x, INDArray) else np.asarray(x)


class ParallelInference:
    """output() over all devices of a (data-axis) mesh.

    model: an initialized MultiLayerNetwork or ComputationGraph.
    mesh:  jax.sharding.Mesh with a "data" axis (default: all devices).
    batchLimit: optional max examples per dispatch; larger inputs are
        chunked host-side (reference: ParallelInference.batchLimit).
    """

    def __init__(self, model, mesh=None, batchLimit=0, batchBuckets=None):
        model._require_init()
        self.model = model
        self.mesh = mesh if mesh is not None else \
            build_mesh({DATA_AXIS: len(jax.devices())})
        self.batchLimit = int(batchLimit)
        self._n = self.mesh.shape[DATA_AXIS]
        # padding-bucket executable cache: request batches are padded UP
        # to the nearest bucket so the serving tier compiles one
        # executable per bucket, never one per request size (the retrace
        # budget is len(buckets) — aot.sentinel_budget). None keeps the
        # legacy exact-size dispatch (one compile per distinct B).
        self.batchBuckets = None if batchBuckets is None else \
            tuple(sorted(int(b) for b in batchBuckets))
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(DATA_AXIS))
        # prefix-pytree shardings: params/states replicated, batch
        # sharded; compiled through the AOT executable cache so a warm
        # process serves its first request without paying XLA
        from deeplearning4j_tpu.runtime import aot

        self._jit = aot.cached_jit(
            model._forward_infer, owner=model,
            entry="parallel_inference",
            extra=f"|pi[mesh={sorted(dict(self.mesh.shape).items())}]",
            in_shardings=(rep, rep, shard),
            out_shardings=shard)

    # upstream builder-pattern compatibility --------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._batchLimit = 0
            self._batchBuckets = None

        def workers(self, n):
            self._mesh = build_mesh({DATA_AXIS: int(n)})
            return self

        def batchLimit(self, n):
            self._batchLimit = int(n)
            return self

        def batchBuckets(self, *sizes):
            self._batchBuckets = tuple(int(s) for s in sizes)
            return self

        def inferenceMode(self, _mode):
            return self  # INPLACE/BATCHED both lower to one SPMD dispatch

        def queueLimit(self, _n):
            return self  # no host queue in the SPMD design

        def build(self):
            return ParallelInference(self._model, mesh=self._mesh,
                                     batchLimit=self._batchLimit,
                                     batchBuckets=self._batchBuckets)

    # -----------------------------------------------------------------
    def _target_batch(self, B):
        """The dispatch batch for B requested rows: bucket-canonicalised
        (when batchBuckets is set), then rounded up to a multiple of the
        mesh size (XLA needs equal shards)."""
        if self.batchBuckets:
            from deeplearning4j_tpu.runtime.aot import bucket_batch

            B = bucket_batch(B, self.batchBuckets)
        return B + ((-B) % self._n)

    def _pad(self, a, B):
        """Pad the batch axis up to _target_batch(B); surplus rows are
        sliced off after the dispatch."""
        from deeplearning4j_tpu.runtime.aot import pad_batch

        return pad_batch(a, self._target_batch(B))

    def precompile(self, batchSizes=None, featuresShape=None,
                   cache=None):
        """AOT warm-start of the sharded forward for every batch bucket
        (or the given batchSizes): a serving process hits its first
        request with a hot executable. featuresShape: per-example shape
        override (derived from the model conf's InputType otherwise).
        Returns {batch: {key, status, seconds}}."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.nn.multilayer import shape_for_input_type

        sizes = tuple(batchSizes) if batchSizes is not None else \
            (self.batchBuckets or ())
        if not sizes:
            raise ValueError(
                "precompile needs batchSizes=... or batchBuckets set at "
                "construction")
        from deeplearning4j_tpu.nn.graph import ComputationGraph as _CG

        if isinstance(self.model, _CG) \
                and len(self.model.conf.networkInputs) != 1:
            # output() serves multi-input graphs fine, but there is no
            # canonical single example feed to warm with — fail HERE
            # with intent, not mid-trace with a KeyError
            raise ValueError(
                "precompile supports single-input ComputationGraphs; "
                "warm a multi-input graph by running one real batch "
                "through output()")
        report = {}
        for B in sizes:
            Bt = self._target_batch(int(B))
            if featuresShape is not None:
                shape = (Bt,) + tuple(featuresShape)
                x = np.zeros(shape, np.float32)
            elif isinstance(self.model, ComputationGraph):
                name = self.model.conf.networkInputs[0]
                it = self.model.conf.inputTypes.get(name)
                x = np.zeros(shape_for_input_type(it, Bt), np.float32)
            else:
                x = np.zeros(shape_for_input_type(
                    self.model.conf.inputType, Bt), np.float32)
            if isinstance(self.model, ComputationGraph):
                feed = {self.model.conf.networkInputs[0]: x}
            else:
                feed = x
            k_, status, secs = self._jit.warm(
                self.model._params, self.model._states, feed,
                cache=cache)
            if status is not None:
                report[int(B)] = {"key": k_, "status": status,
                                  "seconds": round(secs, 3)}
        return report

    def _run(self, inputs, B):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            feed = {n: self._pad(np.asarray(a), B)
                    for n, a in inputs.items()}
            outs = self._jit(self.model._params, self.model._states, feed)
            outs = [np.asarray(o)[:B] for o in outs]
            return outs
        x = self._pad(np.asarray(inputs), B)
        out = self._jit(self.model._params, self.model._states, x)
        return [np.asarray(out)[:B]]

    def output(self, features):
        """Run inference with the batch split across the mesh. Accepts a
        single array (MultiLayerNetwork) or an array / list-of-arrays /
        dict for ComputationGraph inputs. Returns INDArray (or a list
        for multi-output graphs)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            if isinstance(features, dict):
                inputs = {n: _unwrap(a) for n, a in features.items()}
            else:
                inputs = self.model._coerce_inputs(features)
                inputs = {n: np.asarray(a) for n, a in inputs.items()}
            B = next(iter(inputs.values())).shape[0]
        else:
            inputs = _unwrap(features)
            B = inputs.shape[0]

        if self.batchLimit and B > self.batchLimit:
            chunks = []
            for s in range(0, B, self.batchLimit):
                e = min(B, s + self.batchLimit)
                sub = ({n: a[s:e] for n, a in inputs.items()}
                       if isinstance(inputs, dict) else inputs[s:e])
                chunks.append(self._run(sub, e - s))
            outs = [np.concatenate([c[i] for c in chunks], axis=0)
                    for i in range(len(chunks[0]))]
        else:
            outs = self._run(inputs, B)
        outs = [INDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

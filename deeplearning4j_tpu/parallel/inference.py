"""Batch-sharded SPMD inference.

Reference: org.deeplearning4j.parallelism.ParallelInference — upstream
wraps a model per GPU behind a worker queue and round-robins incoming
batches (INPLACE/BATCHED modes, observables for async callers). The
queue exists because each cuda device needs its own host thread and
model replica. TPU-native design: ONE jitted forward whose input is
sharded over the mesh's data axis — XLA splits the batch across chips,
weights stay replicated, and there is no host-side queue to tune. The
`workers(n)` knob becomes the mesh size; INPLACE vs BATCHED collapses
into the single SPMD dispatch.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.ndarray import INDArray
from deeplearning4j_tpu.parallel.mesh import build_mesh, DATA_AXIS


def _unwrap(x):
    return x.jax() if isinstance(x, INDArray) else np.asarray(x)


class ParallelInference:
    """output() over all devices of a (data-axis) mesh.

    model: an initialized MultiLayerNetwork or ComputationGraph.
    mesh:  jax.sharding.Mesh with a "data" axis (default: all devices).
    batchLimit: optional max examples per dispatch; larger inputs are
        chunked host-side (reference: ParallelInference.batchLimit).
    """

    def __init__(self, model, mesh=None, batchLimit=0):
        model._require_init()
        self.model = model
        self.mesh = mesh if mesh is not None else \
            build_mesh({DATA_AXIS: len(jax.devices())})
        self.batchLimit = int(batchLimit)
        self._n = self.mesh.shape[DATA_AXIS]
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(DATA_AXIS))
        # prefix-pytree shardings: params/states replicated, batch sharded
        self._jit = jax.jit(model._forward_infer,
                            in_shardings=(rep, rep, shard),
                            out_shardings=shard)

    # upstream builder-pattern compatibility --------------------------
    class Builder:
        def __init__(self, model):
            self._model = model
            self._mesh = None
            self._batchLimit = 0

        def workers(self, n):
            self._mesh = build_mesh({DATA_AXIS: int(n)})
            return self

        def batchLimit(self, n):
            self._batchLimit = int(n)
            return self

        def inferenceMode(self, _mode):
            return self  # INPLACE/BATCHED both lower to one SPMD dispatch

        def queueLimit(self, _n):
            return self  # no host queue in the SPMD design

        def build(self):
            return ParallelInference(self._model, mesh=self._mesh,
                                     batchLimit=self._batchLimit)

    # -----------------------------------------------------------------
    def _pad(self, a, B):
        """Pad the batch axis to a multiple of the mesh size (XLA needs
        equal shards); surplus rows are sliced off after the dispatch."""
        rem = (-B) % self._n
        if rem == 0:
            return a
        return np.concatenate(
            [a, np.zeros((rem,) + tuple(a.shape[1:]), a.dtype)], axis=0)

    def _run(self, inputs, B):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            feed = {n: self._pad(np.asarray(a), B)
                    for n, a in inputs.items()}
            outs = self._jit(self.model._params, self.model._states, feed)
            outs = [np.asarray(o)[:B] for o in outs]
            return outs
        x = self._pad(np.asarray(inputs), B)
        out = self._jit(self.model._params, self.model._states, x)
        return [np.asarray(out)[:B]]

    def output(self, features):
        """Run inference with the batch split across the mesh. Accepts a
        single array (MultiLayerNetwork) or an array / list-of-arrays /
        dict for ComputationGraph inputs. Returns INDArray (or a list
        for multi-output graphs)."""
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(self.model, ComputationGraph):
            if isinstance(features, dict):
                inputs = {n: _unwrap(a) for n, a in features.items()}
            else:
                inputs = self.model._coerce_inputs(features)
                inputs = {n: np.asarray(a) for n, a in inputs.items()}
            B = next(iter(inputs.values())).shape[0]
        else:
            inputs = _unwrap(features)
            B = inputs.shape[0]

        if self.batchLimit and B > self.batchLimit:
            chunks = []
            for s in range(0, B, self.batchLimit):
                e = min(B, s + self.batchLimit)
                sub = ({n: a[s:e] for n, a in inputs.items()}
                       if isinstance(inputs, dict) else inputs[s:e])
                chunks.append(self._run(sub, e - s))
            outs = [np.concatenate([c[i] for c in chunks], axis=0)
                    for i in range(len(chunks[0]))]
        else:
            outs = self._run(inputs, B)
        outs = [INDArray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs

"""Measure gradient-reduction overlap potential from the HLO schedule.

SCALING.md's data-parallel model hides a fraction of the gradient
all-reduce under remaining backward compute (`DataParallelModel.overlap`).
Round 3 ASSERTED 0.70; this module MEASURES the quantity the assertion
stands on: where XLA actually places the gradient all-reduces in the
compiled module's instruction schedule relative to the remaining
backward/update compute.

Method (documented so the number is reproducible):
- Compile the flagship data-parallel train step (replicated params,
  batch sharded over the data axis — GSPMD inserts the grad
  all-reduces) on the virtual multi-device CPU mesh. Schedule STRUCTURE
  (which ops are emitted after which) is what we need; it does not
  depend on the toy shapes used to compile.
- Walk the optimized entry computation in instruction order. For each
  all-reduce carrying gradient payload, overlap potential = the
  fraction of heavy-compute instructions (convolution/dot, where
  essentially all ResNet FLOPs live) scheduled AFTER it — compute that
  an async collective (TPU all-reduce-start/done) could hide under.
- The model constant = payload-weighted mean over all grad all-reduces.

Caveats, stated: instruction COUNT is the compute weight (a structure
metric, not a time simulation), and the CPU backend's scheduler stands
in for the TPU latency-hiding scheduler (both run XLA's scheduling on
the same post-GSPMD module; the TPU one additionally makes collectives
async, which this metric models as "hideable under whatever is
scheduled after").
"""

from __future__ import annotations

import re

import numpy as np


# element widths in BITS: s4/u4/f4 pack two per byte in XLA buffers
# (ShapeUtil::ByteSizeOf), so pricing them at a whole byte would double-
# count exactly the quantized buffers a traffic table should rank
_DTYPE_BITS = {"f64": 64, "f32": 32, "bf16": 16, "f16": 16, "s32": 32,
               "u32": 32, "s8": 8, "u8": 8, "pred": 8, "s64": 64,
               "u64": 64, "s16": 16, "u16": 16, "s4": 4, "u4": 4,
               "f8e4m3": 8, "f8e5m2": 8, "f8e4m3fn": 8, "f8e5m2fnuz": 8,
               "f8e4m3fnuz": 8, "f8e4m3b11fnuz": 8, "f8e3m4": 8,
               "f4e2m1fn": 4, "e8m0fnu": 8,
               "c64": 64, "c128": 128}

# longest-first alternation so f8e4m3fn doesn't half-match as f8e4m3
_SHAPE_RE = re.compile(
    "(" + "|".join(sorted(_DTYPE_BITS, key=len, reverse=True))
    + r")\[([0-9,]*)\]")
_OP_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[^=]*?\s([a-z][\w\-]*)\(")


def _shape_bytes(text):
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += (n * _DTYPE_BITS[dt] + 7) // 8
    return total


def entry_instructions(hlo_text):
    """(opcode, line) pairs of the ENTRY computation, in schedule order."""
    lines = hlo_text.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.lstrip().startswith("ENTRY "))
    except StopIteration:
        raise ValueError("no ENTRY computation in HLO text")
    out = []
    for l in lines[start + 1:]:
        if l.strip() == "}":
            break
        m = _OP_RE.match(l)
        if m:
            out.append((m.group(1), l))
    return out

def measure_schedule_overlap(hlo_text, compute_ops=("convolution", "dot")):
    """-> dict with per-all-reduce placement and the payload-weighted
    overlap fraction."""
    instrs = entry_instructions(hlo_text)
    # fusions can swallow dots/convs: count a fusion as compute when its
    # line calls a fused computation whose name marks conv/dot fusion
    compute_pos = [i for i, (op, l) in enumerate(instrs)
                   if op in compute_ops
                   or (op == "fusion" and ("conv" in l or "dot" in l))]
    # sync form ("all-reduce", CPU backend) and async form
    # ("all-reduce-start", TPU latency-hiding scheduler) both count;
    # "all-reduce-done" is the completion marker, not a new reduction
    ar = [(i, _shape_bytes(l.split("=", 1)[1].split("all-reduce", 1)[0]))
          for i, (op, l) in enumerate(instrs)
          if op in ("all-reduce", "all-reduce-start")]
    if not ar or not compute_pos:
        return {"all_reduces": [], "weighted_overlap": 0.0,
                "n_all_reduces": len(ar),
                "n_compute_ops": len(compute_pos)}
    total_c = len(compute_pos)
    details = []
    for pos, nbytes in ar:
        after = sum(1 for c in compute_pos if c > pos)
        details.append({"schedule_index": pos, "bytes": nbytes,
                        "compute_after_fraction": after / total_c})
    wsum = sum(d["bytes"] for d in details)
    overlap = (sum(d["bytes"] * d["compute_after_fraction"]
                   for d in details) / wsum) if wsum else 0.0
    return {"all_reduces": details, "weighted_overlap": round(overlap, 4),
            "n_compute_ops": total_c, "n_all_reduces": len(details)}


def measure_flagship_overlap(n_devices=8, image=32, classes=8,
                             per_device_batch=2):
    """Compile the ResNet-50 DP train step on an n-device mesh and
    measure where its gradient all-reduces sit in the schedule."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.nn import Adam
    from deeplearning4j_tpu.parallel import mesh as _mesh
    from deeplearning4j_tpu.zoo import ResNet50

    devs = jax.devices()[:n_devices]
    mesh = _mesh.build_mesh({_mesh.DATA_AXIS: len(devs)}, devs)
    net = ResNet50(numClasses=classes, inputShape=(3, image, image),
                   updater=Adam(1e-3)).init()
    repl = NamedSharding(mesh, P())
    params = jax.device_put(net._params, repl)
    upd = jax.device_put(net._upd_states, repl)
    states = jax.device_put(net._states, repl)
    B = per_device_batch * len(devs)
    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.rand(B, 3, image, image), jnp.float32),
                       NamedSharding(mesh, P(_mesh.DATA_AXIS)))
    y = jax.device_put(
        jnp.asarray(np.eye(classes, dtype="float32")[
            rng.randint(0, classes, B)]),
        NamedSharding(mesh, P(_mesh.DATA_AXIS)))
    key = jax.device_put(jax.random.key(0), repl)
    it0 = jax.device_put(jnp.asarray(0, jnp.int32), repl)
    compiled = jax.jit(net._train_step).lower(
        params, upd, states, it0, {"input": x}, [y], key, None, None
    ).compile()
    return measure_schedule_overlap(compiled.as_text())

"""Version-tolerant shard_map.

jax moved shard_map from jax.experimental.shard_map (<= 0.4.x, with a
`check_rep` flag) to the top-level jax.shard_map (with `check_vma`).
The container matrix this repo runs on spans both; importing the new
location unconditionally took the ENTIRE parallel package down at
collection time on older jax. All parallel modules import shard_map
from here, written against the NEW calling convention — the shim maps
check_vma onto check_rep when only the legacy entry point exists.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6: top-level, check_vma
    from jax import shard_map as _shard_map
except ImportError:  # legacy: experimental, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = set(inspect.signature(_shard_map).parameters)


def shard_map(f, *, mesh, in_specs, out_specs, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside a shard_map body.
    lax.axis_size is the modern spelling; on legacy jax a psum of the
    Python constant 1 folds to the same static int."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

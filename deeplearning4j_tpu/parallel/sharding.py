"""Parameter sharding rules for model (tensor) parallelism.

Reference: none — the reference is data-parallel only (its multi-GPU and
Spark paths replicate the full model). Tensor parallelism is a TPU-first
capability: parameters are annotated with PartitionSpecs over the mesh
"model" axis and XLA's SPMD partitioner (GSPMD; see PAPERS.md sharding
papers) propagates shardings through the computation and inserts the
all-gather / reduce-scatter collectives over ICI.

Rules follow the Megatron layout:
  dense W [in, out]      -> P(None, "model")   (column parallel)
  conv  W [kh,kw,ci,co]  -> P(None,None,None,"model")
  lstm  W/RW [in, 4H]    -> P(None, "model")
  biases/gains [out]     -> P("model") when their dim is sharded
Small params (< min_shard_size) stay replicated — collective latency beats
the memory win.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def shard_batch(arr, mesh: Mesh, batch_axis=DATA_AXIS, dim=0):
    """Place one batch array with dim `dim` sharded over `batch_axis`.

    REJECTS indivisible batches with an error naming the axis instead
    of letting the placement silently pad (uneven GSPMD tiling pads the
    trailing shard with garbage rows that would train): the same check
    the partition-plan analyzer reports statically as PAR03, enforced
    at the runtime boundary every trainer shares."""
    if batch_axis not in mesh.shape:
        raise ValueError(
            f"mesh has no axis '{batch_axis}' (axes: "
            f"{list(mesh.shape)}); build the mesh with a data-parallel "
            "axis or pass batch_axis=")
    width = mesh.shape[batch_axis]
    if arr.shape[dim] % width != 0:
        raise ValueError(
            f"Global batch {arr.shape[dim]} not divisible by "
            f"data-parallel mesh axis '{batch_axis}' (size {width}): "
            "refusing to silently pad; use a batch size that is a "
            f"multiple of {width} (PAR03)")
    spec = [None] * arr.ndim
    spec[dim] = batch_axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def shard_batch_stack(tree, mesh: Mesh, batch_axis=DATA_AXIS):
    """Place a fitDataSet staging stack — a pytree of [k, B, ...] arrays
    (None components pass through) — with the BATCH dim (dim 1) sharded
    over `batch_axis` and the k staging dim replicated, through the same
    divisibility-checked shard_batch every trainer uses. Each of the k
    steps of the on-device loop then indexes a correctly-sharded global
    batch."""
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda a: shard_batch(a, mesh, batch_axis=batch_axis, dim=1), tree)


def spec_for_param(name: str, shape, model_axis=MODEL_AXIS, min_shard_size=2 ** 16):
    """PartitionSpec for one parameter array by name/shape convention."""
    if int(np.prod(shape)) < min_shard_size:
        return P()
    if len(shape) == 2:
        # dense / recurrent / embedding weights: shard the output dim
        return P(None, model_axis)
    if len(shape) == 4:
        # conv HWIO: shard output channels
        return P(None, None, None, model_axis)
    if len(shape) == 1:
        return P(model_axis)
    return P()


def shard_params(params, mesh: Mesh, model_axis=MODEL_AXIS,
                 min_shard_size=2 ** 16, on_indivisible="replicate"):
    """Annotate+place a params pytree (list/dict of per-layer dicts) onto
    the mesh with tensor-parallel shardings; returns the placed pytree.

    on_indivisible: what to do when a selected dim does not divide by
    the model-axis size — "replicate" (default; GSPMD requires even
    tiling, and replication is always correct) or "error" to fail
    loudly naming the axis (the strict mode a validated plan uses)."""
    if on_indivisible not in ("replicate", "error"):
        raise ValueError("on_indivisible must be 'replicate' or 'error'")

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        # shard only when divisible; otherwise replicate (GSPMD requires
        # even tiling for the annotated dim)
        spec = spec_for_param(name, leaf.shape, model_axis, min_shard_size)
        width = mesh.shape[model_axis]
        ok = True
        for dim, axis in zip(leaf.shape, tuple(spec) + (None,) * (leaf.ndim - len(spec))):
            if axis == model_axis and dim % width != 0:
                if on_indivisible == "error":
                    raise ValueError(
                        f"param {jax.tree_util.keystr(path)} dim {dim} "
                        f"is not divisible by mesh axis "
                        f"'{model_axis}' (size {width}) (PAR03)")
                ok = False
        if not ok:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def replicate_params(params, mesh: Mesh):
    return jax.device_put(params, NamedSharding(mesh, P()))


class ZeroShardedUpdate:
    """ZeRO-style cross-replica weight-update sharding (Xu et al.,
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training", arXiv:2004.13336).

    Installed as a network's ``_update_impl`` hook (MultiLayerNetwork /
    ComputationGraph per-layer update, SameDiff whole-dict update). The
    forward/backward is UNTOUCHED — same GSPMD program, same global-batch
    loss/BN semantics as the replicated path. Only the weight update is
    re-annotated, exactly the paper's transformation:

      * each eligible gradient leaf is viewed flat and constrained to
        1/dp shards over the data axis — the SPMD partitioner lowers the
        gradient reduction feeding it as a reduce-scatter (TPU; XLA:CPU
        lacks the ReduceScatterCreator pass and emits the equivalent
        all-reduce + dynamic-slice, see dp_weight_update_bytes),
      * the optimizer applies to ONLY the local shard of params and
        updater state (updater state is ALLOCATED sharded from init —
        each chip ever holds 1/dp of the fp32 moments, which is where
        the HBM win for big optimizers comes from),
      * the fresh flat params are constrained back to replicated — one
        all-gather — and reshaped for the next forward.

    Eligibility is per LEAF on the total element count n: a leaf shards
    when ``n >= min_shard_size and n % dp == 0``; anything else —
    scalar/vector leaves (biases, BN gamma/beta) below min_shard_size,
    or sizes dp does not divide — stays REPLICATED (the explicit
    pad-or-replicate policy: never pad; the partition-plan analyzer
    reports the same fallback statically as PAR03). Because the view is
    a reshape and replicated-leaf math is byte-for-byte the default
    update, a model with no eligible leaves trains bitwise-identically
    to the replicated path.
    """

    def __init__(self, mesh: Mesh, axis=DATA_AXIS, min_shard_size=2 ** 16):
        if axis not in mesh.shape:
            raise ValueError(
                f"mesh has no axis '{axis}' (axes: {list(mesh.shape)}); "
                "build the mesh with a data-parallel axis or pass axis=")
        self.mesh = mesh
        self.axis = axis
        self.dp = int(mesh.shape[axis])
        self.min_shard_size = int(min_shard_size)
        self._sharded = NamedSharding(mesh, P(axis))
        self._repl = NamedSharding(mesh, P())

    # ----- eligibility / views ----------------------------------------
    def eligible(self, leaf) -> bool:
        """Shard-or-replicate decision for one array/abstract leaf (by
        total element count — the flat view shards dim 0 of the
        flattened vector, so leading-dim divisibility is irrelevant)."""
        n = int(np.prod(leaf.shape)) if hasattr(leaf, "shape") else int(leaf)
        return n > 0 and n >= self.min_shard_size and n % self.dp == 0

    def _tmap(self, f, *trees):
        return jax.tree_util.tree_map(f, *trees)

    def view(self, tree):
        """Traced: eligible leaves -> flat 1-D views constrained to 1/dp
        shards over the data axis; ineligible leaves pass through."""
        wsc = jax.lax.with_sharding_constraint
        return self._tmap(
            lambda a: wsc(a.reshape(-1), self._sharded)
            if self.eligible(a) else a, tree)

    def constrain_state(self, state):
        """Traced: pin eligible (already-flat) state leaves to the
        sharded layout so the carry cannot silently replicate."""
        wsc = jax.lax.with_sharding_constraint
        return self._tmap(
            lambda a: wsc(a, self._sharded) if self.eligible(a) else a,
            state)

    # ----- the update hook --------------------------------------------
    def __call__(self, updater, grads, upd_state, iteration, params):
        """reduce-scatter(grads) -> local 1/dp shard update -> all-gather
        (params). Drop-in for the default apply-and-subtract: returns
        (new_params at full shape, new updater state in the sharded view
        layout)."""
        wsc = jax.lax.with_sharding_constraint
        gv = self.view(grads)
        pv = self.view(params)
        upd, new_state = updater.apply(gv, upd_state, iteration, params=pv)
        new_state = self.constrain_state(new_state)
        new_pv = self._tmap(
            lambda p, u: (p - u).astype(p.dtype), pv, upd)
        # pin the POST-cast result sharded before replicating: without
        # this the partitioner may sink the param-dtype convert past the
        # all-gather and move a wider intermediate (x64 promotes updater
        # scalar math to f64) — the gather must carry param-dtype bytes
        new_pv = self.constrain_state(new_pv)
        # all-gather the fresh shards back to the replicated full-shape
        # params the next forward reads
        return self._tmap(
            lambda full, flat: wsc(flat, self._repl).reshape(full.shape)
            if self.eligible(full) else flat,
            params, new_pv), new_state

    # ----- state allocation / (un)view --------------------------------
    def init_state(self, updater, params):
        """Fresh updater state ALLOCATED in the sharded layout: init runs
        under jit with sharded out_shardings, so each chip materialises
        only its 1/dp shard — no full-size state buffer ever exists
        (ISSUE: 'allocated sharded from init, not sliced from a
        replicated copy')."""
        views = self._tmap(
            lambda a: a.reshape(-1) if self.eligible(a) else a, params)
        shapes = jax.eval_shape(updater.init, views)
        if not jax.tree_util.tree_leaves(shapes):
            return updater.init(views)  # stateless (Sgd/NoOp): ()/empty
        shardings = self._tmap(
            lambda s: self._sharded if self.eligible(s) else self._repl,
            shapes)
        return jax.jit(updater.init, out_shardings=shardings)(views)

    def place_state(self, state):
        """Re-place an EXISTING state tree (full-shape or already
        viewed) into the sharded layout — the mid-training switch and
        checkpoint-restore path; values are preserved bitwise (the view
        is a reshape)."""
        def place(a):
            a = jnp.asarray(a)
            if self.eligible(a):
                return jax.device_put(a.reshape(-1), self._sharded)
            return jax.device_put(a, self._repl)

        return self._tmap(place, state)

    def unview_state(self, state, updater, params):
        """Sharded view layout -> the canonical full-shape state layout
        (checkpoints save THIS form, so a sharded-mode save restores
        into any mode bitwise; reshape is lossless)."""
        template = jax.eval_shape(updater.init, params)
        return self._tmap(
            lambda s, t: jnp.reshape(s, t.shape), state, template)

    def per_chip_state_bytes(self, state) -> int:
        """Measured per-chip resident bytes of one state tree (device
        0's addressable shards) — the number the analytic
        dp_weight_update_bytes(sharded=True) opt_state_resident_bytes
        bill is judged against."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(state):
            if not hasattr(leaf, "addressable_shards"):
                total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
                continue
            dev0 = leaf.addressable_shards[0]
            total += int(np.prod(dev0.data.shape)) * leaf.dtype.itemsize
        return total


def dp_weight_update_bytes(grad_bytes, dp, master_bytes=None,
                           opt_state_bytes=None, sharded=False):
    """Analytic per-replica HBM bytes of the data-parallel weight-update
    path — the model the hbm_ledger attribution's `collective` bin
    (weight_update rows) is judged against, and the bill cross-replica
    weight-update sharding (Xu et al., "Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training") removes.

    Terms per replica, dp = data-parallel degree:
      allreduce:       ring all-reduce of the gradients moves
                       2*(dp-1)/dp * G bytes through each replica's HBM
                       (reduce-scatter + all-gather halves)
      update_replicated: every replica redundantly reads+writes the full
                       fp32 master params and updater state and re-reads
                       the full reduced gradient — identical work dp
                       times over
      update_sharded:  the same update with cross-replica sharding: each
                       replica touches only its 1/dp slice (plus the
                       all-gather of updated params, already counted in
                       the allreduce-equivalent traffic of that scheme)

    master/opt default to fp32 buffers the same element count as the
    (fp32) grads. Returns the terms plus `sharding_saves_bytes` — the
    per-replica HBM cut the sharded update offers; compare it against
    the attribution's measured weight_update collective rows before
    spending a live window on the rewrite.

    sharded=True returns the ZeRO bill of the IMPLEMENTED scheme
    (ZeroShardedUpdate) — the analytic yardstick its measured
    weight_update collective bin and per-chip updater-state bytes are
    CI-gated against. Terms per replica:

      reduce_scatter_bytes  (dp-1)/dp * G on the wire (the gradient
                            reduction, scattered instead of replicated)
      all_gather_bytes      (dp-1)/dp * M on the wire (the fresh params)
      update_bytes          (2M + 2S + G)/dp — the optimizer touches
                            only the local shard
      opt_state_resident_bytes  S/dp per chip (state allocated sharded)
      hlo_collective_bytes  the per-replica HBM bytes the hbm_ledger
                            charges the COLLECTIVE rows of the
                            PARTITIONED step, by lowering:
                              reduce_scatter:    rs (out G/dp + in G)
                                                 + ag (out M + in M/dp)
                                                 — what TPU emits;
                              all_reduce_gather: XLA:CPU lacks the
                                                 ReduceScatterCreator
                                                 pass and lowers the
                                                 scattered reduction as
                                                 all-reduce (2G) + a
                                                 local dynamic-slice
                                                 (not a collective),
                                                 plus the same param
                                                 all-gather — the form
                                                 the tier-1 CPU gate
                                                 prices.
                            Both models cover the ELIGIBLE (actually
                            sharded) bytes; leaves the replicate
                            fallback keeps pay the plain 2G all-reduce
                            on top (the caller adds that term).
    """
    G = int(grad_bytes)
    M = G if master_bytes is None else int(master_bytes)
    S = G if opt_state_bytes is None else int(opt_state_bytes)
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    allreduce = 2 * (dp - 1) * G // dp
    update_repl = 2 * M + 2 * S + G
    update_shard = (2 * M + 2 * S + G) // dp
    rec = {
        "allreduce_bytes": allreduce,
        "update_replicated_bytes": update_repl,
        "update_sharded_bytes": update_shard,
        "sharding_saves_bytes": update_repl - update_shard,
        "dp": int(dp),
        "mode": "sharded" if sharded else "replicated",
    }
    if not sharded:
        rec["update_bytes"] = update_repl
        rec["opt_state_resident_bytes"] = S
        return rec
    rs = (dp - 1) * G // dp
    ag = (dp - 1) * M // dp
    rec.update({
        "reduce_scatter_bytes": rs,
        "all_gather_bytes": ag,
        "collective_wire_bytes": rs + ag,
        "update_bytes": update_shard,
        "opt_state_resident_bytes": S // dp,
        "hlo_collective_bytes": {
            "reduce_scatter": (G + G // dp) + (M + M // dp),
            "all_reduce_gather": 2 * G + (M + M // dp),
        },
    })
    return rec

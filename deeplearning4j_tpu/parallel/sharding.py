"""Parameter sharding rules for model (tensor) parallelism.

Reference: none — the reference is data-parallel only (its multi-GPU and
Spark paths replicate the full model). Tensor parallelism is a TPU-first
capability: parameters are annotated with PartitionSpecs over the mesh
"model" axis and XLA's SPMD partitioner (GSPMD; see PAPERS.md sharding
papers) propagates shardings through the computation and inserts the
all-gather / reduce-scatter collectives over ICI.

Rules follow the Megatron layout:
  dense W [in, out]      -> P(None, "model")   (column parallel)
  conv  W [kh,kw,ci,co]  -> P(None,None,None,"model")
  lstm  W/RW [in, 4H]    -> P(None, "model")
  biases/gains [out]     -> P("model") when their dim is sharded
Small params (< min_shard_size) stay replicated — collective latency beats
the memory win.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import (
    DATA_AXIS, GROUP_AXIS, INTRA_AXIS, MODEL_AXIS,
)


def shard_batch(arr, mesh: Mesh, batch_axis=DATA_AXIS, dim=0):
    """Place one batch array with dim `dim` sharded over `batch_axis`
    (one axis name, or a tuple of axis names for a factored data axis —
    the hierarchical trainer shards the batch over ("group", "intra")).

    REJECTS indivisible batches with an error naming the axis instead
    of letting the placement silently pad (uneven GSPMD tiling pads the
    trailing shard with garbage rows that would train): the same check
    the partition-plan analyzer reports statically as PAR03, enforced
    at the runtime boundary every trainer shares."""
    axes = batch_axis if isinstance(batch_axis, tuple) else (batch_axis,)
    width = 1
    for ax in axes:
        if ax not in mesh.shape:
            raise ValueError(
                f"mesh has no axis '{ax}' (axes: "
                f"{list(mesh.shape)}); build the mesh with a "
                "data-parallel axis or pass batch_axis=")
        width *= mesh.shape[ax]
    if arr.shape[dim] % width != 0:
        raise ValueError(
            f"Global batch {arr.shape[dim]} not divisible by "
            f"data-parallel mesh axis '{batch_axis}' (size {width}): "
            "refusing to silently pad; use a batch size that is a "
            f"multiple of {width} (PAR03)")
    spec = [None] * arr.ndim
    spec[dim] = batch_axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def shard_batch_stack(tree, mesh: Mesh, batch_axis=DATA_AXIS):
    """Place a fitDataSet staging stack — a pytree of [k, B, ...] arrays
    (None components pass through) — with the BATCH dim (dim 1) sharded
    over `batch_axis` and the k staging dim replicated, through the same
    divisibility-checked shard_batch every trainer uses. Each of the k
    steps of the on-device loop then indexes a correctly-sharded global
    batch."""
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda a: shard_batch(a, mesh, batch_axis=batch_axis, dim=1), tree)


def spec_for_param(name: str, shape, model_axis=MODEL_AXIS, min_shard_size=2 ** 16):
    """PartitionSpec for one parameter array by name/shape convention."""
    if int(np.prod(shape)) < min_shard_size:
        return P()
    if len(shape) == 2:
        # dense / recurrent / embedding weights: shard the output dim
        return P(None, model_axis)
    if len(shape) == 4:
        # conv HWIO: shard output channels
        return P(None, None, None, model_axis)
    if len(shape) == 1:
        return P(model_axis)
    return P()


def shard_params(params, mesh: Mesh, model_axis=MODEL_AXIS,
                 min_shard_size=2 ** 16, on_indivisible="replicate"):
    """Annotate+place a params pytree (list/dict of per-layer dicts) onto
    the mesh with tensor-parallel shardings; returns the placed pytree.

    on_indivisible: what to do when a selected dim does not divide by
    the model-axis size — "replicate" (default; GSPMD requires even
    tiling, and replication is always correct) or "error" to fail
    loudly naming the axis (the strict mode a validated plan uses)."""
    if on_indivisible not in ("replicate", "error"):
        raise ValueError("on_indivisible must be 'replicate' or 'error'")

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        # shard only when divisible; otherwise replicate (GSPMD requires
        # even tiling for the annotated dim)
        spec = spec_for_param(name, leaf.shape, model_axis, min_shard_size)
        width = mesh.shape[model_axis]
        ok = True
        for dim, axis in zip(leaf.shape, tuple(spec) + (None,) * (leaf.ndim - len(spec))):
            if axis == model_axis and dim % width != 0:
                if on_indivisible == "error":
                    raise ValueError(
                        f"param {jax.tree_util.keystr(path)} dim {dim} "
                        f"is not divisible by mesh axis "
                        f"'{model_axis}' (size {width}) (PAR03)")
                ok = False
        if not ok:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def replicate_params(params, mesh: Mesh):
    return jax.device_put(params, NamedSharding(mesh, P()))


# ----------------------------------------------------------------------
# quantized gradient collectives (EQuARX-style block int8, PAPERS.md
# arXiv:2506.17615) — the shard_map building blocks the compressed
# trainer steps share
# ----------------------------------------------------------------------

#: per-block scale granularity of gradient_compression="block_int8"
DEFAULT_COMPRESSION_BLOCK = 256


def _quant_scales(flat, axis, mode, block):
    """Per-ELEMENT f32 dequant scale, shared across replicas: per-tensor
    absmax ("int8") or per-block absmax ("block_int8"), pmax'd over the
    data axis so every replica quantizes against the same grid (the
    scale exchange is the small side channel EQuARX pays)."""
    if mode == "int8":
        scale = jnp.maximum(jnp.max(jnp.abs(flat)), 1e-12)
        return jax.lax.pmax(scale, axis)
    n = flat.size
    pad = (-n) % block
    mag = jnp.abs(jnp.pad(flat, (0, pad))) if pad else jnp.abs(flat)
    s = jnp.maximum(jnp.max(mag.reshape(-1, block), axis=1), 1e-12)
    s = jax.lax.pmax(s, axis)
    return jnp.repeat(s, block)[:n]


def _quantize(g, axis, dp, mode, block):
    """The shared quantize front-end of both compressed collectives:
    flatten to f32, build the replica-shared scale grid, snap to the
    int8 grid in the integer accumulation dtype. Returns
    (q, per-element scales, f32 flat) — ONE definition, so the
    replicated psum and the composed psum_scatter can never drift off
    the grid that their bitwise-parity gate relies on."""
    flat = g.reshape(-1).astype(jnp.float32)
    sc = _quant_scales(flat, axis, mode, block)
    q = jnp.clip(jnp.round(flat / sc * 127.0), -127, 127) \
        .astype(_acc_dtype(dp))
    return q, sc, flat


def _acc_dtype(dp):
    # the sum of dp int8 lanes needs headroom: 127*dp <= 32512 fits
    # int16 through dp=256; past that accumulate in int32
    return jnp.int16 if dp <= 256 else jnp.int32


def quantized_psum_mean(g, axis, dp, mode="int8", block=None):
    """Compressed gradient all-reduce of one leaf inside shard_map:
    int8 quantize on a replica-shared scale grid, integer psum,
    dequantized MEAN in the leaf's dtype."""
    block = DEFAULT_COMPRESSION_BLOCK if block is None else int(block)
    q, sc, _ = _quantize(g, axis, dp, mode, block)
    summed = jax.lax.psum(q, axis)
    mean = summed.astype(jnp.float32) * (sc / 127.0) / dp
    return mean.reshape(g.shape).astype(g.dtype)


def quantized_psum_scatter_mean(flat, axis, dp, mode="int8", block=None):
    """Compressed gradient REDUCE-SCATTER of one flat leaf (n % dp == 0)
    inside shard_map: quantize as above, psum_scatter the integer
    lanes, dequantize only the local 1/dp shard of the mean — the
    compressed half of the ZeRO composition (reduce-scatter -> local
    shard update -> all-gather)."""
    block = DEFAULT_COMPRESSION_BLOCK if block is None else int(block)
    n = flat.size
    q, sc, _ = _quantize(flat, axis, dp, mode, block)
    shard = jax.lax.psum_scatter(q, axis, scatter_dimension=0, tiled=True)
    if mode != "int8":
        i = jax.lax.axis_index(axis)
        sc = jax.lax.dynamic_slice_in_dim(sc, i * (n // dp), n // dp)
    mean = shard.astype(jnp.float32) * (sc / 127.0) / dp
    return mean.astype(flat.dtype)


# ----------------------------------------------------------------------
# hierarchical 2-hop sparse gradient exchange (ROADMAP item 4): dense or
# block_int8 reduce-scatter inside a node group, Strom threshold-sparse
# exchange between group leaders, all-gather fan-back — wire bytes scale
# with capacity x groups instead of capacity x dp, which is what moves
# the sparse-vs-dense crossover past dp128
# ----------------------------------------------------------------------

#: default node-group size of gradient_compression="hierarchical" (the
#: intra-group reduce-scatter hop spans this many contiguous chips)
DEFAULT_COMPRESSION_GROUP = 8


def default_compression_group(dp):
    """The node-group size "hierarchical" picks when none is given: the
    largest divisor of dp that is <= DEFAULT_COMPRESSION_GROUP,
    and leaves >= 2 groups (so the sparse leader hop actually
    exchanges something). A dp with no such divisor (dp < 4, or a
    prime dp) has no 2-hop factorization at all — that raises, naming
    the flat modes as the fallback, rather than silently degenerating
    to one group whose leader exchange would be a no-op."""
    dp = int(dp)
    for g in range(min(dp // 2, DEFAULT_COMPRESSION_GROUP), 1, -1):
        if dp % g == 0:
            return g
    raise ValueError(
        f"data-parallel degree {dp} has no hierarchical factorization: "
        f"the 2-hop exchange needs a group size g with 2 <= g <= dp/2 "
        f"(>= 2 chips per group AND >= 2 groups), which requires a "
        f"composite dp >= 4; use gradient_compression='threshold' or "
        f"'block_int8' on this mesh instead")


def hierarchical_shard_elems(n, group_size):
    """Per-chip shard length of one n-element leaf under the
    hierarchical exchange: leaves are zero-padded up to a multiple of
    the group size before the intra-group reduce-scatter (padding zeros
    quantize to 0 and never cross the threshold, so the padding is
    mathematically invisible on the wire)."""
    n, g = int(n), int(group_size)
    return (n + (-n) % g) // g


def hierarchical_mesh(mesh: Mesh, group_size, batch_axis=DATA_AXIS):
    """Factor a 1-D pure data-parallel mesh into the 2-D
    (GROUP_AXIS, INTRA_AXIS) mesh the hierarchical exchange shard_maps
    over. The device ORDER is preserved — intra is innermost, so one
    group's chips stay contiguous (fastest ICI links) and replicated
    placements on either mesh are interchangeable. Rejects meshes with
    extra axes and indivisible/degenerate group sizes loudly, naming
    the constraint."""
    names = tuple(mesh.axis_names)
    if names != (batch_axis,):
        raise ValueError(
            f"gradient_compression='hierarchical' needs a 1-D pure "
            f"data-parallel mesh over '{batch_axis}', got axes "
            f"{list(names)}: the 2-hop exchange re-factors the data "
            "axis itself and cannot coexist with other mesh axes")
    dp = int(mesh.shape[batch_axis])
    g = int(group_size)
    if g < 2:
        raise ValueError(
            f"compressionGroupSize must be >= 2, got {g}: a 1-chip "
            "group has no intra-group reduction — that is the flat "
            "gradient_compression='threshold' mode; use it directly")
    if g > dp:
        raise ValueError(
            f"compressionGroupSize {g} exceeds the data-parallel "
            f"degree {dp}: a group cannot span more chips than the "
            "mesh has")
    if g == dp:
        raise ValueError(
            f"compressionGroupSize {g} equals the data-parallel degree "
            f"{dp}, leaving a single node group — hop 2's sparse "
            "leader exchange would have no peer to exchange with; use "
            "gradient_compression='block_int8' for pure in-group "
            f"quantization, or a divisor of {dp} that is <= {dp // 2}")
    if dp % g != 0:
        raise ValueError(
            f"data-parallel degree {dp} is not divisible by "
            f"compressionGroupSize {g}: node groups must tile the "
            f"data axis exactly (pick a divisor of {dp})")
    devices = np.asarray(mesh.devices).reshape(-1).reshape(dp // g, g)
    return Mesh(devices, (GROUP_AXIS, INTRA_AXIS))


def hierarchical_grad_exchange(g, res, tau, *, group_size, n_groups,
                               capacity, group_axis=GROUP_AXIS,
                               intra_axis=INTRA_AXIS,
                               intra_mode="block_int8", block=None):
    """The 2-hop exchange of ONE gradient leaf inside shard_map over the
    (group, intra) mesh:

      hop 1  dense (intra_mode=None) or block_int8 psum_scatter over
             the intra axis, divided by group_size — each chip ends
             with the GROUP MEAN of its 1/group_size shard of the leaf
             (the group now acts as ONE virtual Strom replica, so the
             transmitted +-tau has the same effective magnitude as the
             flat threshold mode's — without the /group_size the final
             /dp would shrink every update by group_size and the mode
             would train group_size-times slower than flat),
      hop 2  fixed-capacity Strom threshold exchange of that shard over
             the group axis (each intra position is the leader for its
             own shard): error feedback in, threshold_encode_fixed,
             (idx, +-tau) all-gathers, scatter-add, /n_groups,
      hop 3  all-gather fan-back over the intra axis to the full leaf.

    `res` is this chip's 1-D residual shard (hierarchical_shard_elems
    long). Returns (mean in g's shape/dtype, new residual shard f32,
    transmitted-entry count) — residual clipping and the adaptive tau
    stay with the caller, exactly as in the flat threshold step."""
    from deeplearning4j_tpu.ndarray.compression import (
        threshold_cap, threshold_encode_fixed,
    )

    block = DEFAULT_COMPRESSION_BLOCK if block is None else int(block)
    gsz = int(group_size)
    ng = int(n_groups)
    flat = g.reshape(-1).astype(jnp.float32)
    n = flat.size
    pad = (-n) % gsz
    if pad:
        flat = jnp.pad(flat, (0, pad))
    m = flat.size // gsz
    # hop 1: group-sum reduce-scatter inside the node group
    if intra_mode == "block_int8":
        q, sc, _ = _quantize(flat, intra_axis, gsz, "block_int8", block)
        shard_q = jax.lax.psum_scatter(q, intra_axis,
                                       scatter_dimension=0, tiled=True)
        i = jax.lax.axis_index(intra_axis)
        sc = jax.lax.dynamic_slice_in_dim(sc, i * m, m)
        shard = shard_q.astype(jnp.float32) * (sc / (127.0 * gsz))
    else:
        shard = jax.lax.psum_scatter(flat, intra_axis,
                                     scatter_dimension=0, tiled=True) / gsz
    # hop 2: sparse leader exchange of this shard across groups
    acc = shard + res.astype(shard.dtype)
    cap = threshold_cap(acc.size, capacity)
    idx, val, _, new_res = threshold_encode_fixed(acc, tau, cap)
    gi = jax.lax.all_gather(idx, group_axis, tiled=True)
    gv = jax.lax.all_gather(val, group_axis, tiled=True)
    mean_shard = jnp.zeros_like(acc).at[gi].add(gv) / ng
    # hop 3: fan the mean shard back out to the full leaf
    full = jax.lax.all_gather(mean_shard, intra_axis, tiled=True)
    if pad:
        full = full[:n]
    sent = jnp.sum(jnp.abs(val) > 0)
    return full.reshape(g.shape).astype(g.dtype), new_res, sent


class ZeroShardedUpdate:
    """ZeRO-style cross-replica weight-update sharding (Xu et al.,
    "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
    Training", arXiv:2004.13336).

    Installed as a network's ``_update_impl`` hook (MultiLayerNetwork /
    ComputationGraph per-layer update, SameDiff whole-dict update). The
    forward/backward is UNTOUCHED — same GSPMD program, same global-batch
    loss/BN semantics as the replicated path. Only the weight update is
    re-annotated, exactly the paper's transformation:

      * each eligible gradient leaf is viewed flat and constrained to
        1/dp shards over the data axis — the SPMD partitioner lowers the
        gradient reduction feeding it as a reduce-scatter (TPU; XLA:CPU
        lacks the ReduceScatterCreator pass and emits the equivalent
        all-reduce + dynamic-slice, see dp_weight_update_bytes),
      * the optimizer applies to ONLY the local shard of params and
        updater state (updater state is ALLOCATED sharded from init —
        each chip ever holds 1/dp of the fp32 moments, which is where
        the HBM win for big optimizers comes from),
      * the fresh flat params are constrained back to replicated — one
        all-gather — and reshaped for the next forward.

    Eligibility is per LEAF on the total element count n: a leaf shards
    when ``n >= min_shard_size and n % dp == 0``; anything else —
    scalar/vector leaves (biases, BN gamma/beta) below min_shard_size,
    or sizes dp does not divide — stays REPLICATED (the explicit
    pad-or-replicate policy: never pad; the partition-plan analyzer
    reports the same fallback statically as PAR03). Because the view is
    a reshape and replicated-leaf math is byte-for-byte the default
    update, a model with no eligible leaves trains bitwise-identically
    to the replicated path.
    """

    def __init__(self, mesh: Mesh, axis=DATA_AXIS, min_shard_size=2 ** 16):
        if axis not in mesh.shape:
            raise ValueError(
                f"mesh has no axis '{axis}' (axes: {list(mesh.shape)}); "
                "build the mesh with a data-parallel axis or pass axis=")
        self.mesh = mesh
        self.axis = axis
        self.dp = int(mesh.shape[axis])
        self.min_shard_size = int(min_shard_size)
        self._sharded = NamedSharding(mesh, P(axis))
        self._repl = NamedSharding(mesh, P())

    # ----- eligibility / views ----------------------------------------
    def eligible(self, leaf) -> bool:
        """Shard-or-replicate decision for one array/abstract leaf (by
        total element count — the flat view shards dim 0 of the
        flattened vector, so leading-dim divisibility is irrelevant)."""
        n = int(np.prod(leaf.shape)) if hasattr(leaf, "shape") else int(leaf)
        return n > 0 and n >= self.min_shard_size and n % self.dp == 0

    def _tmap(self, f, *trees):
        return jax.tree_util.tree_map(f, *trees)

    def view(self, tree):
        """Traced: eligible leaves -> flat 1-D views constrained to 1/dp
        shards over the data axis; ineligible leaves pass through."""
        wsc = jax.lax.with_sharding_constraint
        return self._tmap(
            lambda a: wsc(a.reshape(-1), self._sharded)
            if self.eligible(a) else a, tree)

    def constrain_state(self, state):
        """Traced: pin eligible (already-flat) state leaves to the
        sharded layout so the carry cannot silently replicate."""
        wsc = jax.lax.with_sharding_constraint
        return self._tmap(
            lambda a: wsc(a, self._sharded) if self.eligible(a) else a,
            state)

    # ----- the update hook --------------------------------------------
    def __call__(self, updater, grads, upd_state, iteration, params):
        """reduce-scatter(grads) -> local 1/dp shard update -> all-gather
        (params). Drop-in for the default apply-and-subtract: returns
        (new_params at full shape, new updater state in the sharded view
        layout)."""
        wsc = jax.lax.with_sharding_constraint
        gv = self.view(grads)
        pv = self.view(params)
        upd, new_state = updater.apply(gv, upd_state, iteration, params=pv)
        new_state = self.constrain_state(new_state)
        new_pv = self._tmap(
            lambda p, u: (p - u).astype(p.dtype), pv, upd)
        # pin the POST-cast result sharded before replicating: without
        # this the partitioner may sink the param-dtype convert past the
        # all-gather and move a wider intermediate (x64 promotes updater
        # scalar math to f64) — the gather must carry param-dtype bytes
        new_pv = self.constrain_state(new_pv)
        # all-gather the fresh shards back to the replicated full-shape
        # params the next forward reads
        return self._tmap(
            lambda full, flat: wsc(flat, self._repl).reshape(full.shape)
            if self.eligible(full) else flat,
            params, new_pv), new_state

    # ----- state allocation / (un)view --------------------------------
    def init_state(self, updater, params):
        """Fresh updater state ALLOCATED in the sharded layout: init runs
        under jit with sharded out_shardings, so each chip materialises
        only its 1/dp shard — no full-size state buffer ever exists
        (ISSUE: 'allocated sharded from init, not sliced from a
        replicated copy')."""
        views = self._tmap(
            lambda a: a.reshape(-1) if self.eligible(a) else a, params)
        shapes = jax.eval_shape(updater.init, views)
        if not jax.tree_util.tree_leaves(shapes):
            return updater.init(views)  # stateless (Sgd/NoOp): ()/empty
        shardings = self._tmap(
            lambda s: self._sharded if self.eligible(s) else self._repl,
            shapes)
        return jax.jit(updater.init, out_shardings=shardings)(views)

    def place_state(self, state):
        """Re-place an EXISTING state tree (full-shape or already
        viewed) into the sharded layout — the mid-training switch and
        checkpoint-restore path; values are preserved bitwise (the view
        is a reshape)."""
        def place(a):
            a = jnp.asarray(a)
            if self.eligible(a):
                return jax.device_put(a.reshape(-1), self._sharded)
            return jax.device_put(a, self._repl)

        return self._tmap(place, state)

    def unview_state(self, state, updater, params):
        """Sharded view layout -> the canonical full-shape state layout
        (checkpoints save THIS form, so a sharded-mode save restores
        into any mode bitwise; reshape is lossless)."""
        template = jax.eval_shape(updater.init, params)
        return self._tmap(
            lambda s, t: jnp.reshape(s, t.shape), state, template)

    def per_chip_state_bytes(self, state) -> int:
        """Measured per-chip resident bytes of one state tree (device
        0's addressable shards) — the number the analytic
        dp_weight_update_bytes(sharded=True) opt_state_resident_bytes
        bill is judged against."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(state):
            if not hasattr(leaf, "addressable_shards"):
                total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
                continue
            dev0 = leaf.addressable_shards[0]
            total += int(np.prod(dev0.data.shape)) * leaf.dtype.itemsize
        return total


class ManualZeroUpdate:
    """ZeroShardedUpdate's shard_map twin: the compressed-collective
    composition of compression and ZeRO (ISSUE 11). The compressed
    trainer steps run inside an EXPLICIT shard_map, where the GSPMD
    sharding annotations ZeroShardedUpdate relies on cannot apply — so
    this hook spells the same transformation out with manual
    collectives:

      * eligible gradient leaves take a QUANTIZED reduce-scatter
        (quantized_psum_scatter_mean: int8/block_int8 lanes through
        psum_scatter) — each replica receives only its 1/dp shard of
        the reduced gradient, at compressed wire cost,
      * ineligible leaves take the compressed all-reduce
        (quantized_psum_mean) and update replicated, exactly the
        GSPMD path's replicate fallback,
      * the optimizer applies to the LOCAL 1/dp shard of params and
        updater state (state layout identical to ZeroShardedUpdate's:
        flat leaves sharded over the data axis — allocation,
        checkpoint unview and per-chip byte accounting are all shared
        with the GSPMD implementation),
      * the fresh local param shards are all-gathered (param dtype)
        back to the full shapes the next forward reads.

    Installed as the net's `_update_impl` by
    ParallelWrapper._place_sharded_update when gradient_compression is
    "int8"/"block_int8" and weight_update="sharded"."""

    def __init__(self, zero: ZeroShardedUpdate, compression: str,
                 block=None):
        if compression not in ("int8", "block_int8"):
            raise ValueError(
                "ManualZeroUpdate composes the sharded weight update "
                "with gradient_compression 'int8'/'block_int8', got "
                f"{compression!r} (the 'threshold' step's per-replica "
                "error-feedback residual has no per-parameter "
                "reduce-scatter form)")
        self.zero = zero
        self.axis = zero.axis
        self.dp = zero.dp
        self.compression = compression
        self.block = DEFAULT_COMPRESSION_BLOCK if block is None \
            else int(block)

    def __call__(self, updater, grads, upd_state, iteration, params):
        z, ax, dp = self.zero, self.axis, self.dp
        i = jax.lax.axis_index(ax)
        tmap = jax.tree_util.tree_map

        def reduce_leaf(g, p):
            if z.eligible(p):
                return quantized_psum_scatter_mean(
                    g.reshape(-1), ax, dp, self.compression, self.block)
            return quantized_psum_mean(g, ax, dp, self.compression,
                                       self.block)

        def pview(p):
            if z.eligible(p):
                flat = p.reshape(-1)
                return jax.lax.dynamic_slice_in_dim(
                    flat, i * (flat.size // dp), flat.size // dp)
            return p

        gv = tmap(reduce_leaf, grads, params)
        pv = tmap(pview, params)
        upd, new_state = updater.apply(gv, upd_state, iteration,
                                       params=pv)
        new_pv = tmap(lambda p, u: (p - u).astype(p.dtype), pv, upd)

        def unview(full, flat):
            if z.eligible(full):
                return jax.lax.all_gather(
                    flat, ax, tiled=True).reshape(full.shape)
            return flat

        return tmap(unview, params, new_pv), new_state


# ----------------------------------------------------------------------
# the bytes-on-wire bill per compression mode
# ----------------------------------------------------------------------

#: selectable gradient_compression modes (None = dense psum)
COMPRESSION_MODES = (None, "int8", "block_int8", "threshold",
                     "hierarchical")

#: default fraction of a leaf's elements the fixed-capacity threshold
#: encoder may transmit per step (ParallelWrapper encodingCapacity)
DEFAULT_ENCODING_CAPACITY = 0.125


def compressed_wire_bytes(grad_bytes, dp, compression=None, block=None,
                          capacity=None, itemsize=4, group_size=None,
                          intra_mode="block_int8"):
    """LOGICAL per-replica bytes-on-wire of ONE gradient reduction under
    a compression mode — the bill PAR06 reports, bench records and the
    tier-1 ceiling gate holds block_int8 under 30% of dense against.
    Ring-collective convention (what each replica sends):

      dense       2*(dp-1)/dp * G            (reduce-scatter + all-gather
                                             halves of the all-reduce)
      int8        2*(dp-1)/dp * (N + 4)      one byte per element + one
                                             fp32 scale
      block_int8  2*(dp-1)/dp * (N + 4*ceil(N/block))
                                             one byte per element + one
                                             fp32 scale per block
                                             (EQuARX-style)
      threshold   (dp-1) * cap * 5           ring all-gather of each
                                             replica's cap (int32 index,
                                             sign byte) pairs;
                                             cap = ceil(N*capacity)
                                             (Strom's sparse messages
                                             are gathered, not reduced)
      hierarchical  two honest terms over the (groups x group_size)
                    factorization (Np = N padded to the group size,
                    Ns = Np/group_size the per-chip shard):
                    intra   (I-1)/I * (Np + 4*ceil(Np/block))  quantized
                            reduce-scatter (or (I-1)/I * Np*itemsize
                            dense when intra_mode=None) PLUS the
                            (I-1)/I * Np*itemsize fan-back all-gather
                    leader  (groups-1) * cap(Ns) * 5 sparse ring
                            exchange of the shard between group leaders
                    — capacity bytes scale with GROUPS, not dp, which
                    is what moves the sparse crossover past dp128

    N = grad elements (grad_bytes / itemsize). Returns
    {wire_bytes, dense_wire_bytes, ratio, mode}; the hierarchical mode
    adds {intra_wire_bytes, leader_wire_bytes, group_size, groups,
    intra_mode, flat_threshold_wire_bytes, vs_flat_threshold}."""
    if compression not in COMPRESSION_MODES:
        raise ValueError(
            f"unknown gradient_compression {compression!r}; pick one of "
            f"{COMPRESSION_MODES}")
    if group_size is not None and compression != "hierarchical":
        raise ValueError(
            f"group_size only applies to "
            f"gradient_compression='hierarchical', got group_size="
            f"{group_size} with {compression!r}")
    block = DEFAULT_COMPRESSION_BLOCK if block is None else int(block)
    capacity = DEFAULT_ENCODING_CAPACITY if capacity is None \
        else float(capacity)
    G = int(grad_bytes)
    N = G // int(itemsize)
    dense = 2 * (dp - 1) * G // dp
    extra = {}
    if compression is None:
        wire = dense
    elif compression == "int8":
        wire = 2 * (dp - 1) * (N + 4) // dp
    elif compression == "block_int8":
        wire = 2 * (dp - 1) * (N + 4 * _ceil_div(N, block)) // dp
    elif compression == "threshold":
        from deeplearning4j_tpu.ndarray.compression import threshold_cap

        wire = (dp - 1) * threshold_cap(N, capacity) * 5
    else:  # hierarchical
        from deeplearning4j_tpu.ndarray.compression import threshold_cap

        gsz = default_compression_group(dp) if group_size is None \
            else int(group_size)
        if gsz < 2 or gsz >= dp or dp % gsz != 0:
            raise ValueError(
                f"hierarchical group_size {gsz} must be a divisor of "
                f"dp={dp} with 2 <= group_size <= dp/2 (node groups "
                "tile the data axis exactly and the leader exchange "
                "needs >= 2 groups)")
        if intra_mode not in (None, "block_int8"):
            raise ValueError(
                f"hierarchical intra_mode must be None (dense) or "
                f"'block_int8', got {intra_mode!r}")
        groups = dp // gsz
        Ns = hierarchical_shard_elems(N, gsz)
        Np = Ns * gsz
        if intra_mode == "block_int8":
            hop1 = (gsz - 1) * (Np + 4 * _ceil_div(Np, block)) // gsz
        else:
            hop1 = (gsz - 1) * Np * int(itemsize) // gsz
        hop3 = (gsz - 1) * Np * int(itemsize) // gsz
        leader = (groups - 1) * threshold_cap(Ns, capacity) * 5
        wire = hop1 + hop3 + leader
        flat_thr = (dp - 1) * threshold_cap(N, capacity) * 5
        extra = {
            "intra_wire_bytes": int(hop1 + hop3),
            "leader_wire_bytes": int(leader),
            "group_size": gsz,
            "groups": groups,
            "intra_mode": intra_mode or "dense",
            "flat_threshold_wire_bytes": int(flat_thr),
            "vs_flat_threshold": round(wire / flat_thr, 4)
            if flat_thr else 1.0,
        }
    # publish the static bill as gauges: a scrape of /metrics shows the
    # per-replica bytes-on-wire the current config is billed for
    # (host-side analytic math — never inside a traced function)
    from deeplearning4j_tpu.runtime import telemetry

    _g = telemetry.get_registry().gauge(
        "dl4j_compressed_wire_bytes",
        "analytic per-replica gradient bytes-on-wire per step",
        labels=("mode",))
    _g.labels(mode=compression or "dense").set(int(wire))
    _g.labels(mode="dense").set(int(dense))
    rec = {
        "wire_bytes": int(wire),
        "dense_wire_bytes": int(dense),
        "ratio": round(wire / dense, 4) if dense else 1.0,
        "mode": compression or "dense",
    }
    rec.update(extra)
    return rec


def _ceil_div(a, b):
    return -(-int(a) // int(b))


def compressed_hlo_collective_bytes(leaf_elems, dp, compression,
                                    block=None, capacity=None,
                                    sharded=False, eligible=None,
                                    itemsize=4, group_size=None,
                                    intra_mode="block_int8"):
    """Per-replica HBM bytes the hbm_ledger charges the COLLECTIVE rows
    of the compressed dp step AS LOWERED on this backend — the analytic
    twin the tier-1 measured-bytes gate holds the dp8 CPU compile
    within 10% of. Convention (hbm_ledger._instruction_bytes): an op
    charges its output bytes plus its distinct-operand input bytes.

    `leaf_elems`: per-leaf element counts (the quantizer/encoder runs
    per leaf, so scale/capacity rounding is per leaf). Emitted ops per
    leaf of n elements, acc = int16 for dp <= 256 else int32:

      int8        scale pmax (all-reduce f32 scalar: 8 B) +
                  integer psum (all-reduce acc[n]: 2 * n * acc_bytes)
      block_int8  scale pmax (all-reduce f32 [ceil(n/block)]) +
                  integer psum as above
      threshold   all-gather idx int32 [cap]->[dp*cap] + all-gather val
                  [cap]->[dp*cap] in the residual dtype: each charges
                  (dp+1) * cap * itemsize_of_part
      hierarchical (pass group_size; acc from _acc_dtype(group_size) —
                  the integer sum spans only the group's lanes):
                  per leaf with np = n padded to group_size, ns =
                  np/group_size, groups = dp/group_size:
                  scale pmax (all-reduce f32 [ceil(np/block)], quantized
                  hop 1 only) + intra reduce-scatter (in np + out ns, at
                  acc bytes quantized / f32 dense) + the two leader
                  all-gathers ((groups+1) * cap(ns) * {4, itemsize}) +
                  the f32 fan-back all-gather (in ns + out np)

    sharded=True (int8/block_int8 only): leaves for which
    `eligible(n)` is True take the quantized reduce-scatter
    (in acc[n] + out acc[n/dp]) plus the param-dtype all-gather of the
    fresh shards (in n/dp + out n, at `itemsize`); ineligible leaves
    keep the compressed all-reduce."""
    from deeplearning4j_tpu.ndarray.compression import threshold_cap

    block = DEFAULT_COMPRESSION_BLOCK if block is None else int(block)
    capacity = DEFAULT_ENCODING_CAPACITY if capacity is None \
        else float(capacity)
    # the bill and the lowering share ONE accumulator-width definition
    # (_acc_dtype) so they cannot drift apart; the analyzer's COL03
    # check (analysis.collectives.check_acc_dtype) cross-checks both
    # against the dp<=256 int16 bound independently
    acc = jnp.dtype(_acc_dtype(dp)).itemsize
    if compression == "hierarchical":
        gsz = default_compression_group(dp) if group_size is None \
            else int(group_size)
        groups = dp // gsz
        # hop 1 sums int8 lanes across the GROUP only — the
        # accumulator width tracks the group size, not dp
        acc = jnp.dtype(_acc_dtype(gsz)).itemsize
    total = 0
    for n in leaf_elems:
        n = int(n)
        if compression == "threshold":
            cap = threshold_cap(n, capacity)     # the encoder's rule
            total += (dp + 1) * cap * 4          # idx int32 gather
            total += (dp + 1) * cap * itemsize   # value gather
            continue
        if compression == "hierarchical":
            ns = hierarchical_shard_elems(n, gsz)
            np_ = ns * gsz
            cap = threshold_cap(ns, capacity)
            if intra_mode == "block_int8":
                total += 2 * _ceil_div(np_, block) * 4  # scale pmax
                total += np_ * acc + ns * acc    # int reduce-scatter
            else:
                total += (np_ + ns) * 4          # f32 reduce-scatter
            total += (groups + 1) * cap * 4      # leader idx gather
            total += (groups + 1) * cap * itemsize  # leader val gather
            total += (ns + np_) * 4              # f32 fan-back gather
            continue
        nb = _ceil_div(n, block) if compression == "block_int8" else 1
        scale = 2 * nb * 4                       # pmax all-reduce
        if sharded and eligible is not None and eligible(n):
            rs = n * acc + (n // dp) * acc       # reduce-scatter
            ag = n * itemsize + (n // dp) * itemsize  # param all-gather
            total += scale + rs + ag
        else:
            total += scale + 2 * n * acc         # integer all-reduce
    return int(total)


def dp_weight_update_bytes(grad_bytes, dp, master_bytes=None,
                           opt_state_bytes=None, sharded=False,
                           compression=None, compression_block=None,
                           encoding_capacity=None,
                           compression_group=None):
    """Analytic per-replica HBM bytes of the data-parallel weight-update
    path — the model the hbm_ledger attribution's `collective` bin
    (weight_update rows) is judged against, and the bill cross-replica
    weight-update sharding (Xu et al., "Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training") removes.

    Terms per replica, dp = data-parallel degree:
      allreduce:       ring all-reduce of the gradients moves
                       2*(dp-1)/dp * G bytes through each replica's HBM
                       (reduce-scatter + all-gather halves)
      update_replicated: every replica redundantly reads+writes the full
                       fp32 master params and updater state and re-reads
                       the full reduced gradient — identical work dp
                       times over
      update_sharded:  the same update with cross-replica sharding: each
                       replica touches only its 1/dp slice (plus the
                       all-gather of updated params, already counted in
                       the allreduce-equivalent traffic of that scheme)

    master/opt default to fp32 buffers the same element count as the
    (fp32) grads. Returns the terms plus `sharding_saves_bytes` — the
    per-replica HBM cut the sharded update offers; compare it against
    the attribution's measured weight_update collective rows before
    spending a live window on the rewrite.

    sharded=True returns the ZeRO bill of the IMPLEMENTED scheme
    (ZeroShardedUpdate) — the analytic yardstick its measured
    weight_update collective bin and per-chip updater-state bytes are
    CI-gated against. Terms per replica:

      reduce_scatter_bytes  (dp-1)/dp * G on the wire (the gradient
                            reduction, scattered instead of replicated)
      all_gather_bytes      (dp-1)/dp * M on the wire (the fresh params)
      update_bytes          (2M + 2S + G)/dp — the optimizer touches
                            only the local shard
      opt_state_resident_bytes  S/dp per chip (state allocated sharded)
      hlo_collective_bytes  the per-replica HBM bytes the hbm_ledger
                            charges the COLLECTIVE rows of the
                            PARTITIONED step, by lowering:
                              reduce_scatter:    rs (out G/dp + in G)
                                                 + ag (out M + in M/dp)
                                                 — what TPU emits;
                              all_reduce_gather: XLA:CPU lacks the
                                                 ReduceScatterCreator
                                                 pass and lowers the
                                                 scattered reduction as
                                                 all-reduce (2G) + a
                                                 local dynamic-slice
                                                 (not a collective),
                                                 plus the same param
                                                 all-gather — the form
                                                 the tier-1 CPU gate
                                                 prices.
                            Both models cover the ELIGIBLE (actually
                            sharded) bytes; leaves the replicate
                            fallback keeps pay the plain 2G all-reduce
                            on top (the caller adds that term).

    compression (None / "int8" / "block_int8" / "threshold") bills the
    compressed gradient reduction on top of either mode (the ISSUE 11
    composition): `compressed_wire` carries the compressed_wire_bytes
    record for the gradient half, and under sharded=True
    `compressed_reduce_scatter_bytes` + `collective_wire_bytes_compressed`
    replace the gradient reduce-scatter's wire cost with its quantized
    form (the param all-gather stays dense — params are not quantized).
    "threshold" does not compose with sharded=True (no per-parameter
    reduce-scatter form) and raises.
    """
    if compression not in COMPRESSION_MODES:
        raise ValueError(
            f"unknown gradient_compression {compression!r}; pick one of "
            f"{COMPRESSION_MODES}")
    if sharded and compression in ("threshold", "hierarchical"):
        raise ValueError(
            f"weight_update sharding does not compose with "
            f"gradient_compression={compression!r}: the Strom step "
            "carries per-replica error-feedback residuals and "
            "transmits sparse messages, which have no per-parameter "
            "reduce-scatter form; bill 'int8'/'block_int8' (compressed "
            "reduce-scatter) or the dense sharded path")
    G = int(grad_bytes)
    M = G if master_bytes is None else int(master_bytes)
    S = G if opt_state_bytes is None else int(opt_state_bytes)
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    allreduce = 2 * (dp - 1) * G // dp
    update_repl = 2 * M + 2 * S + G
    update_shard = (2 * M + 2 * S + G) // dp
    rec = {
        "allreduce_bytes": allreduce,
        "update_replicated_bytes": update_repl,
        "update_sharded_bytes": update_shard,
        "sharding_saves_bytes": update_repl - update_shard,
        "dp": int(dp),
        "mode": "sharded" if sharded else "replicated",
        "gradient_compression": compression,
    }
    if compression is not None:
        rec["compressed_wire"] = compressed_wire_bytes(
            G, dp, compression, block=compression_block,
            capacity=encoding_capacity,
            group_size=compression_group
            if compression == "hierarchical" else None)
    if not sharded:
        rec["update_bytes"] = update_repl
        rec["opt_state_resident_bytes"] = S
        return rec
    rs = (dp - 1) * G // dp
    ag = (dp - 1) * M // dp
    rec.update({
        "reduce_scatter_bytes": rs,
        "all_gather_bytes": ag,
        "collective_wire_bytes": rs + ag,
        "update_bytes": update_shard,
        "opt_state_resident_bytes": S // dp,
        "hlo_collective_bytes": {
            "reduce_scatter": (G + G // dp) + (M + M // dp),
            "all_reduce_gather": 2 * G + (M + M // dp),
        },
    })
    if compression is not None:
        # the gradient half of the compressed wire bill IS the
        # compressed reduce-scatter (one of the all-reduce's two
        # halves); the param all-gather stays dense
        rs_c = rec["compressed_wire"]["wire_bytes"] // 2
        rec["compressed_reduce_scatter_bytes"] = rs_c
        rec["collective_wire_bytes_compressed"] = rs_c + ag
    return rec

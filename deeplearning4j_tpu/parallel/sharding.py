"""Parameter sharding rules for model (tensor) parallelism.

Reference: none — the reference is data-parallel only (its multi-GPU and
Spark paths replicate the full model). Tensor parallelism is a TPU-first
capability: parameters are annotated with PartitionSpecs over the mesh
"model" axis and XLA's SPMD partitioner (GSPMD; see PAPERS.md sharding
papers) propagates shardings through the computation and inserts the
all-gather / reduce-scatter collectives over ICI.

Rules follow the Megatron layout:
  dense W [in, out]      -> P(None, "model")   (column parallel)
  conv  W [kh,kw,ci,co]  -> P(None,None,None,"model")
  lstm  W/RW [in, 4H]    -> P(None, "model")
  biases/gains [out]     -> P("model") when their dim is sharded
Small params (< min_shard_size) stay replicated — collective latency beats
the memory win.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def shard_batch(arr, mesh: Mesh, batch_axis=DATA_AXIS, dim=0):
    """Place one batch array with dim `dim` sharded over `batch_axis`.

    REJECTS indivisible batches with an error naming the axis instead
    of letting the placement silently pad (uneven GSPMD tiling pads the
    trailing shard with garbage rows that would train): the same check
    the partition-plan analyzer reports statically as PAR03, enforced
    at the runtime boundary every trainer shares."""
    if batch_axis not in mesh.shape:
        raise ValueError(
            f"mesh has no axis '{batch_axis}' (axes: "
            f"{list(mesh.shape)}); build the mesh with a data-parallel "
            "axis or pass batch_axis=")
    width = mesh.shape[batch_axis]
    if arr.shape[dim] % width != 0:
        raise ValueError(
            f"Global batch {arr.shape[dim]} not divisible by "
            f"data-parallel mesh axis '{batch_axis}' (size {width}): "
            "refusing to silently pad; use a batch size that is a "
            f"multiple of {width} (PAR03)")
    spec = [None] * arr.ndim
    spec[dim] = batch_axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def shard_batch_stack(tree, mesh: Mesh, batch_axis=DATA_AXIS):
    """Place a fitDataSet staging stack — a pytree of [k, B, ...] arrays
    (None components pass through) — with the BATCH dim (dim 1) sharded
    over `batch_axis` and the k staging dim replicated, through the same
    divisibility-checked shard_batch every trainer uses. Each of the k
    steps of the on-device loop then indexes a correctly-sharded global
    batch."""
    import jax.tree_util as jtu

    return jtu.tree_map(
        lambda a: shard_batch(a, mesh, batch_axis=batch_axis, dim=1), tree)


def spec_for_param(name: str, shape, model_axis=MODEL_AXIS, min_shard_size=2 ** 16):
    """PartitionSpec for one parameter array by name/shape convention."""
    if int(np.prod(shape)) < min_shard_size:
        return P()
    if len(shape) == 2:
        # dense / recurrent / embedding weights: shard the output dim
        return P(None, model_axis)
    if len(shape) == 4:
        # conv HWIO: shard output channels
        return P(None, None, None, model_axis)
    if len(shape) == 1:
        return P(model_axis)
    return P()


def shard_params(params, mesh: Mesh, model_axis=MODEL_AXIS,
                 min_shard_size=2 ** 16, on_indivisible="replicate"):
    """Annotate+place a params pytree (list/dict of per-layer dicts) onto
    the mesh with tensor-parallel shardings; returns the placed pytree.

    on_indivisible: what to do when a selected dim does not divide by
    the model-axis size — "replicate" (default; GSPMD requires even
    tiling, and replication is always correct) or "error" to fail
    loudly naming the axis (the strict mode a validated plan uses)."""
    if on_indivisible not in ("replicate", "error"):
        raise ValueError("on_indivisible must be 'replicate' or 'error'")

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        # shard only when divisible; otherwise replicate (GSPMD requires
        # even tiling for the annotated dim)
        spec = spec_for_param(name, leaf.shape, model_axis, min_shard_size)
        width = mesh.shape[model_axis]
        ok = True
        for dim, axis in zip(leaf.shape, tuple(spec) + (None,) * (leaf.ndim - len(spec))):
            if axis == model_axis and dim % width != 0:
                if on_indivisible == "error":
                    raise ValueError(
                        f"param {jax.tree_util.keystr(path)} dim {dim} "
                        f"is not divisible by mesh axis "
                        f"'{model_axis}' (size {width}) (PAR03)")
                ok = False
        if not ok:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, params)


def replicate_params(params, mesh: Mesh):
    return jax.device_put(params, NamedSharding(mesh, P()))


def dp_weight_update_bytes(grad_bytes, dp, master_bytes=None,
                           opt_state_bytes=None):
    """Analytic per-replica HBM bytes of the data-parallel weight-update
    path — the model the hbm_ledger attribution's `collective` bin
    (weight_update rows) is judged against, and the bill cross-replica
    weight-update sharding (Xu et al., "Automatic Cross-Replica
    Sharding of Weight Update in Data-Parallel Training") removes.

    Terms per replica, dp = data-parallel degree:
      allreduce:       ring all-reduce of the gradients moves
                       2*(dp-1)/dp * G bytes through each replica's HBM
                       (reduce-scatter + all-gather halves)
      update_replicated: every replica redundantly reads+writes the full
                       fp32 master params and updater state and re-reads
                       the full reduced gradient — identical work dp
                       times over
      update_sharded:  the same update with cross-replica sharding: each
                       replica touches only its 1/dp slice (plus the
                       all-gather of updated params, already counted in
                       the allreduce-equivalent traffic of that scheme)

    master/opt default to fp32 buffers the same element count as the
    (fp32) grads. Returns the terms plus `sharding_saves_bytes` — the
    per-replica HBM cut the sharded update offers; compare it against
    the attribution's measured weight_update collective rows before
    spending a live window on the rewrite."""
    G = int(grad_bytes)
    M = G if master_bytes is None else int(master_bytes)
    S = G if opt_state_bytes is None else int(opt_state_bytes)
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    allreduce = 2 * (dp - 1) * G // dp
    update_repl = 2 * M + 2 * S + G
    update_shard = (2 * M + 2 * S + G) // dp
    return {
        "allreduce_bytes": allreduce,
        "update_replicated_bytes": update_repl,
        "update_sharded_bytes": update_shard,
        "sharding_saves_bytes": update_repl - update_shard,
        "dp": int(dp),
    }

"""Spark-facade entry points: SparkDl4jMultiLayer / SparkComputationGraph.

Reference: the dl4j-spark subproject —
org.deeplearning4j.spark.impl.multilayer.SparkDl4jMultiLayer and
impl.graph.SparkComputationGraph: `new SparkDl4jMultiLayer(sc, conf,
trainingMaster)` then `fit(JavaRDD<DataSet>)`.

TPU translation: the Spark cluster's role (shard data, run workers,
aggregate) is played by the device mesh + the existing TrainingMaster
classes (`parallel/trainer.py`), which already implement the two
upstream aggregation strategies (parameter averaging, shared
gradients). This module is the ENTRY-POINT parity layer so upstream
call sites port 1:1: the `sc` slot takes a `jax.sharding.Mesh` (or
None for all local devices) — the mesh IS the cluster context here —
and the "RDD" is any DataSetIterator or list of DataSet (a
pre-sharded, already-local dataset; there is no JVM cluster to ship
closures to).
"""

from __future__ import annotations

from deeplearning4j_tpu.parallel import trainer as _trainer


class _DeferredMaster:
    """A TrainingMaster configured before the net exists (upstream
    builds the TrainingMaster first and hands it to the Spark wrapper,
    which owns the net). bind() attaches net + mesh."""

    def __init__(self, cls, kwargs):
        self._cls = cls
        self._kwargs = dict(kwargs)

    def bind(self, net, mesh):
        return self._cls(net, mesh=mesh, **self._kwargs)


class ParameterAveragingTrainingMasterBuilder:
    """Reference: ParameterAveragingTrainingMaster.Builder — the
    `rddDataSetNumExamples`/`batchSizePerWorker` sizing args don't
    exist here (batches keep whatever size the iterator yields; the
    mesh shards them), so the constructor takes no required args."""

    def __init__(self):
        self._kw = {}

    def averagingFrequency(self, k):
        self._kw["averagingFrequency"] = int(k)
        return self

    def build(self):
        return _DeferredMaster(_trainer.ParameterAveragingTrainingMaster,
                               self._kw)


class SharedTrainingMasterBuilder:
    """Reference: SharedTrainingMaster.Builder (gradient-sharing mode;
    int8-quantized allreduce by default). `thresholdAlgorithm` selects
    Strom-2015 threshold encoding and maps to REAL trainer config —
    a number / FixedThresholdAlgorithm pins tau,
    AdaptiveThresholdAlgorithm / TargetSparsityThresholdAlgorithm wire
    the adaptive tau loop, ResidualClippingPostProcessor wires residual
    clipping; unknown algorithms raise at build-time binding naming the
    supported set (SharedTrainingMaster does the mapping)."""

    def __init__(self):
        self._kw = {}

    def thresholdAlgorithm(self, algo):
        self._kw["thresholdAlgorithm"] = algo
        return self

    def residualPostProcessor(self, rpp):
        self._kw["residualPostProcessor"] = rpp
        return self

    def gradientCompression(self, gc):
        self._kw["gradient_compression"] = gc
        return self

    def targetSparsity(self, s):
        self._kw["targetSparsity"] = float(s)
        return self

    def encodingCapacity(self, c):
        self._kw["encodingCapacity"] = float(c)
        return self

    def compressionBlock(self, b):
        self._kw["compressionBlock"] = int(b)
        return self

    def compressionGroupSize(self, g):
        """Node-group size of the hierarchical 2-hop exchange — selects
        gradient_compression='hierarchical' (dense/block_int8
        reduce-scatter inside each g-chip group, Strom threshold
        exchange between group leaders). Must be a divisor of the
        data-parallel degree in [2, dp/2] — at least 2 chips per group
        AND at least 2 groups; the binding raises naming the
        constraint otherwise (SharedTrainingMaster does the mapping)."""
        self._kw["compressionGroupSize"] = int(g)
        return self

    def intraGroupCompression(self, mode):
        """Hop-1 encoding inside the node group: 'block_int8' (default)
        or None for the dense f32 reduce-scatter."""
        self._kw["intraGroupCompression"] = mode
        return self

    def weightUpdate(self, mode):
        """'replicated' or 'sharded' (ZeRO) — int8/block_int8 compose
        with 'sharded' via the compressed reduce-scatter."""
        self._kw["weight_update"] = mode
        return self

    def build(self):
        return _DeferredMaster(_trainer.SharedTrainingMaster, self._kw)


class SparkDl4jMultiLayer:
    """Reference: SparkDl4jMultiLayer(sc, conf, trainingMaster).

    `mesh`: jax Mesh or None (all local devices, data-parallel).
    `conf_or_net`: a built configuration (init() is called for you,
    like the Spark wrapper does) or an already-initialized net.
    `trainingMaster`: a *Builder().build() deferred master, an already
    -bound ParallelWrapper, or None (plain data-parallel).
    """

    def __init__(self, mesh, conf_or_net, trainingMaster=None):
        cls = type(self)._net_cls()
        if isinstance(conf_or_net, cls):
            self._net = conf_or_net
            if getattr(self._net, "_params", None) is None:
                self._net.init()
        else:
            self._net = cls(conf_or_net).init()
        if trainingMaster is None:
            self._master = _trainer.ParallelWrapper(self._net, mesh=mesh)
        elif isinstance(trainingMaster, _DeferredMaster):
            self._master = trainingMaster.bind(self._net, mesh)
        elif isinstance(trainingMaster, _trainer.ParallelWrapper):
            if trainingMaster.net is not self._net:
                raise ValueError(
                    "bound trainingMaster wraps a different network than "
                    "this facade's — fit() would train one net while "
                    "evaluate()/getNetwork() used the other; pass the same "
                    "net to both, or pass a *TrainingMasterBuilder result")
            self._master = trainingMaster
        else:
            raise ValueError(
                f"trainingMaster must be a TrainingMaster builder result, "
                f"a bound ParallelWrapper, or None; got {trainingMaster!r}")

    @classmethod
    def _net_cls(cls):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork

    # ---------------- reference API -----------------------------------
    def fit(self, data, epochs=None):
        """`data`: DataSetIterator, list of DataSet, or a single
        DataSet (the RDD analog). Returns the trained network, like
        the reference's fit(JavaRDD<DataSet>)."""
        from deeplearning4j_tpu.data.dataset import DataSet

        n_ep = 1 if epochs is None else int(epochs)
        if n_ep < 1:
            raise ValueError(f"epochs must be >= 1, got {epochs}")
        if isinstance(data, DataSet):
            data = [data]  # single batch honors epochs like a list does
        if isinstance(data, (list, tuple)):
            for _ in range(n_ep):
                for ds in data:
                    self._master.fit(ds)
        else:
            self._master.fit(data, epochs=n_ep)
        return self._net

    def getNetwork(self):
        return self._net

    def getTrainingMaster(self):
        return self._master

    def evaluate(self, iterator):
        return self._net.evaluate(iterator)

    def evaluateRegression(self, iterator):
        return self._net.evaluateRegression(iterator)

    def evaluateROC(self, iterator, thresholdSteps=0):
        return self._net.evaluateROC(iterator, thresholdSteps=thresholdSteps)


class SparkComputationGraph(SparkDl4jMultiLayer):
    """Reference: SparkComputationGraph — same wrapper over a
    ComputationGraph (single-input/-output graphs, matching the
    ParallelWrapper support surface)."""

    @classmethod
    def _net_cls(cls):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph

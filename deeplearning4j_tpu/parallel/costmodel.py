"""Analytic cost model for TPU collectives and data-parallel scaling.

Reference: upstream DL4J justifies its gradient-sharing design with
measured Ethernet allreduce costs (Strom 2015 threshold encoding in
`SharedTrainingMaster`); there is no analytic model — scaling claims are
empirical Spark runs. On TPU the interconnect is regular (2D/3D torus
ICI inside a slice, DCN between slices), so collective time is
predictable from first principles; this module implements the standard
ring/torus model (as popularized by the public "How to Scale Your
Model" book) and uses it to *prove* the SURVEY §6 claim — ≥80% scaling
efficiency from 8 to 128 chips for the flagship ResNet-50 config —
without needing 128 physical chips.

Model (bandwidth term + latency term, per mesh axis):

  all_gather(D bytes, axis N, bw W)      = D*(N-1)/N / W  +  (N-1)*t_hop
  reduce_scatter                          = same as all_gather
  all_reduce                              = 2 * all_gather  (RS + AG)
  ppermute (neighbor shift)               = D / W_link      +  t_hop

where W is the *bidirectional* bandwidth available to the axis (a torus
ring sends both ways), multiplied across mesh axes when XLA splits the
collective over several ICI dimensions.  DCN-crossing collectives use
the per-chip DCN share instead of ICI.
"""

from __future__ import annotations

from dataclasses import dataclass, field


_HOP_LATENCY_S = 1e-6  # per-hop ICI latency floor (~1 us)
_DCN_LATENCY_S = 10e-6  # per-round DCN latency floor


@dataclass(frozen=True)
class ChipSpec:
    """Public headline specs for one TPU generation (per chip)."""

    name: str
    bf16_flops: float            # peak bf16 FLOP/s
    hbm_bytes_per_s: float       # HBM bandwidth
    ici_link_bytes_per_s: float  # ONE-way bandwidth of one ICI link
    ici_torus_axes: int          # 2 => 2D torus (v5e), 3 => 3D (v4/v5p)
    dcn_bytes_per_s: float       # per-CHIP share of host DCN bandwidth
    max_slice_chips: int         # pod/slice size before DCN is required


CHIPS = {
    "v5e": ChipSpec("v5e", bf16_flops=197e12, hbm_bytes_per_s=819e9,
                    ici_link_bytes_per_s=45e9, ici_torus_axes=2,
                    dcn_bytes_per_s=6.25e9, max_slice_chips=256),
    "v5p": ChipSpec("v5p", bf16_flops=459e12, hbm_bytes_per_s=2765e9,
                    ici_link_bytes_per_s=90e9, ici_torus_axes=3,
                    dcn_bytes_per_s=6.25e9, max_slice_chips=8960),
    "v4": ChipSpec("v4", bf16_flops=275e12, hbm_bytes_per_s=1228e9,
                   ici_link_bytes_per_s=45e9, ici_torus_axes=3,
                   dcn_bytes_per_s=6.25e9, max_slice_chips=4096),
}


def _axis_bw(chip: ChipSpec, n_ici_axes: int) -> float:
    """Bidirectional bandwidth a collective can drive when XLA spreads it
    over `n_ici_axes` torus dimensions (each axis = one link pair)."""
    n = max(1, min(n_ici_axes, chip.ici_torus_axes))
    return 2.0 * chip.ici_link_bytes_per_s * n


def all_gather_time(nbytes: float, axis_size: int, chip: ChipSpec, *,
                    n_ici_axes: int = 1, dcn: bool = False) -> float:
    """Time to all-gather an array whose FULL (gathered) size is `nbytes`
    over a mesh axis of `axis_size` devices."""
    if axis_size <= 1:
        return 0.0
    if dcn:
        bw = chip.dcn_bytes_per_s
        hops = axis_size - 1
        lat = _DCN_LATENCY_S
    else:
        bw = _axis_bw(chip, n_ici_axes)
        # splitting over k torus axes also splits the ring: each axis
        # carries a ring of ~N^(1/k) devices, traversed concurrently, so
        # the latency chain is k*(N^(1/k)-1) hops, not N-1
        k = max(1, min(n_ici_axes, chip.ici_torus_axes))
        hops = k * (axis_size ** (1.0 / k) - 1.0)
        lat = _HOP_LATENCY_S
    frac = (axis_size - 1) / axis_size
    return nbytes * frac / bw + hops * lat


def reduce_scatter_time(nbytes, axis_size, chip, *, n_ici_axes=1,
                        dcn=False):
    return all_gather_time(nbytes, axis_size, chip, n_ici_axes=n_ici_axes,
                           dcn=dcn)


def all_reduce_time(nbytes, axis_size, chip, *, n_ici_axes=1, dcn=False):
    """psum = reduce-scatter + all-gather (the bandwidth-optimal lowering
    XLA uses); 2x the one-pass cost, independent of axis size for large N."""
    return 2.0 * all_gather_time(nbytes, axis_size, chip,
                                 n_ici_axes=n_ici_axes, dcn=dcn)


def ppermute_time(nbytes, chip, *, dcn=False):
    """One neighbor-to-neighbor shift (ring attention / pipeline stage
    handoff): pure point-to-point over a single link."""
    if dcn:
        return nbytes / chip.dcn_bytes_per_s + _DCN_LATENCY_S
    return nbytes / chip.ici_link_bytes_per_s + _HOP_LATENCY_S


@dataclass
class DataParallelModel:
    """Scaling model for the psum gradient-sharing trainer
    (`parallel.trainer`): per-step compute time is constant per replica
    (batch-per-chip fixed — weak scaling), communication is one gradient
    all-reduce, partially overlapped with the backward pass.

    `overlap` is the fraction of allreduce time hidden under backprop
    compute: XLA's latency-hiding scheduler starts layer-k's grad
    reduction while layer k-1's backward runs. The default 0.63 is
    MEASURED, not assumed: parallel/overlap.py compiles the flagship
    ResNet-50 DP step and reads the schedule — 151 per-layer grad
    all-reduces interleaved through the backward, payload-weighted
    compute-after fraction 0.626 (big early-layer grads finish last and
    have the least compute behind them, which is why it is not ~1.0).
    """

    step_time_s: float           # measured single-chip train-step time
    grad_bytes: float            # bytes all-reduced per step
    chip: ChipSpec = field(default_factory=lambda: CHIPS["v5e"])
    overlap: float = 0.63        # measured: parallel/overlap.py
    compression: float = 1.0     # 1.0 = dense bf16/fp32; 0.25 = int8-of-fp32

    def comm_time(self, n_chips: int) -> float:
        nbytes = self.grad_bytes * self.compression
        in_slice = min(n_chips, self.chip.max_slice_chips)
        t = all_reduce_time(nbytes, in_slice, self.chip,
                            n_ici_axes=self.chip.ici_torus_axes)
        n_slices = -(-n_chips // self.chip.max_slice_chips)
        if n_slices > 1:
            # hierarchical: ICI allreduce inside each slice, then a
            # cross-slice allreduce of the already-reduced grads over DCN
            t += all_reduce_time(nbytes, n_slices, self.chip, dcn=True)
        return t

    def step_time(self, n_chips: int) -> float:
        exposed = max(0.0, self.comm_time(n_chips) * (1.0 - self.overlap))
        return self.step_time_s + exposed

    def efficiency(self, n_chips: int, base_chips: int = 1) -> float:
        """Throughput per chip at n_chips relative to base_chips."""
        return self.step_time(base_chips) / self.step_time(n_chips)

    def report(self, chip_counts=(1, 8, 16, 32, 64, 128, 256, 512)):
        return {
            n: {
                "step_ms": round(self.step_time(n) * 1e3, 3),
                "comm_ms": round(self.comm_time(n) * 1e3, 3),
                "efficiency_vs_1": round(self.efficiency(n), 4),
            }
            for n in chip_counts
        }


def layer_step_flops(param_count, out_shape, out_kind="feedforward"):
    """Forward-pass FLOP estimate for one layer from its parameter count
    and internal output shape (leading batch dim included).

    Every parameter of a dense/conv/recurrent layer participates in one
    multiply-accumulate per output POSITION (spatial site / time step /
    single vector), so flops ~= 2 * params * batch * positions:
      FF   [B, N]          -> positions = 1
      CNN  [B, H, W, C]    -> positions = H * W
      CNN3D[B, D, H, W, C] -> positions = D * H * W
      RNN  [B, F, T]       -> positions = T
    Parameterless layers (pooling, activation) cost ~0 by this model —
    correct at the granularity the pipeline-balance report needs, where
    matmul/conv FLOPs dominate by orders of magnitude. The backward pass
    is a constant ~2x of this everywhere, so SKEW ratios are unaffected.
    """
    if not param_count or not out_shape or len(out_shape) < 2:
        return 0
    batch = out_shape[0] or 1
    if out_kind == "recurrent":
        positions = out_shape[2] if len(out_shape) > 2 and out_shape[2] else 1
    else:
        # trailing dim is the feature/channel width in every internal
        # layout (FF [B,N], CNN NHWC, CNN3D NDHWC)
        positions = 1
        for d in out_shape[1:-1]:
            positions *= d or 1
    return int(2 * param_count * batch * positions)


def resnet50_scaling(step_time_s: float = 0.0546,
                     param_count: int = 25_610_216,
                     grad_dtype_bytes: int = 2,
                     chip: str = "v5e",
                     compression: float = 1.0) -> dict:
    """The SURVEY §6 proof obligation: flagship ResNet-50 DP scaling.

    Defaults are the round-3 measured step time (BENCH_NOTES.md, batch
    128 bf16 on the real v5e-class chip) and the bf16 gradient size the
    trainer all-reduces.
    """
    m = DataParallelModel(step_time_s=step_time_s,
                          grad_bytes=param_count * grad_dtype_bytes,
                          chip=CHIPS[chip], compression=compression)
    rep = m.report()
    rep["efficiency_8_to_128"] = round(
        m.step_time(8) / m.step_time(128), 4)
    return rep

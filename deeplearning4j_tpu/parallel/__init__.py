"""Distributed training: meshes, data/tensor/sequence parallelism.

Reference subsystems replaced: deeplearning4j-parallel-wrapper (multi-GPU),
deeplearning4j-scaleout/spark (SharedTrainingMaster gradient sharing over
Aeron), and the NCCL/MPI transports — all collapsed into jax.sharding
meshes + XLA ICI collectives.
"""

from deeplearning4j_tpu.parallel.mesh import (
    build_mesh, data_parallel_mesh, DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS,
    GROUP_AXIS, INTRA_AXIS,
)
from deeplearning4j_tpu.parallel.trainer import (
    ParallelWrapper, SharedTrainingMaster, ParameterAveragingTrainingMaster,
    FixedThresholdAlgorithm, AdaptiveThresholdAlgorithm,
    TargetSparsityThresholdAlgorithm, ResidualClippingPostProcessor,
)
from deeplearning4j_tpu.parallel.sharding import (
    ZeroShardedUpdate, ManualZeroUpdate, dp_weight_update_bytes,
    compressed_wire_bytes, compressed_hlo_collective_bytes,
    COMPRESSION_MODES, replicate_params, shard_params, spec_for_param,
    DEFAULT_COMPRESSION_GROUP, default_compression_group,
    hierarchical_grad_exchange, hierarchical_mesh, hierarchical_shard_elems,
)
from deeplearning4j_tpu.parallel.sequence import ring_attention, ulysses_attention
from deeplearning4j_tpu.parallel.pipeline import PipelineParallel, partition_stages
from deeplearning4j_tpu.parallel.multihost import (
    initialize as initializeMultiHost, hybrid_mesh, is_coordinator, num_hosts,
)
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.parallel.spark import (
    SparkDl4jMultiLayer, SparkComputationGraph,
    ParameterAveragingTrainingMasterBuilder, SharedTrainingMasterBuilder,
)
from deeplearning4j_tpu.parallel.costmodel import (
    CHIPS, ChipSpec, DataParallelModel, all_reduce_time, all_gather_time,
    reduce_scatter_time, ppermute_time, resnet50_scaling,
)

__all__ = [
    "build_mesh", "data_parallel_mesh", "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS",
    "PIPE_AXIS", "ParallelWrapper", "SharedTrainingMaster",
    "ParameterAveragingTrainingMaster", "FixedThresholdAlgorithm",
    "AdaptiveThresholdAlgorithm", "TargetSparsityThresholdAlgorithm",
    "ResidualClippingPostProcessor", "shard_params",
    "replicate_params", "spec_for_param", "ZeroShardedUpdate",
    "ManualZeroUpdate", "dp_weight_update_bytes",
    "compressed_wire_bytes", "compressed_hlo_collective_bytes",
    "COMPRESSION_MODES", "GROUP_AXIS", "INTRA_AXIS",
    "DEFAULT_COMPRESSION_GROUP", "default_compression_group",
    "hierarchical_grad_exchange", "hierarchical_mesh",
    "hierarchical_shard_elems", "ring_attention", "ulysses_attention",
    "PipelineParallel", "partition_stages",
    "initializeMultiHost", "hybrid_mesh", "is_coordinator", "num_hosts",
    "ParallelInference",
    "SparkDl4jMultiLayer", "SparkComputationGraph",
    "ParameterAveragingTrainingMasterBuilder", "SharedTrainingMasterBuilder",
    "CHIPS", "ChipSpec", "DataParallelModel", "all_reduce_time",
    "all_gather_time", "reduce_scatter_time", "ppermute_time",
    "resnet50_scaling",
]

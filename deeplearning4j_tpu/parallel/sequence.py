"""Sequence / context parallelism for long sequences.

Reference: the reference has no sequence parallelism — its LSTM BPTT path
is bounded by single-GPU memory. This module is the TPU-first capability
that replaces it for long-context attention models:

  * ring_attention — blockwise attention where each chip holds a T/n slice
    of Q/K/V and K,V blocks rotate around the ICI ring via ppermute
    (Liu et al., Ring Attention; see PAPERS.md retrieval theme). Exact
    (not approximate) attention with O(T/n) memory per chip and
    communication overlapped with the block matmuls by XLA.
  * ulysses_attention — all-to-all style: resharding [seq-parallel] ->
    [head-parallel] around a local attention, communication O(T·E/n)
    (DeepSpeed-Ulysses pattern).

Both are shard_map programs over a mesh "seq" axis and compose with the
"data" axis for dp×sp training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel._compat import (
    axis_size as _compat_axis_size, shard_map,
)

from deeplearning4j_tpu.ops.attention import _block_attn
from deeplearning4j_tpu.parallel.mesh import SEQ_AXIS


def _ring_attention_local(q, k, v, axis_name, causal, chunk_index_fn=None):
    """Per-shard body: q,k,v are the local [B,H,Tl,D] slices."""
    n = _compat_axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, H, Tl, D = q.shape

    acc0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, Tl), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, Tl), q.dtype)

    q_pos = (my * Tl + jnp.arange(Tl))[:, None]

    def step(i, carry_kv):
        (acc, m, l), (kr, vr) = carry_kv
        # source shard of the kv block currently held: it has rotated i hops
        src = (my - i) % n
        mask = None
        if causal:
            k_pos = (src * Tl + jnp.arange(Tl))[None, :]
            mask = (q_pos >= k_pos)[None, None]
        acc, m, l = _block_attn(q, kr, vr, (acc, m, l), mask=mask)
        # rotate kv to the next chip on the ring (ICI neighbour exchange)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        return (acc, m, l), (kr, vr)

    carry = ((acc0, m0, l0), (k, v))
    carry = lax.fori_loop(0, n, step, carry)
    (acc, m, l), _ = carry
    return acc / l[..., None]


def ring_attention(q, k, v, mesh: Mesh, axis: str = SEQ_AXIS, causal: bool = False):
    """Exact distributed attention over sequence-sharded q,k,v [B,H,T,D]
    (T sharded over `axis`). Returns output with the same sharding."""
    spec = P(None, None, axis, None)

    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name, causal):
    """All-to-all resharding: [B, H/n local? ...]. Incoming shards are
    sequence-sharded [B,H,Tl,D]; all_to_all regroups to head-sharded
    [B,Hl,T,D], local full-T attention, then the reverse."""
    def seq_to_head(x):
        # [B,H,Tl,D] -> split H into n groups -> a2a over seq axis -> concat T
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    def head_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    from deeplearning4j_tpu.ops.attention import dot_product_attention

    o = dot_product_attention(qh, kh, vh, causal=causal)
    return head_to_seq(o)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = SEQ_AXIS, causal: bool = False):
    """DeepSpeed-Ulysses style sequence parallelism (requires H % n == 0)."""
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    return fn(q, k, v)

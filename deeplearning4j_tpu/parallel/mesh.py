"""Device mesh construction.

Reference: the reference's device topology handling is implicit in its
NCCL/Aeron transports (one process per GPU, ring discovered at runtime).
TPU-native design: an explicit jax.sharding.Mesh over named logical axes —
"data" (DP replicas), "model" (tensor parallel), "seq" (sequence/context
parallel). XLA lowers cross-axis reductions to ICI collectives; DCN vs ICI
routing follows the mesh's device order, so axes that communicate most
(model/seq) should map to devices on the same ICI domain — pass them last
so they're innermost (contiguous) in the device mesh.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
#: the 2-D factorization of a pure data-parallel mesh the hierarchical
#: gradient exchange runs over: "group" ranges over node groups (the
#: sparse leader hop), "intra" over the chips of one group (the dense/
#: quantized reduce-scatter hop). intra is INNERMOST so one group's
#: chips sit on contiguous (fastest-ICI) devices.
GROUP_AXIS = "group"
INTRA_AXIS = "intra"


def build_mesh(axes=None, devices=None) -> Mesh:
    """build_mesh({"data": 4, "model": 2}) -> Mesh of shape (4, 2).

    Axis sizes may include one -1 (filled from the device count). Innermost
    (last) axes get contiguous devices => fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devices)}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if len(devices) % known != 0:
            raise ValueError(
                f"Cannot infer -1 axis: {len(devices)} devices not divisible "
                f"by fixed axes product {known}")
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"Mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {len(devices)}")
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(n=None) -> Mesh:
    devs = jax.devices()
    return build_mesh({DATA_AXIS: n or len(devs)}, devs[: n or len(devs)])


def replicated(mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh, axis=DATA_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))

"""Distributed training wrappers.

Reference: two reference subsystems collapse into this module —
  * org.deeplearning4j.parallelism.ParallelWrapper (single-host multi-GPU:
    replicate model per device, average gradients),
  * the Spark gradient-sharing stack (SharedTrainingMaster /
    SharedTrainingWrapper + Aeron UDP threshold-encoded allreduce,
    Strom 2015).

TPU design: data parallelism is a SHARDING, not a worker framework. The
network's existing jitted train step is re-jitted with parameter/optimizer
shardings = replicated and batch shardings = split over the mesh "data"
axis; XLA's SPMD partitioner inserts the bf16 gradient all-reduce over ICI
(the role of NCCL/Aeron). Threshold encoding existed because Ethernet
allreduce was the bottleneck; dense bf16 over ICI is faster than any
host-side sparse encode/decode, so the default is dense. An optional int8
quantized allreduce (EQuARX-style, see PAPERS.md) is provided for
DCN-limited deployments via gradient_compression="int8" using an explicit
shard_map psum.

Determinism: batch stats (BN) and losses are computed over the GLOBAL
batch (GSPMD reduces across shards), so DP training at any width produces
the same result as single-device training on the combined batch — the
property the reference's parameter-averaging mode only approximates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import mesh as _mesh
from deeplearning4j_tpu.nn.multilayer import _unwrap


class ParallelWrapper:
    """Data-parallel trainer over a device mesh.

    Usage (reference ParallelWrapper.Builder parity):
        pw = ParallelWrapper(net)              # all local devices
        pw = ParallelWrapper(net, mesh=mesh)   # explicit mesh
        pw.fit(iterator)
    """

    def __init__(self, net, mesh=None, gradient_compression=None,
                 batch_axis=_mesh.DATA_AXIS, threshold=1e-3,
                 targetSparsity=None, weight_update="replicated",
                 min_shard_size=2 ** 16):
        if getattr(net, "_solver", None) is not None:
            raise ValueError(
                "distributed trainers require "
                "optimizationAlgo=STOCHASTIC_GRADIENT_DESCENT: a shard-"
                "local line search (LBFGS/CG) would accept a different "
                "step size on every replica and silently desynchronize "
                "the supposedly-replicated parameters")
        self.net = net
        self.mesh = mesh or _mesh.data_parallel_mesh()
        self.batch_axis = batch_axis
        self.gradient_compression = gradient_compression
        self.threshold = float(threshold)
        # reference: AdaptiveThresholdAlgorithm — adapt the threshold so
        # the transmitted fraction tracks this target (None = fixed)
        self.targetSparsity = None if targetSparsity is None \
            else float(targetSparsity)
        self._repl = NamedSharding(self.mesh, P())
        self._jit = None
        self._residual = None  # threshold mode: (error feedback, threshold)
        if gradient_compression not in (None, "int8", "threshold"):
            raise ValueError(
                "gradient_compression must be None, 'int8' or 'threshold'")
        if weight_update not in ("replicated", "sharded"):
            raise ValueError(
                "weight_update must be 'replicated' or 'sharded', got "
                f"{weight_update!r}")
        if weight_update == "sharded" and gradient_compression is not None:
            raise ValueError(
                f"weight_update='sharded' requires gradient_compression="
                f"None (got {gradient_compression!r}): the compressed "
                "steps run inside an explicit shard_map, where the "
                "GSPMD sharding annotations the ZeRO update relies on "
                "(reduce-scatter -> shard update -> all-gather) cannot "
                "apply. Use the dense psum path, or keep the update "
                "replicated.")
        self.weight_update = weight_update
        self.min_shard_size = int(min_shard_size)
        self._zero = None
        if weight_update == "sharded":
            from deeplearning4j_tpu.parallel.sharding import \
                ZeroShardedUpdate

            self._zero = ZeroShardedUpdate(
                self.mesh, axis=self.batch_axis,
                min_shard_size=self.min_shard_size)

    # ------------------------------------------------------------------
    def _shard_batch(self, arr):
        """Divisibility-checked batch placement (sharding.shard_batch:
        rejects indivisible batches naming the axis, never pads)."""
        from deeplearning4j_tpu.parallel.sharding import shard_batch

        if arr is None:
            return None
        return shard_batch(arr, self.mesh, batch_axis=self.batch_axis)

    def _place_replicated(self):
        """Move the net's params/opt/layer state onto the mesh: params
        and layer state replicated always; the updater state replicated
        (default) or in the ZeRO 1/dp-shard layout when
        weight_update='sharded' (the hook + sharded allocation live in
        _place_sharded_update). Idempotent — ResilientFit re-runs it
        after every checkpoint restore."""
        n = self.net
        n._params = jax.device_put(n._params, self._repl)
        n._states = jax.device_put(n._states, self._repl)
        if self._zero is not None:
            self._place_sharded_update()
        else:
            self._uninstall_sharded_update()
            n._upd_states = jax.device_put(n._upd_states, self._repl)

    def _uninstall_sharded_update(self):
        """Remove a PREVIOUS sharded-mode wrapper's ZeRO hook from the
        net and restore the canonical full-shape updater state: a stale
        `_update_impl` would keep running the sharded update against
        the old wrapper's mesh (and ParameterAveragingTrainingMaster's
        shard_map step would die deep in tracing on the flat-view
        state — exactly the failure its construction check exists to
        prevent)."""
        n = self.net
        if getattr(n, "_update_impl", None) is None:
            return
        unview = getattr(n, "_upd_state_unview", None)
        if unview is not None:
            n._upd_states = unview(n._upd_states)
        n._update_impl = None
        n._upd_state_unview = None

    def _update_units(self):
        """(key, updater, params) per trainable unit, both net types."""
        n = self.net
        if self._is_graph():
            return [(name, n._updaters[name], n._params[name])
                    for name in n._layer_names]
        return [(i, n._updaters[i], n._params[i])
                for i in range(len(n.layers))]

    def _place_sharded_update(self):
        """Install the ZeRO update hook and put the updater state into
        the sharded layout: a fresh net (iteration 0) ALLOCATES the
        state sharded — each chip only ever materialises its 1/dp shard
        of the fp32 moments — while mid-training state (including a
        restored checkpoint's canonical full-shape layout) is re-placed
        bitwise (the view is a reshape)."""
        n, z = self.net, self._zero
        n._update_impl = z
        n._upd_state_unview = self._unview_upd_states
        fresh = n._iteration == 0
        new = dict(n._upd_states) if self._is_graph() \
            else list(n._upd_states)
        for key, u, p in self._update_units():
            if not p:
                continue
            new[key] = z.init_state(u, p) if fresh \
                else z.place_state(n._upd_states[key])
        n._upd_states = new

    def _unview_upd_states(self, upd_states):
        """Sharded view layout -> the canonical full-shape updater-state
        layout (installed as net._upd_state_unview; checkpoints save the
        canonical form so a sharded-mode save restores into any mode
        bitwise — see util.sharded_checkpoint._net_state)."""
        z = self._zero
        new = dict(upd_states) if self._is_graph() else list(upd_states)
        for key, u, p in self._update_units():
            if not p:
                continue
            new[key] = z.unview_state(upd_states[key], u, p)
        return new

    def _aot_extra(self):
        """Key suffix describing program context the net's config hash
        cannot see: the mesh, the compression mode and the weight-update
        mode all change the traced program."""
        return (f"|pw[mesh={sorted(dict(self.mesh.shape).items())},"
                f"axis={self.batch_axis},"
                f"comp={self.gradient_compression},"
                f"wu={self.weight_update}]")

    def _build_jit(self):
        n = self.net
        if self.gradient_compression == "threshold":
            # per-replica residuals: leading device axis, sharded over the
            # mesh so each replica carries its own error feedback; the
            # (possibly adaptive) threshold rides along replicated
            ndev = self.mesh.shape[self.batch_axis]
            res = jax.device_put(
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros((ndev,) + p.shape, p.dtype),
                    n._params),
                NamedSharding(self.mesh, P(self.batch_axis)))
            t = jax.device_put(jnp.asarray(self.threshold, jnp.float32),
                               self._repl)
            self._residual = (res, t)
            # threshold mode threads adaptive residual state through a
            # different arity and its threshold value is trace-baked:
            # stays on the plain jit (no AOT caching)
            self._jit = jax.jit(self._threshold_step,
                                donate_argnums=(0, 1, 2, 3))
            return
        step = n._train_step if self.gradient_compression is None \
            else self._compressed_step
        # params/opt/state replicated; batch args sharded over the data
        # axis. Routed through the AOT executable cache (runtime.aot):
        # the extra key part carries the mesh/compression/update mode.
        from deeplearning4j_tpu.runtime import aot

        self._jit = aot.cached_jit(step, owner=n, entry="pw_train_step",
                                   extra=self._aot_extra(),
                                   donate_argnums=(0, 1, 2))

    def _compressed_step(self, params, upd_states, states, iteration, x, y,
                         key, fmask, lmask):
        """Train step with an explicit int8-quantized gradient all-reduce
        (EQuARX-style). Uses shard_map over the data axis so the quantize →
        psum → dequantize pipeline is expressed directly."""
        from deeplearning4j_tpu.parallel._compat import shard_map

        n = self.net
        mesh, ax = self.mesh, self.batch_axis

        def qall(g):
            scale = jnp.maximum(jnp.max(jnp.abs(g)), 1e-12)
            scale = jax.lax.pmax(scale, ax)
            q = jnp.clip(jnp.round(g / scale * 127.0), -127, 127).astype(jnp.int8)
            summed = jax.lax.psum(q.astype(jnp.int32), ax)
            return summed.astype(g.dtype) * (scale / 127.0) / jax.lax.psum(1, ax)

        def sync_states(states):
            # Per-shard batch stats (BN running mean/var) diverge across the
            # mesh; pmean keeps the returned "replicated" state consistent on
            # every device (cross-replica BN, mean-of-shard-stats).
            return jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, ax)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, states)

        def shard_step(params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s):
            return n._train_step(
                params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s,
                grad_transform=lambda g: jax.tree_util.tree_map(qall, g),
                loss_transform=lambda l: jax.lax.pmean(l, ax),
                state_transform=sync_states)

        spec_b = P(ax)
        return shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), P(), P(), P(), spec_b, spec_b, P(), spec_b if fmask is not None else P(),
                      spec_b if lmask is not None else P()),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,
        )(params, upd_states, states, iteration, x, y, key, fmask, lmask)

    def _threshold_step(self, params, upd_states, states, residual,
                        iteration, x, y, key, fmask, lmask):
        """Train step with threshold-encoded gradient sharing (reference:
        Strom 2015, the algorithm behind upstream SharedTrainingMaster's
        sparse updates). Each replica adds its residual to the fresh
        gradient, transmits only entries with |g| >= threshold — encoded
        as +-threshold — and keeps the remainder as next step's residual
        (error feedback). On ICI the "transmission" is a dense psum of
        the thresholded tensor: the sparse wire format upstream pairs
        with this algorithm is an Ethernet-era optimization, while the
        algorithm's semantics (sparsified, error-compensated updates)
        are preserved exactly."""
        from deeplearning4j_tpu.parallel._compat import shard_map

        n = self.net
        mesh, ax = self.mesh, self.batch_axis
        target = self.targetSparsity

        def sync_states(states):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, ax)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, states)

        def shard_step(params_r, upd_r, states_r, res_in, it_r, x_s, y_s,
                       key_r, fm_s, lm_s):
            res_s, t = res_in
            new_res_cell = []

            def encode_all(grads):
                g_leaves, treedef = jax.tree_util.tree_flatten(grads)
                r_leaves = jax.tree_util.tree_flatten(res_s)[0]
                means, new_rs = [], []
                sent = total = 0.0
                for g, r in zip(g_leaves, r_leaves):
                    acc = g + r[0].astype(g.dtype)  # drop local dev axis
                    hit = jnp.abs(acc) >= t.astype(g.dtype)
                    enc = jnp.where(hit,
                                    jnp.sign(acc) * t.astype(g.dtype),
                                    jnp.zeros((), g.dtype))
                    new_rs.append((acc - enc)[None].astype(r.dtype))
                    means.append(jax.lax.psum(enc, ax) / jax.lax.psum(1, ax))
                    sent = sent + jnp.sum(hit)
                    total = total + hit.size
                if target is None:
                    new_t = t
                else:
                    # adaptive threshold (reference:
                    # AdaptiveThresholdAlgorithm): multiplicative steps
                    # keep the mean transmitted fraction near the target
                    frac = jax.lax.pmean(sent / total, ax)
                    new_t = jnp.where(
                        frac > 1.25 * target, t * 1.1,
                        jnp.where(frac < 0.8 * target, t / 1.1, t))
                new_res_cell.append(
                    (jax.tree_util.tree_unflatten(treedef, new_rs),
                     new_t.astype(jnp.float32)))
                return jax.tree_util.tree_unflatten(treedef, means)

            out = n._train_step(
                params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s,
                grad_transform=encode_all,
                loss_transform=lambda l: jax.lax.pmean(l, ax),
                state_transform=sync_states)
            return out + (new_res_cell[0],)

        spec_b = P(ax)
        return shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), P(), P(), (spec_b, P()), P(), spec_b, spec_b,
                      P(),
                      spec_b if fmask is not None else P(),
                      spec_b if lmask is not None else P()),
            out_specs=(P(), P(), P(), P(), (spec_b, P())),
            check_vma=False,
        )(params, upd_states, states, residual, iteration, x, y, key,
          fmask, lmask)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs=None):
        from deeplearning4j_tpu.data.dataset import DataSet

        n = self.net
        n._require_init()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        if labels is not None:
            self._fit_batch(DataSet(data, labels))
            return self
        if isinstance(data, DataSet):
            self._fit_batch(data)
            return self
        for _ in range(epochs or 1):
            data.reset()
            while data.hasNext():
                self._fit_batch(data.next())
            n._epoch += 1
        return self

    def _is_graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return isinstance(self.net, ComputationGraph)

    def _fit_batch(self, ds):
        n = self.net
        x = _unwrap(ds.getFeatures())
        y = _unwrap(ds.getLabels())
        fmask = _unwrap(ds.getFeaturesMaskArray())
        lmask = _unwrap(ds.getLabelsMaskArray())
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        if self._is_graph():
            # ComputationGraph._train_step takes an inputs dict + labels
            # list (single-input/-output graphs through this wrapper)
            if len(n.conf.networkInputs) != 1 or len(n.conf.networkOutputs) != 1:
                raise ValueError(
                    "ParallelWrapper supports single-input/single-output "
                    "ComputationGraphs; use MultiDataSet-aware training "
                    "directly for multi-IO graphs")
            x = {n.conf.networkInputs[0]: x}
            y = [y]
            fmask = None if fmask is None else {n.conf.networkInputs[0]: fmask}
            lmask = None if lmask is None else [lmask]
        key = jax.random.fold_in(jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        if self._residual is not None:
            (n._params, n._upd_states, n._states, loss,
             self._residual) = self._jit(
                n._params, n._upd_states, n._states, self._residual,
                jnp.asarray(n._iteration, jnp.int32), x, y, key, fmask, lmask)
        else:
            n._params, n._upd_states, n._states, loss = self._jit(
                n._params, n._upd_states, n._states,
                jnp.asarray(n._iteration, jnp.int32), x, y, key, fmask, lmask)
        n._score = float(loss)
        n._iteration += 1
        for lst in n._listeners:
            lst.iterationDone(n, n._iteration, n._epoch)

    def fitDataSet(self, iterator, stepsPerSync=1, epochs=None):
        """Sharded form of MultiLayerNetwork.fitDataSet: k fresh batches
        are staged as ONE [k, B, ...] stack per component, placed with
        the batch dim sharded over the data axis (sharding.
        shard_batch_stack — the same divisibility-checked shard_batch
        every trainer uses, never padding), and trained by one jitted
        lax.fori_loop whose step i indexes a correctly-sharded global
        batch — GSPMD inserts the gradient collectives inside the loop.
        One host sync and one transfer per k batches; double-buffered
        staging; ragged tail through the per-batch sharded fit path.
        Supports gradient_compression None (dense psum via GSPMD) and
        'int8' (explicit shard_map allreduce)."""
        from deeplearning4j_tpu.data.iterators import stack_datasets
        from deeplearning4j_tpu.nn.multilayer import (
            fit_dataset_jit, run_fit_dataset_epoch)
        from deeplearning4j_tpu.parallel.sharding import shard_batch_stack

        n = self.net
        n._require_init()
        k = int(stepsPerSync)
        if k < 1:
            raise ValueError(f"stepsPerSync must be >= 1, got {k}")
        if k == 1:
            it0 = n._iteration
            self.fit(iterator, epochs=epochs)
            self._fit_dataset_syncs = n._iteration - it0  # 1/batch
            return self
        if self.gradient_compression == "threshold":
            raise ValueError(
                "fitDataSet supports gradient_compression None/'int8'; "
                "the 'threshold' step threads per-replica residual state "
                "through a different arity — use fit()")
        bp = getattr(n.conf, "backpropType", None)
        if bp == "tbptt" or str(getattr(bp, "name", bp)) == "TruncatedBPTT":
            raise ValueError(
                "fitDataSet does not support truncated BPTT; use fit()")
        if self._is_graph() and (len(n.conf.networkInputs) != 1
                                 or len(n.conf.networkOutputs) != 1):
            raise ValueError(
                "ParallelWrapper supports single-input/single-output "
                "ComputationGraphs")
        step = self.trainStep()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        jloop = fit_dataset_jit(n, k, step_fn=step, owner=self,
                                aot_extra=self._aot_extra())

        if self._is_graph():
            name = n.conf.networkInputs[0]

            def stack_fn(batches):
                x, y, fm, lm = stack_datasets(batches)
                return ({name: x}, [y],
                        None if fm is None else {name: fm},
                        None if lm is None else [lm])
        else:
            stack_fn = stack_datasets

        def place(staged):
            return shard_batch_stack(staged, self.mesh, self.batch_axis)

        self._fit_dataset_syncs = 0
        for _ in range(epochs or 1):
            iterator.reset()
            self._fit_dataset_syncs += run_fit_dataset_epoch(
                n, iterator, k, stack_fn, self._fit_batch, jloop,
                place=place)
            n._epoch += 1
        return self

    def precompile(self, batchSize=32, featuresShape=None,
                   labelsShape=None, cache=None):
        """AOT warm-start of the sharded train step (see
        MultiLayerNetwork.precompile): places the model on the mesh,
        builds the distributed step and compiles (or loads from the
        persistent cache) its executable for one GLOBAL batch
        signature. Composes with weight_update='sharded' — the ZeRO
        layout is part of the cache key, and the updater state is
        allocated sharded before the warm lowering, exactly as fit()
        would. The threshold-compression mode is not cacheable (its
        step threads residual state); precompile returns {} there."""
        from deeplearning4j_tpu.nn.multilayer import example_batch

        n = self.net
        n._require_init()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        if not hasattr(self._jit, "warm"):
            return {}
        if self._is_graph():
            featuresShape, labelsShape = n._example_shapes(
                batchSize, featuresShape, labelsShape)
            x = np.zeros(featuresShape, np.float32)
            y = np.zeros(labelsShape, np.float32)
        else:
            x, y = example_batch(n, batchSize, featuresShape,
                                 labelsShape)
        x = self._shard_batch(jnp.asarray(x))
        y = self._shard_batch(jnp.asarray(y))
        if self._is_graph():
            x = {n.conf.networkInputs[0]: x}
            y = [y]
        key = jax.random.fold_in(
            jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        res = self._jit.warm(
            n._params, n._upd_states, n._states,
            jnp.asarray(n._iteration, jnp.int32), x, y, key, None, None,
            cache=cache)
        k_, status, secs = res
        return {} if status is None else {
            "pw_train_step": {"key": k_, "status": status,
                              "seconds": round(secs, 3)}}

    def trainStep(self):
        """The un-jitted per-batch step function with the canonical
        `(params, upd, states, it, x, y, key, fmask, lmask) ->
        (params', upd', states', loss)` signature, for harnesses that
        splice logic around it before jitting — runtime.resilience
        wraps it in the non-finite guard. The threshold mode threads a
        residual through the step (a different arity), so it cannot be
        guarded this way."""
        if self.gradient_compression is None:
            return self.net._train_step
        if self.gradient_compression == "int8":
            return self._compressed_step
        raise ValueError(
            "trainStep() supports gradient_compression None/'int8'; the "
            "'threshold' step carries per-replica residual state and is "
            "not wrappable — run it without the non-finite guard")

    def averagingFrequency(self, *_):
        # synchronous psum makes per-step averaging exact already; the
        # reference's periodic-averaging semantics live in
        # ParameterAveragingTrainingMaster below
        return self

    def workers(self, *_):
        return self


class SharedTrainingMaster(ParallelWrapper):
    """Gradient-sharing distributed trainer (reference: Spark
    SharedTrainingMaster). Alias of ParallelWrapper with the quantized
    all-reduce enabled by default — the ICI-native analog of the
    reference's threshold-encoded sparse updates. Pass
    ``gradient_compression=None`` for the dense bf16 psum, or
    ``"threshold"`` for the reference's actual Strom-2015 algorithm
    (sparsified +-threshold updates with per-replica error feedback —
    see ParallelWrapper._threshold_step)."""

    def __init__(self, net, mesh=None, thresholdAlgorithm=None, **kw):
        if thresholdAlgorithm is not None:
            # parity with upstream's ThresholdAlgorithm arg: a number (or
            # object with .threshold) selects the Strom encoding
            gc = kw.get("gradient_compression", "threshold")
            if gc != "threshold":
                raise ValueError(
                    f"thresholdAlgorithm given together with "
                    f"gradient_compression={gc!r}: the threshold algorithm "
                    "only applies to the 'threshold' (Strom-2015) encoding; "
                    "drop one of the two arguments")
            kw.setdefault("gradient_compression", "threshold")
            kw.setdefault("threshold",
                          getattr(thresholdAlgorithm, "threshold",
                                  thresholdAlgorithm))
        if kw.get("weight_update") == "sharded":
            # the ZeRO update needs the dense GSPMD psum path; asking for
            # it implies opting out of this master's int8 default
            kw.setdefault("gradient_compression", None)
        kw.setdefault("gradient_compression", "int8")
        super().__init__(net, mesh=mesh, **kw)


class ParameterAveragingTrainingMaster(ParallelWrapper):
    """Parameter-averaging distributed trainer (reference: Spark
    ParameterAveragingTrainingMaster.java). Each data-shard replica takes
    LOCAL updater steps on its own copy of the parameters — no per-step
    gradient allreduce — and every ``averagingFrequency`` iterations the
    parameters, updater state and layer state are averaged across the mesh
    (``pmean`` over ICI plays the role of the Spark driver's aggregate).

    With ``averagingFrequency=1`` and plain SGD this is mathematically
    identical to synchronous gradient sharing; larger frequencies trade
    fidelity for fewer collectives, exactly the reference's knob.
    """

    def __init__(self, net, mesh=None, averagingFrequency=5,
                 batch_axis=_mesh.DATA_AXIS, weight_update="replicated"):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(net, ComputationGraph):
            raise ValueError(
                "ParameterAveragingTrainingMaster supports "
                "MultiLayerNetwork; for ComputationGraph data-parallel "
                "training use ParallelWrapper/SharedTrainingMaster "
                "(single-input/-output graphs)")
        if weight_update == "sharded":
            # reject HERE, not deep in jit tracing: this master keeps a
            # PER-REPLICA stacked copy of params+updater state (local
            # steps, periodic pmean) — there is no single cross-replica
            # update to shard, and the stacked state's leading replica
            # axis would collide with the ZeRO flat-shard views
            raise ValueError(
                "ParameterAveragingTrainingMaster does not support "
                "weight_update='sharded': its replicas take LOCAL "
                "updater steps on per-replica state, so there is no "
                "cross-replica weight update to shard. The ZeRO-style "
                "sharded update is supported by ParallelWrapper and "
                "SharedTrainingMaster(gradient_compression=None).")
        super().__init__(net, mesh=mesh, batch_axis=batch_axis,
                         weight_update=weight_update)
        if int(averagingFrequency) < 1:
            raise ValueError("averagingFrequency must be >= 1")
        self._avg_freq = int(averagingFrequency)
        self._stacked = None  # (params, upd_states, states) + replica axis

    def trainStep(self):
        raise ValueError(
            "ParameterAveragingTrainingMaster's step is not expressible "
            "as one wrappable train step: it takes LOCAL per-replica "
            "steps on stacked state with a periodic pmean, all inside "
            "its own _fit_batch. Wrap ParallelWrapper/"
            "SharedTrainingMaster in ResilientFit instead, or run this "
            "master without the non-finite guard")

    def fitDataSet(self, iterator, stepsPerSync=1, epochs=None):
        if int(stepsPerSync) == 1:
            return self.fit(iterator, epochs=epochs)
        raise ValueError(
            "ParameterAveragingTrainingMaster does not support "
            "stepsPerSync > 1: it picks a different executable per "
            "iteration host-side (averaging vs local step), which a "
            "single traced k-loop cannot express without paying the "
            "full-state pmean every step; use ParallelWrapper/"
            "SharedTrainingMaster for the k-stack loop")

    def averagingFrequency(self, k):
        if self._jit is not None:
            raise RuntimeError("set averagingFrequency before the first fit()")
        if int(k) < 1:
            raise ValueError("averagingFrequency must be >= 1")
        self._avg_freq = int(k)
        return self

    # ------------------------------------------------------------------
    def _place_replicated(self):
        """Give every replica its own (initially identical) copy: stack each
        leaf along a leading replica axis sharded over the data axis."""
        # a net previously trained under a sharded-update wrapper must
        # shed the ZeRO hook + flat-view state before stacking
        self._uninstall_sharded_update()
        n, dp = self.net, self.mesh.shape[self.batch_axis]

        def stack(tree):
            def one(a):
                a = jnp.asarray(a)
                sh = NamedSharding(self.mesh,
                                   P(self.batch_axis, *([None] * a.ndim)))
                return jax.device_put(jnp.stack([a] * dp), sh)
            return jax.tree_util.tree_map(one, tree)

        self._stacked = (stack(n._params), stack(n._upd_states),
                         stack(n._states))

    def _build_jit(self):
        from deeplearning4j_tpu.parallel._compat import shard_map

        n, mesh, ax = self.net, self.mesh, self.batch_axis

        def make_step(do_avg):
            # two step variants chosen HOST-side by the iteration counter:
            # the averaging collective only exists in the executable that
            # runs at averaging points — a traced jnp.where would make XLA
            # pay the full pmean of params+opt+state every single step,
            # which is exactly the traffic this mode exists to avoid
            def shard_step(params, upd, states, it, x, y, key, fm, lm):
                sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
                params, upd, states = sq(params), sq(upd), sq(states)
                # decorrelate per-replica dropout like distinct Spark workers
                key = jax.random.fold_in(key, jax.lax.axis_index(ax))
                p, u, s, loss = n._train_step(params, upd, states, it, x, y,
                                              key, fm, lm)
                if do_avg:
                    avg = lambda t: jax.tree_util.tree_map(
                        lambda a: jax.lax.pmean(a, ax)
                        if jnp.issubdtype(a.dtype, jnp.inexact) else a, t)
                    p, u, s = avg(p), avg(u), avg(s)
                loss = jax.lax.pmean(loss, ax)
                ex = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
                return ex(p), ex(u), ex(s), loss

            def step(params, upd, states, it, x, y, key, fm, lm):
                spec_b = P(ax)
                return shard_map(
                    shard_step, mesh=mesh,
                    in_specs=(spec_b, spec_b, spec_b, P(), spec_b, spec_b, P(),
                              spec_b if fm is not None else P(),
                              spec_b if lm is not None else P()),
                    out_specs=(spec_b, spec_b, spec_b, P()),
                    check_vma=False,
                )(params, upd, states, it, x, y, key, fm, lm)

            return jax.jit(step, donate_argnums=(0, 1, 2))

        self._jit = make_step(False)
        self._jit_avg = make_step(True)

    def _fit_batch(self, ds):
        from deeplearning4j_tpu.nn.multilayer import _unwrap as unw

        n = self.net
        x, y = unw(ds.getFeatures()), unw(ds.getLabels())
        fmask, lmask = unw(ds.getFeaturesMaskArray()), unw(ds.getLabelsMaskArray())
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        key = jax.random.fold_in(jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        p, u, s = self._stacked
        step = self._jit_avg if (n._iteration + 1) % self._avg_freq == 0 \
            else self._jit
        p, u, s, loss = step(p, u, s, jnp.asarray(n._iteration, jnp.int32),
                             x, y, key, fmask, lmask)
        self._stacked = (p, u, s)
        n._score = float(loss)
        n._iteration += 1
        for lst in n._listeners:
            lst.iterationDone(n, n._iteration, n._epoch)

    def fit(self, data, labels=None, epochs=None):
        super().fit(data, labels, epochs)
        self._sync_to_net()
        return self

    def _sync_to_net(self):
        """Expose the replica-average as the net's canonical model (the
        reference's driver-side aggregated model)."""
        if self._stacked is None:
            return

        def collapse(tree):
            return jax.tree_util.tree_map(
                lambda a: a.mean(0) if jnp.issubdtype(a.dtype, jnp.inexact)
                else a[0], tree)

        n = self.net
        p, u, s = self._stacked
        n._params, n._upd_states, n._states = collapse(p), collapse(u), collapse(s)

"""Distributed training wrappers.

Reference: two reference subsystems collapse into this module —
  * org.deeplearning4j.parallelism.ParallelWrapper (single-host multi-GPU:
    replicate model per device, average gradients),
  * the Spark gradient-sharing stack (SharedTrainingMaster /
    SharedTrainingWrapper + Aeron UDP threshold-encoded allreduce,
    Strom 2015).

TPU design: data parallelism is a SHARDING, not a worker framework. The
network's existing jitted train step is re-jitted with parameter/optimizer
shardings = replicated and batch shardings = split over the mesh "data"
axis; XLA's SPMD partitioner inserts the bf16 gradient all-reduce over ICI
(the role of NCCL/Aeron). Threshold encoding existed because Ethernet
allreduce was the bottleneck; dense bf16 over ICI is faster than any
host-side sparse encode/decode, so the default is dense. For DCN-limited
deployments three compressed modes are selectable per config, each an
explicit shard_map program with a statically billed bytes-on-wire
contract (parallel.sharding.compressed_wire_bytes):

  gradient_compression="int8"        per-tensor-scale quantized allreduce
  gradient_compression="block_int8"  per-BLOCK-scale quantized allreduce
                                     (EQuARX-style, PAPERS.md
                                     arXiv:2506.17615) — tighter scales,
                                     same wire bytes + a small scale
                                     side channel
  gradient_compression="threshold"   Strom-2015 sparse sign encoding
                                     with per-replica error-feedback
                                     residuals, fixed-capacity top-|g|
                                     encoding so shapes stay static and
                                     the step remains ONE jitted
                                     executable; the residual rides the
                                     donated updater-state carry (and
                                     therefore fitDataSet's k-loop and
                                     ResilientFit checkpoints)

"int8"/"block_int8" compose with weight_update="sharded": the gradient
reduction becomes a QUANTIZED reduce-scatter and the optimizer runs on
the local 1/dp shard (parallel.sharding.ManualZeroUpdate).

Determinism: batch stats (BN) and losses are computed over the GLOBAL
batch (GSPMD reduces across shards), so DP training at any width produces
the same result as single-device training on the combined batch — the
property the reference's parameter-averaging mode only approximates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel import mesh as _mesh
from deeplearning4j_tpu.nn.multilayer import _unwrap


# ----------------------------------------------------------------------
# threshold-algorithm configs (reference: org.nd4j.parameterserver
# ThresholdAlgorithm implementations) — SharedTrainingMaster maps these
# to real trainer config instead of passing an opaque kwarg through
# ----------------------------------------------------------------------

class FixedThresholdAlgorithm:
    """A constant Strom threshold tau (reference:
    FixedThresholdAlgorithm)."""

    def __init__(self, threshold):
        self.threshold = float(threshold)


class AdaptiveThresholdAlgorithm:
    """Adapt tau multiplicatively so the mean transmitted fraction
    tracks `sparsityTarget` (reference: AdaptiveThresholdAlgorithm)."""

    def __init__(self, initialThreshold=1e-3, sparsityTarget=1e-2):
        self.threshold = float(initialThreshold)
        self.sparsityTarget = float(sparsityTarget)


class TargetSparsityThresholdAlgorithm(AdaptiveThresholdAlgorithm):
    """Alias of the adaptive algorithm with the target spelled first
    (reference: TargetSparsityThresholdAlgorithm)."""

    def __init__(self, sparsityTarget=1e-2, initialThreshold=1e-3):
        super().__init__(initialThreshold, sparsityTarget)


class ResidualClippingPostProcessor:
    """Clip the error-feedback residual to +-(clipValue * tau) every
    `frequency` iterations (reference:
    ResidualClippingPostProcessor) — bounds how much stale gradient a
    slow-moving coordinate can accumulate."""

    def __init__(self, clipValue=5.0, frequency=1):
        self.clipValue = float(clipValue)
        self.frequency = int(frequency)
        if self.clipValue <= 0:
            raise ValueError(
                f"clipValue must be > 0, got {clipValue}")
        if self.frequency < 1:
            raise ValueError(
                f"frequency must be >= 1, got {frequency}")


#: the named threshold algorithms SharedTrainingMaster accepts (a bare
#: number is shorthand for FixedThresholdAlgorithm)
THRESHOLD_ALGORITHMS = (FixedThresholdAlgorithm,
                        AdaptiveThresholdAlgorithm,
                        TargetSparsityThresholdAlgorithm)

#: the packed updater-state carry of the threshold step: the canonical
#: (params, upd, states, it, ...) signature is preserved by riding the
#: error-feedback residual and the live tau INSIDE the donated upd slot
_PACK_KEYS = frozenset({"upd", "ef", "tau"})


def _is_packed(upd):
    return isinstance(upd, dict) and set(upd.keys()) == _PACK_KEYS


class ParallelWrapper:
    """Data-parallel trainer over a device mesh.

    Usage (reference ParallelWrapper.Builder parity):
        pw = ParallelWrapper(net)              # all local devices
        pw = ParallelWrapper(net, mesh=mesh)   # explicit mesh
        pw.fit(iterator)
    """

    def __init__(self, net, mesh=None, gradient_compression=None,
                 batch_axis=_mesh.DATA_AXIS, threshold=1e-3,
                 targetSparsity=None, weight_update="replicated",
                 min_shard_size=2 ** 16, encodingCapacity=None,
                 residualClip=None, residualClipFrequency=1,
                 compressionBlock=None, compressionGroupSize=None,
                 intraGroupCompression="block_int8"):
        from deeplearning4j_tpu.parallel.sharding import (
            COMPRESSION_MODES, DEFAULT_COMPRESSION_BLOCK,
            DEFAULT_ENCODING_CAPACITY, default_compression_group,
            hierarchical_mesh,
        )

        if getattr(net, "_solver", None) is not None:
            raise ValueError(
                "distributed trainers require "
                "optimizationAlgo=STOCHASTIC_GRADIENT_DESCENT: a shard-"
                "local line search (LBFGS/CG) would accept a different "
                "step size on every replica and silently desynchronize "
                "the supposedly-replicated parameters")
        self.net = net
        self.mesh = mesh or _mesh.data_parallel_mesh()
        self.batch_axis = batch_axis
        self.gradient_compression = gradient_compression
        self.threshold = float(threshold)
        if gradient_compression in ("threshold", "hierarchical") \
                and self.threshold <= 0:
            raise ValueError(
                f"threshold (tau) must be > 0, got {threshold}: the "
                "Strom encoder transmits sign(g)*tau, so a non-positive "
                "tau would negate (or zero) every transmitted update")
        # reference: AdaptiveThresholdAlgorithm — adapt the threshold so
        # the transmitted fraction tracks this target (None = fixed)
        self.targetSparsity = None if targetSparsity is None \
            else float(targetSparsity)
        # fixed-capacity encoding: the threshold step may transmit at
        # most ceil(capacity * n) entries per leaf per step (static
        # shapes — one executable). Auto (None) leaves headroom over an
        # adaptive sparsity target.
        if encodingCapacity is None:
            cap = DEFAULT_ENCODING_CAPACITY if self.targetSparsity is None \
                else max(DEFAULT_ENCODING_CAPACITY,
                         min(1.0, 2.0 * self.targetSparsity))
        else:
            cap = float(encodingCapacity)
            if not 0.0 < cap <= 1.0:
                raise ValueError(
                    f"encodingCapacity must be in (0, 1], got {cap}")
            if self.targetSparsity is not None \
                    and self.targetSparsity > cap:
                raise ValueError(
                    f"targetSparsity {self.targetSparsity} exceeds "
                    f"encodingCapacity {cap}: the fixed-capacity "
                    "encoder can never transmit more than the capacity "
                    "fraction, so the adaptive threshold could not "
                    "reach its target")
        self.encoding_capacity = cap
        self.residual_clip = None if residualClip is None \
            else float(residualClip)
        self.residual_clip_frequency = int(residualClipFrequency)
        if self.residual_clip is not None and self.residual_clip <= 0:
            raise ValueError(
                f"residualClip must be > 0, got {residualClip}")
        if self.residual_clip_frequency < 1:
            raise ValueError(
                "residualClipFrequency must be >= 1, got "
                f"{residualClipFrequency}")
        self.compression_block = DEFAULT_COMPRESSION_BLOCK \
            if compressionBlock is None else int(compressionBlock)
        if self.compression_block < 1:
            raise ValueError(
                f"compressionBlock must be >= 1, got {compressionBlock}")
        self._repl = NamedSharding(self.mesh, P())
        self._jit = None
        if gradient_compression not in COMPRESSION_MODES:
            raise ValueError(
                "gradient_compression must be one of "
                f"{COMPRESSION_MODES}, got {gradient_compression!r}")
        if intraGroupCompression not in (None, "block_int8"):
            raise ValueError(
                "intraGroupCompression must be None (dense hop-1 "
                "reduce-scatter) or 'block_int8', got "
                f"{intraGroupCompression!r}")
        self.intra_compression = intraGroupCompression
        self._hmesh = None
        self._n_groups = None
        self.compression_group = None
        if gradient_compression == "hierarchical":
            dp = self.mesh.shape.get(self.batch_axis, 0)
            gsz = default_compression_group(dp) \
                if compressionGroupSize is None else int(compressionGroupSize)
            # hierarchical_mesh does the loud validation (divisibility,
            # 1-D pure-data mesh, g >= 2)
            self._hmesh = hierarchical_mesh(
                self.mesh, gsz, batch_axis=self.batch_axis)
            self._n_groups = dp // gsz
            self.compression_group = gsz
            # ONE mesh everywhere in hierarchical mode: placements and
            # the shard_map step must agree on the (group, intra) mesh,
            # or every step would reshard through a mesh change
            self._repl = NamedSharding(self._hmesh, P())
        elif compressionGroupSize is not None:
            raise ValueError(
                f"compressionGroupSize given together with "
                f"gradient_compression={gradient_compression!r}: the "
                "node-group size only applies to the 'hierarchical' "
                "2-hop exchange; drop one of the two arguments")
        if weight_update not in ("replicated", "sharded"):
            raise ValueError(
                "weight_update must be 'replicated' or 'sharded', got "
                f"{weight_update!r}")
        if weight_update == "sharded" \
                and gradient_compression in ("threshold", "hierarchical"):
            raise ValueError(
                "weight_update='sharded' composes with "
                "gradient_compression None/'int8'/'block_int8' "
                "(compressed reduce-scatter -> 1/dp shard update -> "
                "all-gather), but not "
                f"{gradient_compression!r}: the Strom exchange's "
                "per-replica error-feedback residual transmits sparse "
                "all-gathered messages, which have no per-parameter "
                "reduce-scatter form. Pick 'int8'/'block_int8', or "
                "keep the update replicated.")
        if gradient_compression in ("int8", "block_int8") \
                and weight_update == "sharded" \
                and getattr(net.conf, "gradientNormalization", None) \
                is not None:
            raise ValueError(
                "gradient normalization is applied to the REDUCED "
                "gradient, but the compressed sharded update "
                "reduce-scatters inside the weight-update hook — the "
                "normalization would see per-replica gradients and "
                "silently change semantics. Drop gradientNormalization "
                "or use weight_update='replicated'.")
        self.weight_update = weight_update
        self.min_shard_size = int(min_shard_size)
        self._zero = None
        if weight_update == "sharded":
            from deeplearning4j_tpu.parallel.sharding import \
                ZeroShardedUpdate

            self._zero = ZeroShardedUpdate(
                self.mesh, axis=self.batch_axis,
                min_shard_size=self.min_shard_size)

    @property
    def _residual(self):
        """Threshold mode's (error-feedback tree, live tau) — carried
        INSIDE the packed updater state (the donated step carry), so
        fitDataSet's k-loop and ResilientFit checkpoints see it for
        free. None outside threshold mode / before placement."""
        u = getattr(self.net, "_upd_states", None)
        if _is_packed(u):
            return (u["ef"], u["tau"])
        return None

    # ------------------------------------------------------------------
    def _shard_batch(self, arr):
        """Divisibility-checked batch placement (sharding.shard_batch:
        rejects indivisible batches naming the axis, never pads).
        Hierarchical mode shards over BOTH factor axes of the 2-D
        (group, intra) mesh — same device order, same per-chip rows as
        the flat data mesh, but placed on the mesh the step runs on."""
        from deeplearning4j_tpu.parallel.sharding import shard_batch

        if arr is None:
            return None
        if self._hmesh is not None:
            return shard_batch(
                arr, self._hmesh,
                batch_axis=(_mesh.GROUP_AXIS, _mesh.INTRA_AXIS))
        return shard_batch(arr, self.mesh, batch_axis=self.batch_axis)

    def _place_replicated(self):
        """Move the net's params/opt/layer state onto the mesh: params
        and layer state replicated always; the updater state replicated
        (default) or in the ZeRO 1/dp-shard layout when
        weight_update='sharded' (the hook + sharded allocation live in
        _place_sharded_update). Idempotent — ResilientFit re-runs it
        after every checkpoint restore."""
        n = self.net
        n._params = jax.device_put(n._params, self._repl)
        n._states = jax.device_put(n._states, self._repl)
        if self.gradient_compression == "threshold":
            self._uninstall_sharded_update()
            self._pack_threshold_state()
            return
        if self.gradient_compression == "hierarchical":
            self._uninstall_sharded_update()
            self._pack_hier_state()
            return
        self._unpack_threshold_state()
        if self._zero is not None:
            self._place_sharded_update()
        else:
            self._uninstall_sharded_update()
            n._upd_states = jax.device_put(n._upd_states, self._repl)

    # ----- threshold mode: the packed residual carry -------------------
    def _pack_threshold_state(self):
        """Wrap the net's updater state as {'upd', 'ef', 'tau'}: the
        per-replica error-feedback residual (leading [dp] device axis,
        sharded over the data axis) and the LIVE tau ride the donated
        updater-state slot, so the step keeps the canonical
        (params, upd, states, ...) signature — one jitted executable,
        k-loop carry and ResilientFit guard/checkpoints all for free.
        Re-placement of an already-packed state (checkpoint restore,
        repeated _place_replicated) is bitwise."""
        n = self.net
        ndev = self.mesh.shape[self.batch_axis]
        ef_sh = NamedSharding(self.mesh, P(self.batch_axis))
        if _is_packed(n._upd_states):
            pack = n._upd_states
            self._check_carry_layout(
                pack, lambda p: (ndev,) + p.shape, "threshold")
            upd = jax.device_put(pack["upd"], self._repl)
            ef = jax.device_put(pack["ef"], ef_sh)
            tau = jax.device_put(jnp.asarray(pack["tau"], jnp.float32),
                                 self._repl)
        else:
            upd = jax.device_put(n._upd_states, self._repl)
            ef = jax.device_put(
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros((ndev,) + p.shape, p.dtype),
                    n._params), ef_sh)
            tau = jax.device_put(jnp.asarray(self.threshold, jnp.float32),
                                 self._repl)
        n._upd_states = {"upd": upd, "ef": ef, "tau": tau}
        # checkpoints save the CANONICAL plain updater state here; the
        # residual itself is saved separately (writeModel trainer_state
        # — see _ckpt_trainer_state) so a threshold-mode save still
        # restores into any mode
        n._upd_state_unview = (
            lambda packed: packed["upd"] if _is_packed(packed) else packed)

    def _check_carry_layout(self, pack, expect_shape, mode):
        """Refuse to re-place a packed {upd, ef, tau} carry whose
        residual layout belongs to the OTHER sparse mode: flat threshold
        carries per-replica full-shape residuals [dp, *p.shape],
        hierarchical carries per-chip shard residuals [groups, group,
        m]. Silently re-placing one as the other would device_put
        garbage into the step."""
        ef_leaves = jax.tree_util.tree_leaves(pack["ef"])
        p_leaves = jax.tree_util.tree_leaves(self.net._params)
        for e, p in zip(ef_leaves, p_leaves):
            want = tuple(expect_shape(p))
            if tuple(e.shape) != want:
                raise ValueError(
                    f"packed residual carry has leaf shape {tuple(e.shape)} "
                    f"where gradient_compression={mode!r} expects {want}: "
                    "the carry was packed by the other sparse mode "
                    "(flat 'threshold' vs 'hierarchical' residual "
                    "layouts are incompatible). Re-fit from a plain "
                    "updater state, or restore a checkpoint taken in "
                    "the same mode.")

    def _pack_hier_state(self):
        """Hierarchical-mode packed carry: same {'upd', 'ef', 'tau'}
        shape as the flat threshold mode, but the error-feedback
        residual lives where hop 2 encodes — the per-chip 1/group_size
        shard of each (zero-padded) leaf, laid out [n_groups,
        group_size, shard_elems] and sharded over BOTH mesh axes, so the
        shard_map step sees exactly its local f32 residual row."""
        from deeplearning4j_tpu.parallel.sharding import \
            hierarchical_shard_elems

        n = self.net
        gsz, ng = self.compression_group, self._n_groups
        ef_sh = NamedSharding(
            self._hmesh, P(_mesh.GROUP_AXIS, _mesh.INTRA_AXIS))
        if _is_packed(n._upd_states):
            pack = n._upd_states
            self._check_carry_layout(
                pack,
                lambda p: (ng, gsz, hierarchical_shard_elems(p.size, gsz)),
                "hierarchical")
            upd = jax.device_put(pack["upd"], self._repl)
            ef = jax.device_put(pack["ef"], ef_sh)
            tau = jax.device_put(jnp.asarray(pack["tau"], jnp.float32),
                                 self._repl)
        else:
            upd = jax.device_put(n._upd_states, self._repl)
            ef = jax.device_put(
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros(
                        (ng, gsz, hierarchical_shard_elems(p.size, gsz)),
                        jnp.float32),
                    n._params), ef_sh)
            tau = jax.device_put(jnp.asarray(self.threshold, jnp.float32),
                                 self._repl)
        n._upd_states = {"upd": upd, "ef": ef, "tau": tau}
        n._upd_state_unview = (
            lambda packed: packed["upd"] if _is_packed(packed) else packed)

    def _unpack_threshold_state(self):
        """Drop a PREVIOUS threshold-mode wrapper's packed carry: restore
        the plain updater state and clear the unview hook, so dense/int8
        wrappers (and the net's own fit) see the canonical layout."""
        n = self.net
        if not _is_packed(getattr(n, "_upd_states", None)):
            return
        n._upd_states = n._upd_states["upd"]
        n._upd_state_unview = None

    def _ckpt_trainer_state(self):
        """The trainer-owned step state a checkpoint must persist for a
        bitwise resume — threshold mode's error-feedback residual and
        live tau (util.sharded_checkpoint writeModel trainer_state=...).
        None when the mode carries no such state."""
        u = getattr(self.net, "_upd_states", None)
        if _is_packed(u):
            return {"ef": u["ef"], "tau": u["tau"]}
        return None

    def _restore_trainer_state(self, state):
        """Install a checkpoint's trainer state into the packed carry
        (call after _place_replicated has packed fresh zeros)."""
        if state is None:
            return
        n = self.net
        if not _is_packed(n._upd_states):
            raise ValueError(
                "restoring sparse-exchange trainer state needs "
                "gradient_compression='threshold' or 'hierarchical' "
                "(the packed carry is not installed)")
        if self._hmesh is not None:
            from deeplearning4j_tpu.parallel.sharding import \
                hierarchical_shard_elems

            gsz, ng = self.compression_group, self._n_groups
            self._check_carry_layout(
                state,
                lambda p: (ng, gsz, hierarchical_shard_elems(p.size, gsz)),
                "hierarchical")
            ef_sh = NamedSharding(
                self._hmesh, P(_mesh.GROUP_AXIS, _mesh.INTRA_AXIS))
        else:
            ndev = self.mesh.shape[self.batch_axis]
            self._check_carry_layout(
                state, lambda p: (ndev,) + p.shape, "threshold")
            ef_sh = NamedSharding(self.mesh, P(self.batch_axis))
        n._upd_states = {
            "upd": n._upd_states["upd"],
            "ef": jax.device_put(state["ef"], ef_sh),
            "tau": jax.device_put(jnp.asarray(state["tau"], jnp.float32),
                                  self._repl),
        }

    def _uninstall_sharded_update(self):
        """Remove a PREVIOUS sharded-mode wrapper's ZeRO hook from the
        net and restore the canonical full-shape updater state: a stale
        `_update_impl` would keep running the sharded update against
        the old wrapper's mesh (and ParameterAveragingTrainingMaster's
        shard_map step would die deep in tracing on the flat-view
        state — exactly the failure its construction check exists to
        prevent)."""
        n = self.net
        if getattr(n, "_update_impl", None) is None:
            return
        unview = getattr(n, "_upd_state_unview", None)
        if unview is not None:
            n._upd_states = unview(n._upd_states)
        n._update_impl = None
        n._upd_state_unview = None

    def _update_units(self):
        """(key, updater, params) per trainable unit, both net types."""
        n = self.net
        if self._is_graph():
            return [(name, n._updaters[name], n._params[name])
                    for name in n._layer_names]
        return [(i, n._updaters[i], n._params[i])
                for i in range(len(n.layers))]

    def _place_sharded_update(self):
        """Install the ZeRO update hook and put the updater state into
        the sharded layout: a fresh net (iteration 0) ALLOCATES the
        state sharded — each chip only ever materialises its 1/dp shard
        of the fp32 moments — while mid-training state (including a
        restored checkpoint's canonical full-shape layout) is re-placed
        bitwise (the view is a reshape)."""
        n, z = self.net, self._zero
        if self.gradient_compression is None:
            n._update_impl = z
        else:
            # compressed modes trace inside an explicit shard_map where
            # GSPMD annotations cannot apply: the manual twin runs the
            # QUANTIZED reduce-scatter -> local 1/dp shard update ->
            # all-gather with the same eligibility and state layout
            from deeplearning4j_tpu.parallel.sharding import \
                ManualZeroUpdate

            n._update_impl = ManualZeroUpdate(
                z, self.gradient_compression, self.compression_block)
        n._upd_state_unview = self._unview_upd_states
        fresh = n._iteration == 0
        new = dict(n._upd_states) if self._is_graph() \
            else list(n._upd_states)
        for key, u, p in self._update_units():
            if not p:
                continue
            new[key] = z.init_state(u, p) if fresh \
                else z.place_state(n._upd_states[key])
        n._upd_states = new

    def _unview_upd_states(self, upd_states):
        """Sharded view layout -> the canonical full-shape updater-state
        layout (installed as net._upd_state_unview; checkpoints save the
        canonical form so a sharded-mode save restores into any mode
        bitwise — see util.sharded_checkpoint._net_state)."""
        z = self._zero
        new = dict(upd_states) if self._is_graph() else list(upd_states)
        for key, u, p in self._update_units():
            if not p:
                continue
            new[key] = z.unview_state(upd_states[key], u, p)
        return new

    def _aot_extra(self):
        """Key suffix describing program context the net's config hash
        cannot see: the mesh, the compression mode (and its static
        knobs — block size, encoding capacity, adaptive target,
        residual clipping; the tau VALUE rides as a runtime array) and
        the weight-update mode all change the traced program."""
        return (f"|pw[mesh={sorted(dict(self.mesh.shape).items())},"
                f"axis={self.batch_axis},"
                f"comp={self.gradient_compression},"
                f"blk={self.compression_block},"
                f"cap={self.encoding_capacity},"
                f"tgt={self.targetSparsity},"
                f"clip={self.residual_clip}"
                f"@{self.residual_clip_frequency},"
                f"grp={self.compression_group},"
                f"imode={self.intra_compression},"
                f"wu={self.weight_update}]")

    def _build_jit(self):
        n = self.net
        if self.gradient_compression is None:
            step = n._train_step
        elif self.gradient_compression == "threshold":
            step = self._threshold_step
        elif self.gradient_compression == "hierarchical":
            step = self._hierarchical_step
        else:
            step = self._compressed_step
        # params/opt/state replicated; batch args sharded over the data
        # axis. Routed through the AOT executable cache (runtime.aot):
        # the extra key part carries the mesh/compression/update mode.
        # The threshold step qualifies too now that its residual rides
        # the donated updater-state carry (tau is a runtime array, not
        # a trace-baked constant).
        from deeplearning4j_tpu.runtime import aot

        self._jit = aot.cached_jit(step, owner=n, entry="pw_train_step",
                                   extra=self._aot_extra(),
                                   donate_argnums=(0, 1, 2))

    def _upd_specs(self):
        """shard_map partition specs for the updater-state argument:
        replicated by default; under the compressed sharded update the
        eligible leaves live as flat 1/dp shards over the data axis —
        read off the PLACED state's actual shardings so the spec tree
        can never drift from the layout."""
        if self._zero is None:
            return P()
        return jax.tree_util.tree_map(
            lambda l: l.sharding.spec if hasattr(l, "sharding") else P(),
            self.net._upd_states)

    def _compressed_step(self, params, upd_states, states, iteration, x, y,
                         key, fmask, lmask):
        """Train step with an explicit quantized gradient all-reduce:
        per-tensor scale ("int8") or per-block scale ("block_int8",
        EQuARX-style). shard_map over the data axis expresses the
        quantize → integer psum → dequantize pipeline directly
        (parallel.sharding.quantized_psum_mean). With
        weight_update='sharded' the gradient reduction instead happens
        INSIDE the weight-update hook (ManualZeroUpdate): a QUANTIZED
        reduce-scatter feeds the local 1/dp shard update and the fresh
        shards are all-gathered — compression and ZeRO stack."""
        from deeplearning4j_tpu.parallel._compat import shard_map
        from deeplearning4j_tpu.parallel.sharding import \
            quantized_psum_mean

        n = self.net
        mesh, ax = self.mesh, self.batch_axis
        dp = int(self.mesh.shape[ax])
        mode, blk = self.gradient_compression, self.compression_block
        sharded = self._zero is not None

        def qall_tree(grads):
            return jax.tree_util.tree_map(
                lambda g: quantized_psum_mean(g, ax, dp, mode, blk),
                grads)

        def sync_states(states):
            # Per-shard batch stats (BN running mean/var) diverge across the
            # mesh; pmean keeps the returned "replicated" state consistent on
            # every device (cross-replica BN, mean-of-shard-stats).
            return jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, ax)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, states)

        def shard_step(params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s):
            # sharded: grads reach the update hook UNREDUCED — the
            # ManualZeroUpdate hook performs the compressed
            # reduce-scatter (eligible leaves) / all-reduce (fallback)
            return n._train_step(
                params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s,
                grad_transform=None if sharded else qall_tree,
                loss_transform=lambda l: jax.lax.pmean(l, ax),
                state_transform=sync_states)

        spec_b = P(ax)
        upd_specs = self._upd_specs()
        return shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), upd_specs, P(), P(), spec_b, spec_b, P(),
                      spec_b if fmask is not None else P(),
                      spec_b if lmask is not None else P()),
            out_specs=(P(), upd_specs, P(), P()),
            check_vma=False,
        )(params, upd_states, states, iteration, x, y, key, fmask, lmask)

    def _threshold_step(self, params, upd_states, states, iteration, x, y,
                        key, fmask, lmask):
        """Train step with threshold-encoded gradient sharing (reference:
        Strom 2015, the algorithm behind upstream SharedTrainingMaster's
        sparse updates). Each replica adds its error-feedback residual
        to the fresh gradient and transmits at most
        ceil(encodingCapacity * n) entries per leaf — the top-|.|
        candidates with |value| >= tau, encoded as +-tau (sign
        encoding); the remainder is next step's residual. The fixed
        capacity keeps every shape static, so the whole step is ONE
        jitted executable whose carry (residual + live tau) rides the
        donated updater-state slot with the canonical signature.

        The wire format is genuinely sparse: each replica all-gathers
        its (index, +-tau) pairs and scatter-adds the dp messages into
        the dense mean — bytes-on-wire scale with the capacity, not the
        model (parallel.sharding.compressed_wire_bytes bills it)."""
        from deeplearning4j_tpu.parallel._compat import shard_map
        from deeplearning4j_tpu.ndarray.compression import (
            threshold_cap, threshold_encode_fixed,
        )

        n = self.net
        mesh, ax = self.mesh, self.batch_axis
        target = self.targetSparsity
        capacity = self.encoding_capacity
        clip, clip_freq = self.residual_clip, self.residual_clip_frequency

        def sync_states(states):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, ax)
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, states)

        def shard_step(params_r, pack, states_r, it_r, x_s, y_s,
                       key_r, fm_s, lm_s):
            upd_r, res_s, t = pack["upd"], pack["ef"], pack["tau"]
            new_pack_cell = []

            def encode_all(grads):
                g_leaves, treedef = jax.tree_util.tree_flatten(grads)
                r_leaves = jax.tree_util.tree_flatten(res_s)[0]
                means, new_rs = [], []
                sent = 0.0
                total = 0
                dp = jax.lax.psum(1, ax)
                for g, r in zip(g_leaves, r_leaves):
                    acc = (g + r[0].astype(g.dtype)).reshape(-1)
                    cap = threshold_cap(acc.size, capacity)
                    idx, val, dense, res = threshold_encode_fixed(
                        acc, t, cap)
                    # the sparse transmission: every replica broadcasts
                    # its cap (index, +-tau) pairs; scatter-add
                    # reassembles the dense sum locally
                    gi = jax.lax.all_gather(idx, ax, tiled=True)
                    gv = jax.lax.all_gather(val, ax, tiled=True)
                    summed = jnp.zeros_like(acc).at[gi].add(gv)
                    means.append((summed / dp).reshape(g.shape)
                                 .astype(g.dtype))
                    if clip is not None:
                        # ResidualClippingPostProcessor: bound stale
                        # accumulation to +-(clip * tau) every clip_freq
                        # iterations
                        lim = (clip * t).astype(res.dtype)
                        clipped = jnp.clip(res, -lim, lim)
                        res = jnp.where((it_r % clip_freq) == 0,
                                        clipped, res) \
                            if clip_freq > 1 else clipped
                    new_rs.append(res.reshape(g.shape)[None]
                                  .astype(r.dtype))
                    sent = sent + jnp.sum(jnp.abs(val) > 0)
                    total += acc.size
                if target is None:
                    new_t = t
                else:
                    # adaptive threshold (reference:
                    # AdaptiveThresholdAlgorithm): multiplicative steps
                    # keep the mean transmitted fraction near the target
                    frac = jax.lax.pmean(sent / total, ax)
                    new_t = jnp.where(
                        frac > 1.25 * target, t * 1.1,
                        jnp.where(frac < 0.8 * target, t / 1.1, t))
                new_pack_cell.append(
                    (jax.tree_util.tree_unflatten(treedef, new_rs),
                     new_t.astype(jnp.float32)))
                return jax.tree_util.tree_unflatten(treedef, means)

            p, u, s, loss = n._train_step(
                params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s,
                grad_transform=encode_all,
                loss_transform=lambda l: jax.lax.pmean(l, ax),
                state_transform=sync_states)
            new_res, new_t = new_pack_cell[0]
            return p, {"upd": u, "ef": new_res, "tau": new_t}, s, loss

        spec_b = P(ax)
        ef_specs = jax.tree_util.tree_map(lambda _: P(ax),
                                          self.net._upd_states["ef"])
        pack_specs = {"upd": P(), "ef": ef_specs, "tau": P()}
        return shard_map(
            shard_step, mesh=mesh,
            in_specs=(P(), pack_specs, P(), P(), spec_b, spec_b, P(),
                      spec_b if fmask is not None else P(),
                      spec_b if lmask is not None else P()),
            out_specs=(P(), pack_specs, P(), P()),
            check_vma=False,
        )(params, upd_states, states, iteration, x, y, key, fmask, lmask)

    def _hierarchical_step(self, params, upd_states, states, iteration,
                           x, y, key, fmask, lmask):
        """Train step with the 2-hop hierarchical sparse exchange
        (ROADMAP item 4): hop 1 is a dense-or-block_int8 psum_scatter
        reduce over the INTRA axis (each chip ends up owning the group
        sum of a 1/group_size shard), hop 2 is the fixed-capacity Strom
        threshold exchange over the GROUP axis — every chip encodes its
        shard's above-tau entries and all-gathers the (index, +-tau)
        pairs with the n_groups-1 peer chips holding the SAME shard in
        the other groups — then the dense mean shard is all-gathered
        back over the intra axis. Error feedback lives on the per-chip
        shard (where hop 2 truncates), so the carry {upd, ef, tau}
        rides the donated updater-state slot exactly as the flat
        threshold mode's does: one jitted executable, bitwise k-loop
        and ResilientFit resume. Wire bytes scale with
        capacity x n_groups (not capacity x dp) — bills in
        parallel.sharding.compressed_wire_bytes."""
        from deeplearning4j_tpu.parallel._compat import shard_map
        from deeplearning4j_tpu.parallel.sharding import \
            hierarchical_grad_exchange

        n = self.net
        hmesh = self._hmesh
        gax, iax = _mesh.GROUP_AXIS, _mesh.INTRA_AXIS
        gsz, ng = self.compression_group, self._n_groups
        target = self.targetSparsity
        capacity = self.encoding_capacity
        clip, clip_freq = self.residual_clip, self.residual_clip_frequency
        imode, blk = self.intra_compression, self.compression_block

        def sync_states(states):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, (gax, iax))
                if jnp.issubdtype(a.dtype, jnp.inexact) else a, states)

        def shard_step(params_r, pack, states_r, it_r, x_s, y_s,
                       key_r, fm_s, lm_s):
            upd_r, res_s, t = pack["upd"], pack["ef"], pack["tau"]
            new_pack_cell = []

            def encode_all(grads):
                g_leaves, treedef = jax.tree_util.tree_flatten(grads)
                r_leaves = jax.tree_util.tree_flatten(res_s)[0]
                means, new_rs = [], []
                sent = 0.0
                total = 0
                for g, r in zip(g_leaves, r_leaves):
                    mean, res, nsent = hierarchical_grad_exchange(
                        g, r[0, 0], t, group_size=gsz, n_groups=ng,
                        capacity=capacity, group_axis=gax,
                        intra_axis=iax, intra_mode=imode, block=blk)
                    if clip is not None:
                        lim = (clip * t).astype(res.dtype)
                        clipped = jnp.clip(res, -lim, lim)
                        res = jnp.where((it_r % clip_freq) == 0,
                                        clipped, res) \
                            if clip_freq > 1 else clipped
                    means.append(mean)
                    new_rs.append(res[None, None].astype(r.dtype))
                    sent = sent + nsent
                    total += res.size
                if target is None:
                    new_t = t
                else:
                    # adaptive tau tracks the mean TRANSMITTED fraction
                    # of the per-chip shards (the quantity hop 2 pays
                    # wire for), averaged over the whole 2-D mesh
                    frac = jax.lax.pmean(sent / total, (gax, iax))
                    new_t = jnp.where(
                        frac > 1.25 * target, t * 1.1,
                        jnp.where(frac < 0.8 * target, t / 1.1, t))
                new_pack_cell.append(
                    (jax.tree_util.tree_unflatten(treedef, new_rs),
                     new_t.astype(jnp.float32)))
                return jax.tree_util.tree_unflatten(treedef, means)

            p, u, s, loss = n._train_step(
                params_r, upd_r, states_r, it_r, x_s, y_s, key_r, fm_s, lm_s,
                grad_transform=encode_all,
                loss_transform=lambda l: jax.lax.pmean(l, (gax, iax)),
                state_transform=sync_states)
            new_res, new_t = new_pack_cell[0]
            return p, {"upd": u, "ef": new_res, "tau": new_t}, s, loss

        spec_b = P((gax, iax))
        ef_specs = jax.tree_util.tree_map(lambda _: P(gax, iax),
                                          self.net._upd_states["ef"])
        pack_specs = {"upd": P(), "ef": ef_specs, "tau": P()}
        return shard_map(
            shard_step, mesh=hmesh,
            in_specs=(P(), pack_specs, P(), P(), spec_b, spec_b, P(),
                      spec_b if fmask is not None else P(),
                      spec_b if lmask is not None else P()),
            out_specs=(P(), pack_specs, P(), P()),
            check_vma=False,
        )(params, upd_states, states, iteration, x, y, key, fmask, lmask)

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs=None):
        from deeplearning4j_tpu.data.dataset import DataSet

        n = self.net
        n._require_init()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        if labels is not None:
            self._fit_batch(DataSet(data, labels))
            return self
        if isinstance(data, DataSet):
            self._fit_batch(data)
            return self
        for _ in range(epochs or 1):
            data.reset()
            while data.hasNext():
                self._fit_batch(data.next())
            n._epoch += 1
        return self

    def _is_graph(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return isinstance(self.net, ComputationGraph)

    def _fit_batch(self, ds):
        n = self.net
        x = _unwrap(ds.getFeatures())
        y = _unwrap(ds.getLabels())
        fmask = _unwrap(ds.getFeaturesMaskArray())
        lmask = _unwrap(ds.getLabelsMaskArray())
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        if self._is_graph():
            # ComputationGraph._train_step takes an inputs dict + labels
            # list (single-input/-output graphs through this wrapper)
            if len(n.conf.networkInputs) != 1 or len(n.conf.networkOutputs) != 1:
                raise ValueError(
                    "ParallelWrapper supports single-input/single-output "
                    "ComputationGraphs; use MultiDataSet-aware training "
                    "directly for multi-IO graphs")
            x = {n.conf.networkInputs[0]: x}
            y = [y]
            fmask = None if fmask is None else {n.conf.networkInputs[0]: fmask}
            lmask = None if lmask is None else [lmask]
        key = jax.random.fold_in(jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        n._params, n._upd_states, n._states, loss = self._jit(
            n._params, n._upd_states, n._states,
            jnp.asarray(n._iteration, jnp.int32), x, y, key, fmask, lmask)
        n._score = float(loss)
        n._iteration += 1
        for lst in n._listeners:
            lst.iterationDone(n, n._iteration, n._epoch)

    def fitDataSet(self, iterator, stepsPerSync=1, epochs=None):
        """Sharded form of MultiLayerNetwork.fitDataSet: k fresh batches
        are staged as ONE [k, B, ...] stack per component, placed with
        the batch dim sharded over the data axis (sharding.
        shard_batch_stack — the same divisibility-checked shard_batch
        every trainer uses, never padding), and trained by one jitted
        lax.fori_loop whose step i indexes a correctly-sharded global
        batch — GSPMD inserts the gradient collectives inside the loop.
        One host sync and one transfer per k batches; double-buffered
        staging; ragged tail through the per-batch sharded fit path.
        Supports every gradient_compression mode — the threshold step's
        residual + tau ride the donated updater-state carry, so the
        k-loop threads them like any other state."""
        from deeplearning4j_tpu.data.iterators import stack_datasets
        from deeplearning4j_tpu.nn.multilayer import (
            fit_dataset_jit, run_fit_dataset_epoch)
        from deeplearning4j_tpu.parallel.sharding import shard_batch_stack

        n = self.net
        n._require_init()
        k = int(stepsPerSync)
        if k < 1:
            raise ValueError(f"stepsPerSync must be >= 1, got {k}")
        if k == 1:
            it0 = n._iteration
            self.fit(iterator, epochs=epochs)
            self._fit_dataset_syncs = n._iteration - it0  # 1/batch
            return self
        bp = getattr(n.conf, "backpropType", None)
        if bp == "tbptt" or str(getattr(bp, "name", bp)) == "TruncatedBPTT":
            raise ValueError(
                "fitDataSet does not support truncated BPTT; use fit()")
        if self._is_graph() and (len(n.conf.networkInputs) != 1
                                 or len(n.conf.networkOutputs) != 1):
            raise ValueError(
                "ParallelWrapper supports single-input/single-output "
                "ComputationGraphs")
        step = self.trainStep()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        jloop = fit_dataset_jit(n, k, step_fn=step, owner=self,
                                aot_extra=self._aot_extra())

        if self._is_graph():
            name = n.conf.networkInputs[0]

            def stack_fn(batches):
                x, y, fm, lm = stack_datasets(batches)
                return ({name: x}, [y],
                        None if fm is None else {name: fm},
                        None if lm is None else [lm])
        else:
            stack_fn = stack_datasets

        def place(staged):
            if self._hmesh is not None:
                return shard_batch_stack(
                    staged, self._hmesh,
                    (_mesh.GROUP_AXIS, _mesh.INTRA_AXIS))
            return shard_batch_stack(staged, self.mesh, self.batch_axis)

        self._fit_dataset_syncs = 0
        for _ in range(epochs or 1):
            iterator.reset()
            self._fit_dataset_syncs += run_fit_dataset_epoch(
                n, iterator, k, stack_fn, self._fit_batch, jloop,
                place=place)
            n._epoch += 1
        return self

    def precompile(self, batchSize=32, featuresShape=None,
                   labelsShape=None, cache=None):
        """AOT warm-start of the sharded train step (see
        MultiLayerNetwork.precompile): places the model on the mesh,
        builds the distributed step and compiles (or loads from the
        persistent cache) its executable for one GLOBAL batch
        signature. Composes with weight_update='sharded' — the ZeRO
        layout is part of the cache key, and the updater state is
        allocated sharded before the warm lowering, exactly as fit()
        would — and with every compression mode (the threshold carry
        is part of the warmed signature since it rides the updater
        state)."""
        from deeplearning4j_tpu.nn.multilayer import example_batch

        n = self.net
        n._require_init()
        if self._jit is None:
            self._place_replicated()
            self._build_jit()
        if not hasattr(self._jit, "warm"):
            return {}
        if self._is_graph():
            featuresShape, labelsShape = n._example_shapes(
                batchSize, featuresShape, labelsShape)
            x = np.zeros(featuresShape, np.float32)
            y = np.zeros(labelsShape, np.float32)
        else:
            x, y = example_batch(n, batchSize, featuresShape,
                                 labelsShape)
        x = self._shard_batch(jnp.asarray(x))
        y = self._shard_batch(jnp.asarray(y))
        if self._is_graph():
            x = {n.conf.networkInputs[0]: x}
            y = [y]
        key = jax.random.fold_in(
            jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        res = self._jit.warm(
            n._params, n._upd_states, n._states,
            jnp.asarray(n._iteration, jnp.int32), x, y, key, None, None,
            cache=cache)
        k_, status, secs = res
        return {} if status is None else {
            "pw_train_step": {"key": k_, "status": status,
                              "seconds": round(secs, 3)}}

    def trainStep(self):
        """The un-jitted per-batch step function with the canonical
        `(params, upd, states, it, x, y, key, fmask, lmask) ->
        (params', upd', states', loss)` signature, for harnesses that
        splice logic around it before jitting — runtime.resilience
        wraps it in the non-finite guard. Every compression mode is
        wrappable: the threshold step's residual + tau ride inside the
        updater-state slot, so a guarded skip rolls them back with the
        rest of the carry (exactly the error-feedback semantics a
        skipped step needs)."""
        if self.gradient_compression is None:
            return self.net._train_step
        if self.gradient_compression == "threshold":
            return self._threshold_step
        if self.gradient_compression == "hierarchical":
            return self._hierarchical_step
        return self._compressed_step

    def averagingFrequency(self, *_):
        # synchronous psum makes per-step averaging exact already; the
        # reference's periodic-averaging semantics live in
        # ParameterAveragingTrainingMaster below
        return self

    def workers(self, *_):
        return self


class SharedTrainingMaster(ParallelWrapper):
    """Gradient-sharing distributed trainer (reference: Spark
    SharedTrainingMaster). Alias of ParallelWrapper with the quantized
    all-reduce enabled by default — the ICI-native analog of the
    reference's threshold-encoded sparse updates. Pass
    ``gradient_compression=None`` for the dense bf16 psum,
    ``"block_int8"`` for EQuARX-style per-block scales, or
    ``"threshold"`` / a ``thresholdAlgorithm`` for the reference's
    actual Strom-2015 algorithm (fixed-capacity sparsified +-tau
    updates with per-replica error feedback — see
    ParallelWrapper._threshold_step).

    ``thresholdAlgorithm`` maps to REAL trainer config, not an opaque
    kwarg: a bare number or FixedThresholdAlgorithm pins tau;
    AdaptiveThresholdAlgorithm / TargetSparsityThresholdAlgorithm set
    the initial tau plus targetSparsity (the adaptive loop);
    ``residualPostProcessor=ResidualClippingPostProcessor(...)`` wires
    residual clipping. Unknown algorithm objects raise naming the
    supported set.

    ``compressionGroupSize=g`` selects the hierarchical 2-hop exchange
    (``gradient_compression="hierarchical"``) with node groups of g
    chips: dense/block_int8 reduce-scatter inside each group, Strom
    threshold exchange between group leaders — wire bytes scale with
    capacity x n_groups instead of capacity x dp (see
    ParallelWrapper._hierarchical_step). Composes with
    thresholdAlgorithm / residualPostProcessor, which configure the
    leader hop's encoder."""

    def __init__(self, net, mesh=None, thresholdAlgorithm=None,
                 residualPostProcessor=None, compressionGroupSize=None,
                 **kw):
        if compressionGroupSize is not None:
            # process FIRST so a bare compressionGroupSize= selects the
            # hierarchical mode before the threshold-algorithm mapping
            # defaults gradient_compression (the algorithm then
            # configures hop 2's tau, which IS the Strom encoder)
            gc = kw.get("gradient_compression", "hierarchical")
            if gc != "hierarchical":
                raise ValueError(
                    f"compressionGroupSize given together with "
                    f"gradient_compression={gc!r}: the node-group size "
                    "only applies to the 'hierarchical' 2-hop exchange; "
                    "drop one of the two arguments")
            kw.setdefault("gradient_compression", "hierarchical")
            kw["compressionGroupSize"] = compressionGroupSize
        if thresholdAlgorithm is not None:
            gc = kw.get("gradient_compression", "threshold")
            if gc not in ("threshold", "hierarchical"):
                raise ValueError(
                    f"thresholdAlgorithm given together with "
                    f"gradient_compression={gc!r}: the threshold algorithm "
                    "only applies to the 'threshold' (Strom-2015) encoding "
                    "or the 'hierarchical' 2-hop exchange (whose leader "
                    "hop is the same encoder); drop one of the two "
                    "arguments")
            kw.setdefault("gradient_compression", "threshold")
            algo = thresholdAlgorithm
            if isinstance(algo, (int, float)) \
                    and not isinstance(algo, bool):
                algo = FixedThresholdAlgorithm(algo)
            if isinstance(algo, AdaptiveThresholdAlgorithm):
                kw.setdefault("threshold", algo.threshold)
                kw.setdefault("targetSparsity", algo.sparsityTarget)
            elif isinstance(algo, FixedThresholdAlgorithm) \
                    or hasattr(algo, "threshold"):
                # any object carrying .threshold duck-types as fixed
                kw.setdefault("threshold", float(algo.threshold))
            else:
                names = [c.__name__ for c in THRESHOLD_ALGORITHMS]
                raise ValueError(
                    f"unknown thresholdAlgorithm {thresholdAlgorithm!r}; "
                    f"pass a number (fixed tau) or one of {names}")
        if residualPostProcessor is not None:
            if kw.get("gradient_compression",
                      "threshold") not in ("threshold", "hierarchical") \
                    and thresholdAlgorithm is None:
                raise ValueError(
                    "residualPostProcessor only applies to the "
                    "'threshold' and 'hierarchical' encodings (there "
                    "is no residual elsewhere)")
            rpp = residualPostProcessor
            if not isinstance(rpp, ResidualClippingPostProcessor):
                raise ValueError(
                    f"unknown residualPostProcessor {rpp!r}; supported: "
                    "ResidualClippingPostProcessor")
            kw.setdefault("gradient_compression", "threshold")
            kw.setdefault("residualClip", rpp.clipValue)
            kw.setdefault("residualClipFrequency", rpp.frequency)
        # ISSUE 11: compression and the ZeRO sharded update now STACK
        # (compressed reduce-scatter) — asking for weight_update=
        # "sharded" keeps this master's int8 default instead of
        # silently opting out; only "threshold" cannot compose (the
        # ParallelWrapper constructor rejects that pair loudly)
        kw.setdefault("gradient_compression", "int8")
        super().__init__(net, mesh=mesh, **kw)


class ParameterAveragingTrainingMaster(ParallelWrapper):
    """Parameter-averaging distributed trainer (reference: Spark
    ParameterAveragingTrainingMaster.java). Each data-shard replica takes
    LOCAL updater steps on its own copy of the parameters — no per-step
    gradient allreduce — and every ``averagingFrequency`` iterations the
    parameters, updater state and layer state are averaged across the mesh
    (``pmean`` over ICI plays the role of the Spark driver's aggregate).

    With ``averagingFrequency=1`` and plain SGD this is mathematically
    identical to synchronous gradient sharing; larger frequencies trade
    fidelity for fewer collectives, exactly the reference's knob.
    """

    def __init__(self, net, mesh=None, averagingFrequency=5,
                 batch_axis=_mesh.DATA_AXIS, weight_update="replicated"):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        if isinstance(net, ComputationGraph):
            raise ValueError(
                "ParameterAveragingTrainingMaster supports "
                "MultiLayerNetwork; for ComputationGraph data-parallel "
                "training use ParallelWrapper/SharedTrainingMaster "
                "(single-input/-output graphs)")
        if weight_update == "sharded":
            # reject HERE, not deep in jit tracing: this master keeps a
            # PER-REPLICA stacked copy of params+updater state (local
            # steps, periodic pmean) — there is no single cross-replica
            # update to shard, and the stacked state's leading replica
            # axis would collide with the ZeRO flat-shard views
            raise ValueError(
                "ParameterAveragingTrainingMaster does not support "
                "weight_update='sharded': its replicas take LOCAL "
                "updater steps on per-replica state, so there is no "
                "cross-replica weight update to shard. The ZeRO-style "
                "sharded update is supported by ParallelWrapper and "
                "SharedTrainingMaster(gradient_compression=None).")
        super().__init__(net, mesh=mesh, batch_axis=batch_axis,
                         weight_update=weight_update)
        if int(averagingFrequency) < 1:
            raise ValueError("averagingFrequency must be >= 1")
        self._avg_freq = int(averagingFrequency)
        self._stacked = None  # (params, upd_states, states) + replica axis

    def trainStep(self):
        raise ValueError(
            "ParameterAveragingTrainingMaster's step is not expressible "
            "as one wrappable train step: it takes LOCAL per-replica "
            "steps on stacked state with a periodic pmean, all inside "
            "its own _fit_batch. Wrap ParallelWrapper/"
            "SharedTrainingMaster in ResilientFit instead, or run this "
            "master without the non-finite guard")

    def fitDataSet(self, iterator, stepsPerSync=1, epochs=None):
        if int(stepsPerSync) == 1:
            return self.fit(iterator, epochs=epochs)
        raise ValueError(
            "ParameterAveragingTrainingMaster does not support "
            "stepsPerSync > 1: it picks a different executable per "
            "iteration host-side (averaging vs local step), which a "
            "single traced k-loop cannot express without paying the "
            "full-state pmean every step; use ParallelWrapper/"
            "SharedTrainingMaster for the k-stack loop")

    def averagingFrequency(self, k):
        if self._jit is not None:
            raise RuntimeError("set averagingFrequency before the first fit()")
        if int(k) < 1:
            raise ValueError("averagingFrequency must be >= 1")
        self._avg_freq = int(k)
        return self

    # ------------------------------------------------------------------
    def _place_replicated(self):
        """Give every replica its own (initially identical) copy: stack each
        leaf along a leading replica axis sharded over the data axis."""
        # a net previously trained under a sharded-update or threshold
        # wrapper must shed the ZeRO hook / packed residual carry before
        # stacking
        self._uninstall_sharded_update()
        self._unpack_threshold_state()
        n, dp = self.net, self.mesh.shape[self.batch_axis]

        def stack(tree):
            def one(a):
                a = jnp.asarray(a)
                sh = NamedSharding(self.mesh,
                                   P(self.batch_axis, *([None] * a.ndim)))
                return jax.device_put(jnp.stack([a] * dp), sh)
            return jax.tree_util.tree_map(one, tree)

        self._stacked = (stack(n._params), stack(n._upd_states),
                         stack(n._states))

    def _build_jit(self):
        from deeplearning4j_tpu.parallel._compat import shard_map

        n, mesh, ax = self.net, self.mesh, self.batch_axis

        def make_step(do_avg):
            # two step variants chosen HOST-side by the iteration counter:
            # the averaging collective only exists in the executable that
            # runs at averaging points — a traced jnp.where would make XLA
            # pay the full pmean of params+opt+state every single step,
            # which is exactly the traffic this mode exists to avoid
            def shard_step(params, upd, states, it, x, y, key, fm, lm):
                sq = lambda t: jax.tree_util.tree_map(lambda a: a[0], t)
                params, upd, states = sq(params), sq(upd), sq(states)
                # decorrelate per-replica dropout like distinct Spark workers
                key = jax.random.fold_in(key, jax.lax.axis_index(ax))
                p, u, s, loss = n._train_step(params, upd, states, it, x, y,
                                              key, fm, lm)
                if do_avg:
                    avg = lambda t: jax.tree_util.tree_map(
                        lambda a: jax.lax.pmean(a, ax)
                        if jnp.issubdtype(a.dtype, jnp.inexact) else a, t)
                    p, u, s = avg(p), avg(u), avg(s)
                loss = jax.lax.pmean(loss, ax)
                ex = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
                return ex(p), ex(u), ex(s), loss

            def step(params, upd, states, it, x, y, key, fm, lm):
                spec_b = P(ax)
                return shard_map(
                    shard_step, mesh=mesh,
                    in_specs=(spec_b, spec_b, spec_b, P(), spec_b, spec_b, P(),
                              spec_b if fm is not None else P(),
                              spec_b if lm is not None else P()),
                    out_specs=(spec_b, spec_b, spec_b, P()),
                    check_vma=False,
                )(params, upd, states, it, x, y, key, fm, lm)

            return jax.jit(step, donate_argnums=(0, 1, 2))

        self._jit = make_step(False)
        self._jit_avg = make_step(True)

    def _fit_batch(self, ds):
        from deeplearning4j_tpu.nn.multilayer import _unwrap as unw

        n = self.net
        x, y = unw(ds.getFeatures()), unw(ds.getLabels())
        fmask, lmask = unw(ds.getFeaturesMaskArray()), unw(ds.getLabelsMaskArray())
        x = self._shard_batch(x)
        y = self._shard_batch(y)
        fmask = self._shard_batch(fmask)
        lmask = self._shard_batch(lmask)
        key = jax.random.fold_in(jax.random.key(n.conf.seed ^ 0x5EED), n._iteration)
        p, u, s = self._stacked
        step = self._jit_avg if (n._iteration + 1) % self._avg_freq == 0 \
            else self._jit
        p, u, s, loss = step(p, u, s, jnp.asarray(n._iteration, jnp.int32),
                             x, y, key, fmask, lmask)
        self._stacked = (p, u, s)
        n._score = float(loss)
        n._iteration += 1
        for lst in n._listeners:
            lst.iterationDone(n, n._iteration, n._epoch)

    def fit(self, data, labels=None, epochs=None):
        super().fit(data, labels, epochs)
        self._sync_to_net()
        return self

    def _sync_to_net(self):
        """Expose the replica-average as the net's canonical model (the
        reference's driver-side aggregated model)."""
        if self._stacked is None:
            return

        def collapse(tree):
            return jax.tree_util.tree_map(
                lambda a: a.mean(0) if jnp.issubdtype(a.dtype, jnp.inexact)
                else a[0], tree)

        n = self.net
        p, u, s = self._stacked
        n._params, n._upd_states, n._states = collapse(p), collapse(u), collapse(s)

"""Multi-host (pod / pod-slice) bootstrap.

Reference: the Spark side of the reference — SharedTrainingMaster's
cluster bootstrap (driver + executors discovering each other over
Aeron/Spark) — and its NCCL/MPI transports. TPU-native design: hosts
join one JAX distributed runtime (`jax.distributed.initialize`, the
PJRT-level analog of the Spark driver handshake), after which
`jax.devices()` spans every chip in the pod and ALL the single-host
machinery in this package (ParallelWrapper, SharedTrainingMaster,
ParameterAveragingTrainingMaster, PipelineParallel, ring attention)
works unchanged — XLA routes collectives over ICI within a slice and
DCN across slices.

The one multi-host-specific concern is AXIS PLACEMENT: axes that
communicate every step (model/sequence parallel) must ride ICI, and
only the gradient/averaging axis should cross DCN. `hybrid_mesh`
encodes that: DCN axes outermost over slices, ICI axes innermost within
a slice (jax mesh_utils.create_hybrid_device_mesh ordering).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel import mesh as _mesh


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, **kw):
    """Join this host to the pod's distributed runtime (reference: the
    Spark/Aeron cluster join). On Cloud TPU the arguments are
    auto-detected from the environment; pass them explicitly elsewhere.
    Call once, before any jax computation, on EVERY host."""
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id, **kw)


def is_coordinator() -> bool:
    """True on the process that should write checkpoints/logs
    (reference: the Spark driver role)."""
    return jax.process_index() == 0


def num_hosts() -> int:
    return jax.process_count()


def hybrid_mesh(dcn_axes: dict, ici_axes: dict, devices=None) -> Mesh:
    """Mesh spanning pod slices: ``dcn_axes`` partition across slices
    (cheap, infrequent communication — data parallel / parameter
    averaging), ``ici_axes`` partition within a slice (model / sequence /
    pipeline axes that talk every layer).

    hybrid_mesh({"data": 4}, {"model": 4, "seq": 2}) on 4 slices of 8
    chips -> Mesh("data"=4, "model"=4, "seq"=2) with every "model"/"seq"
    group fully inside one slice.

    With a single slice (or CPU test devices) this degrades to an
    ordinary build_mesh over dcn+ici axes in that order."""
    devices = list(devices if devices is not None else jax.devices())
    dcn_total = int(np.prod(list(dcn_axes.values()))) if dcn_axes else 1
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    names = tuple(list(dcn_axes) + list(ici_axes))
    if n_slices <= 1:
        if dcn_total > 1 and n_slices == 1 and len(devices) < dcn_total * int(
                np.prod(list(ici_axes.values()) or [1])):
            raise ValueError(
                f"dcn axes {dcn_axes} need {dcn_total} slices; "
                f"found {n_slices}")
        return _mesh.build_mesh({**dcn_axes, **ici_axes}, devices)
    from jax.experimental import mesh_utils

    total = dcn_total * int(np.prod(list(ici_axes.values()) or [1]))
    if total != len(devices):
        raise ValueError(
            f"hybrid mesh axes {dcn_axes} x {ici_axes} cover {total} "
            f"devices but the pod has {len(devices)}; every in-slice chip "
            "must be covered by an ici axis (add e.g. a 'model' or inner "
            "'data' axis)")
    # canonical usage: both shapes span the SAME combined axis list, with
    # 1s where an axis doesn't partition that network level; the result's
    # shape is their elementwise product, ici axes contiguous in-slice
    ici_shape = tuple([1] * len(dcn_axes) + list(ici_axes.values()))
    dcn_shape = tuple(list(dcn_axes.values()) + [1] * len(ici_axes))
    arr = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=ici_shape, dcn_mesh_shape=dcn_shape, devices=devices)
    return Mesh(arr, names)

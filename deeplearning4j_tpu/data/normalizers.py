"""Data normalization.

Reference: org.nd4j.linalg.dataset.api.preprocessor
(NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
VGG16ImagePreProcessor). Normalizers fit summary statistics from an
iterator or DataSet, then act as the iterator's preProcessor; stats are
computed on host in fp64 (a one-pass streaming fit, not a TPU op) and the
transform itself is a cheap vectorised numpy op applied before the batch
is shipped to device.
"""

from __future__ import annotations

import numpy as np


def _feat(x):
    from deeplearning4j_tpu.ndarray import INDArray

    return x.toNumpy() if isinstance(x, INDArray) else np.asarray(x)


def _feature_axes(a: np.ndarray) -> tuple:
    """Axes to reduce over so stats are per-feature: examples for 2d [N,F];
    examples+time for 3d [N,F,T]; examples+spatial for 4d [N,C,H,W]."""
    if a.ndim == 2:
        return (0,)
    if a.ndim == 3:
        return (0, 2)
    if a.ndim == 4:
        return (0, 2, 3)
    return tuple(range(a.ndim - 1))


def _float_dtype(a: np.ndarray):
    """Keep float dtypes; promote ints/uint8 images to float32 so
    normalization never truncates or wraps."""
    return a.dtype if np.issubdtype(a.dtype, np.floating) else np.float32


def _expand(stat: np.ndarray, ndim: int) -> np.ndarray:
    """Reshape per-feature stats [F] for broadcasting against the data."""
    if ndim == 2:
        return stat
    shape = [1, len(stat)] + [1] * (ndim - 2)
    return stat.reshape(shape)


class DataNormalization:
    """Base: fit(data) then preProcess(ds) / transform / revert."""

    def __init__(self):
        self._fit_label = False

    def fitLabel(self, fitLabels: bool):
        self._fit_label = bool(fitLabels)
        return self

    def isFitLabel(self) -> bool:
        return self._fit_label

    # -- fitting -------------------------------------------------------
    def fit(self, data):
        """Accepts a DataSet or a DataSetIterator (streamed one-pass fit)."""
        from deeplearning4j_tpu.data.dataset import DataSet

        self._reset_stats()
        if isinstance(data, DataSet):
            self._accumulate(_feat(data.getFeatures()),
                             _feat(data.getLabels()) if self._fit_label and data.getLabels() is not None else None)
        elif hasattr(data, "_raw_batches"):
            # bypass the iterator's padding and any installed preprocessor —
            # stats must come from the raw data, once per real example
            data.reset()
            for f, l in data._raw_batches():
                self._accumulate(f, l if self._fit_label and l is not None else None)
            data.reset()
        else:
            data.reset()
            while data.hasNext():
                ds = data.next()
                self._accumulate(_feat(ds.getFeatures()),
                                 _feat(ds.getLabels()) if self._fit_label and ds.getLabels() is not None else None)
            data.reset()
        self._finalize_stats()
        return self

    # -- application ---------------------------------------------------
    def preProcess(self, ds):
        """In-place DataSet transform (DataSetPreProcessor interface)."""
        ds.setFeatures(self._apply(_feat(ds.getFeatures()), label=False))
        if self._fit_label and ds.getLabels() is not None:
            ds.setLabels(self._apply(_feat(ds.getLabels()), label=True))
        return ds

    def transform(self, ds_or_features):
        from deeplearning4j_tpu.data.dataset import DataSet

        if isinstance(ds_or_features, DataSet):
            return self.preProcess(ds_or_features)
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._apply(_feat(ds_or_features), label=False))

    def revertFeatures(self, features):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._revert(_feat(features), label=False))

    def revertLabels(self, labels):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._revert(_feat(labels), label=True))

    def revert(self, ds):
        ds.setFeatures(self.revertFeatures(ds.getFeatures()))
        if self._fit_label and ds.getLabels() is not None:
            ds.setLabels(self.revertLabels(ds.getLabels()))
        return ds


class NormalizerStandardize(DataNormalization):
    """Zero-mean unit-variance per feature (streamed Chan et al. merge)."""

    def _reset_stats(self):
        self._n = 0
        self._sum = None
        self._sumsq = None
        self._ln = 0
        self._lsum = None
        self._lsumsq = None

    def _accumulate(self, f, l):
        axes = _feature_axes(f)
        cnt = int(np.prod([f.shape[a] for a in axes]))
        s = f.sum(axis=axes, dtype=np.float64)
        ss = (f.astype(np.float64) ** 2).sum(axis=axes)
        if self._sum is None:
            self._sum, self._sumsq = s, ss
        else:
            self._sum += s
            self._sumsq += ss
        self._n += cnt
        if l is not None:
            laxes = _feature_axes(l)
            lcnt = int(np.prod([l.shape[a] for a in laxes]))
            ls = l.sum(axis=laxes, dtype=np.float64)
            lss = (l.astype(np.float64) ** 2).sum(axis=laxes)
            if self._lsum is None:
                self._lsum, self._lsumsq = ls, lss
            else:
                self._lsum += ls
                self._lsumsq += lss
            self._ln += lcnt

    def _finalize_stats(self):
        self._mean = self._sum / self._n
        var = self._sumsq / self._n - self._mean ** 2
        self._std = np.sqrt(np.maximum(var, 1e-12))
        if self._lsum is not None:
            self._lmean = self._lsum / self._ln
            lvar = self._lsumsq / self._ln - self._lmean ** 2
            self._lstd = np.sqrt(np.maximum(lvar, 1e-12))

    def _apply(self, a, label):
        mean = self._lmean if label else self._mean
        std = self._lstd if label else self._std
        return ((a - _expand(mean, a.ndim)) / _expand(std, a.ndim)).astype(_float_dtype(a))

    def _revert(self, a, label):
        mean = self._lmean if label else self._mean
        std = self._lstd if label else self._std
        return (a * _expand(std, a.ndim) + _expand(mean, a.ndim)).astype(_float_dtype(a))

    def getMean(self):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._mean)

    def getStd(self):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._std)

    # -- persistence (reference: NormalizerSerializer) -----------------
    def save(self, path):
        np.savez(path, kind=np.array("standardize"), mean=self._mean, std=self._std,
                 fit_label=self._fit_label,
                 lmean=getattr(self, "_lmean", np.zeros(0)),
                 lstd=getattr(self, "_lstd", np.zeros(0)))

    @staticmethod
    def load(path):
        z = np.load(path, allow_pickle=False)
        n = NormalizerStandardize()
        n._mean, n._std = z["mean"], z["std"]
        n._fit_label = bool(z["fit_label"])
        if z["lmean"].size:
            n._lmean, n._lstd = z["lmean"], z["lstd"]
        return n


class NormalizerMinMaxScaler(DataNormalization):
    """Scale each feature into [minRange, maxRange] (default [0, 1])."""

    def __init__(self, minRange: float = 0.0, maxRange: float = 1.0):
        super().__init__()
        self._lo, self._hi = float(minRange), float(maxRange)

    def _reset_stats(self):
        self._min = None
        self._max = None
        self._lmin = None
        self._lmax = None

    def _accumulate(self, f, l):
        axes = _feature_axes(f)
        mn, mx = f.min(axis=axes), f.max(axis=axes)
        self._min = mn if self._min is None else np.minimum(self._min, mn)
        self._max = mx if self._max is None else np.maximum(self._max, mx)
        if l is not None:
            laxes = _feature_axes(l)
            lmn, lmx = l.min(axis=laxes), l.max(axis=laxes)
            self._lmin = lmn if self._lmin is None else np.minimum(self._lmin, lmn)
            self._lmax = lmx if self._lmax is None else np.maximum(self._lmax, lmx)

    def _finalize_stats(self):
        pass

    def _apply(self, a, label):
        mn = self._lmin if label else self._min
        mx = self._lmax if label else self._max
        rng = np.maximum(mx - mn, 1e-12)
        unit = (a - _expand(mn, a.ndim)) / _expand(rng, a.ndim)
        return (unit * (self._hi - self._lo) + self._lo).astype(_float_dtype(a))

    def _revert(self, a, label):
        mn = self._lmin if label else self._min
        mx = self._lmax if label else self._max
        rng = np.maximum(mx - mn, 1e-12)
        unit = (a - self._lo) / (self._hi - self._lo)
        return (unit * _expand(rng, a.ndim) + _expand(mn, a.ndim)).astype(_float_dtype(a))

    def getMin(self):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._min)

    def getMax(self):
        from deeplearning4j_tpu.ndarray import Nd4j

        return Nd4j.create(self._max)

    def save(self, path):
        np.savez(path, kind=np.array("minmax"), min=self._min, max=self._max,
                 lo=self._lo, hi=self._hi, fit_label=self._fit_label,
                 lmin=(self._lmin if self._lmin is not None else np.zeros(0)),
                 lmax=(self._lmax if self._lmax is not None else np.zeros(0)))

    @staticmethod
    def load(path):
        z = np.load(path, allow_pickle=False)
        n = NormalizerMinMaxScaler(float(z["lo"]), float(z["hi"]))
        n._min, n._max = z["min"], z["max"]
        n._fit_label = bool(z["fit_label"])
        if z["lmin"].size:
            n._lmin, n._lmax = z["lmin"], z["lmax"]
        return n


class ImagePreProcessingScaler(DataNormalization):
    """Pixel scaler: [0, maxPixel] -> [minRange, maxRange]. Needs no fit
    (reference: ImagePreProcessingScaler, fit is a no-op)."""

    def __init__(self, minRange: float = 0.0, maxRange: float = 1.0,
                 maxPixelVal: float = 255.0):
        super().__init__()
        self._lo, self._hi = float(minRange), float(maxRange)
        self._maxpix = float(maxPixelVal)

    def fit(self, data):
        return self

    def _apply(self, a, label):
        return (a / self._maxpix * (self._hi - self._lo) + self._lo).astype(np.float32)

    def _revert(self, a, label):
        return ((a - self._lo) / (self._hi - self._lo) * self._maxpix).astype(np.float32)

    def preProcess(self, ds):
        ds.setFeatures(self._apply(_feat(ds.getFeatures()), label=False))
        return ds


class VGG16ImagePreProcessor(DataNormalization):
    """Subtract ImageNet channel means from [N, 3, H, W] (reference:
    VGG16ImagePreProcessor; BGR means 123.68/116.779/103.939 in RGB order)."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, data):
        return self

    def _apply(self, a, label):
        return (a - self.MEANS.reshape(1, 3, 1, 1)).astype(np.float32)

    def _revert(self, a, label):
        return (a + self.MEANS.reshape(1, 3, 1, 1)).astype(np.float32)

    def preProcess(self, ds):
        ds.setFeatures(self._apply(_feat(ds.getFeatures()), label=False))
        return ds

"""Built-in dataset iterators.

Reference: deeplearning4j-datasets iterators (MnistDataSetIterator,
IrisDataSetIterator, Cifar10DataSetIterator, org.deeplearning4j.datasets.*).
The reference downloads archives on first use; this container has no
network egress, so each iterator resolves data in priority order:

1. local files under ``$DL4J_TPU_DATA_DIR`` (default ``~/.deeplearning4j``)
   in the standard formats (MNIST idx / CIFAR-10 binary batches),
2. a bundled in-process copy (iris via sklearn's packaged CSV),
3. a documented deterministic synthetic generator with the same shapes,
   dtypes and class structure — sufficient for convergence smoke tests
   and benchmarking, clearly flagged via ``.isSynthetic``.

All iterators pad the final partial batch (masked) so every batch has one
static shape — XLA compiles a single executable per epoch.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSetIterator


def _data_dir() -> Path:
    return Path(os.environ.get("DL4J_TPU_DATA_DIR",
                               os.path.expanduser("~/.deeplearning4j")))


# ------------------------------------------------------- k-batch staging
def iter_stacks(iterator, k: int):
    """Yield lists of up to `k` consecutive batches from a
    DataSetIterator (or any object with hasNext/next, or a plain
    iterable). Every yielded list except possibly the last has exactly
    `k` entries — the staging unit of ``fitDataSet(stepsPerSync=k)``;
    the short final list is the ragged tail the caller runs through
    plain per-batch ``fit()``."""
    k = int(k)
    if k < 1:
        raise ValueError(f"stepsPerSync must be >= 1, got {k}")
    buf = []
    if hasattr(iterator, "hasNext"):
        while iterator.hasNext():
            buf.append(iterator.next())
            if len(buf) == k:
                yield buf
                buf = []
    else:
        for ds in iterator:
            buf.append(ds)
            if len(buf) == k:
                yield buf
                buf = []
    if buf:
        yield buf


def _to_numpy(a):
    if a is None:
        return None
    return np.asarray(a.toNumpy() if hasattr(a, "toNumpy") else a)


def stack_mask_group(arrs, what):
    """Stack one mask component across a k-batch group. All-None stays
    None; mixed presence synthesises an all-ones mask for the maskless
    batches (semantically "nothing masked" — the padded final batch of
    an epoch is the one batch that carries a mask, and it must be able
    to share a stack with unmasked ones). Shapes must agree: the stack
    is one fixed-shape device buffer."""
    if all(a is None for a in arrs):
        return None
    template = next(a for a in arrs if a is not None)
    filled = [np.ones_like(template) if a is None else a for a in arrs]
    shapes = {a.shape for a in filled}
    if len(shapes) > 1:
        raise ValueError(
            f"fitDataSet stack has ragged {what} shapes {sorted(shapes)}: "
            "device staging needs one fixed shape per component (the "
            "built-in iterators pad their final batch already)")
    return np.stack(filled)


def stack_datasets(batches):
    """Stack k DataSets into one host-side [k, B, ...] stack per
    component -> (features, labels, featuresMask, labelsMask), masks
    None when absent everywhere. The stack is what
    ``fitDataSet(stepsPerSync=k)`` ships to the device in ONE transfer;
    the jitted k-loop ``dynamic_index_in_dim``s batch i per step."""

    def stack(getter, what):
        arrs = [_to_numpy(getattr(ds, getter)()) for ds in batches]
        if any(a is None for a in arrs):
            if all(a is None for a in arrs):
                return None
            raise ValueError(
                f"fitDataSet stack has batches with and without {what}")
        shapes = {a.shape for a in arrs}
        if len(shapes) > 1:
            raise ValueError(
                f"fitDataSet stack has ragged {what} shapes "
                f"{sorted(shapes)}: device staging needs one fixed shape "
                "per component (the built-in iterators pad their final "
                "batch already)")
        return np.stack(arrs)

    return (stack("getFeatures", "features"),
            stack("getLabels", "labels"),
            stack_mask_group([_to_numpy(ds.getFeaturesMaskArray())
                              for ds in batches], "features-mask"),
            stack_mask_group([_to_numpy(ds.getLabelsMaskArray())
                              for ds in batches], "labels-mask"))


# ------------------------------------------------------------------ IRIS
def _iris_arrays():
    try:  # sklearn ships the CSV inside the wheel — no network needed
        from sklearn.datasets import load_iris

        d = load_iris()
        return d.data.astype(np.float32), d.target.astype(np.int64), False
    except Exception:
        # synthetic stand-in: 3 Gaussian clusters in 4-d with iris-like
        # means/scales, 50 examples per class, fixed seed
        rng = np.random.RandomState(42)
        means = np.array([[5.0, 3.4, 1.5, 0.25], [5.9, 2.8, 4.3, 1.3],
                          [6.6, 3.0, 5.6, 2.0]], np.float32)
        scales = np.array([[0.35, 0.38, 0.17, 0.1], [0.51, 0.31, 0.47, 0.2],
                           [0.63, 0.32, 0.55, 0.27]], np.float32)
        f = np.concatenate([means[c] + scales[c] * rng.randn(50, 4)
                            for c in range(3)]).astype(np.float32)
        t = np.repeat(np.arange(3), 50)
        return f, t, True


class IrisDataSetIterator(DataSetIterator):
    """Reference: org.deeplearning4j.datasets.iterator.impl.IrisDataSetIterator."""

    def __init__(self, batchSize: int = 150, numExamples: int = 150,
                 shuffle=False, seed=123):
        f, t, synth = _iris_arrays()
        f, t = f[:numExamples], t[:numExamples]
        labels = np.eye(3, dtype=np.float32)[t]
        self.isSynthetic = synth
        super().__init__(f, labels, batchSize, shuffle=shuffle, seed=seed)


# ------------------------------------------------------------------ MNIST
def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as fh:
        magic, = struct.unpack(">i", fh.read(4))
        ndim = magic & 0xFF
        shape = struct.unpack(f">{ndim}i", fh.read(4 * ndim))
        return np.frombuffer(fh.read(), np.uint8).reshape(shape)


def _find_idx(base: Path, names: list[str]):
    for n in names:
        for cand in (base / n, base / (n + ".gz")):
            if cand.exists():
                return cand
    return None


def _synthetic_digits(n: int, classes: int, hw: int, channels: int,
                      template_seed: int, noise_seed: int):
    """Deterministic class-conditional images: each class is a fixed random
    low-frequency template; examples are the template plus pixel noise and
    a small random translation. Templates depend only on ``template_seed``
    so the train and test splits (different ``noise_seed``) draw from the
    SAME class distributions — a model trained on the synthetic train split
    generalises to the synthetic test split, like real MNIST."""
    trng = np.random.RandomState(template_seed)
    # low-freq templates: upsampled coarse grids, one per class
    coarse = trng.rand(classes, channels, 7, 7).astype(np.float32)
    reps = hw // 7 + 1
    templates = np.kron(coarse, np.ones((1, 1, reps, reps), np.float32))[:, :, :hw, :hw]
    rng = np.random.RandomState(noise_seed)
    labels = rng.randint(0, classes, n)
    out = np.empty((n, channels, hw, hw), np.float32)
    shifts = rng.randint(-2, 3, size=(n, 2))
    noise = rng.rand(n, channels, hw, hw).astype(np.float32)
    for i in range(n):
        img = np.roll(templates[labels[i]], tuple(shifts[i]), axis=(1, 2))
        out[i] = np.clip(0.75 * img + 0.25 * noise[i], 0, 1)
    return (out * 255).astype(np.uint8), labels


_MNIST_MIRRORS = (
    # reference: MnistFetcher downloads from these well-known hosts
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
)
_MNIST_FILES = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
                "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")


def fetch_mnist(timeout: float = 15.0) -> bool:
    """Fetch-or-cache real MNIST into ``$DL4J_TPU_DATA_DIR/mnist``
    (reference: base.MnistFetcher). Returns True when the four idx files
    are present afterwards (already cached, or downloaded now). Failure is
    LOUD (warning naming every mirror tried), never an exception —
    air-gapped hosts fall back to synthetic data visibly."""
    import warnings

    base = _data_dir() / "mnist"
    base.mkdir(parents=True, exist_ok=True)

    def have_all():
        return all(
            _find_idx(base, [f.replace(".gz", "")]) is not None
            for f in _MNIST_FILES)

    if have_all():
        return True
    import urllib.request

    errors = []
    for f in _MNIST_FILES:
        if _find_idx(base, [f.replace(".gz", "")]) is not None:
            continue
        ok = False
        for mirror in _MNIST_MIRRORS:
            tmp = base / (f + ".part")
            try:
                # write to a temp name and rename only after validating so
                # neither an interrupted download nor a captive portal's
                # HTML-with-200 can poison the cache
                with urllib.request.urlopen(mirror + f,
                                            timeout=timeout) as resp, \
                        open(tmp, "wb") as out:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        out.write(chunk)
                with open(tmp, "rb") as fh:
                    if fh.read(2) != b"\x1f\x8b":
                        raise ValueError("not gzip (captive portal?)")
                import gzip

                with gzip.open(tmp, "rb") as gz:  # idx magic: 0x0000 08/01
                    head = gz.read(4)
                    if len(head) != 4 or head[:2] != b"\x00\x00":
                        raise ValueError("not an idx file")
                tmp.rename(base / f)
                ok = True
                break
            except Exception as e:  # per-mirror: keep trying
                errors.append(f"{mirror}{f}: {type(e).__name__}")
                tmp.unlink(missing_ok=True)
        if not ok:
            break
    if not have_all():
        warnings.warn(
            "Real MNIST could not be fetched (no network egress?); tried "
            + "; ".join(errors[:6])
            + f". Drop the idx files into {base} to use real data — "
            "synthetic digits will be used instead.", stacklevel=2)
        return False
    return True


def _load_idx_or_synth(base, img_names, lbl_names, num_classes,
                       numExamples, seed, train, what):
    """Shared idx-or-synthetic loader behind the MNIST-family iterators:
    returns (uint8 images [N,1,28,28], int labels, isSynthetic)."""
    img_p = _find_idx(base, img_names)
    lbl_p = _find_idx(base, lbl_names)
    if img_p is not None and lbl_p is not None:
        return (_read_idx(img_p)[:, None, :, :],
                _read_idx(lbl_p).astype(np.int64), False)
    n = numExamples or 10000
    if not numExamples and train:
        import warnings

        warnings.warn(f"{what} idx files not found; using {n} synthetic "
                      f"examples (pass numExamples to override)",
                      stacklevel=3)
    imgs, labels = _synthetic_digits(n, num_classes, 28, 1,
                                     template_seed=seed,
                                     noise_seed=seed + (1 if train else 2))
    return imgs, labels, True


def _finish_mnist_like(self, imgs, labels, num_classes, numExamples,
                       batchSize, train, shuffle, seed, reshapeToCnn):
    """Shared truncate/scale/flatten/one-hot tail of the MNIST-family
    iterators."""
    if numExamples:
        imgs, labels = imgs[:numExamples], labels[:numExamples]
    f = imgs.astype(np.float32) / 255.0
    if not reshapeToCnn:
        f = f.reshape(len(f), -1)
    onehot = np.eye(num_classes, dtype=np.float32)[labels]
    DataSetIterator.__init__(
        self, f, onehot, batchSize,
        shuffle=(train if shuffle is None else shuffle), seed=seed)


class MnistDataSetIterator(DataSetIterator):
    """Reference: MnistDataSetIterator — features [B, 784] float32 in [0, 1]
    (or [B, 1, 28, 28] with ``reshapeToCnn=True``), one-hot labels [B, 10].

    Looks for idx files (train-images-idx3-ubyte[.gz] etc.) under
    ``$DL4J_TPU_DATA_DIR/mnist`` (fetch_mnist() downloads and caches them
    when the host has egress); synthesises digits otherwise — loudly."""

    NUM_CLASSES = 10

    _DIR = "mnist"

    def __init__(self, batchSize: int, train: bool = True, seed: int = 123,
                 numExamples: int = None, shuffle: bool = None,
                 reshapeToCnn: bool = False):
        base = _data_dir() / self._DIR
        tag = "train" if train else "t10k"
        imgs, labels, self.isSynthetic = _load_idx_or_synth(
            base,
            [f"{tag}-images-idx3-ubyte", f"{tag}-images.idx3-ubyte"],
            [f"{tag}-labels-idx1-ubyte", f"{tag}-labels.idx1-ubyte"],
            self.NUM_CLASSES, numExamples, seed, train, self._DIR)
        _finish_mnist_like(self, imgs, labels, self.NUM_CLASSES,
                           numExamples, batchSize, train, shuffle, seed,
                           reshapeToCnn)


class Cifar10DataSetIterator(DataSetIterator):
    """Reference: Cifar10DataSetIterator — features [B, 3, 32, 32] float32,
    one-hot labels [B, 10]. Reads CIFAR-10 binary batches
    (data_batch_*.bin / test_batch.bin) under ``$DL4J_TPU_DATA_DIR/cifar10``;
    synthesises otherwise."""

    def __init__(self, batchSize: int, train: bool = True, seed: int = 123,
                 numExamples: int = None, shuffle: bool = None):
        base = _data_dir() / "cifar10"
        names = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [base / n for n in names]
        # the archive layout nests under cifar-10-batches-bin/
        nested = base / "cifar-10-batches-bin"
        if not all(p.exists() for p in paths) and nested.exists():
            paths = [nested / n for n in names]
        if all(p.exists() for p in paths):
            recs = np.concatenate([
                np.frombuffer(p.read_bytes(), np.uint8).reshape(-1, 3073)
                for p in paths])
            labels = recs[:, 0].astype(np.int64)
            imgs = recs[:, 1:].reshape(-1, 3, 32, 32)
            self.isSynthetic = False
        else:
            if numExamples:
                n = numExamples
            else:
                n = 10000
                if train:
                    import warnings

                    warnings.warn("CIFAR-10 batches not found; using 10000 "
                                  "synthetic examples (pass numExamples to "
                                  "override)", stacklevel=2)
            imgs, labels = _synthetic_digits(n, 10, 32, 3, template_seed=seed,
                                             noise_seed=seed + (1 if train else 2))
            self.isSynthetic = True
        if numExamples:
            imgs, labels = imgs[:numExamples], labels[:numExamples]
        f = imgs.astype(np.float32) / 255.0
        onehot = np.eye(10, dtype=np.float32)[labels]
        super().__init__(f, onehot, batchSize,
                         shuffle=(train if shuffle is None else shuffle), seed=seed)


# legacy alias matching the reference's older class name
CifarDataSetIterator = Cifar10DataSetIterator


class RandomDataSetIterator:
    """Reference: org.nd4j RandomDataSetIterator (Values.RANDOM_UNIFORM etc.)
    — synthetic batches for smoke tests and benchmarks. Batches are
    generated lazily, one per ``next()`` (seeded by batch index), so
    bench-scale shapes use constant host memory."""

    def __init__(self, numBatches: int, featuresShape, labelsShape, seed: int = 123):
        self._num = int(numBatches)
        self._fshape = tuple(featuresShape)
        self._lshape = tuple(labelsShape)
        self._seed = seed
        self._i = 0
        self._preprocessor = None

    def reset(self):
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < self._num

    def next(self, num=None):
        from deeplearning4j_tpu.data.dataset import DataSet

        rng = np.random.RandomState(self._seed + self._i)
        self._i += 1
        ds = DataSet(rng.rand(*self._fshape).astype(np.float32),
                     rng.rand(*self._lshape).astype(np.float32))
        if self._preprocessor is not None:
            self._preprocessor.preProcess(ds)
        return ds

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def batch(self) -> int:
        return self._fshape[0]

    def totalExamples(self) -> int:
        return self._num * self._fshape[0]

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return self._preprocessor


class FashionMnistDataSetIterator(MnistDataSetIterator):
    """Reference: FashionMnistDataSetIterator — identical idx format to
    MNIST (28x28, 10 classes), read from
    ``$DL4J_TPU_DATA_DIR/fashion-mnist``; synthesises loudly otherwise."""

    _DIR = "fashion-mnist"


class EmnistDataSetIterator(DataSetIterator):
    """Reference: EmnistDataSetIterator with its Set enum — the EMNIST
    splits share MNIST's idx format but differ in class count. Files
    ``emnist-<set>-{train,test}-{images,labels}-idx?-ubyte[.gz]`` under
    ``$DL4J_TPU_DATA_DIR/emnist``; synthetic fallback is loud."""

    SETS = {"complete": 62, "byclass": 62, "bymerge": 47, "balanced": 47,
            "letters": 26, "digits": 10, "mnist": 10}

    def __init__(self, dataSet: str, batchSize: int, train: bool = True,
                 seed: int = 123, numExamples: int = None,
                 shuffle: bool = None, reshapeToCnn: bool = False):
        key = str(dataSet).lower()
        if key not in self.SETS:
            raise ValueError(f"unknown EMNIST set {dataSet!r}; one of "
                             f"{sorted(self.SETS)}")
        self.numClasses = self.SETS[key]
        base = _data_dir() / "emnist"
        tag = "train" if train else "test"
        # "complete" is upstream's alias for the byclass files
        filekey = "byclass" if key == "complete" else key
        imgs, labels, self.isSynthetic = _load_idx_or_synth(
            base,
            [f"emnist-{filekey}-{tag}-images-idx3-ubyte"],
            [f"emnist-{filekey}-{tag}-labels-idx1-ubyte"],
            self.numClasses, numExamples, seed, train, f"EMNIST({key})")
        if not self.isSynthetic:
            if key == "letters":
                labels = labels - 1  # 1-based in the format
            # the official EMNIST idx files store images TRANSPOSED
            # relative to MNIST orientation; undo it so models/visuals
            # are orientation-compatible with MNIST (upstream does too)
            imgs = imgs.transpose(0, 1, 3, 2)
        _finish_mnist_like(self, imgs, labels, self.numClasses,
                           numExamples, batchSize, train, shuffle, seed,
                           reshapeToCnn)

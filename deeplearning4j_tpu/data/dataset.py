"""DataSet / DataSetIterator.

Reference: org.nd4j.linalg.dataset.DataSet and
org.nd4j.linalg.dataset.api.iterator.DataSetIterator. Iterators here yield
fixed-shape batches (padding the final partial batch when needed) because
XLA compiles one executable per shape — the reference's variable final
minibatch would force a recompile every epoch.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.ndarray import INDArray, Nd4j


def _wrap(a):
    if a is None or isinstance(a, INDArray):
        return a
    return INDArray(a) if not isinstance(a, np.ndarray) else Nd4j.create(a)


class DataSet:
    def __init__(self, features=None, labels=None, featuresMask=None, labelsMask=None):
        self._features = _wrap(features)
        self._labels = _wrap(labels)
        self._fmask = _wrap(featuresMask)
        self._lmask = _wrap(labelsMask)

    def getFeatures(self) -> INDArray:
        return self._features

    def getLabels(self) -> INDArray:
        return self._labels

    def getFeaturesMaskArray(self):
        return self._fmask

    def getLabelsMaskArray(self):
        return self._lmask

    def setFeatures(self, f):
        self._features = _wrap(f)

    def setLabels(self, l):
        self._labels = _wrap(l)

    def numExamples(self) -> int:
        return self._features.shape()[0] if self._features is not None else 0

    def sample(self, n: int, seed=None) -> "DataSet":
        rng = np.random.RandomState(seed)
        idx = rng.choice(self.numExamples(), size=n, replace=False)
        f = self._features.toNumpy()[idx]
        l = self._labels.toNumpy()[idx]
        return DataSet(f, l)

    def splitTestAndTrain(self, fraction_or_n):
        n = self.numExamples()
        n_train = int(fraction_or_n * n) if isinstance(fraction_or_n, float) else int(fraction_or_n)
        f, l = self._features.toNumpy(), self._labels.toNumpy()
        return SplitTestAndTrain(DataSet(f[:n_train], l[:n_train]),
                                 DataSet(f[n_train:], l[n_train:]))

    def shuffle(self, seed=None):
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.numExamples())
        self._features = _wrap(self._features.toNumpy()[idx])
        self._labels = _wrap(self._labels.toNumpy()[idx])

    def asList(self):
        f, l = self._features.toNumpy(), self._labels.toNumpy()
        return [DataSet(f[i:i + 1], l[i:i + 1]) for i in range(self.numExamples())]


class SplitTestAndTrain:
    def __init__(self, train, test):
        self._train, self._test = train, test

    def getTrain(self) -> DataSet:
        return self._train

    def getTest(self) -> DataSet:
        return self._test


def _pad_batch(f, l, fm, lm, n):
    """Pad a short final batch to n rows with repeated last rows and a
    zero label-mask over the pad, so XLA reuses one compiled executable
    per batch shape and the padded rows contribute no loss."""
    pad = n - len(f)
    f = np.concatenate([f, np.repeat(f[-1:], pad, axis=0)])
    l = np.concatenate([l, np.repeat(l[-1:], pad, axis=0)])
    if fm is not None:
        fm = np.concatenate([fm, np.repeat(fm[-1:], pad, axis=0)])
    if lm is None:
        lm = np.ones((n,) + (() if l.ndim == 2 else (l.shape[2],)),
                     np.float32)
        lm[-pad:] = 0.0
    else:
        lm = np.concatenate([lm, np.zeros((pad,) + lm.shape[1:], lm.dtype)])
    return f, l, fm, lm


class DataSetIterator:
    """Base in-memory iterator over (features, labels) arrays."""

    def __init__(self, features, labels, batchSize: int, shuffle=False, seed=123,
                 featuresMask=None, labelsMask=None, pad_final=True):
        self._f = np.asarray(features)
        self._l = np.asarray(labels)
        self._fm = None if featuresMask is None else np.asarray(featuresMask)
        self._lm = None if labelsMask is None else np.asarray(labelsMask)
        self._batch = int(batchSize)
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._pad_final = pad_final
        self._preprocessor = None
        self.reset()

    # ----- iterator protocol (reference names) ------------------------
    def reset(self):
        self._cursor = 0
        order = np.arange(len(self._f))
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            rng.shuffle(order)
        self._order = order
        self._epoch += 1

    def hasNext(self) -> bool:
        return self._cursor < len(self._f)

    def next(self, num=None) -> DataSet:
        n = num or self._batch
        idx = self._order[self._cursor:self._cursor + n]
        self._cursor += n
        f, l = self._f[idx], self._l[idx]
        fm = None if self._fm is None else self._fm[idx]
        lm = None if self._lm is None else self._lm[idx]
        if self._pad_final and len(idx) < n:
            f, l, fm, lm = _pad_batch(f, l, fm, lm, n)
        ds = DataSet(f, l, fm, lm)
        if self._preprocessor is not None:
            self._preprocessor.preProcess(ds)
        return ds

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def _raw_batches(self):
        """Yield (features, labels) numpy batches with NO padding and NO
        preprocessor — the view statistics-fitting code must see (used by
        DataNormalization.fit so padded duplicate rows and an already-set
        preprocessor can't bias the stats)."""
        for i in range(0, len(self._f), self._batch):
            idx = self._order[i:i + self._batch]
            yield self._f[idx], self._l[idx]

    def batch(self) -> int:
        return self._batch

    def totalExamples(self) -> int:
        return len(self._f)

    def inputColumns(self) -> int:
        return int(np.prod(self._f.shape[1:]))

    def totalOutcomes(self) -> int:
        return int(self._l.shape[-1])

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return self._preprocessor


class ListDataSetIterator(DataSetIterator):
    """Iterator over a list of DataSets (reference: ListDataSetIterator)."""

    def __init__(self, datasets, batchSize=None):
        f = np.concatenate([d.getFeatures().toNumpy() for d in datasets])
        l = np.concatenate([d.getLabels().toNumpy() for d in datasets])
        super().__init__(f, l, batchSize or len(f))


class ExistingDataSetIterator(DataSetIterator):
    def __init__(self, dataset: DataSet, batchSize=None):
        super().__init__(dataset.getFeatures().toNumpy(),
                         dataset.getLabels().toNumpy(),
                         batchSize or dataset.numExamples())


class KFoldIterator:
    """K-fold cross-validation splits over one DataSet (reference:
    org.deeplearning4j.datasets.iterator.KFoldIterator): next() yields
    the k-th TRAINING fold as a DataSet; testFold() returns the held-out
    fold for the split most recently emitted. Fold sizes follow the
    reference: the first N % k folds get one extra example."""

    def __init__(self, k: int, dataset: DataSet):
        if k < 2:
            raise ValueError("k must be >= 2")
        n = dataset.numExamples()
        if k > n:
            raise ValueError(f"k={k} exceeds the {n} examples")
        self.k = int(k)
        self._f = dataset.getFeatures().toNumpy()
        self._l = dataset.getLabels().toNumpy()
        base, extra = divmod(n, self.k)
        sizes = [base + (1 if i < extra else 0) for i in range(self.k)]
        bounds = np.cumsum([0] + sizes)
        self._folds = [(int(bounds[i]), int(bounds[i + 1]))
                       for i in range(self.k)]
        self.reset()

    def reset(self):
        self._i = 0
        # a stale held-out fold from a previous pass must not satisfy
        # testFold()'s call-next()-first contract
        if hasattr(self, "_test"):
            del self._test

    def hasNext(self) -> bool:
        return self._i < self.k

    def next(self) -> DataSet:
        if not self.hasNext():
            raise StopIteration
        lo, hi = self._folds[self._i]
        self._test = DataSet(self._f[lo:hi], self._l[lo:hi])
        train_f = np.concatenate([self._f[:lo], self._f[hi:]])
        train_l = np.concatenate([self._l[:lo], self._l[hi:]])
        self._i += 1
        return DataSet(train_f, train_l)

    def testFold(self) -> DataSet:
        if not hasattr(self, "_test"):
            raise RuntimeError("call next() first")
        return self._test

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class MultipleEpochsIterator:
    """Replays an underlying iterator numEpochs times as one epoch
    (reference: org.deeplearning4j.datasets.iterator
    .MultipleEpochsIterator) — lets fit(iterator) run multi-epoch
    training without a fit(..., epochs=) argument."""

    def __init__(self, numEpochs: int, underlying):
        if numEpochs < 1:
            raise ValueError("numEpochs must be >= 1")
        self.numEpochs = int(numEpochs)
        self._it = underlying
        self.reset()

    def reset(self):
        self._epoch = 0
        self._it.reset()

    def hasNext(self) -> bool:
        if self._it.hasNext():
            return True
        # hasNext()==True must guarantee next() succeeds: an EMPTY
        # underlying iterator has no batch in ANY remaining epoch, so
        # advance epochs (reset + re-check) until a batch is actually
        # available (ADVICE r4 — remaining epochs alone don't imply a
        # remaining batch). next() below tolerates the advanced state.
        while self._epoch + 1 < self.numEpochs:
            self._epoch += 1
            self._it.reset()
            if self._it.hasNext():
                return True
        return False

    def next(self, num=None) -> DataSet:
        if not self._it.hasNext():
            if self._epoch + 1 >= self.numEpochs:
                raise StopIteration
            self._epoch += 1
            self._it.reset()
            if not self._it.hasNext():  # empty underlying: same contract
                raise StopIteration
        return self._it.next(num) if num is not None else self._it.next()

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def batch(self):
        return self._it.batch()

    def totalExamples(self):
        return self._it.totalExamples() * self.numEpochs

    def inputColumns(self):
        return self._it.inputColumns()

    def totalOutcomes(self):
        return self._it.totalOutcomes()

    def setPreProcessor(self, pp):
        self._it.setPreProcessor(pp)

    def getPreProcessor(self):
        return self._it.getPreProcessor()

    def _raw_batches(self):
        # normalizer statistics fitting: one UNPADDED pass over the
        # underlying data — replaying epochs or seeing pad rows would
        # bias the stats (see DataSetIterator._raw_batches)
        return self._it._raw_batches()


class ViewIterator(ExistingDataSetIterator):
    """Batched view over one DataSet (reference:
    org.deeplearning4j.datasets.iterator.impl.ViewIterator). Same
    unwrapping as ExistingDataSetIterator, but batchSize is required."""

    def __init__(self, dataset: DataSet, batchSize: int):
        super().__init__(dataset, int(batchSize))


class MiniBatchFileDataSetIterator:
    """Disk-backed minibatches (reference: org.deeplearning4j.datasets
    .iterator.MiniBatchFileDataSetIterator): splits a DataSet into one
    .npz file per batch under rootDir at construction, then streams
    them back one at a time — the host never holds more than one batch
    after the initial split, which is the point for datasets larger
    than host RAM that arrive batch-wise. Masks persist with their
    batches, and the final short batch pads like every other iterator
    here (fixed shapes, one XLA executable)."""

    def __init__(self, dataset: DataSet, batchSize: int, rootDir=None,
                 delete_on_exhaust=False, pad_final=True):
        import os
        import tempfile

        self._dir = str(rootDir) if rootDir is not None \
            else tempfile.mkdtemp(prefix="minibatch_")
        os.makedirs(self._dir, exist_ok=True)
        self._batch = int(batchSize)
        self._delete = bool(delete_on_exhaust)
        self._pad_final = bool(pad_final)
        f = dataset.getFeatures().toNumpy()
        l = dataset.getLabels().toNumpy()
        fm = dataset.getFeaturesMaskArray()
        lm = dataset.getLabelsMaskArray()
        fm = None if fm is None else fm.toNumpy()
        lm = None if lm is None else lm.toNumpy()
        self._n = len(f)
        self._in_cols = int(np.prod(f.shape[1:]))
        self._outcomes = int(l.shape[-1])
        self._paths = []
        for i in range(0, len(f), self._batch):
            p = os.path.join(self._dir, f"dataset-{len(self._paths)}.npz")
            rec = {"features": f[i:i + self._batch],
                   "labels": l[i:i + self._batch]}
            if fm is not None:
                rec["features_mask"] = fm[i:i + self._batch]
            if lm is not None:
                rec["labels_mask"] = lm[i:i + self._batch]
            np.savez(p, **rec)
            self._paths.append(p)
        self._preprocessor = None
        self._exhausted_deleted = False
        self.reset()

    def rootDir(self):
        return self._dir

    def reset(self):
        if self._exhausted_deleted:
            raise RuntimeError(
                "this MiniBatchFileDataSetIterator was built with "
                "delete_on_exhaust=True and its batch files are gone — "
                "a reset would silently iterate zero batches")
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < len(self._paths)

    def _load(self, i):
        z = np.load(self._paths[i])
        return (z["features"], z["labels"],
                z["features_mask"] if "features_mask" in z.files else None,
                z["labels_mask"] if "labels_mask" in z.files else None)

    def next(self, num=None) -> DataSet:
        import os

        if num is not None and int(num) != self._batch:
            raise ValueError(
                f"batches were split to files at batchSize={self._batch}; "
                f"next({num}) cannot re-batch them")
        if not self.hasNext():
            raise StopIteration
        f, l, fm, lm = self._load(self._i)
        if self._pad_final and len(f) < self._batch:
            f, l, fm, lm = _pad_batch(f, l, fm, lm, self._batch)
        ds = DataSet(f, l, fm, lm)
        self._i += 1
        if self._delete and not self.hasNext():
            for p in self._paths:
                os.unlink(p)
            self._paths = []
            self._exhausted_deleted = True
        if self._preprocessor is not None:
            self._preprocessor.preProcess(ds)
        return ds

    def _raw_batches(self):
        # unpadded, preprocessor-free pass for normalizer statistics
        # (same contract as DataSetIterator._raw_batches)
        for i in range(len(self._paths)):
            f, l, _, _ = self._load(i)
            yield f, l

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def batch(self) -> int:
        return self._batch

    def totalExamples(self) -> int:
        return self._n

    def inputColumns(self) -> int:
        return self._in_cols

    def totalOutcomes(self) -> int:
        return self._outcomes

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return self._preprocessor


def _npz_member_shapes(path, *names):
    """Shapes of arrays inside an .npz WITHOUT decompressing their data:
    one ZipFile open, parsing just each member's .npy format header."""
    import zipfile

    shapes = {}
    with zipfile.ZipFile(path) as zf:
        for name in names:
            with zf.open(name + ".npy") as fh:
                version = np.lib.format.read_magic(fh)
                if version == (1, 0):
                    shape, _, _ = np.lib.format.read_array_header_1_0(fh)
                else:
                    shape, _, _ = np.lib.format.read_array_header_2_0(fh)
            shapes[name] = shape
    return shapes


class ExistingMiniBatchDataSetIterator:
    """Streams previously saved minibatch files (reference:
    org.deeplearning4j.datasets.iterator.ExistingMiniBatchDataSetIterator)
    — the read-side pair of MiniBatchFileDataSetIterator: point it at a
    rootDir of dataset-*.npz files (any directory the writer produced,
    from this process or an earlier one)."""

    def __init__(self, rootDir, pattern="dataset-%d.npz", pad_final=True):
        import os
        import re

        self._dir = str(rootDir)
        if not os.path.isdir(self._dir):
            raise ValueError(f"{self._dir} is not a directory")
        rx = re.compile("^" + re.escape(pattern).replace("%d", r"(\d+)")
                        + "$")
        found = []
        for f in os.listdir(self._dir):
            m = rx.match(f)
            if m:
                found.append((int(m.group(1)), os.path.join(self._dir, f)))
        if not found:
            raise ValueError(
                f"no files matching {pattern!r} in {self._dir}")
        self._paths = [p for _, p in sorted(found)]
        self._pad_final = bool(pad_final)
        # batch size = the writer's (first file's) row count; total
        # examples = true rows on disk — the metadata sweep reads ONLY
        # each member's .npy header (ADVICE r4: np.load + touching the
        # array decompressed every full features buffer, O(dataset) I/O
        # at construction, against the streaming intent)
        first = _npz_member_shapes(self._paths[0], "features", "labels")
        self._in_cols = int(np.prod(first["features"][1:]))
        self._outcomes = int(first["labels"][-1])
        sizes = [first["features"][0]] + [
            int(_npz_member_shapes(p, "features")["features"][0])
            for p in self._paths[1:]]
        self._batch = sizes[0]
        self._n = sum(sizes)
        self._preprocessor = None
        self.reset()

    def reset(self):
        self._i = 0

    def hasNext(self) -> bool:
        return self._i < len(self._paths)

    def _load(self, i):
        z = np.load(self._paths[i])
        return (z["features"], z["labels"],
                z["features_mask"] if "features_mask" in z.files else None,
                z["labels_mask"] if "labels_mask" in z.files else None)

    def next(self, num=None) -> DataSet:
        if num is not None and int(num) != self._batch:
            raise ValueError(
                f"batches were split to files at batchSize={self._batch}; "
                f"next({num}) cannot re-batch them")
        if not self.hasNext():
            raise StopIteration
        f, l, fm, lm = self._load(self._i)
        self._i += 1
        if self._pad_final and len(f) < self._batch:
            f, l, fm, lm = _pad_batch(f, l, fm, lm, self._batch)
        ds = DataSet(f, l, fm, lm)
        if self._preprocessor is not None:
            self._preprocessor.preProcess(ds)
        return ds

    def _raw_batches(self):
        # unpadded, preprocessor-free pass for normalizer statistics
        for i in range(len(self._paths)):
            f, l, _, _ = self._load(i)
            yield f, l

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def batch(self) -> int:
        return self._batch

    def totalExamples(self) -> int:
        return self._n

    def inputColumns(self) -> int:
        return self._in_cols

    def totalOutcomes(self) -> int:
        return self._outcomes

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return self._preprocessor

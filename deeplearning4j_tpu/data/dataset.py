"""DataSet / DataSetIterator.

Reference: org.nd4j.linalg.dataset.DataSet and
org.nd4j.linalg.dataset.api.iterator.DataSetIterator. Iterators here yield
fixed-shape batches (padding the final partial batch when needed) because
XLA compiles one executable per shape — the reference's variable final
minibatch would force a recompile every epoch.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.ndarray import INDArray, Nd4j


def _wrap(a):
    if a is None or isinstance(a, INDArray):
        return a
    return INDArray(a) if not isinstance(a, np.ndarray) else Nd4j.create(a)


class DataSet:
    def __init__(self, features=None, labels=None, featuresMask=None, labelsMask=None):
        self._features = _wrap(features)
        self._labels = _wrap(labels)
        self._fmask = _wrap(featuresMask)
        self._lmask = _wrap(labelsMask)

    def getFeatures(self) -> INDArray:
        return self._features

    def getLabels(self) -> INDArray:
        return self._labels

    def getFeaturesMaskArray(self):
        return self._fmask

    def getLabelsMaskArray(self):
        return self._lmask

    def setFeatures(self, f):
        self._features = _wrap(f)

    def setLabels(self, l):
        self._labels = _wrap(l)

    def numExamples(self) -> int:
        return self._features.shape()[0] if self._features is not None else 0

    def sample(self, n: int, seed=None) -> "DataSet":
        rng = np.random.RandomState(seed)
        idx = rng.choice(self.numExamples(), size=n, replace=False)
        f = self._features.toNumpy()[idx]
        l = self._labels.toNumpy()[idx]
        return DataSet(f, l)

    def splitTestAndTrain(self, fraction_or_n):
        n = self.numExamples()
        n_train = int(fraction_or_n * n) if isinstance(fraction_or_n, float) else int(fraction_or_n)
        f, l = self._features.toNumpy(), self._labels.toNumpy()
        return SplitTestAndTrain(DataSet(f[:n_train], l[:n_train]),
                                 DataSet(f[n_train:], l[n_train:]))

    def shuffle(self, seed=None):
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.numExamples())
        self._features = _wrap(self._features.toNumpy()[idx])
        self._labels = _wrap(self._labels.toNumpy()[idx])

    def asList(self):
        f, l = self._features.toNumpy(), self._labels.toNumpy()
        return [DataSet(f[i:i + 1], l[i:i + 1]) for i in range(self.numExamples())]


class SplitTestAndTrain:
    def __init__(self, train, test):
        self._train, self._test = train, test

    def getTrain(self) -> DataSet:
        return self._train

    def getTest(self) -> DataSet:
        return self._test


class DataSetIterator:
    """Base in-memory iterator over (features, labels) arrays."""

    def __init__(self, features, labels, batchSize: int, shuffle=False, seed=123,
                 featuresMask=None, labelsMask=None, pad_final=True):
        self._f = np.asarray(features)
        self._l = np.asarray(labels)
        self._fm = None if featuresMask is None else np.asarray(featuresMask)
        self._lm = None if labelsMask is None else np.asarray(labelsMask)
        self._batch = int(batchSize)
        self._shuffle = shuffle
        self._seed = seed
        self._epoch = 0
        self._pad_final = pad_final
        self._preprocessor = None
        self.reset()

    # ----- iterator protocol (reference names) ------------------------
    def reset(self):
        self._cursor = 0
        order = np.arange(len(self._f))
        if self._shuffle:
            rng = np.random.RandomState(self._seed + self._epoch)
            rng.shuffle(order)
        self._order = order
        self._epoch += 1

    def hasNext(self) -> bool:
        return self._cursor < len(self._f)

    def next(self, num=None) -> DataSet:
        n = num or self._batch
        idx = self._order[self._cursor:self._cursor + n]
        self._cursor += n
        f, l = self._f[idx], self._l[idx]
        fm = None if self._fm is None else self._fm[idx]
        lm = None if self._lm is None else self._lm[idx]
        if self._pad_final and len(idx) < n:
            # pad to full batch with repeated rows + zero label-mask so XLA
            # reuses the compiled executable; loss of padded rows is masked
            pad = n - len(idx)
            f = np.concatenate([f, np.repeat(f[-1:], pad, axis=0)])
            l = np.concatenate([l, np.repeat(l[-1:], pad, axis=0)])
            if fm is not None:
                fm = np.concatenate([fm, np.repeat(fm[-1:], pad, axis=0)])
            if lm is None:
                lm = np.ones((n,) + (() if l.ndim == 2 else (l.shape[2],)), np.float32)
                lm[-pad:] = 0.0
            else:
                lm = np.concatenate([lm, np.zeros((pad,) + lm.shape[1:], lm.dtype)])
        ds = DataSet(f, l, fm, lm)
        if self._preprocessor is not None:
            self._preprocessor.preProcess(ds)
        return ds

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def _raw_batches(self):
        """Yield (features, labels) numpy batches with NO padding and NO
        preprocessor — the view statistics-fitting code must see (used by
        DataNormalization.fit so padded duplicate rows and an already-set
        preprocessor can't bias the stats)."""
        for i in range(0, len(self._f), self._batch):
            idx = self._order[i:i + self._batch]
            yield self._f[idx], self._l[idx]

    def batch(self) -> int:
        return self._batch

    def totalExamples(self) -> int:
        return len(self._f)

    def inputColumns(self) -> int:
        return int(np.prod(self._f.shape[1:]))

    def totalOutcomes(self) -> int:
        return int(self._l.shape[-1])

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return self._preprocessor


class ListDataSetIterator(DataSetIterator):
    """Iterator over a list of DataSets (reference: ListDataSetIterator)."""

    def __init__(self, datasets, batchSize=None):
        f = np.concatenate([d.getFeatures().toNumpy() for d in datasets])
        l = np.concatenate([d.getLabels().toNumpy() for d in datasets])
        super().__init__(f, l, batchSize or len(f))


class ExistingDataSetIterator(DataSetIterator):
    def __init__(self, dataset: DataSet, batchSize=None):
        super().__init__(dataset.getFeatures().toNumpy(),
                         dataset.getLabels().toNumpy(),
                         batchSize or dataset.numExamples())

"""Audio feature extraction — the DataVec audio path.

Reference: datavec-data-audio (WavFileRecordReader + the spectrogram
feature extraction upstream delegates to musicg/JTransforms on the JVM
host). TPU-first design: framing, windowing, FFT, mel filterbank and
DCT all run as ONE jitted batched program — the mel projection and DCT
are matmuls (MXU work), and the whole front-end can sit on device in
front of an acoustic model exactly like image augmentation does.

Shapes: waveforms [B, T] float -> Spectrogram [B, frames, bins] ->
MelSpectrogram [B, frames, numMel] -> MFCC [B, frames, numCoeffs].
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.data.records import RecordReader


def _hann(n):
    # periodic Hann, the STFT convention
    return 0.5 - 0.5 * jnp.cos(2.0 * jnp.pi * jnp.arange(n) / n)


def _frame(x, frame_length, frame_step):
    """[B, T] -> [B, frames, frame_length]; trailing partial frame is
    dropped (static shapes)."""
    B, T = x.shape
    n = 1 + (T - frame_length) // frame_step
    if n < 1:
        raise ValueError(
            f"signal length {T} shorter than frame_length {frame_length}")
    idx = (jnp.arange(n)[:, None] * frame_step
           + jnp.arange(frame_length)[None, :])
    return x[:, idx]


def mel_filterbank(num_mel, fft_length, sample_rate, fmin=0.0, fmax=None):
    """[bins, num_mel] triangular mel filterbank (HTK mel scale —
    the convention upstream's speech examples use)."""
    fmax = fmax if fmax is not None else sample_rate / 2.0
    if not (0 <= fmin < fmax <= sample_rate / 2.0):
        raise ValueError(f"need 0 <= fmin < fmax <= nyquist, got "
                         f"[{fmin}, {fmax}] at rate {sample_rate}")

    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + np.asarray(f) / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (np.asarray(m) / 2595.0) - 1.0)

    bins = fft_length // 2 + 1
    mel_pts = np.linspace(hz_to_mel(fmin), hz_to_mel(fmax), num_mel + 2)
    hz_pts = mel_to_hz(mel_pts)
    bin_freqs = np.arange(bins) * sample_rate / fft_length
    fb = np.zeros((bins, num_mel), np.float32)
    for m in range(num_mel):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (bin_freqs - lo) / max(ctr - lo, 1e-9)
        down = (hi - bin_freqs) / max(hi - ctr, 1e-9)
        fb[:, m] = np.maximum(0.0, np.minimum(up, down))
    dead = np.flatnonzero(fb.max(0) == 0.0)
    if dead.size:
        raise ValueError(
            f"mel filters {dead.tolist()} are all-zero: triangles narrower "
            f"than the FFT bin spacing ({sample_rate / fft_length:.1f} Hz). "
            "Reduce num_mel or increase fft_length")
    return fb


def _dct2(n_in, n_out):
    """[n_in, n_out] orthonormal DCT-II matrix (scipy.fft.dct norm='ortho')."""
    k = np.arange(n_out)[None, :]
    i = np.arange(n_in)[:, None]
    m = np.cos(np.pi * k * (2 * i + 1) / (2.0 * n_in))
    m *= np.sqrt(2.0 / n_in)
    m[:, 0] *= np.sqrt(0.5)
    return m.astype(np.float32)


class SpectrogramTransform:
    """|STFT|^2 power spectrogram (reference: the musicg spectrogram
    upstream's audio readers produce). The full pipeline (framing,
    window, FFT, and subclasses' mel/DCT matmuls) compiles as ONE jitted
    program, created lazily on first apply()."""

    def __init__(self, frameLength=400, frameStep=160, fftLength=None):
        self.frameLength = int(frameLength)
        self.frameStep = int(frameStep)
        self.fftLength = int(fftLength or self.frameLength)
        if self.fftLength < self.frameLength:
            raise ValueError("fftLength must be >= frameLength")
        self._jit = None

    def _compute(self, x):
        frames = _frame(x, self.frameLength, self.frameStep)
        frames = frames * _hann(self.frameLength)
        spec = jnp.fft.rfft(frames, n=self.fftLength)
        return jnp.abs(spec) ** 2

    def apply(self, waveforms):
        x = jnp.asarray(waveforms, jnp.float32)
        if x.ndim != 2:
            raise ValueError(f"waveforms must be [B, T], got {x.shape}")
        if self._jit is None:  # lazy: subclass __init__ finishes first
            self._jit = jax.jit(self._compute)
        return self._jit(x)

    def __call__(self, waveforms):
        return self.apply(waveforms)


class MelSpectrogramTransform(SpectrogramTransform):
    def __init__(self, numMel=40, sampleRate=16000, fmin=0.0, fmax=None,
                 logScale=True, **kw):
        super().__init__(**kw)
        self.numMel = int(numMel)
        self.sampleRate = int(sampleRate)
        self.logScale = bool(logScale)
        self._fb = jnp.asarray(mel_filterbank(
            self.numMel, self.fftLength, self.sampleRate, fmin, fmax))

    def _compute(self, x):
        power = super()._compute(x)
        mel = power @ self._fb  # [B, frames, numMel] — an MXU matmul
        if self.logScale:
            mel = jnp.log(mel + 1e-6)
        return mel


class MFCCTransform(MelSpectrogramTransform):
    def __init__(self, numCoeffs=13, **kw):
        kw.setdefault("logScale", True)
        super().__init__(**kw)
        if not self.logScale:
            raise ValueError("MFCC requires logScale=True (DCT of log-mel)")
        self.numCoeffs = int(numCoeffs)
        if self.numCoeffs > self.numMel:
            raise ValueError(
                f"numCoeffs {self.numCoeffs} > numMel {self.numMel}")
        self._dct = jnp.asarray(_dct2(self.numMel, self.numCoeffs))

    def _compute(self, x):
        return super()._compute(x) @ self._dct


class WavFileRecordReader(RecordReader):
    """PCM .wav files -> float waveforms in [-1, 1] (reference:
    datavec-data-audio WavFileRecordReader). Directory layout and record
    shape mirror ImageRecordReader — ``root/<label>/<file>.wav`` ->
    ``[waveform float array, labelIndex]`` with getLabels()/numLabels()
    — so RecordReaderDataSetIterator consumes it directly. Stereo is
    averaged to mono; `length` pads/truncates to a fixed static shape.
    All files must share one sample rate (validated at initialize;
    exposed as `.sampleRate`)."""

    arrayRecords = True  # record = [array, labelIndex]

    def __init__(self, length=None):
        self.length = None if length is None else int(length)
        self.sampleRate = None
        self._files = []
        self._label_names = []
        self._i = 0

    def initialize(self, root):
        import wave
        from pathlib import Path

        root = Path(root)
        classes = sorted(d.name for d in root.iterdir() if d.is_dir())
        self._label_names = classes
        self._files = []
        rates = {}
        for ci, cname in enumerate(classes):
            for f in sorted((root / cname).iterdir()):
                if f.suffix.lower() == ".wav" and f.is_file():
                    self._files.append((f, ci))
                    with wave.open(str(f), "rb") as w:
                        rates.setdefault(w.getframerate(), f)
        if not self._files:
            raise ValueError(f"no .wav files under {root} "
                             "(expected root/<label>/<file>.wav)")
        if len(rates) > 1:
            raise ValueError(
                f"mixed sample rates {sorted(rates)} under {root}; "
                "resample to one rate first")
        self.sampleRate = next(iter(rates))
        self._i = 0
        return self

    def getLabels(self):
        return list(self._label_names)

    def numLabels(self) -> int:
        return len(self._label_names)

    def hasNext(self):
        return self._i < len(self._files)

    def reset(self):
        self._i = 0

    @staticmethod
    def _read(path):
        import wave

        with wave.open(str(path), "rb") as w:
            nch = w.getnchannels()
            width = w.getsampwidth()
            raw = w.readframes(w.getnframes())
            rate = w.getframerate()
        if width == 2:
            data = np.frombuffer(raw, "<i2").astype(np.float32) / 32768.0
        elif width == 1:  # unsigned 8-bit PCM
            data = (np.frombuffer(raw, "u1").astype(np.float32) - 128.0) / 128.0
        else:
            raise ValueError(f"unsupported WAV sample width {width} bytes")
        if nch > 1:
            data = data.reshape(-1, nch).mean(1)
        return data, rate

    def next(self):
        path, label = self._files[self._i]
        self._i += 1
        data, _ = self._read(path)
        if self.length is not None:
            if len(data) >= self.length:
                data = data[:self.length]
            else:
                data = np.pad(data, (0, self.length - len(data)))
        return [data, label]

"""Image augmentation transforms — the DataVec ImageTransform family.

Reference: datavec-data-image org.datavec.image.transform.{Flip,Crop,
Resize,Rotate,Pipeline}ImageTransform + ImageTransformProcess. Upstream
applies OpenCV ops per-image on the JVM host; TPU-first design runs the
whole batch as ONE jitted program on device — vectorized (vmap) random
flips/crops/rotations keyed by a counter-based RNG, so augmentation
rides the accelerator's idle ETL gap instead of the host CPU and is
bit-reproducible from (seed, batch counter).

Transforms operate on [B, H, W, C] float arrays (the internal layout);
`ImageAugmentationPreProcessor` is the DataSetPreProcessor adapter that
converts from/to the NCHW API layout around them.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


class ImageTransform:
    """Base: apply(key, images[B,H,W,C]) -> images. Pure (jit-safe)."""

    def apply(self, key, images):
        raise NotImplementedError

    def __call__(self, key, images):
        return self.apply(key, images)


class FlipImageTransform(ImageTransform):
    """Random horizontal flip per image (reference: FlipImageTransform;
    flipMode=1 — horizontal — is the augmentation one actually uses)."""

    def __init__(self, p=0.5):
        if not (0.0 <= p <= 1.0):
            raise ValueError(f"flip probability must be in [0,1], got {p}")
        self.p = float(p)

    def apply(self, key, images):
        flips = jax.random.bernoulli(key, self.p, (images.shape[0],))
        return jnp.where(flips[:, None, None, None],
                         images[:, :, ::-1, :], images)


class RandomCropTransform(ImageTransform):
    """Zero-pad by `pad` then crop a random [height, width] window per
    image (reference: CropImageTransform with random coords — the
    CIFAR/ImageNet pad-and-crop recipe)."""

    def __init__(self, height, width, pad=0):
        self.h, self.w, self.pad = int(height), int(width), int(pad)

    def apply(self, key, images):
        B, H, W, C = images.shape
        p = self.pad
        xp = jnp.pad(images, ((0, 0), (p, p), (p, p), (0, 0)))
        max_y = H + 2 * p - self.h
        max_x = W + 2 * p - self.w
        if max_y < 0 or max_x < 0:
            raise ValueError(
                f"crop {self.h}x{self.w} larger than padded image "
                f"{H + 2 * p}x{W + 2 * p}")
        ky, kx = jax.random.split(key)
        ys = jax.random.randint(ky, (B,), 0, max_y + 1)
        xs = jax.random.randint(kx, (B,), 0, max_x + 1)

        def crop_one(img, y, x):
            return jax.lax.dynamic_slice(img, (y, x, 0),
                                         (self.h, self.w, C))

        return jax.vmap(crop_one)(xp, ys, xs)


class ResizeImageTransform(ImageTransform):
    """Deterministic bilinear resize (reference: ResizeImageTransform)."""

    def __init__(self, height, width):
        self.h, self.w = int(height), int(width)

    def apply(self, key, images):
        B, _, _, C = images.shape
        return jax.image.resize(images, (B, self.h, self.w, C), "bilinear")


class RotateImageTransform(ImageTransform):
    """Random rotation, angle uniform in [-maxAngleDeg, +maxAngleDeg]
    about the image centre, bilinear sampling, zero fill (reference:
    RotateImageTransform)."""

    def __init__(self, maxAngleDeg):
        self.max_rad = float(maxAngleDeg) * np.pi / 180.0

    def apply(self, key, images):
        from jax.scipy.ndimage import map_coordinates

        B, H, W, C = images.shape
        angles = jax.random.uniform(key, (B,), jnp.float32,
                                    minval=-self.max_rad,
                                    maxval=self.max_rad)
        # the coordinate grid stays f32 whatever the image dtype: bf16's
        # 8-bit mantissa can't even represent integers past 256, which
        # would shift sample coords by up to a pixel on large images
        yy, xx = jnp.meshgrid(jnp.arange(H, dtype=jnp.float32),
                              jnp.arange(W, dtype=jnp.float32),
                              indexing="ij")
        cy, cx = (H - 1) / 2.0, (W - 1) / 2.0

        def rot_one(img, a):
            ca, sa = jnp.cos(a), jnp.sin(a)
            sy = cy + (yy - cy) * ca - (xx - cx) * sa
            sx = cx + (yy - cy) * sa + (xx - cx) * ca

            def chan(c):
                return map_coordinates(c.astype(jnp.float32), [sy, sx],
                                       order=1, mode="constant", cval=0.0)

            return jnp.stack([chan(img[..., k]) for k in range(C)],
                             -1).astype(img.dtype)

        return jax.vmap(rot_one)(images, angles)


class PipelineImageTransform(ImageTransform):
    """Sequential composition with independent per-stage keys
    (reference: PipelineImageTransform / ImageTransformProcess)."""

    def __init__(self, *transforms):
        if len(transforms) == 1 and isinstance(transforms[0], (list, tuple)):
            transforms = tuple(transforms[0])
        if not transforms:
            raise ValueError("PipelineImageTransform needs >= 1 transform")
        self.transforms = list(transforms)

    def apply(self, key, images):
        for i, t in enumerate(self.transforms):
            images = t.apply(jax.random.fold_in(key, i), images)
        return images


class ImageAugmentationPreProcessor:
    """DataSetPreProcessor adapter: set on any DataSetIterator via
    setPreProcessor. Applies the transform to each batch's features on
    device — NCHW API batches are converted to NHWC around the jitted
    transform. A per-batch counter folds into the seed, so a restarted
    run re-draws the identical augmentation stream (the framework's
    determinism contract)."""

    def __init__(self, transform: ImageTransform, seed=123,
                 dataFormat="NCHW"):
        self.transform = transform
        self.seed = int(seed)
        fmt = str(dataFormat).upper()
        if fmt not in ("NCHW", "NHWC"):
            raise ValueError(f"dataFormat must be NCHW or NHWC, got "
                             f"{dataFormat!r}")
        self.dataFormat = fmt
        self._counter = 0
        nchw = fmt == "NCHW"

        def run(key, x):
            # layout conversion INSIDE the jit: one fused XLA program
            # per batch, not three dispatches with two extra copies
            if nchw:
                x = jnp.transpose(x, (0, 2, 3, 1))
            x = self.transform.apply(key, x)
            if nchw:
                x = jnp.transpose(x, (0, 3, 1, 2))
            return x

        self._jit = jax.jit(run)

    def preProcess(self, ds):
        x = ds.getFeatures().jax()
        if x.ndim != 4:
            raise ValueError(
                f"image augmentation needs 4-d features, got shape "
                f"{tuple(x.shape)}")
        key = jax.random.fold_in(jax.random.key(self.seed), self._counter)
        self._counter += 1
        ds.setFeatures(self._jit(key, x))
        return ds

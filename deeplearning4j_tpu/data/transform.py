"""DataVec transform DSL: joins, reducers, condition filters, analysis.

Reference: datavec-api org.datavec.api.transform —
  join.Join (Inner/LeftOuter/RightOuter/FullOuter on key columns),
  reduce.Reducer (ReduceOp Sum/Mean/Count/Min/Max/Stdev by key),
  condition.* + filter.ConditionFilter,
  analysis.AnalyzeLocal -> DataAnalysis.
Upstream executes these on Spark; ETL is host-side by design there and
here — the device path starts where RecordReaderDataSetIterator hands
batches to the jitted trainers. These operate on the same
(Schema, list-of-records) pairs as data.records.TransformProcess.
"""

from __future__ import annotations

import math
from collections import OrderedDict

from deeplearning4j_tpu.data.records import Schema


# ---------------------------------------------------------------- conditions
class ConditionOp:
    LessThan = "LessThan"
    LessOrEqual = "LessOrEqual"
    GreaterThan = "GreaterThan"
    GreaterOrEqual = "GreaterOrEqual"
    Equal = "Equal"
    NotEqual = "NotEqual"
    InSet = "InSet"
    NotInSet = "NotInSet"

    _FNS = {
        "LessThan": lambda v, t: v < t,
        "LessOrEqual": lambda v, t: v <= t,
        "GreaterThan": lambda v, t: v > t,
        "GreaterOrEqual": lambda v, t: v >= t,
        "Equal": lambda v, t: v == t,
        "NotEqual": lambda v, t: v != t,
        "InSet": lambda v, t: v in t,
        "NotInSet": lambda v, t: v not in t,
    }


class ColumnCondition:
    """Reference: condition.column.*ColumnCondition. Evaluates one column
    of a record dict against a fixed value/set."""

    def __init__(self, column, op, value):
        if op not in ConditionOp._FNS:
            raise ValueError(f"unknown ConditionOp {op!r}")
        self.column = column
        self.op = op
        self.value = set(value) if op in (ConditionOp.InSet,
                                          ConditionOp.NotInSet) else value

    def condition(self, record: dict) -> bool:
        if self.column not in record:
            raise KeyError(f"condition column '{self.column}' not in record "
                           f"(have {sorted(record)})")
        return ConditionOp._FNS[self.op](record[self.column], self.value)


# upstream has typed variants; semantics are identical here
DoubleColumnCondition = ColumnCondition
IntegerColumnCondition = ColumnCondition
CategoricalColumnCondition = ColumnCondition
StringColumnCondition = ColumnCondition


class ConditionFilter:
    """Reference: filter.ConditionFilter — REMOVES records matching the
    condition. Usable directly as TransformProcess.Builder.filter(...)'s
    predicate."""

    def __init__(self, condition):
        self._c = condition

    def __call__(self, record: dict) -> bool:
        return self._c.condition(record)

    removeExample = __call__


# ---------------------------------------------------------------------- join
class Join:
    """Reference: transform.join.Join."""

    Inner = "Inner"
    LeftOuter = "LeftOuter"
    RightOuter = "RightOuter"
    FullOuter = "FullOuter"

    class Builder:
        def __init__(self, joinType="Inner"):
            if joinType not in (Join.Inner, Join.LeftOuter, Join.RightOuter,
                                Join.FullOuter):
                raise ValueError(f"unknown join type {joinType!r}")
            self._type = joinType
            self._keys = None
            self._left = None
            self._right = None

        def setJoinColumns(self, *names):
            self._keys = list(names)
            return self

        def setSchemas(self, left: Schema, right: Schema):
            self._left, self._right = left, right
            return self

        def build(self):
            if not self._keys or self._left is None or self._right is None:
                raise ValueError("Join needs setJoinColumns and setSchemas")
            for k in self._keys:
                for side, sch in (("left", self._left), ("right", self._right)):
                    if k not in sch.getColumnNames():
                        raise ValueError(
                            f"join column '{k}' missing from {side} schema "
                            f"{sch.getColumnNames()}")
            return Join(self._type, self._keys, self._left, self._right)

    def __init__(self, joinType, keys, left, right):
        self.joinType = joinType
        self.keys = keys
        self.left = left
        self.right = right

    def getOutputSchema(self) -> Schema:
        """Key columns once, then left non-key columns, then right
        non-key columns (upstream's column order)."""
        cols = [self.left._cols[self.left.getIndexOfColumn(k)]
                for k in self.keys]
        for n, k, m in self.left._cols:
            if n not in self.keys:
                cols.append((n, k, m))
        for n, k, m in self.right._cols:
            if n not in self.keys:
                cols.append((n, k, m))
        names = [c[0] for c in cols]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"joined schemas share non-key column names {sorted(dupes)}; "
                "rename them first (TransformProcess renameColumn)")
        return Schema(cols)


def executeJoin(join: Join, leftRecords, rightRecords):
    """Local join execution (reference: upstream executes Join on Spark;
    the algorithm — hash-join on the key tuple — is the same).
    Returns (outputSchema, records). Missing side in outer joins fills
    None (upstream NullWritable)."""
    out_schema = join.getOutputSchema()
    lnames = join.left.getColumnNames()
    rnames = join.right.getColumnNames()
    lkey = [join.left.getIndexOfColumn(k) for k in join.keys]
    rkey = [join.right.getIndexOfColumn(k) for k in join.keys]
    lrest = [i for i, n in enumerate(lnames) if n not in join.keys]
    rrest = [i for i, n in enumerate(rnames) if n not in join.keys]

    rindex = OrderedDict()
    for r in rightRecords:
        rindex.setdefault(tuple(r[i] for i in rkey), []).append(r)

    out = []
    matched_rkeys = set()
    for l in leftRecords:
        key = tuple(l[i] for i in lkey)
        matches = rindex.get(key)
        if matches:
            matched_rkeys.add(key)
            for r in matches:
                out.append(list(key) + [l[i] for i in lrest]
                           + [r[i] for i in rrest])
        elif join.joinType in (Join.LeftOuter, Join.FullOuter):
            out.append(list(key) + [l[i] for i in lrest]
                       + [None] * len(rrest))
    if join.joinType in (Join.RightOuter, Join.FullOuter):
        for key, rows in rindex.items():
            if key not in matched_rkeys:
                for r in rows:
                    out.append(list(key) + [None] * len(lrest)
                               + [r[i] for i in rrest])
    return out_schema, out


# ------------------------------------------------------------------- reducer
class ReduceOp:
    Sum = "Sum"
    Mean = "Mean"
    Count = "Count"
    Min = "Min"
    Max = "Max"
    Stdev = "Stdev"
    TakeFirst = "TakeFirst"
    TakeLast = "TakeLast"


def _stdev(vals):
    n = len(vals)
    if n < 2:
        return 0.0
    m = sum(vals) / n
    return math.sqrt(sum((v - m) ** 2 for v in vals) / (n - 1))  # sample,
    # matching upstream's StandardDeviation


_REDUCE_FNS = {
    ReduceOp.Sum: lambda vs: sum(float(v) for v in vs),
    ReduceOp.Mean: lambda vs: sum(float(v) for v in vs) / len(vs),
    ReduceOp.Count: len,
    ReduceOp.Min: lambda vs: min(float(v) for v in vs),
    ReduceOp.Max: lambda vs: max(float(v) for v in vs),
    ReduceOp.Stdev: lambda vs: _stdev([float(v) for v in vs]),
    ReduceOp.TakeFirst: lambda vs: vs[0],
    ReduceOp.TakeLast: lambda vs: vs[-1],
}


class Reducer:
    """Reference: transform.reduce.Reducer — group records by key
    columns, aggregate every other column."""

    class Builder:
        def __init__(self, defaultOp=ReduceOp.TakeFirst):
            if defaultOp not in _REDUCE_FNS:
                raise ValueError(f"unknown ReduceOp {defaultOp!r}")
            self._default = defaultOp
            self._keys = []
            self._ops = {}  # column -> op

        def keyColumns(self, *names):
            self._keys = list(names)
            return self

        def _add(self, op, names):
            for n in names:
                self._ops[n] = op
            return self

        def sumColumns(self, *names):
            return self._add(ReduceOp.Sum, names)

        def meanColumns(self, *names):
            return self._add(ReduceOp.Mean, names)

        def countColumns(self, *names):
            return self._add(ReduceOp.Count, names)

        def minColumns(self, *names):
            return self._add(ReduceOp.Min, names)

        def maxColumns(self, *names):
            return self._add(ReduceOp.Max, names)

        def stdevColumns(self, *names):
            return self._add(ReduceOp.Stdev, names)

        def takeFirstColumns(self, *names):
            return self._add(ReduceOp.TakeFirst, names)

        def takeLastColumns(self, *names):
            return self._add(ReduceOp.TakeLast, names)

        def build(self):
            if not self._keys:
                raise ValueError("Reducer needs keyColumns(...)")
            return Reducer(self._keys, self._ops, self._default)

    def __init__(self, keys, ops, default):
        self.keys = keys
        self.ops = ops
        self.default = default

    def _op_for(self, name):
        return self.ops.get(name, self.default)

    def getOutputSchema(self, schema: Schema) -> Schema:
        cols = []
        for n, k, m in schema._cols:
            if n in self.keys:
                cols.append((n, k, m))
                continue
            op = self._op_for(n)
            if op == ReduceOp.Count:
                cols.append((f"count({n})", "integer", None))
            elif op in (ReduceOp.TakeFirst, ReduceOp.TakeLast):
                cols.append((n, k, m))
            else:
                cols.append((f"{op.lower()}({n})", "double", None))
        return Schema(cols)

    def execute(self, schema: Schema, records):
        """-> (outputSchema, one record per distinct key, in first-seen
        key order)."""
        names = schema.getColumnNames()
        for k in self.keys:
            if k not in names:
                raise ValueError(f"key column '{k}' not in schema {names}")
        kidx = [schema.getIndexOfColumn(k) for k in self.keys]
        groups = OrderedDict()
        for r in records:
            groups.setdefault(tuple(r[i] for i in kidx), []).append(r)
        out = []
        for key, rows in groups.items():
            rec = []
            for i, n in enumerate(names):
                if n in self.keys:
                    rec.append(key[self.keys.index(n)])
                else:
                    rec.append(_REDUCE_FNS[self._op_for(n)](
                        [r[i] for r in rows]))
            out.append(rec)
        return self.getOutputSchema(schema), out


# ------------------------------------------------------------------ analysis
class NumericalColumnAnalysis:
    def __init__(self, vals):
        self.countTotal = len(vals)
        self.countMissing = sum(1 for v in vals if v is None)
        nums = [float(v) for v in vals if v is not None]
        self.min = min(nums) if nums else float("nan")
        self.max = max(nums) if nums else float("nan")
        self.mean = sum(nums) / len(nums) if nums else float("nan")
        self.sampleStdev = _stdev(nums)
        self.countZero = sum(1 for v in nums if v == 0.0)
        self.countNegative = sum(1 for v in nums if v < 0.0)

    def __repr__(self):
        return (f"min={self.min:g} max={self.max:g} mean={self.mean:g} "
                f"stdev={self.sampleStdev:g} n={self.countTotal} "
                f"missing={self.countMissing}")


class CategoricalColumnAnalysis:
    def __init__(self, vals):
        self.countTotal = len(vals)
        self.countMissing = sum(1 for v in vals if v is None)
        counts = {}
        for v in vals:
            if v is not None:
                counts[v] = counts.get(v, 0) + 1
        self.mapOfUniqueAndCounts = counts

    def getUnique(self):
        return sorted(self.mapOfUniqueAndCounts)

    def __repr__(self):
        return (f"states={self.getUnique()} n={self.countTotal} "
                f"missing={self.countMissing}")


class DataAnalysis:
    """Reference: transform.analysis.DataAnalysis (AnalyzeLocal output):
    per-column summary statistics, printable as a table."""

    def __init__(self, schema: Schema, analyses: dict):
        self.schema = schema
        self._a = analyses

    def getColumnAnalysis(self, name):
        if name not in self._a:
            raise ValueError(f"no analysis for column '{name}' "
                             f"(have {sorted(self._a)})")
        return self._a[name]

    def __repr__(self):
        rows = [f"  {n!r} ({self.schema.getType(n)}): {self._a[n]!r}"
                for n in self.schema.getColumnNames()]
        return "DataAnalysis[\n" + "\n".join(rows) + "\n]"


def analyze(schema: Schema, records) -> DataAnalysis:
    """Reference: AnalyzeLocal.analyze(schema, recordReader) — here over
    materialised records (the reader is already list-like host-side)."""
    analyses = {}
    for i, name in enumerate(schema.getColumnNames()):
        vals = [r[i] for r in records]
        if schema.getType(name) in ("double", "integer"):
            analyses[name] = NumericalColumnAnalysis(vals)
        else:
            analyses[name] = CategoricalColumnAnalysis(vals)
    return DataAnalysis(schema, analyses)


# --------------------------------------------------------------------
# data quality (reference: datavec-api transform.analysis.quality —
# AnalyzeLocal.analyzeQuality -> DataQualityAnalysis of per-column
# ColumnQuality counts)
# --------------------------------------------------------------------

class ColumnQuality:
    def __init__(self):
        self.countValid = 0
        self.countInvalid = 0
        self.countMissing = 0
        self.countTotal = 0

    def __repr__(self):
        extra = "".join(f" {k}={v}" for k, v in vars(self).items()
                        if k.startswith("count")
                        and k not in ("countValid", "countInvalid",
                                      "countMissing", "countTotal") and v)
        return (f"{type(self).__name__}(valid={self.countValid} "
                f"invalid={self.countInvalid} missing={self.countMissing} "
                f"total={self.countTotal}{extra})")


class DoubleColumnQuality(ColumnQuality):
    def __init__(self):
        super().__init__()
        self.countNaN = 0
        self.countInfinite = 0


class IntegerColumnQuality(ColumnQuality):
    pass


class CategoricalColumnQuality(ColumnQuality):
    pass


class StringColumnQuality(ColumnQuality):
    def __init__(self):
        super().__init__()
        self.countEmptyString = 0


class DataQualityAnalysis:
    """Reference: transform.analysis.quality.DataQualityAnalysis —
    per-column validity audit, printable as a table."""

    def __init__(self, schema: Schema, qualities: dict):
        self.schema = schema
        self._q = qualities

    def getColumnQuality(self, name) -> ColumnQuality:
        if name not in self._q:
            raise ValueError(f"no quality record for column '{name}' "
                             f"(have {sorted(self._q)})")
        return self._q[name]

    def isClean(self) -> bool:
        return all(q.countInvalid == 0 and q.countMissing == 0
                   for q in self._q.values())

    def __repr__(self):
        rows = [f"  {n!r} ({self.schema.getType(n)}): {self._q[n]!r}"
                for n in self.schema.getColumnNames()]
        return "DataQualityAnalysis[\n" + "\n".join(rows) + "\n]"


def _quality_double(vals):
    q = DoubleColumnQuality()
    for v in vals:
        if v is None:
            q.countMissing += 1
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            f = float(v)
        else:
            try:  # CSV-sourced records are strings: parse THEN classify,
                f = float(str(v))  # so 'nan'/'1e999' can't count valid
            except ValueError:
                q.countInvalid += 1
                continue
        if math.isnan(f):
            q.countNaN += 1
            q.countInvalid += 1
        elif math.isinf(f):
            q.countInfinite += 1
            q.countInvalid += 1
        else:
            q.countValid += 1
    return q


def _quality_integer(vals):
    q = IntegerColumnQuality()
    for v in vals:
        if v is None:
            q.countMissing += 1
        elif isinstance(v, bool):
            q.countInvalid += 1
        elif isinstance(v, int):
            q.countValid += 1
        elif isinstance(v, float):
            # non-finite floats cannot be int(v)'d — they are invalid,
            # not a crash (a quality audit must tolerate dirty data)
            if math.isfinite(v) and v == int(v):
                q.countValid += 1  # integral float parses upstream
            else:
                q.countInvalid += 1
        else:
            try:
                int(str(v))
                q.countValid += 1
            except ValueError:
                q.countInvalid += 1
    return q


def _quality_categorical(vals, states):
    q = CategoricalColumnQuality()
    for v in vals:
        if v is None:
            q.countMissing += 1
        elif states is not None and v in states:
            q.countValid += 1
        else:
            q.countInvalid += 1
    return q


def _quality_string(vals):
    q = StringColumnQuality()
    for v in vals:
        if v is None:
            q.countMissing += 1
        elif isinstance(v, str):
            q.countValid += 1
            if v == "":
                q.countEmptyString += 1
        else:
            q.countInvalid += 1
    return q


def analyzeQuality(schema: Schema, records) -> DataQualityAnalysis:
    """Reference: AnalyzeLocal.analyzeQuality(schema, recordReader).
    Every count* field sums to countTotal per column; `isClean()` is the
    gate a pipeline checks before training."""
    qualities = {}
    for i, name in enumerate(schema.getColumnNames()):
        vals = [r[i] for r in records]
        typ = schema.getType(name)
        if typ == "double":
            q = _quality_double(vals)
        elif typ == "integer":
            q = _quality_integer(vals)
        elif typ == "categorical":
            q = _quality_categorical(vals, schema.getMeta(name))
        else:
            q = _quality_string(vals)
        q.countTotal = len(vals)
        qualities[name] = q
    return DataQualityAnalysis(schema, qualities)

"""Datasets, iterators and normalizers.

Reference: org.nd4j.linalg.dataset + deeplearning4j-datasets.
"""

from deeplearning4j_tpu.data.dataset import (
    DataSet, DataSetIterator, ListDataSetIterator, ExistingDataSetIterator,
    SplitTestAndTrain,
)
from deeplearning4j_tpu.data.multidataset import MultiDataSet, MultiDataSetIterator

"""Datasets, iterators, normalizers and record readers.

Reference: org.nd4j.linalg.dataset + deeplearning4j-datasets + datavec.
"""

from deeplearning4j_tpu.data.dataset import (
    DataSet, DataSetIterator, ListDataSetIterator, ExistingDataSetIterator,
    KFoldIterator, MultipleEpochsIterator, ViewIterator,
    MiniBatchFileDataSetIterator, ExistingMiniBatchDataSetIterator,
    SplitTestAndTrain,
)
from deeplearning4j_tpu.data.multireader import (
    RecordReaderMultiDataSetIterator,
)
from deeplearning4j_tpu.data.multidataset import MultiDataSet, MultiDataSetIterator
from deeplearning4j_tpu.data.normalizers import (
    DataNormalization, NormalizerStandardize, NormalizerMinMaxScaler,
    ImagePreProcessingScaler, VGG16ImagePreProcessor,
)
from deeplearning4j_tpu.data.iterators import (
    IrisDataSetIterator, MnistDataSetIterator, FashionMnistDataSetIterator,
    EmnistDataSetIterator, Cifar10DataSetIterator,
    CifarDataSetIterator, RandomDataSetIterator,
)
from deeplearning4j_tpu.data.transform import (
    Join, executeJoin, Reducer, ReduceOp, ConditionFilter, ConditionOp,
    ColumnCondition, DoubleColumnCondition, IntegerColumnCondition,
    CategoricalColumnCondition, StringColumnCondition, DataAnalysis,
    analyze, DataQualityAnalysis, analyzeQuality,
)
from deeplearning4j_tpu.data.columnar import (
    ColumnarRecordReader, writeColumnar,
)
from deeplearning4j_tpu.data.augment import (
    ImageTransform, FlipImageTransform, RandomCropTransform,
    ResizeImageTransform, RotateImageTransform, PipelineImageTransform,
    ImageAugmentationPreProcessor,
)
from deeplearning4j_tpu.data.audio import (
    SpectrogramTransform, MelSpectrogramTransform, MFCCTransform,
    WavFileRecordReader, mel_filterbank,
)
from deeplearning4j_tpu.data.resilient import RetryingDataSetIterator
from deeplearning4j_tpu.data.records import (
    RecordReader, CSVRecordReader, CollectionRecordReader, ImageRecordReader,
    Schema, TransformProcess, RecordReaderDataSetIterator,
    CSVSequenceRecordReader, SequenceRecordReaderDataSetIterator,
)

__all__ = [
    "DataSet", "DataSetIterator", "ListDataSetIterator",
    "ExistingDataSetIterator", "KFoldIterator", "MultipleEpochsIterator",
    "ViewIterator", "MiniBatchFileDataSetIterator",
    "ExistingMiniBatchDataSetIterator", "RecordReaderMultiDataSetIterator",
    "SplitTestAndTrain", "MultiDataSet",
    "MultiDataSetIterator", "DataNormalization", "NormalizerStandardize",
    "NormalizerMinMaxScaler", "ImagePreProcessingScaler",
    "VGG16ImagePreProcessor", "IrisDataSetIterator", "MnistDataSetIterator", "FashionMnistDataSetIterator",
    "EmnistDataSetIterator",
    "Cifar10DataSetIterator", "CifarDataSetIterator", "RandomDataSetIterator",
    "RetryingDataSetIterator",
    "RecordReader", "CSVRecordReader", "CollectionRecordReader",
    "ImageRecordReader", "Schema", "TransformProcess",
    "RecordReaderDataSetIterator", "CSVSequenceRecordReader",
    "SequenceRecordReaderDataSetIterator", "Join", "executeJoin",
    "Reducer", "ReduceOp", "ConditionFilter", "ConditionOp",
    "ColumnCondition", "DoubleColumnCondition", "IntegerColumnCondition",
    "CategoricalColumnCondition", "StringColumnCondition",
    "DataAnalysis", "analyze", "DataQualityAnalysis", "analyzeQuality",
    "ColumnarRecordReader", "writeColumnar",
    "ImageTransform", "FlipImageTransform",
    "RandomCropTransform", "ResizeImageTransform",
    "RotateImageTransform", "PipelineImageTransform",
    "ImageAugmentationPreProcessor", "SpectrogramTransform",
    "MelSpectrogramTransform", "MFCCTransform", "WavFileRecordReader",
    "mel_filterbank",
]

"""Retrying data path.

Reference: production data sources (GCS/object stores, network record
readers) fail transiently; upstream's record readers surface those as
IOExceptions straight into fit(). RetryingDataSetIterator wraps any
DataSetIterator so transient fetch errors are absorbed with the shared
capped-backoff policy (runtime.resilience.RetryPolicy — the same one
checkpoint I/O uses) instead of killing a multi-hour pod job, while
non-transient errors still propagate after maxRetries.
"""

from __future__ import annotations

from deeplearning4j_tpu.runtime.resilience import RetryPolicy, retry


class RetryingDataSetIterator:
    """Wrap a DataSetIterator (or MultiDataSetIterator) so hasNext()/
    next() retry transient failures with deterministic backoff.

    retriesExhausted errors re-raise the ORIGINAL exception — callers
    see the same type the base iterator threw, just later. Retries are
    counted in .retries (per-run total) and observable via on_retry.
    """

    def __init__(self, base, policy: RetryPolicy = None, on_retry=None):
        self._base = base
        self._policy = policy or RetryPolicy()
        self.retries = 0
        self._user_on_retry = on_retry

    def _on_retry(self, attempt, exc, delay):
        self.retries += 1
        if self._user_on_retry is not None:
            self._user_on_retry(attempt, exc, delay)

    def reset(self):
        retry(self._base.reset, self._policy, self._on_retry)

    def hasNext(self):
        return retry(self._base.hasNext, self._policy, self._on_retry)

    def next(self, num=None):
        if num is None:  # some custom iterators define next(self) only
            return retry(self._base.next, self._policy, self._on_retry)
        return retry(lambda: self._base.next(num), self._policy,
                     self._on_retry)

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def __getattr__(self, name):  # batch()/totalExamples()/preprocessors
        return getattr(self._base, name)

"""RecordReaderMultiDataSetIterator — multi-input/-output batches from
record readers.

Reference: org.deeplearning4j.datasets.datavec
.RecordReaderMultiDataSetIterator (Builder: addReader / addInput /
addOutput / addOutputOneHot) — the standard way to feed a multi-input
ComputationGraph from tabular sources. Readers are materialized
host-side once into column-sliced float matrices (same design as
RecordReaderDataSetIterator), then batching/padding delegates to
MultiDataSetIterator so every batch is fixed-shape for XLA.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.data.multidataset import MultiDataSetIterator


class RecordReaderMultiDataSetIterator:
    class Builder:
        def __init__(self, batchSize: int):
            self._batch = int(batchSize)
            self._readers = {}   # name -> RecordReader
            self._specs = []     # (role, reader, kind, args) in call order
            self._sequence = set()  # names added via addSequenceReader

        def addReader(self, name, recordReader):
            if name in self._readers:
                raise ValueError(f"reader {name!r} already added")
            self._readers[name] = recordReader
            return self

        def addSequenceReader(self, name, sequenceReader):
            """A time-series reader (CSVSequenceRecordReader-style:
            next() returns one sequence as a list of per-step rows).
            Specs over it produce [B, C, T] NCW arrays padded to the
            reader's longest sequence, with the matching [B, T] mask
            attached at the spec's position (reference overload:
            RecordReaderMultiDataSetIterator.Builder
            .addSequenceReader)."""
            if name in self._readers:
                raise ValueError(f"reader {name!r} already added")
            self._readers[name] = sequenceReader
            self._sequence.add(name)
            return self

        def _check(self, name):
            if name not in self._readers:
                raise ValueError(
                    f"unknown reader {name!r}; addReader it first "
                    f"(have {sorted(self._readers)})")

        def addInput(self, readerName, columnFirst=None, columnLast=None):
            """All columns when no range is given (reference overload)."""
            self._check(readerName)
            self._specs.append(("input", readerName, "cols",
                                (columnFirst, columnLast)))
            return self

        def addOutput(self, readerName, columnFirst, columnLast):
            self._check(readerName)
            self._specs.append(("output", readerName, "cols",
                                (columnFirst, columnLast)))
            return self

        def addOutputOneHot(self, readerName, column, numClasses):
            self._check(readerName)
            self._specs.append(("output", readerName, "onehot",
                                (int(column), int(numClasses))))
            return self

        def build(self):
            if not any(r == "input" for r, *_ in self._specs):
                raise ValueError("at least one addInput(...) is required")
            if not any(r == "output" for r, *_ in self._specs):
                raise ValueError("at least one addOutput/"
                                 "addOutputOneHot(...) is required")
            return RecordReaderMultiDataSetIterator(
                self._batch, self._readers, self._specs,
                sequence=self._sequence)

    def __init__(self, batchSize, readers, specs, sequence=()):
        from deeplearning4j_tpu.data.records import CSVRecordReader

        sequence = set(sequence)
        records, matrices, seqs = {}, {}, {}
        for name, rr in readers.items():
            if name in sequence:
                rr.reset()
                out = []
                while rr.hasNext():
                    steps = rr.next()
                    if not steps:
                        raise ValueError(
                            f"sequence reader {name!r} produced an "
                            "empty sequence")
                    step_widths = {len(row) for row in steps}
                    if len(step_widths) > 1:
                        raise ValueError(
                            f"ragged sequence in reader {name!r} "
                            f"sequence {len(out)}: step widths "
                            f"{sorted(step_widths)}")
                    try:
                        out.append(np.asarray(
                            [[float(v) for v in row] for row in steps],
                            np.float32))
                    except (TypeError, ValueError):
                        raise ValueError(
                            f"non-numeric value in sequence reader "
                            f"{name!r} sequence {len(out)}")
                seqs[name] = out
                records[name] = None
                continue
            # bulk fast path first: EXACTLY CSVRecordReader (matching
            # RecordReaderDataSetIterator's native-parser contract) can
            # hand over the whole file as one float matrix
            m = rr.asMatrix() if type(rr) is CSVRecordReader else None
            if m is not None and m.ndim == 2:
                matrices[name] = m.astype(np.float32, copy=False)
                records[name] = None
                continue
            rr.reset()
            rows = []
            while rr.hasNext():
                rows.append(rr.next())
            records[name] = rows
        counts = {name: (len(seqs[name]) if name in seqs
                         else len(matrices[name]) if records[name] is None
                         else len(records[name]))
                  for name in readers}
        if len(set(counts.values())) > 1:
            raise ValueError(
                f"readers disagree on record count: {counts} — every "
                "reader must yield one record per example")
        n = next(iter(counts.values()))
        if n == 0:
            raise ValueError("readers produced no records")

        widths = {}
        seq_pack = {}   # name -> (padded [N, width, Tmax], mask [N, Tmax])
        for name in readers:
            if name in seqs:
                ss = seqs[name]
                wset = {a.shape[1] for a in ss}
                if len(wset) > 1:
                    raise ValueError(
                        f"sequence reader {name!r} has inconsistent "
                        f"column counts across sequences: {sorted(wset)}")
                widths[name] = wset.pop()
                tmax = max(a.shape[0] for a in ss)
                packed = np.zeros((len(ss), widths[name], tmax),
                                  np.float32)
                mask = np.zeros((len(ss), tmax), np.float32)
                for i, a in enumerate(ss):
                    packed[i, :, :a.shape[0]] = a.T   # [T,C] -> [C,T]
                    mask[i, :a.shape[0]] = 1.0
                seq_pack[name] = (packed, mask)
            elif records[name] is None:
                widths[name] = matrices[name].shape[1]
            else:
                widths[name] = min(len(r) for r in records[name])
        col_cache = {}

        def get_col(name, c):
            """One column of one reader as float32 — parsed ONCE no
            matter how many specs reference it. Ragged/non-numeric rows
            get row-numbered diagnostics."""
            hit = col_cache.get((name, c))
            if hit is not None:
                return hit
            if records[name] is None:
                out = matrices[name][:, c]
            else:
                # c < widths[name] = min row length (spec validation),
                # so indexing cannot go ragged here
                vals = np.empty(n, np.float32)
                for i, r in enumerate(records[name]):
                    try:
                        vals[i] = float(r[c])
                    except (TypeError, ValueError):
                        raise ValueError(
                            f"non-numeric value {r[c]!r} in reader "
                            f"{name!r} row {i} col {c} — one-hot-encode "
                            "categorical columns with addOutputOneHot "
                            "or transform first")
                out = vals
            col_cache[(name, c)] = out
            return out

        features, labels = [], []
        fmasks, lmasks = [], []
        for role, name, kind, args in specs:
            width = widths[name]
            if name in seq_pack:
                packed, mask = seq_pack[name]
                if kind == "cols":
                    first, last = args
                    first = 0 if first is None else int(first)
                    last = width - 1 if last is None else int(last)
                    if not (0 <= first <= last < width):
                        raise ValueError(
                            f"column range [{first}, {last}] out of "
                            f"bounds for sequence reader {name!r} with "
                            f"{width} columns")
                    arr = packed[:, first:last + 1, :]   # [N, C, T]
                else:  # onehot: per-step labels -> [N, num, T]
                    col, num = args
                    if not 0 <= col < width:
                        raise ValueError(
                            f"one-hot column {col} out of bounds for "
                            f"sequence reader {name!r} ({width} cols)")
                    idx = packed[:, col, :].astype(np.int64)  # [N, T]
                    # padded steps carry 0 — valid class index, masked
                    real = mask > 0
                    vals = idx[real]
                    if vals.size and (vals.min() < 0 or vals.max() >= num):
                        raise ValueError(
                            f"label value {vals.min() if vals.min() < 0 else vals.max()}"
                            f" outside [0, {num}) in sequence reader "
                            f"{name!r} col {col}")
                    arr = np.transpose(
                        np.eye(num, dtype=np.float32)[idx], (0, 2, 1))
                if role == "input":
                    features.append(arr)
                    fmasks.append(mask)
                else:
                    labels.append(arr)
                    lmasks.append(mask)
                continue
            if kind == "cols":
                first, last = args
                first = 0 if first is None else int(first)
                last = width - 1 if last is None else int(last)
                if not (0 <= first <= last < width):
                    raise ValueError(
                        f"column range [{first}, {last}] out of bounds "
                        f"for reader {name!r} with {width} columns "
                        "(shortest row governs)")
                arr = np.stack([get_col(name, c)
                                for c in range(first, last + 1)], axis=1)
            else:  # onehot
                col, num = args
                if not 0 <= col < width:
                    raise ValueError(f"one-hot column {col} out of bounds "
                                     f"for reader {name!r} ({width} cols)")
                idx = get_col(name, col).astype(np.int64)
                if idx.min() < 0 or idx.max() >= num:
                    raise ValueError(
                        f"label value {idx.min() if idx.min() < 0 else idx.max()}"
                        f" outside [0, {num}) in reader {name!r} col {col}")
                arr = np.eye(num, dtype=np.float32)[idx]
            if role == "input":
                features.append(arr)
                fmasks.append(None)
            else:
                labels.append(arr)
                lmasks.append(None)

        self._it = MultiDataSetIterator(
            features, labels, batchSize,
            featuresMasks=fmasks if any(m is not None for m in fmasks)
            else None,
            labelsMasks=lmasks if any(m is not None for m in lmasks)
            else None)
        self._batch = int(batchSize)
        self._n = n

    # ---- iterator protocol (delegates to MultiDataSetIterator) -------
    def hasNext(self):
        return self._it.hasNext()

    def next(self):
        return self._it.next()

    def reset(self):
        self._it.reset()

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def batch(self):
        return self._batch

    def totalExamples(self):
        return self._n

"""MultiDataSet — multiple feature/label arrays for ComputationGraph.

Reference: org.nd4j.linalg.dataset.MultiDataSet.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet, _wrap


class MultiDataSet:
    def __init__(self, features, labels, featuresMasks=None, labelsMasks=None):
        self._features = [_wrap(f) for f in self._as_list(features)]
        self._labels = [_wrap(l) for l in self._as_list(labels)]
        self._fmasks = None if featuresMasks is None else [_wrap(m) for m in self._as_list(featuresMasks)]
        self._lmasks = None if labelsMasks is None else [_wrap(m) for m in self._as_list(labelsMasks)]

    @staticmethod
    def _as_list(x):
        return x if isinstance(x, (list, tuple)) else [x]

    def getFeatures(self, idx=None):
        return self._features if idx is None else self._features[idx]

    def getLabels(self, idx=None):
        return self._labels if idx is None else self._labels[idx]

    def getFeaturesMaskArrays(self):
        return self._fmasks

    def getLabelsMaskArrays(self):
        return self._lmasks

    def numExamples(self) -> int:
        return self._features[0].shape()[0]


class MultiDataSetIterator:
    """Fixed-shape batches over multiple feature/label arrays; the final
    partial batch is padded with repeated rows and zeroed label masks so
    XLA never recompiles on a ragged tail (same design as DataSetIterator).
    """

    def __init__(self, featureArrays, labelArrays, batchSize,
                 featuresMasks=None, labelsMasks=None, pad_final=True):
        self._f = [np.asarray(f) for f in MultiDataSet._as_list(featureArrays)]
        self._l = [np.asarray(l) for l in MultiDataSet._as_list(labelArrays)]
        # per-array mask lists may carry None entries (reference:
        # MultiDataSet mask arrays are nullable per input/output — a
        # static input alongside a masked sequence input is the normal
        # multi-reader case)
        self._fm = None if featuresMasks is None else \
            [None if m is None else np.asarray(m)
             for m in MultiDataSet._as_list(featuresMasks)]
        self._lm = None if labelsMasks is None else \
            [None if m is None else np.asarray(m)
             for m in MultiDataSet._as_list(labelsMasks)]
        self._batch = int(batchSize)
        self._pad_final = pad_final
        self.reset()

    def reset(self):
        self._cursor = 0

    def hasNext(self):
        return self._cursor < len(self._f[0])

    @staticmethod
    def _pad(arrs, pad):
        return [None if a is None
                else np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
                for a in arrs]

    def next(self) -> MultiDataSet:
        sl = slice(self._cursor, self._cursor + self._batch)
        self._cursor += self._batch
        f = [a[sl] for a in self._f]
        l = [a[sl] for a in self._l]
        fm = None if self._fm is None else \
            [None if a is None else a[sl] for a in self._fm]
        lm = None if self._lm is None else \
            [None if a is None else a[sl] for a in self._lm]
        short = self._batch - len(f[0])
        if self._pad_final and short > 0:
            f = self._pad(f, short)
            l = self._pad(l, short)
            if fm is not None:
                fm = self._pad(fm, short)
            def tail_mask(lab):
                m = np.ones((self._batch,)
                            + (() if lab.ndim == 2 else (lab.shape[2],)),
                            np.float32)
                m[-short:] = 0.0
                return m

            if lm is None:
                lm = [tail_mask(lab) for lab in l]
            else:
                # a None entry must ALSO gain a pad-zeroing mask: its
                # label was padded with duplicated rows like the rest,
                # and an unmasked duplicate would count in the loss
                lm = [tail_mask(lab) if m is None
                      else np.concatenate(
                          [m, np.zeros((short,) + m.shape[1:], m.dtype)])
                      for m, lab in zip(lm, l)]
        return MultiDataSet(f, l, fm, lm)

"""Record readers and transform pipelines (the DataVec layer).

Reference: datavec-api (CSVRecordReader, CollectionRecordReader,
ImageRecordReader, Schema, TransformProcess) and
deeplearning4j-datavec-iterators (RecordReaderDataSetIterator). ETL runs on
host in numpy — the TPU sees only the final fixed-shape float batches.
"""

from __future__ import annotations

import operator
import os
from pathlib import Path

import numpy as np


# ----------------------------------------------------------- record readers
class RecordReader:
    def hasNext(self) -> bool:
        raise NotImplementedError

    def next(self) -> list:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class CollectionRecordReader(RecordReader):
    """In-memory records (reference: CollectionRecordReader)."""

    def __init__(self, records):
        self._records = [list(r) for r in records]
        self._i = 0

    def hasNext(self):
        return self._i < len(self._records)

    def next(self):
        r = self._records[self._i]
        self._i += 1
        return r

    def reset(self):
        self._i = 0


class CSVRecordReader(RecordReader):
    """Line-per-record CSV (reference: CSVRecordReader). Values come back as
    parsed floats where possible, else strings."""

    def __init__(self, skipNumLines: int = 0, delimiter: str = ","):
        self._skip = skipNumLines
        self._delim = delimiter
        self._lines = None
        self._path = None
        self._i = 0

    def initialize(self, path):
        text = Path(path).read_text()
        lines = [ln for ln in text.splitlines() if ln.strip()]
        self._lines = lines[self._skip:]
        self._path = str(path)
        try:
            st = os.stat(self._path)
            self._stat = (st.st_size, st.st_mtime_ns)
        except OSError:
            self._stat = None
        self._i = 0
        return self

    def asMatrix(self):
        """Whole file as a float32 [rows, cols] matrix via the native
        bulk parser (runtime/textparse.cpp — one buffer sweep instead of
        a per-token Python loop), or None when the content is not a
        clean numeric rectangle / no compiler is available. Callers
        (RecordReaderDataSetIterator) fall back to next()-loop
        semantics on None, so mixed-type CSVs behave exactly as before.

        Reads the file lazily (raw text is not kept resident); if the
        file was deleted or changed since initialize(), returns None so
        the caller serves the CACHED lines — next()-loop and fast path
        always see the same data."""
        if self._path is None:
            return None
        try:
            with open(self._path, "rb") as f:
                data = f.read()
                # fstat AFTER the read, on the open fd: stat-then-read
                # would race a concurrent rewrite between the two calls
                st = os.fstat(f.fileno())
            if self._stat != (st.st_size, st.st_mtime_ns):
                return None
        except OSError:
            return None
        from deeplearning4j_tpu.runtime.textparse import parse_csv_f32

        return parse_csv_f32(data, self._delim, self._skip)

    @staticmethod
    def _parse(tok: str):
        tok = tok.strip()
        try:
            return float(tok) if ("." in tok or "e" in tok.lower()) else int(tok)
        except ValueError:
            return tok

    def hasNext(self):
        return self._lines is not None and self._i < len(self._lines)

    def next(self):
        vals = [self._parse(t) for t in self._lines[self._i].split(self._delim)]
        self._i += 1
        return vals

    def reset(self):
        self._i = 0


class ImageRecordReader(RecordReader):
    """Images from a labelled directory tree (reference: ImageRecordReader
    with ParentPathLabelGenerator): ``root/<label>/<file>.png`` ->
    record ``[CHW float array, labelIndex]``."""

    arrayRecords = True  # record = [array, labelIndex]
    EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".gif"}

    def __init__(self, height: int, width: int, channels: int = 3):
        self._h, self._w, self._c = height, width, channels
        self._files = []
        self._labels = []
        self._i = 0

    def initialize(self, root):
        root = Path(root)
        classes = sorted(d.name for d in root.iterdir() if d.is_dir())
        self._label_names = classes
        self._files = []
        for ci, cname in enumerate(classes):
            for f in sorted((root / cname).iterdir()):
                if f.suffix.lower() in self.EXTS:
                    self._files.append((f, ci))
        self._i = 0
        return self

    def getLabels(self):
        return list(self._label_names)

    def numLabels(self) -> int:
        return len(self._label_names)

    def hasNext(self):
        return self._i < len(self._files)

    def next(self):
        from PIL import Image

        path, label = self._files[self._i]
        self._i += 1
        img = Image.open(path)
        img = img.convert("L" if self._c == 1 else "RGB")
        img = img.resize((self._w, self._h))
        a = np.asarray(img, np.float32)
        a = a[None, :, :] if self._c == 1 else a.transpose(2, 0, 1)  # CHW
        return [a, label]

    def reset(self):
        self._i = 0


# ------------------------------------------------------ schema + transforms
def _ieee_div(a, b):
    """IEEE-754 division matching the reference's Java double semantics:
    x/0.0 = ±Infinity, 0.0/0.0 = NaN — a zero divisor must not abort the
    whole pipeline like Python's ZeroDivisionError would."""
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.float64(a) / np.float64(b))


_MATH_OPS = {"Add": operator.add, "Subtract": operator.sub,
             "Multiply": operator.mul, "Divide": _ieee_div}


class Schema:
    """Column schema (reference: org.datavec.api.transform.schema.Schema)."""

    class Builder:
        def __init__(self):
            self._cols = []  # (name, kind, meta)

        def addColumnDouble(self, name):
            self._cols.append((name, "double", None))
            return self

        def addColumnsDouble(self, *names):
            for n in names:
                self.addColumnDouble(n)
            return self

        def addColumnInteger(self, name):
            self._cols.append((name, "integer", None))
            return self

        def addColumnCategorical(self, name, *stateNames):
            if len(stateNames) == 1 and isinstance(stateNames[0], (list, tuple)):
                stateNames = tuple(stateNames[0])
            self._cols.append((name, "categorical", list(stateNames)))
            return self

        def addColumnString(self, name):
            self._cols.append((name, "string", None))
            return self

        def build(self):
            return Schema(self._cols)

    def __init__(self, cols):
        self._cols = list(cols)

    def getColumnNames(self):
        return [c[0] for c in self._cols]

    def getIndexOfColumn(self, name) -> int:
        return self.getColumnNames().index(name)

    def getType(self, name) -> str:
        return self._cols[self.getIndexOfColumn(name)][1]

    def getMeta(self, name):
        return self._cols[self.getIndexOfColumn(name)][2]

    def numColumns(self) -> int:
        return len(self._cols)


class TransformProcess:
    """Declarative record transform pipeline (reference:
    org.datavec.api.transform.TransformProcess). Each step maps
    (schema, records) -> (schema, records); ``execute`` applies the chain."""

    class Builder:
        def __init__(self, schema: Schema):
            self._initial = schema
            self._steps = []
            # declarative call log for toJson/fromJson — filled
            # automatically by the method wrapper installed below the
            # class body; steps it cannot represent (raw-callable
            # filters) land in _unserializable and make toJson raise
            self._spec = []
            self._unserializable = []

        def removeColumns(self, *names):
            def step(schema, recs):
                drop = {schema.getIndexOfColumn(n) for n in names}
                keep = [i for i in range(schema.numColumns()) if i not in drop]
                new = Schema([schema._cols[i] for i in keep])
                return new, [[r[i] for i in keep] for r in recs]
            self._steps.append(step)
            return self

        def renameColumn(self, old, new):
            def step(schema, recs):
                cols = [(new if n == old else n, k, m) for n, k, m in schema._cols]
                return Schema(cols), recs
            self._steps.append(step)
            return self

        def categoricalToInteger(self, *names):
            def step(schema, recs):
                cols = list(schema._cols)
                for n in names:
                    i = schema.getIndexOfColumn(n)
                    states = schema.getMeta(n)
                    for r in recs:
                        r[i] = states.index(r[i])
                    cols[i] = (n, "integer", None)
                return Schema(cols), recs
            self._steps.append(step)
            return self

        def categoricalToOneHot(self, *names):
            def step(schema, recs):
                for n in names:
                    i = schema.getIndexOfColumn(n)
                    states = schema.getMeta(n)
                    cols = list(schema._cols)
                    onehot_cols = [(f"{n}[{s}]", "integer", None) for s in states]
                    cols[i:i + 1] = onehot_cols
                    for r in recs:
                        if r[i] not in states:  # consistent with ToInteger
                            raise ValueError(f"categoricalToOneHot: value "
                                             f"{r[i]!r} not in states {states}")
                        vec = [1 if r[i] == s else 0 for s in states]
                        r[i:i + 1] = vec
                    schema = Schema(cols)
                return schema, recs
            self._steps.append(step)
            return self

        def doubleMathOp(self, name, op: str, value: float):
            fn = _MATH_OPS[op]

            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    r[i] = fn(float(r[i]), value)
                return schema, recs
            self._steps.append(step)
            return self

        def filter(self, predicate):
            """Keep records where predicate(record_dict) is False (the
            reference's Filter removes matching examples)."""
            def step(schema, recs):
                names = schema.getColumnNames()
                kept = [r for r in recs
                        if not predicate(dict(zip(names, r)))]
                return schema, kept
            self._steps.append(step)
            return self

        def stringToCategorical(self, name, stateNames):
            """Reference: StringToCategoricalTransform — retype a string
            column, validating every value against the states."""
            states = list(stateNames)

            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    if r[i] not in states:
                        raise ValueError(
                            f"stringToCategorical: value {r[i]!r} in "
                            f"column '{name}' not in states {states}")
                cols = list(schema._cols)
                cols[i] = (name, "categorical", states)
                return Schema(cols), recs
            self._steps.append(step)
            return self

        def integerToCategorical(self, name, stateNames):
            """Reference: IntegerToCategoricalTransform — value k becomes
            stateNames[k]."""
            states = list(stateNames)

            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    k = int(r[i])
                    if not (0 <= k < len(states)):
                        raise ValueError(
                            f"integerToCategorical: value {k} in column "
                            f"'{name}' outside [0,{len(states)})")
                    r[i] = states[k]
                cols = list(schema._cols)
                cols[i] = (name, "categorical", states)
                return Schema(cols), recs
            self._steps.append(step)
            return self

        def stringMapTransform(self, name, mapping):
            """Reference: StringMapTransform — replace listed values,
            pass others through."""
            mapping = dict(mapping)

            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    r[i] = mapping.get(r[i], r[i])
                return schema, recs
            self._steps.append(step)
            return self

        def appendStringColumnTransform(self, name, toAppend):
            """Reference: AppendStringColumnTransform."""
            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    r[i] = str(r[i]) + toAppend
                return schema, recs
            self._steps.append(step)
            return self

        def conditionalReplaceValueTransform(self, name, newValue,
                                             condition):
            """Reference: ConditionalReplaceValueTransform — where the
            condition (data.transform ColumnCondition or any
            record-dict predicate) matches, replace the column value."""
            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                names = schema.getColumnNames()
                pred = getattr(condition, "condition", condition)
                for r in recs:
                    if pred(dict(zip(names, r))):
                        r[i] = newValue
                return schema, recs
            self._steps.append(step)
            return self

        def replaceMissingWithValue(self, name, value):
            """Missing = None, NaN, or the empty string (reference: the
            ReplaceInvalid / ReplaceEmpty family; "" is what
            CSVRecordReader yields for an absent field)."""
            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                for r in recs:
                    v = r[i]
                    if v is None or v == "" or \
                            (isinstance(v, float) and v != v):
                        r[i] = value
                return schema, recs
            self._steps.append(step)
            return self

        def doubleColumnsMathOp(self, newName, op, *columns):
            """Reference: DoubleColumnsMathOpTransform — NEW column from
            an op over existing double columns (Add/Subtract/Multiply/
            Divide fold left-to-right; Divide follows Java double
            semantics: x/0.0 = ±Infinity, 0.0/0.0 = NaN)."""
            fn = _MATH_OPS[op]

            def step(schema, recs):
                idx = [schema.getIndexOfColumn(c) for c in columns]
                for r in recs:
                    acc = float(r[idx[0]])
                    for i in idx[1:]:
                        acc = fn(acc, float(r[i]))
                    r.append(acc)
                return Schema(schema._cols + [(newName, "double", None)]), recs
            self._steps.append(step)
            return self

        def addConstantColumn(self, name, kind, value):
            """Reference: AddConstantColumnTransform."""
            def step(schema, recs):
                for r in recs:
                    r.append(value)
                return Schema(schema._cols + [(name, kind, None)]), recs
            self._steps.append(step)
            return self

        def duplicateColumn(self, name, newName):
            """Reference: DuplicateColumnsTransform."""
            def step(schema, recs):
                i = schema.getIndexOfColumn(name)
                kind, meta = schema._cols[i][1], schema._cols[i][2]
                for r in recs:
                    r.append(r[i])
                return Schema(schema._cols + [(newName, kind, meta)]), recs
            self._steps.append(step)
            return self

        def reorderColumns(self, *names):
            """Reference: ReorderColumnsTransform — listed columns first
            (in order), unlisted keep their relative order after."""
            def step(schema, recs):
                all_names = schema.getColumnNames()
                missing = [n for n in names if n not in all_names]
                if missing:
                    raise ValueError(f"reorderColumns: unknown {missing}")
                order = [all_names.index(n) for n in names] + \
                    [i for i, n in enumerate(all_names) if n not in names]
                new = Schema([schema._cols[i] for i in order])
                return new, [[r[i] for i in order] for r in recs]
            self._steps.append(step)
            return self

        def removeAllColumnsExceptFor(self, *names):
            """Reference: TransformProcess.Builder
            .removeAllColumnsExceptFor."""
            def step(schema, recs):
                all_names = schema.getColumnNames()
                missing = [n for n in names if n not in all_names]
                if missing:  # a typo here would silently drop EVERYTHING
                    raise ValueError(
                        f"removeAllColumnsExceptFor: unknown {missing} "
                        f"(schema has {all_names})")
                keep = [i for i, n in enumerate(all_names) if n in names]
                new = Schema([schema._cols[i] for i in keep])
                return new, [[r[i] for i in keep] for r in recs]
            self._steps.append(step)
            return self

        def coordinatesDistanceTransform(self, newColumnName, firstColumn,
                                         secondColumn, delimiter=","):
            """Reference: org.datavec.api.transform.geo
            .CoordinatesDistanceTransform — euclidean distance between
            two delimited-coordinate string columns ("x,y[,z...]"),
            appended as a new double column. Dimensions must agree
            per-row; either side missing/blank yields None."""
            def step(schema, recs):
                i = schema.getIndexOfColumn(firstColumn)
                j = schema.getIndexOfColumn(secondColumn)
                for r in recs:
                    a, b = r[i], r[j]
                    if a in (None, "") or b in (None, ""):
                        r.append(None)
                        continue
                    va = [float(t) for t in str(a).split(delimiter)]
                    vb = [float(t) for t in str(b).split(delimiter)]
                    if len(va) != len(vb):
                        raise ValueError(
                            f"coordinatesDistanceTransform: {a!r} has "
                            f"{len(va)} dims, {b!r} has {len(vb)}")
                    r.append(sum((x - y) ** 2
                                 for x, y in zip(va, vb)) ** 0.5)
                return Schema(schema._cols
                              + [(newColumnName, "double", None)]), recs
            self._steps.append(step)
            return self

        def build(self):
            # the SAME list objects, not copies: _steps is already
            # shared, so _spec/_unserializable must stay in lockstep —
            # a builder mutated after build() must not leave the built
            # process executing steps its serialized form omits
            return TransformProcess(self._initial, self._steps,
                                    spec=self._spec,
                                    unserializable=self._unserializable)

    def __init__(self, initial, steps, spec=None, unserializable=None):
        self._initial = initial
        self._steps = steps
        self._spec = spec
        self._unserializable = [] if unserializable is None \
            else unserializable

    def getInitialSchema(self) -> Schema:
        return self._initial

    def getFinalSchema(self) -> Schema:
        schema = self._initial
        for s in self._steps:
            schema, _ = s(schema, [])
        return schema

    def execute(self, records) -> list:
        schema = self._initial
        recs = [list(r) for r in records]
        for s in self._steps:
            schema, recs = s(schema, recs)
        return recs

    # ------------- JSON serde (reference: TransformProcess.toJson /
    # fromJson — DataVec pipelines persist next to the model) ---------
    def toJson(self) -> str:
        import json as _json

        if self._unserializable:
            raise ValueError(
                "pipeline contains steps whose arguments cannot be "
                f"serialized: {self._unserializable} — raw callables "
                "have no portable form; use "
                "ConditionFilter(ColumnCondition(...)) for "
                "JSON-representable predicates")
        if self._spec is None:
            raise ValueError("this TransformProcess was constructed "
                             "directly from step closures, not through "
                             "Builder — no declarative spec to serialize")
        return _json.dumps({
            "initialSchema": {"columns": self._initial._cols},
            "steps": self._spec,
        }, indent=1)

    @staticmethod
    def fromJson(text: str) -> "TransformProcess":
        import json as _json

        from deeplearning4j_tpu.util import serde as _serde

        d = _json.loads(text)
        cols = [(n, k, m) for n, k, m in d["initialSchema"]["columns"]]
        b = TransformProcess.Builder(Schema(cols))
        for entry in d["steps"]:
            args = _serde.decode(entry["args"], [])
            kwargs = _serde.decode(entry["kwargs"], [])
            getattr(b, entry["op"])(*args, **kwargs)
        return b.build()


def _install_spec_recording():
    """Wrap every chainable TransformProcess.Builder method to log its
    call declaratively for toJson/fromJson, using the package's shared
    tagged-tree codec (util/serde.py) — which snapshots mutable args at
    record time, preserves non-string dict keys, handles numpy scalars
    and in-package objects (ColumnCondition/ConditionFilter), and
    refuses functions. A step whose arguments the codec rejects (a raw
    callable predicate) marks the pipeline unserializable — recorded,
    surfaced by toJson's error."""
    import functools

    from deeplearning4j_tpu.util import serde as _serde

    B = TransformProcess.Builder

    def wrap(name, fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            out = fn(self, *args, **kwargs)
            try:
                arrays = []
                e_args = _serde.encode(list(args), arrays)
                e_kwargs = _serde.encode(kwargs, arrays)
                if arrays:  # transform args are config scalars, never
                    raise TypeError("array-valued transform argument")
                self._spec.append({"op": name, "args": e_args,
                                   "kwargs": e_kwargs})
            except TypeError:
                self._unserializable.append(name)
            return out
        return wrapper

    for name, fn in list(vars(B).items()):
        if name.startswith("_") or name == "build":
            continue
        setattr(B, name, wrap(name, fn))


_install_spec_recording()


# ----------------------------------------------- reader -> DataSet iterator
class RecordReaderDataSetIterator:
    """Reference: org.deeplearning4j.datasets.datavec.RecordReaderDataSetIterator.
    Materialises the reader once, then behaves as a standard fixed-shape
    batch iterator (classification one-hot or regression labels)."""

    def __init__(self, recordReader: RecordReader, batchSize: int,
                 labelIndex: int = -1, numPossibleLabels: int = None,
                 regression: bool = False, shuffle=False, seed=123):
        feats, labels = [], []
        recordReader.reset()
        # readers whose records are [ndarray, labelIndex] (images, audio)
        # rather than flat value lists mark themselves arrayRecords
        image_mode = getattr(recordReader, "arrayRecords", False)
        # bulk fast path: EXACTLY CSVRecordReader (not subclasses — an
        # overridden next()/_parse must keep its say) can hand over the
        # whole file as one numeric matrix; None falls through
        m = (recordReader.asMatrix()
             if type(recordReader) is CSVRecordReader else None)
        if m is not None and m.ndim == 2 and m.shape[1] >= 1:
            li = labelIndex if labelIndex >= 0 else m.shape[1] - 1
            f = np.delete(m, li, axis=1)
            labels = m[:, li].tolist()
            recordReader._i = len(recordReader._lines)  # consumed, like
            # the record loop leaves it
        else:
            while recordReader.hasNext():
                rec = recordReader.next()
                if image_mode:
                    feats.append(rec[0])
                    labels.append(rec[1])
                else:
                    li = labelIndex if labelIndex >= 0 else len(rec) - 1
                    labels.append(rec[li])
                    feats.append([float(v) for j, v in enumerate(rec)
                                  if j != li])
            try:
                f = np.asarray(feats, np.float32)
            except ValueError as e:
                shapes = sorted({np.shape(x) for x in feats})
                raise ValueError(
                    f"records have inconsistent shapes {shapes[:4]}; "
                    "batching needs fixed-size records "
                    "(WavFileRecordReader: pass length=N to pad/truncate)"
                ) from e
        if regression:
            l = np.asarray(labels, np.float32).reshape(len(labels), -1)
        else:
            n_cls = numPossibleLabels or (recordReader.numLabels() if image_mode
                                          else int(max(labels)) + 1)
            l = np.eye(n_cls, dtype=np.float32)[np.asarray(labels, np.int64)]
        from deeplearning4j_tpu.data.dataset import DataSetIterator

        self._it = DataSetIterator(f, l, batchSize, shuffle=shuffle, seed=seed)

    def __getattr__(self, name):  # delegate iterator protocol
        return getattr(self._it, name)

    def __iter__(self):
        return iter(self._it)


class CSVSequenceRecordReader(RecordReader):
    """Time-series reader: each FILE is one sequence, each line one time
    step (reference: datavec CSVSequenceRecordReader). initialize() takes
    a directory (files sorted by name) or an explicit list of paths;
    next() returns the sequence as a list of per-step value lists."""

    def __init__(self, skipNumLines: int = 0, delimiter: str = ","):
        self.skip = int(skipNumLines)
        self.delim = delimiter
        self._files = []
        self._i = 0

    def initialize(self, source):
        import os

        if isinstance(source, (list, tuple)):
            self._files = [str(p) for p in source]
        elif os.path.isdir(source):
            self._files = sorted(
                p for p in (os.path.join(source, f)
                            for f in os.listdir(source)
                            if not f.startswith("."))
                if os.path.isfile(p))
        else:
            self._files = [str(source)]
        self._i = 0
        return self

    def hasNext(self):
        return self._i < len(self._files)

    def next(self):
        path = self._files[self._i]
        self._i += 1
        seq = []
        with open(path) as fh:
            for li, line in enumerate(fh):
                if li < self.skip:
                    continue
                line = line.strip()
                if not line:
                    continue
                seq.append([CSVRecordReader._parse(t)
                            for t in line.split(self.delim)])
        if not seq:
            raise ValueError(f"empty sequence file: {path}")
        return seq

    def reset(self):
        self._i = 0


class SequenceRecordReaderDataSetIterator:
    """Zip a features sequence reader with a labels sequence reader into
    padded+masked recurrent DataSets (reference:
    SequenceRecordReaderDataSetIterator, ALIGN_END-free equal-length or
    padded variable-length batches).

    Output layout matches the recurrent layers' NCW convention:
    features [B, F, T], labels [B, C, T] (one-hot classification when
    numPossibleLabels is set, raw values for regression=True), masks
    [B, T] marking real steps. Sequences in a batch are padded to the
    batch's longest sequence — static shapes per batch, mask-correct
    losses (the XLA-friendly form of the reference's variable-length
    handling)."""

    def __init__(self, featureReader, labelReader, miniBatchSize,
                 numPossibleLabels=-1, regression=False):
        if (numPossibleLabels is None or numPossibleLabels < 1) \
                and not regression:
            raise ValueError(
                "classification needs numPossibleLabels >= 1 "
                "(or pass regression=True)")
        self._fr = featureReader
        self._lr = labelReader
        self.batch = int(miniBatchSize)
        self.numLabels = -1 if numPossibleLabels is None \
            else int(numPossibleLabels)
        self.regression = bool(regression)

    def reset(self):
        self._fr.reset()
        self._lr.reset()

    def hasNext(self):
        return self._fr.hasNext() and self._lr.hasNext()

    def next(self, num=None):
        from deeplearning4j_tpu.data.dataset import DataSet

        n = self.batch if num is None else int(num)
        if n <= 0:
            raise ValueError(f"batch size must be positive, got {n}")
        fseqs, lseqs = [], []
        while len(fseqs) < n and self.hasNext():
            f = self._fr.next()
            l = self._lr.next()
            if len(f) != len(l):
                raise ValueError(
                    f"feature sequence length {len(f)} != label sequence "
                    f"length {len(l)} (readers must be aligned)")
            fseqs.append(np.asarray(f, dtype="float32"))
            lseqs.append(np.asarray(l, dtype="float32"))
        if not fseqs:
            raise ValueError("iterator exhausted (or empty readers); "
                             "call reset() or check the source paths")
        if self._fr.hasNext() != self._lr.hasNext():
            raise ValueError(
                "feature and label readers hold different sequence counts "
                "— a file pair is missing on one side")
        B = len(fseqs)
        T = max(s.shape[0] for s in fseqs)
        F = fseqs[0].shape[1]
        if self.regression:
            # pin the label width on first use and validate every sequence
            # against it — otherwise a ragged sequence surfaces later as an
            # opaque numpy broadcast error (and the width could silently
            # differ between batches)
            if getattr(self, "_label_width", None) is None:
                self._label_width = lseqs[0].shape[1]
            for i, l in enumerate(lseqs):
                if l.shape[1] != self._label_width:
                    raise ValueError(
                        f"regression label width {l.shape[1]} for sequence "
                        f"{i} of this batch does not match the iterator's "
                        f"established width {self._label_width}; all label "
                        "sequences must have the same number of columns")
            C = self._label_width
        else:
            C = self.numLabels
        x = np.zeros((B, F, T), "float32")
        y = np.zeros((B, C, T), "float32")
        mask = np.zeros((B, T), "float32")
        for i, (f, l) in enumerate(zip(fseqs, lseqs)):
            t = f.shape[0]
            x[i, :, :t] = f.T
            mask[i, :t] = 1.0
            if self.regression:
                y[i, :, :t] = l.T
            else:
                ids = l.astype(int).reshape(t, -1)[:, 0]
                if ids.min() < 0 or ids.max() >= C:
                    bad = ids[(ids < 0) | (ids >= C)][0]
                    raise ValueError(
                        f"label value {bad} outside [0, {C}) "
                        f"(numPossibleLabels={C})")
                y[i, ids, np.arange(t)] = 1.0
        return DataSet(x, y, featuresMask=mask, labelsMask=mask)

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

"""Self-described columnar binary storage for record data.

Reference: datavec-arrow's `ArrowRecordReader`/`ArrowRecordWriter` and
the datavec-hadoop columnar readers — upstream persists schema'd record
batches in a columnar binary layout so readers can scan single columns
without parsing rows. pyarrow is not in this image, so the format here
is a minimal self-described native one (magic ``NDC1``), same role:

    NDC1 | uint32 header_len | JSON header | column blocks...

The JSON header carries the full Schema (name/type/states) plus row
count and per-column encodings, so a reader needs NO side information.
Column blocks, in header order:

    double   -> float64 LE contiguous + uint8 validity
    integer  -> int64 LE contiguous + uint8 validity (missing rows 0)
    categorical/string -> uint32 LE offsets[n+1] + utf-8 blob + validity

Validity is an explicit byte per row (arrow's null bitmap, unpacked —
simplicity over the last 7 bits). Missing values round-trip as None.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from deeplearning4j_tpu.data.records import RecordReader, Schema

_MAGIC = b"NDC1"


def writeColumnar(path, schema: Schema, records):
    """Write records (list of row-lists matching `schema`) to `path`.
    Reference: ArrowRecordWriter.writeBatch."""
    rows = [list(r) for r in records]
    n = len(rows)
    names = schema.getColumnNames()
    for r in rows:
        if len(r) != len(names):
            raise ValueError(
                f"record width {len(r)} != schema width {len(names)}")
    header = {"rows": n, "columns": []}
    blocks = []
    for ci, name in enumerate(names):
        typ = schema.getType(name)
        col = [r[ci] for r in rows]
        valid = np.array([v is not None for v in col], np.uint8)
        if typ in ("double", "integer"):
            if typ == "integer":
                for v in col:  # 1.7 in an int column must not silently
                    # truncate; true ints skip the float round-trip
                    # (float() loses precision above 2**53)
                    if v is None or (isinstance(v, (int, np.integer))
                                     and not isinstance(v, bool)):
                        continue
                    if float(v) != int(v):
                        raise ValueError(
                            f"column {name!r} is integer but got "
                            f"non-integral value {v!r}")
            vals = np.array([0 if v is None else v for v in col],
                            "<f8" if typ == "double" else "<i8")
            blocks.append(vals.tobytes())
        else:  # categorical / string: one encode pass builds blob+offsets
            chunks = [("" if v is None else str(v)).encode("utf-8")
                      for v in col]
            offs = np.zeros(n + 1, "<u4")
            pos = 0
            for i, c in enumerate(chunks):
                pos += len(c)
                if pos > 0xFFFFFFFF:
                    # guard BEFORE the uint32 store: modern numpy raises
                    # an opaque OverflowError here, older numpy silently
                    # wraps and corrupts every later offset
                    raise ValueError(
                        f"column {name!r} utf-8 blob exceeds the NDC1 "
                        f"uint32 offset limit (4 GiB) at row {i}: split "
                        "the records across multiple files (the format "
                        "has no u8-offset escape hatch yet)")
                offs[i + 1] = pos
            blocks.append(offs.tobytes() + b"".join(chunks))
        blocks.append(valid.tobytes())
        header["columns"].append(
            {"name": name, "type": typ, "states": schema.getMeta(name)})
    hjson = json.dumps(header).encode("utf-8")
    with open(path, "wb") as fh:
        fh.write(_MAGIC)
        fh.write(struct.pack("<I", len(hjson)))
        fh.write(hjson)
        for b in blocks:
            fh.write(b)
    return path


class ColumnarRecordReader(RecordReader):
    """Read an NDC1 file as a RecordReader (reference: ArrowRecordReader
    — drop-in wherever a RecordReader goes, e.g.
    RecordReaderDataSetIterator), with a columnar fast path
    (`asColumns()`) that hands back whole numpy columns without a
    per-row Python loop."""

    def __init__(self):
        self._schema = None
        self._cols = None   # name -> (values ndarray/list, valid ndarray)
        self._n = 0
        self._i = 0

    def initialize(self, path):
        with open(path, "rb") as fh:
            if fh.read(4) != _MAGIC:
                raise ValueError(f"{path} is not an NDC1 columnar file")
            (hlen,) = struct.unpack("<I", fh.read(4))
            header = json.loads(fh.read(hlen).decode("utf-8"))
            self._n = int(header["rows"])
            cols = {}
            scols = []
            for c in header["columns"]:
                typ = c["type"]
                if typ in ("double", "integer"):
                    dtype = "<f8" if typ == "double" else "<i8"
                    vals = np.frombuffer(fh.read(8 * self._n), dtype)
                else:
                    offs = np.frombuffer(fh.read(4 * (self._n + 1)), "<u4")
                    blob = fh.read(int(offs[-1]))
                    vals = [blob[offs[i]:offs[i + 1]].decode("utf-8")
                            for i in range(self._n)]
                valid = np.frombuffer(fh.read(self._n), np.uint8)
                cols[c["name"]] = (vals, valid)
                scols.append((c["name"], typ, c.get("states")))
            self._schema = Schema(scols)
            self._cols = cols
        self._i = 0
        return self

    def getSchema(self) -> Schema:
        return self._schema

    def asColumns(self):
        """name -> numpy array or list of str. The columnar fast path:
        no row materialisation. Missing numeric rows read NaN — an
        integer column containing missing values promotes to float64
        (pandas-style), so a missing row can never masquerade as 0."""
        out = {}
        for name in self._schema.getColumnNames():
            vals, valid = self._cols[name]
            if isinstance(vals, np.ndarray):
                if (valid == 0).any():
                    v = vals.astype(np.float64)
                    v[valid == 0] = np.nan
                    out[name] = v
                else:
                    out[name] = vals.copy()
            else:
                out[name] = list(vals)
        return out

    def hasNext(self):
        return self._cols is not None and self._i < self._n

    def next(self):
        i = self._i
        self._i += 1
        row = []
        for name in self._schema.getColumnNames():
            vals, valid = self._cols[name]
            if not valid[i]:
                row.append(None)
            elif isinstance(vals, np.ndarray):
                v = vals[i]
                row.append(float(v) if self._schema.getType(name) == "double"
                           else int(v))
            else:
                row.append(vals[i])
        return row

    def reset(self):
        self._i = 0

"""DeepWalk vertex embeddings.

Reference: deeplearning4j-graph org.deeplearning4j.graph.models.deepwalk
.DeepWalk (Builder: windowSize/vectorSize/learningRate/seed; fit over a
RandomWalkIterator on graph.api.Graph) — truncated random walks treated
as sentences, embedded with skip-gram. Upstream trains per-walk with a
hierarchical-softmax tree on the JVM; here walks are generated host-side
once and the embedding trains through nlp.word2vec's single jitted SGNS
step (negative sampling — same objective family, TPU-shaped compute).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.nlp.word2vec import (Word2Vec,
                                             CollectionSentenceIterator)


class Graph:
    """Undirected-by-default adjacency graph, optionally edge-weighted
    (reference: org.deeplearning4j.graph.graph.Graph; weighted walks:
    WeightedWalkIterator)."""

    def __init__(self, numVertices: int):
        if int(numVertices) <= 0:
            raise ValueError("numVertices must be positive")
        self._adj = [[] for _ in range(int(numVertices))]
        self._w = [[] for _ in range(int(numVertices))]

    def numVertices(self) -> int:
        return len(self._adj)

    def addEdge(self, a: int, b: int, directed: bool = False,
                weight: float = 1.0):
        n = self.numVertices()
        if not (0 <= a < n and 0 <= b < n):
            raise ValueError(f"edge ({a},{b}) outside [0,{n})")
        if not (weight > 0):
            raise ValueError(f"edge weight must be positive, got {weight}")
        self._adj[a].append(b)
        self._w[a].append(float(weight))
        if not directed:
            self._adj[b].append(a)
            self._w[b].append(float(weight))
        return self

    def getConnectedVertices(self, v: int):
        return list(self._adj[v])

    def getEdgeWeights(self, v: int):
        return list(self._w[v])


class GraphLoader:
    """Edge-list file loaders (reference:
    org.deeplearning4j.graph.data.GraphLoader). Lines are
    "a<delim>b" or "a<delim>b<delim>weight"; blank lines and
    '#'-comments are skipped; any whitespace works when `delimiter`
    is None."""

    @staticmethod
    def _parse(path, delimiter):
        edges = []
        with open(str(path)) as f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = (line.split(delimiter) if delimiter
                         else line.split())
                parts = [p for p in (s.strip() for s in parts) if p]
                if len(parts) not in (2, 3):
                    raise ValueError(
                        f"{path}:{ln}: expected 'a b' or 'a b weight', "
                        f"got {line!r}")
                a, b = int(parts[0]), int(parts[1])
                w = float(parts[2]) if len(parts) == 3 else 1.0
                edges.append((a, b, w))
        if not edges:
            raise ValueError(f"{path}: no edges")
        return edges

    @staticmethod
    def loadUndirectedGraphEdgeListFile(path, numVertices=None,
                                        delimiter=None):
        return GraphLoader._build(path, numVertices, delimiter,
                                  directed=False)

    @staticmethod
    def loadWeightedEdgeListFile(path, numVertices=None, delimiter=None,
                                 directed=False):
        return GraphLoader._build(path, numVertices, delimiter, directed)

    @staticmethod
    def _build(path, numVertices, delimiter, directed):
        edges = GraphLoader._parse(path, delimiter)
        n = (numVertices if numVertices is not None
             else max(max(a, b) for a, b, _ in edges) + 1)
        g = Graph(n)
        for a, b, w in edges:
            g.addEdge(a, b, directed=directed, weight=w)
        return g


class _IdentityTokenizer:
    """Vertex-id 'sentences' must not be lowercased/regex-split."""

    def create(self, sentence):
        return sentence.split()


class DeepWalk:
    """Builder-constructed DeepWalk (reference: DeepWalk.Builder)."""

    class Builder:
        def __init__(self):
            self._kw = {}

        def windowSize(self, n):
            self._kw["windowSize"] = int(n)
            return self

        def vectorSize(self, n):
            self._kw["vectorSize"] = int(n)
            return self

        def learningRate(self, lr):
            self._kw["learningRate"] = float(lr)
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def returnParam(self, p):
            """node2vec p: smaller -> walks revisit the previous vertex
            more (reference: upstream's weighted/biased walk support;
            parameterisation per Grover & Leskovec 2016)."""
            self._kw["returnParam"] = float(p)
            return self

        def inOutParam(self, q):
            """node2vec q: q>1 keeps walks local (BFS-like, community
            structure); q<1 pushes outward (DFS-like)."""
            self._kw["inOutParam"] = float(q)
            return self

        def build(self):
            return DeepWalk(**self._kw)

    def __init__(self, windowSize=5, vectorSize=100, learningRate=0.025,
                 seed=42, returnParam=1.0, inOutParam=1.0):
        self.windowSize = windowSize
        self.vectorSize = vectorSize
        self.learningRate = learningRate
        self.seed = seed
        if returnParam <= 0 or inOutParam <= 0:
            raise ValueError("returnParam/inOutParam must be > 0")
        self.returnParam = float(returnParam)
        self.inOutParam = float(inOutParam)
        self._w2v = None

    def _walks(self, graph, walkLength, walksPerVertex, rng):
        walks = []
        n = graph.numVertices()
        p, q = self.returnParam, self.inOutParam
        biased = (p != 1.0 or q != 1.0)
        adj_sets = [set(a) for a in graph._adj] if biased else None
        # edge weights multiply every transition probability (reference:
        # WeightedWalkIterator; node2vec defines its alpha bias ON TOP
        # of edge weights)
        wlists = [np.asarray(w) for w in graph._w]
        weighted = any(len(w) and (w != w[0]).any() for w in wlists
                       if len(w))
        # per-vertex first-order distributions are step-invariant:
        # normalize once, not per step. Also serves a biased walk's
        # FIRST step (no prev yet), where unweighted graphs need the
        # uniform all-ones distribution
        probs = ([w / w.sum() if len(w) else w for w in wlists]
                 if (weighted or biased) else None)
        for _ in range(walksPerVertex):
            for start in rng.permutation(n):
                v = int(start)
                prev = None
                walk = [v]
                for _ in range(walkLength - 1):
                    nbrs = graph._adj[v]
                    if not nbrs:
                        break  # dead end: truncate like upstream
                    if not biased and not weighted:
                        nxt = int(nbrs[rng.randint(len(nbrs))])
                    else:
                        # node2vec second-order transition: 1/p to return,
                        # 1 to a mutual neighbour of prev, 1/q outward
                        if biased and prev is not None:
                            alpha = np.array(
                                [1.0 / p if x == prev
                                 else (1.0 if x in adj_sets[prev]
                                       else 1.0 / q)
                                 for x in nbrs])
                            w = alpha * wlists[v]
                            w = w / w.sum()
                        else:  # first-order: precomputed distribution
                            w = probs[v]
                        nxt = int(nbrs[rng.choice(len(nbrs), p=w)])
                    prev, v = v, nxt
                    walk.append(v)
                walks.append(" ".join(map(str, walk)))
        return walks

    def fit(self, graph, walkLength=40, walksPerVertex=10, iterations=5):
        self._n = graph.numVertices()
        rng = np.random.RandomState(self.seed)
        walks = self._walks(graph, int(walkLength), int(walksPerVertex), rng)
        self._w2v = Word2Vec(
            iterator=CollectionSentenceIterator(walks),
            tokenizer=_IdentityTokenizer(),
            minWordFrequency=1, layerSize=self.vectorSize,
            windowSize=self.windowSize, negative=5, seed=self.seed,
            iterations=int(iterations), learningRate=self.learningRate,
        ).fit()
        return self

    # ---- query API (reference: DeepWalk/GraphVectors methods) ----
    def _require_fit(self):
        if self._w2v is None:
            raise RuntimeError("call fit() first")

    def _check_vertex(self, v):
        if not (0 <= int(v) < self._n):
            raise ValueError(f"vertex {v} outside [0,{self._n})")

    def getVertexVector(self, v: int):
        self._require_fit()
        self._check_vertex(v)
        return self._w2v.getWordVector(str(int(v)))

    def similarity(self, a: int, b: int) -> float:
        self._require_fit()
        self._check_vertex(a)
        self._check_vertex(b)
        return self._w2v.similarity(str(int(a)), str(int(b)))

    def verticesNearest(self, v: int, top: int = 10):
        self._require_fit()
        self._check_vertex(v)
        return [int(w) for w in self._w2v.wordsNearest(str(int(v)), top)]

    # ---- distributed-linalg products (linalg tier, docs/LINALG.md) ----
    def embeddings(self) -> np.ndarray:
        """[numVertices, vectorSize] embedding matrix, row i = vertex i
        (every vertex is in the vocab: each walk epoch starts one walk
        at every vertex and minWordFrequency is 1)."""
        self._require_fit()
        W = np.asarray(self._w2v._W, np.float32)
        return W[[self._w2v.vocab[str(v)] for v in range(self._n)]]

    def embeddingGram(self, mesh=None) -> np.ndarray:
        """E^T E [vectorSize, vectorSize] — the Gram product downstream
        embedding consumers (whitening, PCA projections) start from.
        With a `mesh` the reduction runs distributed over row-sharded
        embeddings (linalg.gram: one psum over the data axis; vertex
        count must divide the axis — the never-pad PAR03 contract);
        without, a local product."""
        E = self.embeddings()
        if mesh is None:
            return E.T @ E
        from deeplearning4j_tpu import linalg

        dE = linalg.DistributedMatrix(E, mesh, row_axis=linalg.ROW_AXIS)
        return linalg.gram(dE).toNumpy()

    def similarityMatrix(self, mesh=None) -> np.ndarray:
        """All-pairs cosine similarity [n, n] of the vertex embeddings.
        With a `mesh`: linalg.matmul(transpose_b=True) — rows sharded,
        one all_gather of the normalized embeddings over the data axis;
        the result comes back row-sharded and is gathered to host."""
        E = self.embeddings()
        En = E / np.maximum(np.linalg.norm(E, axis=1, keepdims=True),
                            1e-12)
        if mesh is None:
            return En @ En.T
        from deeplearning4j_tpu import linalg

        dE = linalg.DistributedMatrix(En, mesh, row_axis=linalg.ROW_AXIS)
        return linalg.matmul(dE, dE, transpose_b=True).toNumpy()

"""Graph vertex embeddings (reference: deeplearning4j-graph —
org.deeplearning4j.graph: Graph + DeepWalk). Walk generation is host
side; embedding training reuses the jitted SGNS step from nlp/."""

from deeplearning4j_tpu.graph.deepwalk import Graph, GraphLoader, DeepWalk

__all__ = ["Graph", "GraphLoader", "DeepWalk"]

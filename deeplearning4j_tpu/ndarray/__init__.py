"""Array layer: INDArray + Nd4j factory over XLA device buffers.

Reference modules: nd4j-api (org.nd4j.linalg.api.ndarray,
org.nd4j.linalg.factory, org.nd4j.linalg.indexing) with libnd4j replaced
by XLA as the kernel library.
"""

from deeplearning4j_tpu.ndarray.dtype import DataType
from deeplearning4j_tpu.ndarray.ndarray import INDArray
from deeplearning4j_tpu.ndarray.factory import Nd4j
from deeplearning4j_tpu.ndarray.convolution import Convolution
from deeplearning4j_tpu.ndarray.indexing import NDArrayIndex
from deeplearning4j_tpu.ndarray.executioner import XlaExecutioner
from deeplearning4j_tpu.ndarray.transforms import Transforms
from deeplearning4j_tpu.ndarray.compression import (BasicNDArrayCompressor,
                                                    CompressedNDArray,
                                                    Int8Inference)

__all__ = ["Convolution",
           "DataType", "INDArray", "Nd4j", "NDArrayIndex", "XlaExecutioner",
           "Transforms", "BasicNDArrayCompressor", "CompressedNDArray",
           "Int8Inference"]

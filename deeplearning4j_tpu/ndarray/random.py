"""Global RNG for the factory layer.

Reference: org.nd4j.linalg.api.rng.DefaultRandom / Nd4j.getRandom(). The
reference keeps a stateful Mersenne generator per backend. TPU-native
design: a counter-based splittable jax.random key. Each draw splits the
root key deterministically, so results are reproducible for a given seed
regardless of device count or op ordering across hosts — the property the
reference's distributed trainers have to work around.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp


class DefaultRandom:
    """Splittable counter-based RNG with a stateful facade."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._key = None  # materialised lazily: creating a key allocates a
        # device buffer, which would initialise the backend at import time
        # (breaking late platform selection, e.g. the multichip dry-run).

    def setSeed(self, seed: int) -> None:
        with self._lock:
            self._seed = int(seed)
            self._key = None

    def getSeed(self) -> int:
        with self._lock:
            return self._seed

    def nextKey(self) -> jax.Array:
        with self._lock:
            if self._key is None:
                self._key = jax.random.key(self._seed)
            self._key, sub = jax.random.split(self._key)
            return sub

    def nextDouble(self) -> float:
        return float(jax.random.uniform(self.nextKey(), ()))

    def nextGaussian(self) -> float:
        return float(jax.random.normal(self.nextKey(), ()))

    def nextInt(self, bound: int) -> int:
        return int(jax.random.randint(self.nextKey(), (), 0, bound))


_global = DefaultRandom(0)


def getRandom() -> DefaultRandom:
    return _global


def setSeed(seed: int) -> None:
    _global.setSeed(seed)


def _key(seed=None) -> jax.Array:
    return jax.random.key(int(seed)) if seed is not None else _global.nextKey()


def uniform(shape, dtype, minval=0.0, maxval=1.0, seed=None) -> jax.Array:
    if not jnp.issubdtype(dtype, jnp.floating):
        if int(maxval) - int(minval) <= 1:
            raise ValueError(
                "uniform with an integer dtype needs explicit integer bounds "
                f"(got minval={minval}, maxval={maxval}); the float defaults "
                "would yield a constant array"
            )
        return jax.random.randint(_key(seed), shape, int(minval), int(maxval), dtype=dtype)
    return jax.random.uniform(_key(seed), shape, dtype=dtype, minval=minval, maxval=maxval)


def normal(shape, dtype, mean=0.0, std=1.0, seed=None) -> jax.Array:
    return mean + std * jax.random.normal(_key(seed), shape, dtype=dtype)


def bernoulli(shape, p, dtype, seed=None) -> jax.Array:
    return jax.random.bernoulli(_key(seed), p, shape).astype(dtype)

"""NDArray buffer compression + post-training int8 weight quantization.

Reference: nd4j-api `BasicNDArrayCompressor` / `Nd4j.getCompressor()` —
named buffer codecs (GZIP, FLOAT16, INT8, NOOP) with
compress/decompress and a process-wide default algorithm. Upstream uses
these to shrink buffers at rest (serialization, transport); the codec
surface is reproduced 1:1 here.

The TPU-first extension is `quantize_int8` + `Int8Inference`
(dequant-on-use): weights live in HBM as int8 with per-output-channel
scales and are dequantized INSIDE the jitted forward, so XLA fuses the
`q * scale` into the consuming matmul/conv — 4x less weight bandwidth
on the bandwidth-bound inference path, which is the role upstream's
INT8 compression plays for its CUDA buffers.
"""

from __future__ import annotations

import zlib
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.ndarray import INDArray

_ALGOS = ("GZIP", "FLOAT16", "INT8", "THRESHOLD", "NOOP")


# ---------------------------------------------------------------------
# Strom-2015 threshold encoding (shared by the trainer step + codec)
# ---------------------------------------------------------------------

def threshold_cap(n: int, capacity: float) -> int:
    """STATIC per-leaf encoding capacity: how many (index, sign) pairs
    one replica may transmit for an n-element leaf. Fixed at trace time
    so the encoded shapes never vary and the train step stays one
    jitted executable."""
    import math

    return max(1, min(int(n), int(math.ceil(float(n) * float(capacity)))))


def threshold_encode_fixed(flat, tau, cap):
    """Fixed-capacity Strom threshold encoding of ONE flat vector (the
    traced encoder `ParallelWrapper._threshold_step` runs per leaf; the
    host-side THRESHOLD codec below mirrors it exactly).

    The top-`cap` entries of |flat| are candidates; those with
    |value| >= tau transmit as +-tau (sign encoding — Strom 2015), the
    rest transmit nothing. Returns

        idx[cap] int32   candidate positions (top-|.| order)
        val[cap]         +-tau where transmitted, 0 where below tau
        dense[n]         the dense equivalent of the wire message
        residual[n]      flat - dense: the error feedback carried to the
                         next step. Exact by construction:
                         dense + residual == flat bitwise.
    """
    mag = jnp.abs(flat)
    _, idx = jax.lax.top_k(mag, cap)
    cand = jnp.take(flat, idx)
    hit = jnp.abs(cand) >= tau.astype(flat.dtype)
    val = jnp.where(hit, jnp.sign(cand) * tau.astype(flat.dtype),
                    jnp.zeros((), flat.dtype))
    dense = jnp.zeros_like(flat).at[idx].set(val)
    return idx.astype(jnp.int32), val, dense, flat - dense


class CompressedNDArray:
    """Opaque compressed buffer + the descriptor needed to restore it
    (upstream: a compressed INDArray flagged by its CompressionDescriptor)."""

    def __init__(self, algo, payload, shape, dtype, extra=None):
        self.algo = algo
        self.payload = payload
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.extra = extra  # per-algo sidecar (e.g. int8 scale)

    def isCompressed(self):
        return True

    def compressedBytes(self):
        n = len(self.payload) if isinstance(self.payload, bytes) \
            else self.payload.nbytes
        if isinstance(self.extra, dict):
            n += sum(np.asarray(v).nbytes for v in self.extra.values())
        elif self.extra is not None:
            n += np.asarray(self.extra).nbytes
        return n

    def originalBytes(self):
        return int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize

    def ratio(self):
        return self.compressedBytes() / max(self.originalBytes(), 1)

    def __repr__(self):
        return (f"CompressedNDArray(algo={self.algo}, shape={self.shape}, "
                f"ratio={self.ratio():.3f})")


class BasicNDArrayCompressor:
    """`Nd4j.getCompressor()` parity surface.

    GZIP      lossless zlib over the raw buffer
    FLOAT16   cast to f16 (lossy), restored to the original float dtype
    INT8      per-tensor absmax affine int8 (lossy), scale in the sidecar
    THRESHOLD Strom-2015 sparse sign encoding (lossy): indices of
              |x| >= tau as int32 + one sign byte each, decoded dense as
              +-tau — the wire format of the trainer's
              gradient_compression="threshold" step (the same encoder,
              see threshold_encode_fixed), testable host-side in
              isolation
    NOOP      descriptor-only identity (upstream ships one; useful to
              exercise the codec path with zero loss)
    """

    _instance = None

    @classmethod
    def getInstance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self._default = "GZIP"

    def getAvailableCompressors(self):
        return list(_ALGOS)

    def setDefaultCompression(self, algo):
        algo = str(algo).upper()
        if algo not in _ALGOS:
            raise ValueError(f"unknown compressor {algo!r}; "
                             f"available: {_ALGOS}")
        self._default = algo
        return self

    def getDefaultCompression(self):
        return self._default

    def compress(self, arr, algo=None, threshold=1e-3):
        algo = (algo or self._default).upper()
        if algo not in _ALGOS:
            raise ValueError(f"unknown compressor {algo!r}; "
                             f"available: {_ALGOS}")
        x = np.asarray(getattr(arr, "toNumpy", lambda: arr)())
        if algo == "THRESHOLD":
            if not np.issubdtype(x.dtype, np.floating):
                raise ValueError("THRESHOLD compression needs a float "
                                 "array")
            tau = float(threshold)
            if tau <= 0:
                raise ValueError(f"threshold must be > 0, got {tau}")
            flat = np.ascontiguousarray(x).reshape(-1)
            # size-0 and all-below-tau short-circuit: an empty index set
            # is a valid (maximally sparse) message, not an error
            idx = (np.flatnonzero(np.abs(flat) >= tau).astype(np.int32)
                   if flat.size else np.zeros((0,), np.int32))
            signs = np.sign(flat[idx]).astype(np.int8)
            return CompressedNDArray(
                algo, signs, x.shape, x.dtype,
                extra={"threshold": np.float32(tau), "indices": idx})
        if algo == "GZIP":
            return CompressedNDArray(
                algo, zlib.compress(np.ascontiguousarray(x).tobytes(), 6),
                x.shape, x.dtype)
        if algo == "FLOAT16":
            if not np.issubdtype(x.dtype, np.floating):
                raise ValueError("FLOAT16 compression needs a float array")
            return CompressedNDArray(algo, x.astype(np.float16),
                                     x.shape, x.dtype)
        if algo == "INT8":
            if not np.issubdtype(x.dtype, np.floating):
                raise ValueError("INT8 compression needs a float array")
            # size-0 arrays short-circuit: np.max of an empty array is a
            # bare numpy ValueError, not a codec answer (ADVICE r5 #5)
            scale = (float(np.max(np.abs(x))) / 127.0 or 1.0) \
                if x.size else 1.0
            q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
            return CompressedNDArray(algo, q, x.shape, x.dtype,
                                     extra=np.float32(scale))
        return CompressedNDArray(algo, x, x.shape, x.dtype)  # NOOP

    def decompress(self, carr):
        if not isinstance(carr, CompressedNDArray):
            return carr if isinstance(carr, INDArray) else INDArray(carr)
        if carr.algo == "GZIP":
            x = np.frombuffer(zlib.decompress(carr.payload),
                              dtype=carr.dtype).reshape(carr.shape)
        elif carr.algo == "FLOAT16":
            x = carr.payload.astype(carr.dtype)
        elif carr.algo == "INT8":
            x = (carr.payload.astype(np.float32)
                 * np.float32(carr.extra)).astype(carr.dtype)
        elif carr.algo == "THRESHOLD":
            n = int(np.prod(carr.shape, dtype=np.int64))
            x = np.zeros(n, dtype=carr.dtype)
            idx = carr.extra["indices"]
            if idx.size:
                x[idx] = (carr.payload.astype(carr.dtype)
                          * carr.dtype.type(carr.extra["threshold"]))
        else:  # NOOP
            x = carr.payload
        return INDArray(np.asarray(x).reshape(carr.shape))


# ---------------------------------------------------------------------
# post-training int8 weight quantization (dequant-on-use inference)
# ---------------------------------------------------------------------

class QLeaf(NamedTuple):
    """An int8-quantized weight leaf: q int8, scale fp32 broadcast along
    the last (output-channel) axis. NamedTuple = transparent jax pytree."""
    q: jnp.ndarray
    scale: jnp.ndarray


def _eligible(a):
    return (hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
            and getattr(a, "ndim", 0) >= 2)


def quantize_int8(params):
    """fp weight pytree -> same-structure pytree with >=2-D float leaves
    replaced by QLeaf (per-output-channel absmax int8). 1-D leaves
    (biases, BN stats) stay fp — they are a rounding error of the bytes
    and quantizing them costs accuracy for nothing."""

    def quant(a):
        if not _eligible(a):
            return a
        x = jnp.asarray(a, jnp.float32)
        absmax = jnp.max(jnp.abs(x), axis=tuple(range(x.ndim - 1)),
                         keepdims=True)
        scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return QLeaf(q=q, scale=scale.astype(jnp.float32))

    return jax.tree_util.tree_map(quant, params)


def dequantize(qparams, dtype=jnp.float32):
    def dq(leaf):
        if isinstance(leaf, QLeaf):
            return (leaf.q.astype(jnp.float32) * leaf.scale).astype(dtype)
        return leaf

    return jax.tree_util.tree_map(
        dq, qparams, is_leaf=lambda x: isinstance(x, QLeaf))


def quantized_bytes(qparams):
    """(quantized, original-fp32) byte counts for the weight pytree."""
    qb = fb = 0
    for leaf in jax.tree_util.tree_leaves(
            qparams, is_leaf=lambda x: isinstance(x, QLeaf)):
        if isinstance(leaf, QLeaf):
            qb += leaf.q.size + leaf.scale.size * 4
            fb += leaf.q.size * 4
        elif hasattr(leaf, "size"):
            qb += leaf.size * 4
            fb += leaf.size * 4
    return qb, fb


class Int8Inference:
    """Int8 dequant-on-use inference wrapper for a trained
    MultiLayerNetwork OR ComputationGraph (zoo models are graphs):
    `Int8Inference(net).output(x)`.

    Weights are held as int8+scale; the dequant runs inside the jitted
    forward so XLA fuses it into each weight's consumer and the HBM
    working set shrinks ~4x. Accuracy: per-channel absmax keeps zoo-size
    classifiers within a fraction of a point of fp32 top-1 (pinned by
    tests/test_compression.py on a trained MLN and a zoo graph).
    """

    def __init__(self, net):
        net._require_init()
        self._net = net
        self._graph = not hasattr(net, "layers")  # ComputationGraph
        self._qparams = quantize_int8(net._params)
        cdt = net._compute_dtype

        def fwd(qp, states, x):
            return net._forward_infer(dequantize(qp, cdt), states, x)

        self._jit = jax.jit(fwd)

    def output(self, x):
        """Single-input forward. Graphs return their FIRST network
        output (`ComputationGraph.outputSingle` semantics); pass a dict
        of input-name -> array for multi-input graphs."""
        if self._graph and not isinstance(x, dict):
            x = {self._net.conf.networkInputs[0]: _unwrap_arr(x)}
        elif isinstance(x, dict):
            x = {k: _unwrap_arr(v) for k, v in x.items()}
        else:
            x = _unwrap_arr(x)
        out = self._jit(self._qparams, self._net._states, x)
        return INDArray(out[0] if self._graph else out)

    def memoryRatio(self):
        qb, fb = quantized_bytes(self._qparams)
        return qb / max(fb, 1)


def _unwrap_arr(x):
    return x.jax() if isinstance(x, INDArray) else jnp.asarray(x)

"""Data types for the array layer.

Reference: org.nd4j.linalg.api.buffer.DataType — ND4J's dtype enum backs
typed C++ buffers in libnd4j. Here a DataType is a thin name wrapper over a
numpy/jax dtype; XLA owns the buffer layout. BFLOAT16 is first-class (the
TPU MXU native matmul type) rather than an afterthought like HALF on CUDA.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class DataType:
    """Enum-like dtype registry, convertible to/from jax dtypes."""

    _registry: dict[str, "DataType"] = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = jnp.dtype(np_dtype)
        DataType._registry[name] = self

    def __repr__(self) -> str:
        return f"DataType.{self.name}"

    def __eq__(self, other) -> bool:
        if isinstance(other, DataType):
            return self.name == other.name
        try:
            return self.np_dtype == jnp.dtype(other)
        except TypeError:
            return NotImplemented

    def __hash__(self) -> int:
        return hash(self.name)

    def is_floating(self) -> bool:
        return jnp.issubdtype(self.np_dtype, jnp.floating)

    def is_integer(self) -> bool:
        return jnp.issubdtype(self.np_dtype, jnp.integer)

    @staticmethod
    def from_dtype(dt) -> "DataType":
        if isinstance(dt, DataType):
            return dt
        dt = jnp.dtype(dt)
        for v in DataType._registry.values():
            if v.np_dtype == dt:
                return v
        raise ValueError(f"No DataType for dtype {dt}")


DataType.FLOAT = DataType("FLOAT", jnp.float32)
DataType.DOUBLE = DataType("DOUBLE", jnp.float64)
DataType.HALF = DataType("HALF", jnp.float16)
DataType.BFLOAT16 = DataType("BFLOAT16", jnp.bfloat16)
DataType.INT8 = DataType("INT8", jnp.int8)
DataType.INT16 = DataType("INT16", jnp.int16)
DataType.INT32 = DataType("INT32", jnp.int32)
DataType.INT64 = DataType("INT64", jnp.int64)
DataType.UINT8 = DataType("UINT8", jnp.uint8)
DataType.UINT16 = DataType("UINT16", jnp.uint16)
DataType.UINT32 = DataType("UINT32", jnp.uint32)
DataType.UINT64 = DataType("UINT64", jnp.uint64)
DataType.BOOL = DataType("BOOL", jnp.bool_)

# Aliases used throughout the reference API surface (registered so the
# string forms resolve too, e.g. castTo("LONG")).
for _alias, _target in [
    ("INT", DataType.INT32),
    ("LONG", DataType.INT64),
    ("FLOAT32", DataType.FLOAT),
    ("FLOAT64", DataType.DOUBLE),
    ("FLOAT16", DataType.HALF),
]:
    setattr(DataType, _alias, _target)
    DataType._registry[_alias] = _target


def resolve(dt) -> jnp.dtype:
    """Any of DataType / str / np dtype / jnp dtype -> jnp dtype."""
    if isinstance(dt, DataType):
        return dt.np_dtype
    if isinstance(dt, str) and dt.upper() in DataType._registry:
        return DataType._registry[dt.upper()].np_dtype
    return jnp.dtype(dt)


np  # re-exported for convenience of importers

"""XlaExecutioner — op execution environment.

Reference: org.nd4j.linalg.api.ops.executioner.OpExecutioner and its
backends (NativeOpExecutioner dispatching into libnd4j, CudaExecutioner
into CUDA kernels + streams). There is no per-op kernel dispatch to
replicate on TPU: eager jax.numpy calls already execute compiled XLA
programs, and jitted callables fuse whole graphs. What remains useful from
the executioner abstraction is (a) an execution-environment handle
(profiling mode, device info, sync), (b) a jit cache keyed by function, and
(c) commit/sync barriers for timing.
"""

from __future__ import annotations

import time

import jax


class XlaExecutioner:
    _instance = None

    def __init__(self):
        self._profiling = False
        self._jit_cache: dict = {}

    @classmethod
    def instance(cls) -> "XlaExecutioner":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # ----- environment -----------------------------------------------
    def devices(self):
        return jax.devices()

    def deviceCount(self) -> int:
        return jax.device_count()

    def platform(self) -> str:
        return jax.default_backend()

    def enableProfiling(self, flag: bool = True) -> None:
        self._profiling = flag

    # ----- execution --------------------------------------------------
    _JIT_CACHE_MAX = 256

    def exec(self, fn, *args, static_argnums=(), donate_argnums=(), **kw):
        """Execute fn as a single fused XLA computation (jit-cached).

        Keyed on function identity — pass a stable function, not a fresh
        lambda per call, to hit the cache. FIFO-bounded so closure-churn
        can't grow memory without limit.
        """
        key = (fn, tuple(static_argnums), tuple(donate_argnums))
        if key not in self._jit_cache:
            if len(self._jit_cache) >= self._JIT_CACHE_MAX:
                self._jit_cache.pop(next(iter(self._jit_cache)))
            self._jit_cache[key] = jax.jit(
                fn, static_argnums=static_argnums, donate_argnums=donate_argnums
            )
        jitted = self._jit_cache[key]
        if self._profiling:
            t0 = time.perf_counter()
            out = jax.block_until_ready(jitted(*args, **kw))
            print(f"[XlaExecutioner] {getattr(fn, '__name__', fn)}: "
                  f"{(time.perf_counter() - t0) * 1e3:.3f} ms")
            return out
        return jitted(*args, **kw)

    def commit(self) -> None:
        """Synchronisation barrier (reference: stream sync / flushQueue)."""
        for d in jax.live_arrays():
            d.block_until_ready()

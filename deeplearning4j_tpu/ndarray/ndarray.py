"""INDArray — the n-dimensional array of the framework.

Reference surface: org.nd4j.linalg.api.ndarray.INDArray (nd4j-api). In the
reference, an INDArray owns a typed DataBuffer and every op dispatches
through an OpExecutioner into libnd4j C++/CUDA kernels. Here the payload is
a jax.Array: an XLA device buffer resident in TPU HBM. Ops lower to
jax.numpy / lax eagerly; anything called under jax.jit traces and fuses
into a single XLA computation, which is what replaces the libnd4j kernel
library and its hand-written fusion.

Mutation semantics: the reference has true in-place ops (addi, assign,
putScalar) on mutable buffers. XLA buffers are immutable, so the *wrapper*
is the unit of identity: in-place methods rebind ``self._jx`` to the new
buffer and return ``self``. Under donation in jitted train steps XLA reuses
the memory, so the performance-motivated uses of in-place survive.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtype import DataType, resolve


def _unwrap(x):
    return x._jx if isinstance(x, INDArray) else x


def _dims(dimension) -> tuple[int, ...] | None:
    """Normalise the reference's `int... dimension` varargs."""
    if len(dimension) == 0:
        return None
    if len(dimension) == 1 and isinstance(dimension[0], (tuple, list)):
        return tuple(dimension[0])
    return tuple(int(d) for d in dimension)


class INDArray:
    """N-dimensional array backed by an XLA device buffer."""

    __slots__ = ("_jx_", "_np_")
    # Let INDArray win in  np_array + indarray  style expressions.
    __array_priority__ = 100

    def __init__(self, data):
        # numpy input is adopted LAZILY: the HBM buffer materialises on
        # first device use, so host-side pipelines (ETL producers, the C++
        # prefetch ring) can build DataSets without paying a host->device
        # transfer per wrap. dtype is canonicalised eagerly (f64->f32 when
        # x64 is off) so toNumpy() round-trips see jnp.asarray semantics.
        self._np_ = None
        if isinstance(data, INDArray):
            self._jx_ = data._jx_
            self._np_ = data._np_
        elif isinstance(data, jax.Array):
            self._jx_ = data
        elif isinstance(data, np.ndarray):
            self._jx_ = None
            # snapshot (copy) so later caller mutations of their buffer
            # can't change this tensor — matches the old eager
            # jnp.asarray's value semantics; still far cheaper than the
            # host->device transfer it defers
            self._np_ = np.array(
                data, jax.dtypes.canonicalize_dtype(data.dtype), copy=True)
            self._np_.flags.writeable = False
        else:
            self._jx_ = jnp.asarray(data)

    @property
    def _jx(self) -> jax.Array:
        if self._jx_ is None:
            self._jx_ = jnp.asarray(self._np_)
            self._np_ = None  # single owner once device-resident
        return self._jx_

    @_jx.setter
    def _jx(self, value):
        self._jx_ = value
        self._np_ = None

    @property
    def _ref(self):
        """Backing array (host numpy before first device use) — metadata
        reads must not force the HBM transfer."""
        return self._np_ if self._jx_ is None else self._jx_

    # ----- structure -------------------------------------------------
    def shape(self) -> tuple[int, ...]:
        return tuple(self._ref.shape)

    def rank(self) -> int:
        return self._ref.ndim

    def length(self) -> int:
        return int(self._ref.size)

    def size(self, dimension: int) -> int:
        return int(self._ref.shape[dimension])

    def rows(self) -> int:
        return self.size(0)

    def columns(self) -> int:
        return self.size(1)

    def dataType(self) -> DataType:
        return DataType.from_dtype(self._ref.dtype)

    def isScalar(self) -> bool:
        return self._ref.ndim == 0 or self._ref.size == 1

    def isVector(self) -> bool:
        return self._ref.ndim == 1 or (
            self._ref.ndim == 2 and 1 in self._ref.shape
        )

    def isRowVector(self) -> bool:
        return self._ref.ndim == 1 or (self._ref.ndim == 2 and self._ref.shape[0] == 1)

    def isColumnVector(self) -> bool:
        return self._ref.ndim == 2 and self._ref.shape[1] == 1

    def isMatrix(self) -> bool:
        return self._ref.ndim == 2

    def isEmpty(self) -> bool:
        return self._ref.size == 0

    def ordering(self) -> str:
        return "c"

    # ----- conversion ------------------------------------------------
    def toNumpy(self) -> np.ndarray:
        if self._jx_ is None:
            return np.asarray(self._np_)  # still host-side: no device trip
        return np.asarray(self._jx_)

    def jax(self) -> jax.Array:
        """Escape hatch to the underlying buffer (TPU-native extension)."""
        return self._jx

    def distribute(self, mesh, row_axis="data", col_axis=None):
        """Place this 2-D matrix block-sharded over `mesh` as a
        linalg.DistributedMatrix (TPU-native extension; docs/LINALG.md)
        — the entry point to the distributed linear algebra tier
        (SUMMA matmul, Gram, randomized SVD/PCA, CG/least-squares) for
        operands bigger than one chip's HBM. Dims that do not divide
        their mesh axis raise the never-pad PAR03 contract error."""
        from deeplearning4j_tpu.linalg import DistributedMatrix

        return DistributedMatrix(self, mesh, row_axis=row_axis,
                                 col_axis=col_axis)

    def castTo(self, dtype) -> "INDArray":
        return INDArray(self._jx.astype(resolve(dtype)))

    def dup(self) -> "INDArray":
        return INDArray(jnp.array(self._jx, copy=True))

    def detach(self) -> "INDArray":
        return INDArray(jax.lax.stop_gradient(self._jx))

    def assign(self, other) -> "INDArray":
        other = _unwrap(other)
        self._jx = jnp.broadcast_to(jnp.asarray(other, dtype=self._jx.dtype), self._jx.shape)
        return self

    # ----- scalar access ---------------------------------------------
    def _checked_index(self, indices) -> tuple:
        # XLA gather clamps out-of-bounds reads silently; the reference
        # throws, so bounds-check host-side (mirrors putScalar).
        idx = tuple(int(i) for i in indices)
        for i, n in zip(idx, self._jx.shape):
            if not -n <= i < n:
                raise IndexError(f"index {idx} out of bounds for shape {self.shape()}")
        return idx

    def _checked_flat_index(self, i: int) -> int:
        i = int(i)
        if not -self._jx.size <= i < self._jx.size:
            raise IndexError(f"linear index {i} out of bounds for length {self._jx.size}")
        return i

    def _element(self, indices) -> jax.Array:
        """One element; a single index into a non-1d array is linear into the
        flattened array, matching the reference's getDouble(long)/getScalar(long)."""
        if not indices:
            return self._jx.reshape(-1)[0]
        if len(indices) == 1 and self._jx.ndim != 1:
            return self._jx.reshape(-1)[self._checked_flat_index(indices[0])]
        return self._jx[self._checked_index(indices)]

    def getScalar(self, *indices) -> "INDArray":
        return INDArray(self._element(indices))

    def getDouble(self, *indices) -> float:
        return float(self._element(indices))

    def getFloat(self, *indices) -> float:
        return self.getDouble(*indices)

    def getInt(self, *indices) -> int:
        return int(self._element(indices))

    def putScalar(self, *args) -> "INDArray":
        *indices, value = args
        if len(indices) == 1 and isinstance(indices[0], (tuple, list)):
            indices = list(indices[0])
        if len(indices) == 1 and self._jx.ndim > 1:
            # linear index into the flattened array, like the reference
            flat = self._jx.reshape(-1).at[self._checked_flat_index(indices[0])].set(value)
            self._jx = flat.reshape(self._jx.shape)
        else:
            # XLA scatter drops out-of-bounds updates silently; the reference
            # throws, so bounds-check host-side.
            self._jx = self._jx.at[self._checked_index(indices)].set(value)
        return self

    # ----- elementwise arithmetic ------------------------------------
    def _binary(self, other, fn) -> "INDArray":
        return INDArray(fn(self._jx, _unwrap(other)))

    def add(self, other) -> "INDArray":
        return self._binary(other, jnp.add)

    def sub(self, other) -> "INDArray":
        return self._binary(other, jnp.subtract)

    def mul(self, other) -> "INDArray":
        return self._binary(other, jnp.multiply)

    def div(self, other) -> "INDArray":
        return self._binary(other, jnp.divide)

    def rsub(self, other) -> "INDArray":
        return INDArray(jnp.subtract(_unwrap(other), self._jx))

    def rdiv(self, other) -> "INDArray":
        return INDArray(jnp.divide(_unwrap(other), self._jx))

    def addi(self, other) -> "INDArray":
        self._jx = jnp.add(self._jx, _unwrap(other))
        return self

    def subi(self, other) -> "INDArray":
        self._jx = jnp.subtract(self._jx, _unwrap(other))
        return self

    def muli(self, other) -> "INDArray":
        self._jx = jnp.multiply(self._jx, _unwrap(other))
        return self

    def divi(self, other) -> "INDArray":
        self._jx = jnp.divide(self._jx, _unwrap(other))
        return self

    def rsubi(self, other) -> "INDArray":
        self._jx = jnp.subtract(_unwrap(other), self._jx)
        return self

    def rdivi(self, other) -> "INDArray":
        self._jx = jnp.divide(_unwrap(other), self._jx)
        return self

    def neg(self) -> "INDArray":
        return INDArray(jnp.negative(self._jx))

    def negi(self) -> "INDArray":
        self._jx = jnp.negative(self._jx)
        return self

    def fmod(self, other) -> "INDArray":
        return self._binary(other, jnp.fmod)

    # Python operator sugar (the reference is Java; in Python these are
    # the idiomatic entry points and tests/users rely on them).
    __add__ = add
    __radd__ = add
    __sub__ = sub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rsub__ = rsub
    __rtruediv__ = rdiv
    __neg__ = neg

    def __matmul__(self, other) -> "INDArray":
        return self.mmul(other)

    def __pow__(self, p) -> "INDArray":
        return INDArray(jnp.power(self._jx, _unwrap(p)))

    # ----- comparison (BOOL results, like modern nd4j) ----------------
    def eq(self, other) -> "INDArray":
        return self._binary(other, jnp.equal)

    def neq(self, other) -> "INDArray":
        return self._binary(other, jnp.not_equal)

    def gt(self, other) -> "INDArray":
        return self._binary(other, jnp.greater)

    def gte(self, other) -> "INDArray":
        return self._binary(other, jnp.greater_equal)

    def lt(self, other) -> "INDArray":
        return self._binary(other, jnp.less)

    def lte(self, other) -> "INDArray":
        return self._binary(other, jnp.less_equal)

    __eq__ = eq  # matches INDArray.eq broadcasting semantics
    __ne__ = neq
    __gt__ = gt
    __ge__ = gte
    __lt__ = lt
    __le__ = lte
    __hash__ = None

    def equals(self, other) -> bool:
        """Value equality (reference INDArray.equals: shape + values)."""
        other = _unwrap(other)
        if tuple(jnp.shape(other)) != self.shape():
            return False
        return bool(jnp.allclose(self._jx, other, rtol=1e-5, atol=1e-5))

    # ----- linear algebra --------------------------------------------
    def mmul(self, other) -> "INDArray":
        """Matrix multiply on the MXU (reference: cuBLAS gemm)."""
        return INDArray(jnp.matmul(self._jx, _unwrap(other)))

    def tensorMmul(self, other, axes) -> "INDArray":
        return INDArray(jnp.tensordot(self._jx, _unwrap(other), axes=axes))

    def transpose(self) -> "INDArray":
        return INDArray(self._jx.T)

    def permute(self, *order) -> "INDArray":
        return INDArray(jnp.transpose(self._jx, _dims(order)))

    def swapAxes(self, a: int, b: int) -> "INDArray":
        return INDArray(jnp.swapaxes(self._jx, a, b))

    # ----- shape ops --------------------------------------------------
    def reshape(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(self._jx.reshape(shape))

    def ravel(self) -> "INDArray":
        return INDArray(self._jx.reshape(-1))

    def flatten(self) -> "INDArray":
        return self.ravel()

    def broadcast(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(jnp.broadcast_to(self._jx, shape))

    def repeat(self, dimension: int, repeats: int) -> "INDArray":
        return INDArray(jnp.repeat(self._jx, repeats, axis=dimension))

    def squeeze(self, axis=None) -> "INDArray":
        return INDArray(jnp.squeeze(self._jx, axis=axis))

    def expandDims(self, axis: int) -> "INDArray":
        return INDArray(jnp.expand_dims(self._jx, axis))

    # ----- reductions -------------------------------------------------
    def _reduce(self, fn, dimension, keepDims=False, **kw) -> "INDArray":
        axes = _dims(dimension)
        return INDArray(fn(self._jx, axis=axes, keepdims=keepDims, **kw))

    def sum(self, *dimension, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.sum, dimension, keepDims)

    def mean(self, *dimension, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.mean, dimension, keepDims)

    def prod(self, *dimension, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.prod, dimension, keepDims)

    def max(self, *dimension, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.max, dimension, keepDims)

    def min(self, *dimension, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.min, dimension, keepDims)

    def std(self, *dimension, biasCorrected: bool = True, keepDims: bool = False) -> "INDArray":
        # Reference default is the bias-corrected sample std (n-1).
        return self._reduce(jnp.std, dimension, keepDims, ddof=1 if biasCorrected else 0)

    def var(self, *dimension, biasCorrected: bool = True, keepDims: bool = False) -> "INDArray":
        return self._reduce(jnp.var, dimension, keepDims, ddof=1 if biasCorrected else 0)

    def norm1(self, *dimension, keepDims: bool = False) -> "INDArray":
        axes = _dims(dimension)
        return INDArray(jnp.sum(jnp.abs(self._jx), axis=axes, keepdims=keepDims))

    def norm2(self, *dimension, keepDims: bool = False) -> "INDArray":
        axes = _dims(dimension)
        return INDArray(jnp.sqrt(jnp.sum(jnp.square(self._jx), axis=axes, keepdims=keepDims)))

    def normmax(self, *dimension, keepDims: bool = False) -> "INDArray":
        axes = _dims(dimension)
        return INDArray(jnp.max(jnp.abs(self._jx), axis=axes, keepdims=keepDims))

    def _arg_reduce(self, fn, dimension) -> "INDArray":
        axes = _dims(dimension)
        if axes is None or len(axes) == 1:
            return INDArray(fn(self._jx, axis=None if axes is None else axes[0]))
        # multiple dims: collapse them to one trailing axis; the result is a
        # linear index within the combined dims (reference argMax(int...)).
        axes = tuple(a % self._jx.ndim for a in axes)
        keep = [d for d in range(self._jx.ndim) if d not in axes]
        moved = jnp.transpose(self._jx, keep + list(axes))
        flat = moved.reshape(tuple(self._jx.shape[d] for d in keep) + (-1,))
        return INDArray(fn(flat, axis=-1))

    def argMax(self, *dimension) -> "INDArray":
        return self._arg_reduce(jnp.argmax, dimension)

    def argMin(self, *dimension) -> "INDArray":
        return self._arg_reduce(jnp.argmin, dimension)

    def cumsum(self, dimension: int = 0) -> "INDArray":
        return INDArray(jnp.cumsum(self._jx, axis=dimension))

    def cumprod(self, dimension: int = 0) -> "INDArray":
        return INDArray(jnp.cumprod(self._jx, axis=dimension))

    def sumNumber(self) -> float:
        return float(jnp.sum(self._jx))

    def meanNumber(self) -> float:
        return float(jnp.mean(self._jx))

    def maxNumber(self) -> float:
        return float(jnp.max(self._jx))

    def minNumber(self) -> float:
        return float(jnp.min(self._jx))

    def scan(self, condition) -> int:
        """Count of elements matching a boolean condition function."""
        return int(jnp.sum(condition(self._jx)))

    # ----- row/column vector broadcast ops ---------------------------
    def _row_op(self, vec, fn) -> "INDArray":
        v = _unwrap(vec).reshape(1, -1)
        return INDArray(fn(self._jx, v))

    def _col_op(self, vec, fn) -> "INDArray":
        v = _unwrap(vec).reshape(-1, 1)
        return INDArray(fn(self._jx, v))

    def addRowVector(self, v) -> "INDArray":
        return self._row_op(v, jnp.add)

    def subRowVector(self, v) -> "INDArray":
        return self._row_op(v, jnp.subtract)

    def mulRowVector(self, v) -> "INDArray":
        return self._row_op(v, jnp.multiply)

    def divRowVector(self, v) -> "INDArray":
        return self._row_op(v, jnp.divide)

    def addColumnVector(self, v) -> "INDArray":
        return self._col_op(v, jnp.add)

    def subColumnVector(self, v) -> "INDArray":
        return self._col_op(v, jnp.subtract)

    def mulColumnVector(self, v) -> "INDArray":
        return self._col_op(v, jnp.multiply)

    def divColumnVector(self, v) -> "INDArray":
        return self._col_op(v, jnp.divide)

    def addiRowVector(self, v) -> "INDArray":
        self._jx = self._row_op(v, jnp.add)._jx
        return self

    def muliRowVector(self, v) -> "INDArray":
        self._jx = self._row_op(v, jnp.multiply)._jx
        return self

    def addiColumnVector(self, v) -> "INDArray":
        self._jx = self._col_op(v, jnp.add)._jx
        return self

    def muliColumnVector(self, v) -> "INDArray":
        self._jx = self._col_op(v, jnp.multiply)._jx
        return self

    # ----- rows / columns / slices -----------------------------------
    def getRow(self, i: int) -> "INDArray":
        return INDArray(self._jx[i])

    def getColumn(self, i: int) -> "INDArray":
        return INDArray(self._jx[:, i])

    def getRows(self, *rows) -> "INDArray":
        idx = jnp.asarray(_dims(rows), dtype=jnp.int32)
        return INDArray(self._jx[idx])

    def getColumns(self, *cols) -> "INDArray":
        idx = jnp.asarray(_dims(cols), dtype=jnp.int32)
        return INDArray(self._jx[:, idx])

    def putRow(self, i: int, row) -> "INDArray":
        self._jx = self._jx.at[i].set(_unwrap(row))
        return self

    def putColumn(self, i: int, col) -> "INDArray":
        self._jx = self._jx.at[:, i].set(_unwrap(col).reshape(-1))
        return self

    def slice(self, i: int, dimension: int = 0) -> "INDArray":
        return INDArray(jnp.take(self._jx, i, axis=dimension))

    def tensorAlongDimension(self, index: int, *dimension) -> "INDArray":
        dims = _dims(dimension)
        other = [d for d in range(self._jx.ndim) if d not in dims]
        moved = jnp.moveaxis(self._jx, other, range(len(other)))
        flat = moved.reshape((-1,) + moved.shape[len(other):])
        return INDArray(flat[index])

    # ----- fancy get/put (NDArrayIndex protocol) ----------------------
    def get(self, *indices) -> "INDArray":
        from deeplearning4j_tpu.ndarray.indexing import to_index_tuple

        return INDArray(self._jx[to_index_tuple(indices, self.shape())])

    def put(self, indices, value) -> "INDArray":
        from deeplearning4j_tpu.ndarray.indexing import to_index_tuple

        if not isinstance(indices, (tuple, list)):
            indices = (indices,)
        tup = to_index_tuple(tuple(indices), self.shape())
        self._jx = self._jx.at[tup].set(_unwrap(value))
        return self

    def getWhere(self, comp, condition) -> "INDArray":
        mask = condition(self._jx, _unwrap(comp))
        return INDArray(self._jx[mask])

    def replaceWhere(self, replacement, mask) -> "INDArray":
        self._jx = jnp.where(_unwrap(mask).astype(bool), _unwrap(replacement), self._jx)
        return self

    def __getitem__(self, item) -> "INDArray":
        if isinstance(item, tuple):
            item = tuple(_unwrap(i) for i in item)
        else:
            item = _unwrap(item)
        return INDArray(self._jx[item])

    def __setitem__(self, item, value) -> None:
        if isinstance(item, tuple):
            item = tuple(_unwrap(i) for i in item)
        else:
            item = _unwrap(item)
        self._jx = self._jx.at[item].set(_unwrap(value))

    def __len__(self) -> int:
        return self._jx.shape[0]

    def __iter__(self):
        for i in range(self._jx.shape[0]):
            yield INDArray(self._jx[i])

    def __float__(self) -> float:
        return float(self._jx)

    def __int__(self) -> int:
        return int(self._jx)

    def __repr__(self) -> str:
        return f"INDArray{self.shape()}{self._jx.dtype}\n{np.asarray(self._jx)}"

    def __array__(self, dtype=None):
        a = np.asarray(self._jx)
        return a.astype(dtype) if dtype is not None else a


def _register_pytree():
    jax.tree_util.register_pytree_node(
        INDArray,
        lambda a: ((a._jx,), None),
        lambda aux, children: INDArray(children[0]),
    )


_register_pytree()

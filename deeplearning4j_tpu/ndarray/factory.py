"""Nd4j — the static array factory.

Reference: org.nd4j.linalg.factory.Nd4j. The reference factory allocates
typed DataBuffers on the active backend (nd4j-native heap / nd4j-cuda
device). Here creation lowers to jax.numpy, so arrays materialise directly
as XLA device buffers on the default device (TPU HBM), and dtype defaults
to float32 with an overridable global default like Nd4j.setDefaultDataTypes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ndarray.dtype import DataType, resolve
from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap
from deeplearning4j_tpu.ndarray import random as _random


class Nd4j:
    _default_dtype = DataType.FLOAT

    # ----- dtype config ----------------------------------------------
    @staticmethod
    def setDefaultDataTypes(dtype, *_):
        Nd4j._default_dtype = DataType.from_dtype(resolve(dtype))

    @staticmethod
    def defaultFloatingPointType() -> DataType:
        return Nd4j._default_dtype

    @staticmethod
    def dataType() -> DataType:
        return Nd4j._default_dtype

    @staticmethod
    def _dt(dtype):
        return resolve(dtype) if dtype is not None else Nd4j._default_dtype.np_dtype

    # ----- creation ---------------------------------------------------
    @staticmethod
    def create(data=None, *more, shape=None, dtype=None) -> INDArray:
        """Nd4j.create(data), Nd4j.create(rows, cols, ...), Nd4j.create(data, shape)."""
        if data is None and shape is not None:
            return Nd4j.zeros(*shape, dtype=dtype)
        if isinstance(data, int):
            # Nd4j.create(2, 3) — zero-filled array of that shape
            return Nd4j.zeros(data, *more, dtype=dtype)
        if more and shape is None and isinstance(more[0], (tuple, list)):
            shape = tuple(more[0])
        arr = jnp.asarray(_unwrap(data))
        if jnp.issubdtype(arr.dtype, jnp.floating) and dtype is None:
            arr = arr.astype(Nd4j._dt(None))
        elif dtype is not None:
            arr = arr.astype(resolve(dtype))
        if shape is not None:
            arr = arr.reshape(shape)
        return INDArray(arr)

    @staticmethod
    def createFromArray(*values) -> INDArray:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return Nd4j.create(np.asarray(values))

    @staticmethod
    def zeros(*shape, dtype=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(jnp.zeros(shape, dtype=Nd4j._dt(dtype)))

    @staticmethod
    def ones(*shape, dtype=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(jnp.ones(shape, dtype=Nd4j._dt(dtype)))

    @staticmethod
    def zerosLike(arr) -> INDArray:
        return INDArray(jnp.zeros_like(_unwrap(arr)))

    @staticmethod
    def onesLike(arr) -> INDArray:
        return INDArray(jnp.ones_like(_unwrap(arr)))

    @staticmethod
    def empty(dtype=None) -> INDArray:
        return INDArray(jnp.zeros((0,), dtype=Nd4j._dt(dtype)))

    @staticmethod
    def scalar(value, dtype=None) -> INDArray:
        return INDArray(jnp.asarray(value, dtype=Nd4j._dt(dtype) if dtype or not isinstance(value, bool) else jnp.bool_))

    @staticmethod
    def valueArrayOf(shape, value, dtype=None) -> INDArray:
        if isinstance(shape, int):
            shape = (shape,)
        return INDArray(jnp.full(tuple(shape), value, dtype=Nd4j._dt(dtype)))

    @staticmethod
    def eye(n: int, dtype=None) -> INDArray:
        return INDArray(jnp.eye(n, dtype=Nd4j._dt(dtype)))

    @staticmethod
    def diag(v) -> INDArray:
        return INDArray(jnp.diag(_unwrap(v).reshape(-1) if _unwrap(v).ndim != 2 else _unwrap(v)))

    @staticmethod
    def linspace(start, stop, num, dtype=None) -> INDArray:
        return INDArray(jnp.linspace(start, stop, int(num), dtype=Nd4j._dt(dtype)))

    @staticmethod
    def arange(*args, dtype=None) -> INDArray:
        return INDArray(jnp.arange(*args, dtype=dtype if dtype is None else resolve(dtype)).astype(Nd4j._dt(dtype)))

    # ----- random (reference: Nd4j.rand/randn via backend RNG) --------
    @staticmethod
    def rand(*shape, dtype=None, seed=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(_random.uniform(shape, Nd4j._dt(dtype), seed=seed))

    @staticmethod
    def randn(*shape, dtype=None, seed=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return INDArray(_random.normal(shape, Nd4j._dt(dtype), seed=seed))

    @staticmethod
    def getRandom():
        return _random.getRandom()

    # ----- joining / splitting ---------------------------------------
    @staticmethod
    def concat(dimension: int, *arrs) -> INDArray:
        return INDArray(jnp.concatenate([_unwrap(a) for a in arrs], axis=dimension))

    @staticmethod
    def vstack(*arrs) -> INDArray:
        return INDArray(jnp.vstack([_unwrap(a) for a in arrs]))

    @staticmethod
    def hstack(*arrs) -> INDArray:
        return INDArray(jnp.hstack([_unwrap(a) for a in arrs]))

    @staticmethod
    def stack(dimension: int, *arrs) -> INDArray:
        return INDArray(jnp.stack([_unwrap(a) for a in arrs], axis=dimension))

    @staticmethod
    def pile(*arrs) -> INDArray:
        return INDArray(jnp.stack([_unwrap(a) for a in arrs], axis=0))

    @staticmethod
    def tile(arr, *reps) -> INDArray:
        if len(reps) == 1 and isinstance(reps[0], (tuple, list)):
            reps = tuple(reps[0])
        return INDArray(jnp.tile(_unwrap(arr), reps))

    @staticmethod
    def repeat(arr, repeats: int, axis: int = 0) -> INDArray:
        return INDArray(jnp.repeat(_unwrap(arr), repeats, axis=axis))

    # ----- misc ops ---------------------------------------------------
    @staticmethod
    def where(condition, x=None, y=None):
        cond = _unwrap(condition)
        if x is None:
            return [INDArray(i) for i in jnp.where(cond)]
        return INDArray(jnp.where(cond, _unwrap(x), _unwrap(y)))

    @staticmethod
    def sort(arr, dimension: int = -1, ascending: bool = True) -> INDArray:
        s = jnp.sort(_unwrap(arr), axis=dimension)
        if not ascending:
            s = jnp.flip(s, axis=dimension)
        return INDArray(s)

    @staticmethod
    def argsort(arr, dimension: int = -1, ascending: bool = True) -> INDArray:
        s = jnp.argsort(_unwrap(arr), axis=dimension)
        if not ascending:
            s = jnp.flip(s, axis=dimension)
        return INDArray(s)

    @staticmethod
    def reverse(arr, *dimension) -> INDArray:
        if len(dimension) == 1 and isinstance(dimension[0], (tuple, list)):
            dimension = tuple(dimension[0])
        dims = tuple(int(d) for d in dimension) if dimension else None
        return INDArray(jnp.flip(_unwrap(arr), axis=dims))

    @staticmethod
    def gemm(a, b, transposeA: bool = False, transposeB: bool = False, alpha: float = 1.0, beta: float = 0.0, c=None) -> INDArray:
        """General matrix multiply (reference: cuBLAS sgemm → MXU dot)."""
        A = _unwrap(a).T if transposeA else _unwrap(a)
        B = _unwrap(b).T if transposeB else _unwrap(b)
        out = alpha * jnp.matmul(A, B)
        if c is not None and beta != 0.0:
            out = out + beta * _unwrap(c)
        return INDArray(out)

    @staticmethod
    def matmul(a, b) -> INDArray:
        return INDArray(jnp.matmul(_unwrap(a), _unwrap(b)))

    @staticmethod
    def expandDims(arr, axis: int) -> INDArray:
        return INDArray(jnp.expand_dims(_unwrap(arr), axis))

    @staticmethod
    def squeeze(arr, axis: int) -> INDArray:
        return INDArray(jnp.squeeze(_unwrap(arr), axis=axis))

    @staticmethod
    def pad(arr, pad_width, mode: str = "constant", constant_values=0) -> INDArray:
        return INDArray(jnp.pad(_unwrap(arr), pad_width, mode=mode,
                                **({"constant_values": constant_values} if mode == "constant" else {})))

    @staticmethod
    def max(a, b) -> INDArray:
        return INDArray(jnp.maximum(_unwrap(a), _unwrap(b)))

    @staticmethod
    def min(a, b) -> INDArray:
        return INDArray(jnp.minimum(_unwrap(a), _unwrap(b)))

    @staticmethod
    def kron(a, b) -> INDArray:
        """Kronecker product (reference: Nd4j.kron)."""
        return INDArray(jnp.kron(_unwrap(a), _unwrap(b)))

    @staticmethod
    def getCompressor():
        """Reference: Nd4j.getCompressor() -> BasicNDArrayCompressor
        singleton (GZIP/FLOAT16/INT8/NOOP buffer codecs)."""
        from deeplearning4j_tpu.ndarray.compression import \
            BasicNDArrayCompressor

        return BasicNDArrayCompressor.getInstance()

    @staticmethod
    def argMax(arr, *dimension) -> INDArray:
        """Reference: Nd4j.argMax(arr, dims) — flat argmax with no dims.
        Multi-dim reduction raises rather than silently using only the
        first dim."""
        x = _unwrap(arr)
        if len(dimension) > 1:
            raise ValueError(
                "argMax over multiple dimensions is not supported; "
                "reshape to merge the dims first")
        axis = dimension[0] if dimension else None
        return INDArray(jnp.argmax(x, axis=axis))

    @staticmethod
    def sortWithIndices(arr, dimension: int = -1,
                        ascending: bool = True):
        """[indices, sorted] pair (reference: Nd4j.sortWithIndices)."""
        x = _unwrap(arr)
        idx = jnp.argsort(x, axis=dimension)
        if not ascending:
            idx = jnp.flip(idx, axis=dimension)
        return [INDArray(idx),
                INDArray(jnp.take_along_axis(x, idx, axis=dimension))]

    @staticmethod
    def accumulate(*arrs) -> INDArray:
        """Elementwise sum of same-shaped arrays (reference:
        Nd4j.accumulate). Accepts varargs or one list."""
        if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
            arrs = tuple(arrs[0])
        if not arrs:
            raise ValueError("accumulate needs at least one array")
        return INDArray(sum(_unwrap(a) for a in arrs))

    @staticmethod
    def average(*arrs) -> INDArray:
        """Elementwise mean of same-shaped arrays (reference:
        Nd4j.averageAndPropagate family). Accepts varargs or one list."""
        if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
            arrs = tuple(arrs[0])
        if not arrs:
            raise ValueError("average needs at least one array")
        total = Nd4j.accumulate(*arrs)  # shares the summation logic
        return INDArray(total.jax() / float(len(arrs)))

    # ----- file IO (reference: Nd4j.writeNpy/readNpy, writeTxt/readTxt,
    # saveBinary/readBinary) -------------------------------------------
    @staticmethod
    def writeNpy(arr, path):
        """Standard .npy file — numpy-ecosystem interop. Writes through
        an open file object: np.save(str) silently appends ".npy" to
        extension-less paths, breaking the read-back of the SAME path."""
        with open(str(path), "wb") as f:
            np.save(f, np.asarray(_unwrap(arr)), allow_pickle=False)

    @staticmethod
    def readNpy(path) -> INDArray:
        return INDArray(jnp.asarray(np.load(str(path),
                                            allow_pickle=False)))

    @staticmethod
    def saveBinary(arr, path):
        """Binary save (reference: Nd4j.saveBinary). The container IS
        .npy — self-describing shape/dtype, no bespoke format."""
        Nd4j.writeNpy(arr, path)

    @staticmethod
    def readBinary(path) -> INDArray:
        return Nd4j.readNpy(path)

    @staticmethod
    def writeTxt(arr, path):
        """Text format: one "# shape: (..) dtype" header line, then the
        flattened values (reference: Nd4j.writeTxt — upstream's own
        header-plus-values text form, not numpy savetxt)."""
        a = np.asarray(_unwrap(arr))
        with open(str(path), "w", encoding="utf-8") as f:
            f.write(f"# shape: {','.join(map(str, a.shape))} "
                    f"dtype: {a.dtype.name}\n")
            flat = a.reshape(-1)
            if a.dtype.kind == "f":
                lines = (repr(float(v)) for v in flat)
            elif a.dtype.kind == "c":
                lines = (repr(complex(v)) for v in flat)
            else:
                lines = (str(v) for v in flat)
            f.write("\n".join(lines))
            f.write("\n")

    @staticmethod
    def readTxt(path) -> INDArray:
        with open(str(path), encoding="utf-8") as f:
            header = f.readline().strip()
            if not header.startswith("# shape:"):
                raise ValueError(
                    f"{path}: not an Nd4j.writeTxt file (missing header)")
            body = header[len("# shape:"):].strip()
            shape_part, _, dtype_part = body.partition("dtype:")
            shape = tuple(int(s) for s in shape_part.strip().split(",")
                          if s != "")
            dtype = np.dtype(dtype_part.strip() or "float32")
            vals = [ln.strip() for ln in f if ln.strip()]
        # parse by dtype kind: float('True') raises and float() of big
        # int64 silently loses precision past 2**53
        if dtype.kind == "b":
            py = [v == "True" for v in vals]
        elif dtype.kind in "iu":
            py = [int(v) for v in vals]
        elif dtype.kind == "c":
            py = [complex(v) for v in vals]
        else:
            py = [float(v) for v in vals]
        arr = np.asarray(py, dtype).reshape(shape)
        return INDArray(jnp.asarray(arr))

    # ----- executioner / env (reference: Nd4j.getExecutioner()) -------
    @staticmethod
    def getExecutioner():
        from deeplearning4j_tpu.ndarray.executioner import XlaExecutioner

        return XlaExecutioner.instance()

"""Transforms — elementwise transform op set as a static utility.

Reference surface: org.nd4j.linalg.ops.transforms.Transforms (nd4j-api).
In the reference each call dispatches a libnd4j TransformOp kernel; here
each lowers to one jax.numpy/lax primitive that XLA fuses with its
neighbours when traced under jit. All functions take INDArray (or anything
array-like) and return a new INDArray; the reference's `dup=false` in-place
variants are covered by the caller rebinding, since XLA buffers are
immutable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap


def _wrap1(fn):
    def op(x, *args, **kwargs):
        return INDArray(fn(jnp.asarray(_unwrap(x)), *args, **kwargs))
    return op


def _rows(x, dimensions):
    """[N, D] row view for the all-pairs distance family. Upstream's
    `dimensions` selects the vector axis; only the row layout (vectors
    along dim 1, the upstream default) is supported — anything else
    raises rather than silently transposing."""
    a = jnp.asarray(_unwrap(x))
    if a.ndim != 2:
        raise ValueError(f"all-distances expect 2-D [N, D] input, got "
                         f"shape {a.shape}")
    if dimensions and tuple(dimensions) != (1,):
        raise ValueError("only dimensions=1 (vectors along rows) is "
                         "supported")
    return a


class Transforms:
    # ----- exponential / log ------------------------------------------
    exp = staticmethod(_wrap1(jnp.exp))
    log = staticmethod(_wrap1(jnp.log))
    log1p = staticmethod(_wrap1(jnp.log1p))
    expm1 = staticmethod(_wrap1(jnp.expm1))
    sqrt = staticmethod(_wrap1(jnp.sqrt))
    cbrt = staticmethod(_wrap1(jnp.cbrt))
    reciprocal = staticmethod(_wrap1(lambda a: 1.0 / a))

    # ----- trig / hyperbolic ------------------------------------------
    sin = staticmethod(_wrap1(jnp.sin))
    cos = staticmethod(_wrap1(jnp.cos))
    tan = staticmethod(_wrap1(jnp.tan))
    asin = staticmethod(_wrap1(jnp.arcsin))
    acos = staticmethod(_wrap1(jnp.arccos))
    atan = staticmethod(_wrap1(jnp.arctan))
    sinh = staticmethod(_wrap1(jnp.sinh))
    cosh = staticmethod(_wrap1(jnp.cosh))
    tanh = staticmethod(_wrap1(jnp.tanh))
    atanh = staticmethod(_wrap1(jnp.arctanh))

    # ----- sign / rounding / clipping ---------------------------------
    abs = staticmethod(_wrap1(jnp.abs))
    sign = staticmethod(_wrap1(jnp.sign))
    floor = staticmethod(_wrap1(jnp.floor))
    ceil = staticmethod(_wrap1(jnp.ceil))
    round = staticmethod(_wrap1(jnp.round))

    @staticmethod
    def clip(x, minVal, maxVal) -> INDArray:
        return INDArray(jnp.clip(jnp.asarray(_unwrap(x)), minVal, maxVal))

    @staticmethod
    def pow(x, power) -> INDArray:
        return INDArray(jnp.power(jnp.asarray(_unwrap(x)), _unwrap(power)))

    @staticmethod
    def max(x, y) -> INDArray:
        return INDArray(jnp.maximum(jnp.asarray(_unwrap(x)), _unwrap(y)))

    @staticmethod
    def min(x, y) -> INDArray:
        return INDArray(jnp.minimum(jnp.asarray(_unwrap(x)), _unwrap(y)))

    # ----- neural activations -----------------------------------------
    sigmoid = staticmethod(_wrap1(jax.nn.sigmoid))
    relu = staticmethod(_wrap1(jax.nn.relu))
    relu6 = staticmethod(_wrap1(jax.nn.relu6))
    elu = staticmethod(_wrap1(jax.nn.elu))
    gelu = staticmethod(_wrap1(jax.nn.gelu))
    softplus = staticmethod(_wrap1(jax.nn.softplus))
    softsign = staticmethod(_wrap1(jax.nn.soft_sign))
    mish = staticmethod(_wrap1(lambda a: a * jnp.tanh(jax.nn.softplus(a))))
    swish = staticmethod(_wrap1(lambda a: a * jax.nn.sigmoid(a)))
    hardTanh = staticmethod(_wrap1(lambda a: jnp.clip(a, -1.0, 1.0)))
    # reference HardSigmoid is clip(0.2x + 0.5), not jax.nn's relu6(x+3)/6
    hardSigmoid = staticmethod(_wrap1(lambda a: jnp.clip(0.2 * a + 0.5, 0.0, 1.0)))

    @staticmethod
    def leakyRelu(x, alpha=0.01) -> INDArray:
        return INDArray(jax.nn.leaky_relu(jnp.asarray(_unwrap(x)), alpha))

    @staticmethod
    def softmax(x, dimension: int = -1) -> INDArray:
        return INDArray(jax.nn.softmax(jnp.asarray(_unwrap(x)), axis=dimension))

    @staticmethod
    def logSoftmax(x, dimension: int = -1) -> INDArray:
        return INDArray(jax.nn.log_softmax(jnp.asarray(_unwrap(x)), axis=dimension))

    @staticmethod
    def step(x) -> INDArray:  # heaviside, reference: Step
        return INDArray((jnp.asarray(_unwrap(x)) > 0).astype(jnp.float32))

    # ----- vector geometry --------------------------------------------
    @staticmethod
    def unitVec(x) -> INDArray:
        a = jnp.asarray(_unwrap(x))
        return INDArray(a / jnp.linalg.norm(a))

    @staticmethod
    def normalizeZeroMeanAndUnitVariance(x) -> INDArray:
        a = jnp.asarray(_unwrap(x))
        return INDArray((a - a.mean()) / jnp.maximum(a.std(), 1e-12))

    @staticmethod
    def euclideanDistance(x, y) -> float:
        return float(jnp.linalg.norm(jnp.asarray(_unwrap(x)) - _unwrap(y)))

    @staticmethod
    def manhattanDistance(x, y) -> float:
        return float(jnp.abs(jnp.asarray(_unwrap(x)) - _unwrap(y)).sum())

    @staticmethod
    def cosineSim(x, y) -> float:
        a, b = jnp.asarray(_unwrap(x)).ravel(), jnp.asarray(_unwrap(y)).ravel()
        denom = jnp.maximum(jnp.linalg.norm(a) * jnp.linalg.norm(b), 1e-12)
        return float(jnp.dot(a, b) / denom)

    @staticmethod
    def cosineDistance(x, y) -> float:
        return 1.0 - Transforms.cosineSim(x, y)

    @staticmethod
    def hammingDistance(x, y) -> float:
        a, b = jnp.asarray(_unwrap(x)), jnp.asarray(_unwrap(y))
        return float(jnp.mean((a != b).astype(jnp.float32)))

    @staticmethod
    def jaccardDistance(x, y) -> float:
        a, b = jnp.asarray(_unwrap(x)), jnp.asarray(_unwrap(y))
        inter = jnp.minimum(a, b).sum()
        union = jnp.maximum(a, b).sum()
        return float(1.0 - inter / jnp.maximum(union, 1e-12))

    # ----- all-pairs distance matrices (reference:
    # Transforms.allEuclideanDistances / allCosineSimilarities /
    # allManhattanDistances — upstream lowers these to gemm-shaped
    # kernels; here the [N, D] x [M, D] -> [N, M] forms ride the MXU) --
    @staticmethod
    def allEuclideanDistances(x, y, *dimensions) -> INDArray:
        a, b = _rows(x, dimensions), _rows(y, dimensions)
        # ||a-b||^2 = ||a||^2 + ||b||^2 - 2ab, clamped for fp error
        sq = (jnp.sum(a * a, 1)[:, None] + jnp.sum(b * b, 1)[None, :]
              - 2.0 * (a @ b.T))
        return INDArray(jnp.sqrt(jnp.maximum(sq, 0.0)))

    @staticmethod
    def allManhattanDistances(x, y, *dimensions) -> INDArray:
        a, b = _rows(x, dimensions), _rows(y, dimensions)
        # L1 has no gemm form; stream rows so working memory stays
        # O(M*D) instead of materializing the [N, M, D] broadcast
        return INDArray(jax.lax.map(
            lambda row: jnp.sum(jnp.abs(row[None, :] - b), -1), a))

    @staticmethod
    def allCosineSimilarities(x, y, *dimensions) -> INDArray:
        a, b = _rows(x, dimensions), _rows(y, dimensions)
        an = jnp.linalg.norm(a, axis=1)[:, None]
        bn = jnp.linalg.norm(b, axis=1)[None, :]
        return INDArray((a @ b.T) / jnp.maximum(an * bn, 1e-12))

    # ----- comparisons (reference: Transforms.and/or/xor/not) ---------
    @staticmethod
    def isMax(x, dimension: int = None) -> INDArray:
        # one-hot of argmax (first max on ties), matching the reference IsMax op
        a = jnp.asarray(_unwrap(x))
        if dimension is None:
            flat = jnp.zeros(a.size, a.dtype).at[jnp.argmax(a.ravel())].set(1)
            return INDArray(flat.reshape(a.shape))
        idx = jnp.argmax(a, axis=dimension, keepdims=True)
        iota = jax.lax.broadcasted_iota(idx.dtype, a.shape, dimension % a.ndim)
        return INDArray((iota == idx).astype(a.dtype))

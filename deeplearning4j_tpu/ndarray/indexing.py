"""NDArrayIndex — structured slicing.

Reference: org.nd4j.linalg.indexing.NDArrayIndex (all/point/interval/
newAxis and INDArray.get/put). The reference resolves these into strided
views over the same buffer; XLA has no aliased views, so indices resolve to
gather/slice ops (get) and scatter ops (put) which XLA fuses or aliases
where legal.
"""

from __future__ import annotations


class _Index:
    def resolve(self, dim_size: int):
        raise NotImplementedError


class _All(_Index):
    def resolve(self, dim_size: int):
        return slice(None)

    def __repr__(self):
        return "all()"


class _Point(_Index):
    def __init__(self, i: int):
        self.i = int(i)

    def resolve(self, dim_size: int):
        return self.i if self.i >= 0 else dim_size + self.i

    def __repr__(self):
        return f"point({self.i})"


class _Interval(_Index):
    def __init__(self, begin: int, end: int, stride: int = 1, inclusive: bool = False):
        self.begin, self.end, self.stride = int(begin), int(end), int(stride)
        self.inclusive = inclusive

    def resolve(self, dim_size: int):
        end = self.end + 1 if self.inclusive else self.end
        return slice(self.begin, end, self.stride)

    def __repr__(self):
        return f"interval({self.begin},{self.end},{self.stride})"


class _NewAxis(_Index):
    def resolve(self, dim_size: int):
        return None  # numpy newaxis

    def __repr__(self):
        return "newAxis()"


class NDArrayIndex:
    @staticmethod
    def all() -> _Index:
        return _All()

    @staticmethod
    def point(i: int) -> _Index:
        return _Point(i)

    @staticmethod
    def interval(*args, inclusive: bool = False) -> _Index:
        """interval(begin, end) | interval(begin, stride, end[, inclusive]).

        The 3-argument order is (begin, STRIDE, end), matching the
        reference's NDArrayIndex.interval(long, long, long).
        """
        if len(args) == 2:
            begin, end = args
            stride = 1
        elif len(args) == 3:
            begin, stride, end = args
        elif len(args) == 4:
            begin, stride, end, inclusive = args
        else:
            raise TypeError("interval(begin, end) or interval(begin, stride, end[, inclusive])")
        return _Interval(begin, end, stride, inclusive)

    @staticmethod
    def newAxis() -> _Index:
        return _NewAxis()

    @staticmethod
    def indices(*idx) -> list:
        return [int(i) for i in idx]


def to_index_tuple(indices, shape) -> tuple:
    """Translate a mix of NDArrayIndex objects / ints / slices / lists into
    a numpy-style index tuple."""
    out = []
    dim = 0
    for ix in indices:
        if isinstance(ix, _NewAxis):
            out.append(None)
            continue
        if isinstance(ix, _Index):
            out.append(ix.resolve(shape[dim] if dim < len(shape) else 0))
        elif isinstance(ix, (int, slice, list)):
            out.append(ix)
        else:
            out.append(ix)  # arrays for fancy indexing
        dim += 1
    return tuple(out)


# Convenience aliases matching common reference imports
all_ = NDArrayIndex.all
point = NDArrayIndex.point
interval = NDArrayIndex.interval

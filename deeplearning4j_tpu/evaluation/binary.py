"""Multi-label binary evaluation.

Reference: org.nd4j.evaluation.classification.EvaluationBinary — per-output
TP/FP/TN/FN counts with a decision threshold (default 0.5), giving
accuracy / precision / recall / F1 / MCC per output column.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.evaluation.evaluation import _to_np


class EvaluationBinary:
    def __init__(self, nOutputs=None, decisionThreshold=0.5):
        self._n = nOutputs
        self._thr = float(decisionThreshold)
        self._counts = None  # [n, 4] = tp, fp, tn, fn

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self._counts = None

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        if y.ndim == 1:
            y, p = y[:, None], p[:, None]
        if y.ndim == 3:
            y = np.transpose(y, (0, 2, 1)).reshape(-1, y.shape[1])
            p = np.transpose(p, (0, 2, 1)).reshape(-1, p.shape[1])
        keep = None  # [N, M] elementwise keep-mask
        if mask is not None:
            m = _to_np(mask)
            if m.shape == y.shape:  # per-output mask (reference supports both)
                keep = m > 0
            else:
                m = m.reshape(-1) > 0
                y, p = y[m], p[m]
        n = y.shape[1]
        if self._counts is None:
            self._n = self._n or n
            self._counts = np.zeros((self._n, 4), np.int64)
        if n != self._n:
            raise ValueError(f"EvaluationBinary configured for {self._n} outputs "
                             f"but data has {n} columns")
        pred = (p >= self._thr)
        act = (y >= 0.5)
        if keep is None:
            keep = np.ones_like(pred, bool)
        self._counts[:, 0] += (pred & act & keep).sum(0)
        self._counts[:, 1] += (pred & ~act & keep).sum(0)
        self._counts[:, 2] += (~pred & ~act & keep).sum(0)
        self._counts[:, 3] += (~pred & act & keep).sum(0)
        return self

    # ----- per-output metrics -----------------------------------------
    def truePositives(self, i=0):
        return int(self._counts[i, 0])

    def falsePositives(self, i=0):
        return int(self._counts[i, 1])

    def trueNegatives(self, i=0):
        return int(self._counts[i, 2])

    def falseNegatives(self, i=0):
        return int(self._counts[i, 3])

    def accuracy(self, i=0) -> float:
        tp, fp, tn, fn = self._counts[i]
        return float((tp + tn) / max(tp + fp + tn + fn, 1))

    def precision(self, i=0) -> float:
        tp, fp = self._counts[i, 0], self._counts[i, 1]
        return float(tp / max(tp + fp, 1))

    def recall(self, i=0) -> float:
        tp, fn = self._counts[i, 0], self._counts[i, 3]
        return float(tp / max(tp + fn, 1))

    def f1(self, i=0) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / max(p + r, 1e-12)

    def matthewsCorrelation(self, i=0) -> float:
        tp, fp, tn, fn = self._counts[i].astype(np.float64)
        denom = np.sqrt(max((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn), 1e-12))
        return float((tp * tn - fp * fn) / denom)

    def averageAccuracy(self) -> float:
        return float(np.mean([self.accuracy(i) for i in range(self._n)]))

    def averageF1(self) -> float:
        return float(np.mean([self.f1(i) for i in range(self._n)]))

    def numLabels(self) -> int:
        return self._n

    def stats(self) -> str:
        lines = ["==================Evaluation (binary)=================="]
        for i in range(self._n):
            lines.append(f" out {i}: acc={self.accuracy(i):.4f} "
                         f"prec={self.precision(i):.4f} rec={self.recall(i):.4f} "
                         f"f1={self.f1(i):.4f} mcc={self.matthewsCorrelation(i):.4f}")
        return "\n".join(lines)

"""Probability calibration evaluation.

Reference: org.nd4j.evaluation.classification.EvaluationCalibration —
reliability diagrams (predicted-probability bins vs observed frequency),
per-class probability histograms, residual plots, and the expected
calibration error derived from them.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.evaluation.evaluation import _to_np


class EvaluationCalibration:
    def __init__(self, reliabilityDiagNumBins=10, histogramNumBins=10):
        self._rbins = int(reliabilityDiagNumBins)
        self._hbins = int(histogramNumBins)
        self._counts = None   # [C, rbins] predictions per bin, per class
        self._correct = None  # [C, rbins] positives per bin, per class
        self._psum = None     # [C, rbins] summed predicted prob per bin
        self._res_hist = None  # [hbins] |label - prob| residual histogram
        self._prob_hist = None  # [C, hbins] predicted-probability histogram

    def reset(self):
        self._counts = self._correct = self._psum = None
        self._res_hist = self._prob_hist = None

    def _ensure(self, C):
        if self._counts is None:
            self._counts = np.zeros((C, self._rbins), np.int64)
            self._correct = np.zeros((C, self._rbins), np.int64)
            self._psum = np.zeros((C, self._rbins), np.float64)
            self._res_hist = np.zeros(self._hbins, np.int64)
            self._prob_hist = np.zeros((C, self._hbins), np.int64)

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        if mask is not None:
            m = _to_np(mask).astype(bool).reshape(-1)
            y, p = y[m], p[m]
        C = y.shape[-1]
        self._ensure(C)
        bins = np.clip((p * self._rbins).astype(int), 0, self._rbins - 1)
        hb = np.clip((p * self._hbins).astype(int), 0, self._hbins - 1)
        rb = np.clip((np.abs(y - p) * self._hbins).astype(int), 0,
                     self._hbins - 1)
        for c in range(C):
            np.add.at(self._counts[c], bins[:, c], 1)
            np.add.at(self._correct[c], bins[:, c], y[:, c] > 0.5)
            np.add.at(self._psum[c], bins[:, c], p[:, c])
            np.add.at(self._prob_hist[c], hb[:, c], 1)
        np.add.at(self._res_hist, rb.reshape(-1), 1)
        return self

    # ------------------------------------------------------------------
    def getReliabilityDiagram(self, classIdx):
        """(mean predicted prob per bin, observed frequency per bin) —
        empty bins are NaN (reference: ReliabilityDiagram)."""
        n = self._counts[classIdx]
        with np.errstate(invalid="ignore", divide="ignore"):
            meanp = np.where(n > 0, self._psum[classIdx] / n, np.nan)
            freq = np.where(n > 0, self._correct[classIdx] / n, np.nan)
        return meanp, freq

    def expectedCalibrationError(self, classIdx=None):
        """ECE = sum_bins (n_b/N) * |freq_b - meanp_b|; averaged over
        classes when classIdx is None."""
        idxs = range(self._counts.shape[0]) if classIdx is None else [classIdx]
        eces = []
        for c in idxs:
            n = self._counts[c]
            total = n.sum()
            if total == 0:
                continue
            meanp, freq = self.getReliabilityDiagram(c)
            valid = n > 0
            eces.append(float(np.sum(
                n[valid] / total * np.abs(freq[valid] - meanp[valid]))))
        return float(np.mean(eces)) if eces else float("nan")

    def getProbabilityHistogram(self, classIdx):
        return self._prob_hist[classIdx].copy()

    def getResidualPlot(self):
        """Histogram of |label - prediction| residuals (reference:
        EvaluationCalibration.getResidualPlotAllClasses)."""
        return self._res_hist.copy()

    def stats(self) -> str:
        C = self._counts.shape[0] if self._counts is not None else 0
        lines = [f"EvaluationCalibration ({C} classes, "
                 f"{self._rbins} reliability bins)"]
        for c in range(C):
            lines.append(f"  class {c}: ECE="
                         f"{self.expectedCalibrationError(c):.4f}")
        return "\n".join(lines)

"""Evaluation metrics.

Reference: org.nd4j.evaluation (Evaluation, RegressionEvaluation, ROC).
"""

from deeplearning4j_tpu.evaluation.evaluation import Evaluation

"""Evaluation metrics.

Reference: org.nd4j.evaluation (Evaluation, RegressionEvaluation, ROC,
ROCMultiClass, ROCBinary, EvaluationBinary).
"""

from deeplearning4j_tpu.evaluation.evaluation import Evaluation
from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation
from deeplearning4j_tpu.evaluation.roc import ROC, ROCMultiClass, ROCBinary
from deeplearning4j_tpu.evaluation.binary import EvaluationBinary
from deeplearning4j_tpu.evaluation.calibration import EvaluationCalibration

__all__ = ["Evaluation", "RegressionEvaluation", "ROC", "ROCMultiClass",
           "ROCBinary", "EvaluationBinary", "EvaluationCalibration"]

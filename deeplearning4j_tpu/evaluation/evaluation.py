"""Classification evaluation.

Reference: org.nd4j.evaluation.classification.Evaluation — accuracy,
precision/recall/F1 (macro), confusion matrix. Counts accumulate on host
in numpy (evaluation is not a TPU-bound op); predictions stream from
device once per batch.
"""

from __future__ import annotations

import numpy as np


def _to_np(a):
    from deeplearning4j_tpu.ndarray import INDArray

    if isinstance(a, INDArray):
        return a.toNumpy()
    return np.asarray(a)


class Evaluation:
    def __init__(self, numClasses=None, labelsList=None, topN=1):
        # reference overload Evaluation(int numClasses, Integer topN):
        # an int second positional is topN, not a labels list
        if isinstance(labelsList, int):
            topN = labelsList
            labelsList = None
        self._n = numClasses
        self._labels = labelsList
        self._conf = None  # confusion matrix [actual, predicted]
        # reference: Evaluation(int numClasses, Integer topN) — track
        # how often the true class lands in the top-N scores
        self._topN = int(topN)
        self._topn_correct = 0
        self._topn_total = 0

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self._conf = None
        self._topn_correct = 0
        self._topn_total = 0

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        if y.ndim == 3:  # RNN [B,C,T] -> flatten time
            y = np.transpose(y, (0, 2, 1)).reshape(-1, y.shape[1])
            p = np.transpose(p, (0, 2, 1)).reshape(-1, p.shape[1])
            if mask is not None:
                m = _to_np(mask).reshape(-1) > 0
                y, p = y[m], p[m]
        elif mask is not None:
            m = _to_np(mask).reshape(-1) > 0
            y, p = y[m], p[m]
        n = y.shape[-1]
        if self._conf is None:
            self._n = self._n or n
            self._conf = np.zeros((self._n, self._n), np.int64)
        actual = np.argmax(y, axis=-1)
        pred = np.argmax(p, axis=-1)
        np.add.at(self._conf, (actual, pred), 1)
        if self._topN > 1:
            p2 = np.atleast_2d(p)          # unbatched 1-D eval() calls
            a2 = np.atleast_1d(actual)
            k = min(self._topN, p2.shape[-1])
            topk = np.argpartition(-p2, k - 1, axis=-1)[:, :k]
            self._topn_correct += int((topk == a2[:, None]).any(-1).sum())
            self._topn_total += len(a2)
        return self

    # ----- metrics ----------------------------------------------------
    def accuracy(self) -> float:
        c = self._conf
        return float(np.trace(c)) / max(1, c.sum())

    def topNAccuracy(self) -> float:
        """Fraction of examples whose true class was among the topN
        scores (reference: Evaluation.topNAccuracy). topN=1 collapses
        to accuracy()."""
        if self._topN <= 1:
            return self.accuracy()
        return self._topn_correct / max(1, self._topn_total)

    def _per_class(self):
        c = self._conf.astype(np.float64)
        tp = np.diag(c)
        fp = c.sum(axis=0) - tp
        fn = c.sum(axis=1) - tp
        prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 0.0)
        rec = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1), 0.0)
        return prec, rec

    def precision(self, cls=None) -> float:
        prec, _ = self._per_class()
        if cls is not None:
            return float(prec[cls])
        present = self._conf.sum(axis=1) > 0
        return float(prec[present].mean()) if present.any() else 0.0

    def recall(self, cls=None) -> float:
        _, rec = self._per_class()
        if cls is not None:
            return float(rec[cls])
        present = self._conf.sum(axis=1) > 0
        return float(rec[present].mean()) if present.any() else 0.0

    def f1(self, cls=None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / max(p + r, 1e-12)

    def falsePositiveRate(self, cls) -> float:
        c = self._conf.astype(np.float64)
        tp = c[cls, cls]
        fp = c[:, cls].sum() - tp
        tn = np.trace(c) - tp
        neg = c.sum() - c[cls].sum()
        return float(fp / max(neg, 1))

    def getConfusionMatrix(self):
        return self._conf

    def confusionMatrix(self):
        return self._conf

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self._n}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
            "",
            "=========================Confusion Matrix=========================",
            str(self._conf),
        ]
        return "\n".join(lines)

"""ROC / AUC evaluation.

Reference: org.nd4j.evaluation.classification.{ROC, ROCMultiClass, ROCBinary}.
The reference supports exact mode (store all probabilities) and thresholded
mode (fixed threshold bins). We keep both: exact computes AUROC/AUPRC by the
trapezoid rule over the full sorted score set; thresholded accumulates
TP/FP/TN/FN counts per threshold bin so memory stays O(thresholdSteps) over
any stream length.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.evaluation.evaluation import _to_np


_trapz = getattr(np, "trapezoid", None) or np.trapz  # numpy<2 fallback


def _auc(x, y):
    """Trapezoid area under a curve given as unordered (x, y) points."""
    order = np.argsort(x, kind="stable")
    return float(_trapz(np.asarray(y)[order], np.asarray(x)[order]))


class ROC:
    """Binary ROC. `eval(labels, scores)` where labels are {0,1} (single
    column) or one-hot 2-column, and scores are P(class=1)."""

    def __init__(self, thresholdSteps: int = 0):
        self._steps = int(thresholdSteps)
        if self._steps > 0:
            edges = np.linspace(0.0, 1.0, self._steps + 1)
            self._edges = edges
            self._tp = np.zeros(self._steps + 1, np.int64)
            self._fp = np.zeros(self._steps + 1, np.int64)
        else:
            self._scores = []
            self._labels = []
        self._n_pos = 0
        self._n_neg = 0

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self.__init__(self._steps)

    @staticmethod
    def _binary(labels, preds):
        y = _to_np(labels)
        p = _to_np(preds)
        if y.ndim == 2 and y.shape[1] == 2:
            y = y[:, 1]
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        return y.reshape(-1).astype(np.int64), p.reshape(-1).astype(np.float64)

    def eval(self, labels, predictions, mask=None):
        y, p = self._binary(labels, predictions)
        if mask is not None:
            m = _to_np(mask).reshape(-1) > 0
            y, p = y[m], p[m]
        self._n_pos += int((y == 1).sum())
        self._n_neg += int((y == 0).sum())
        if self._steps > 0:
            # prediction >= threshold counts as positive at that threshold;
            # one binning pass + reversed cumsum instead of a per-edge scan
            bins = np.clip(np.searchsorted(self._edges, p, side="right") - 1,
                           0, self._steps)
            tp_bins = np.bincount(bins[y == 1], minlength=self._steps + 1)
            fp_bins = np.bincount(bins[y == 0], minlength=self._steps + 1)
            self._tp += tp_bins[::-1].cumsum()[::-1]
            self._fp += fp_bins[::-1].cumsum()[::-1]
        else:
            self._scores.append(p)
            self._labels.append(y)
        return self

    def _exact_curve(self):
        y = np.concatenate(self._labels)
        p = np.concatenate(self._scores)
        order = np.argsort(-p, kind="stable")
        y, p = y[order], p[order]
        tps = np.cumsum(y == 1)
        fps = np.cumsum(y == 0)
        # take curve points only at distinct-score boundaries so tied groups
        # contribute a single diagonal segment (trapezoid = half credit)
        last_of_group = np.r_[p[1:] != p[:-1], True]
        tps, fps, thr = tps[last_of_group], fps[last_of_group], p[last_of_group]
        tpr = np.concatenate([[0.0], tps / max(self._n_pos, 1)])
        fpr = np.concatenate([[0.0], fps / max(self._n_neg, 1)])
        return fpr, tpr, np.concatenate([[np.inf], thr])

    def getRocCurve(self):
        """(fpr, tpr, thresholds) arrays."""
        if self._steps > 0:
            tpr = self._tp / max(self._n_pos, 1)
            fpr = self._fp / max(self._n_neg, 1)
            return fpr, tpr, self._edges
        return self._exact_curve()

    def calculateAUC(self) -> float:
        fpr, tpr, _ = self.getRocCurve()
        return _auc(fpr, tpr)

    def calculateAUCPR(self) -> float:
        if self._steps > 0:
            tp, fp = self._tp, self._fp
            fn = self._n_pos - tp
            prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1), 1.0)
            rec = tp / max(self._n_pos, 1)
            return _auc(rec, prec)
        y = np.concatenate(self._labels)
        p = np.concatenate(self._scores)
        order = np.argsort(-p, kind="stable")
        y, p = y[order], p[order]
        tps = np.cumsum(y == 1)
        last_of_group = np.r_[p[1:] != p[:-1], True]
        ranks = np.arange(1, len(y) + 1)[last_of_group]
        tps = tps[last_of_group]
        prec = tps / ranks
        rec = tps / max(self._n_pos, 1)
        return _auc(np.concatenate([[0.0], rec]), np.concatenate([[1.0], prec]))

    def stats(self) -> str:
        return (f"ROC (exact={self._steps == 0}): AUROC={self.calculateAUC():.4f}, "
                f"AUPRC={self.calculateAUCPR():.4f}, "
                f"pos={self._n_pos}, neg={self._n_neg}")


class ROCMultiClass:
    """One-vs-all ROC per class (reference: ROCMultiClass)."""

    def __init__(self, thresholdSteps: int = 0):
        self._steps = thresholdSteps
        self._rocs = None

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        if y.ndim == 3:
            y = np.transpose(y, (0, 2, 1)).reshape(-1, y.shape[1])
            p = np.transpose(p, (0, 2, 1)).reshape(-1, p.shape[1])
        if mask is not None:
            m = _to_np(mask).reshape(-1) > 0
            y, p = y[m], p[m]
        n = y.shape[1]
        if self._rocs is None:
            self._rocs = [ROC(self._steps) for _ in range(n)]
        for c in range(n):
            self._rocs[c].eval((y.argmax(-1) == c).astype(np.int64), p[:, c])
        return self

    def calculateAUC(self, classIdx: int) -> float:
        return self._rocs[classIdx].calculateAUC()

    def calculateAUCPR(self, classIdx: int) -> float:
        return self._rocs[classIdx].calculateAUCPR()

    def calculateAverageAUC(self) -> float:
        return float(np.mean([r.calculateAUC() for r in self._rocs]))

    def calculateAverageAUCPR(self) -> float:
        return float(np.mean([r.calculateAUCPR() for r in self._rocs]))

    def stats(self) -> str:
        lines = ["=====================ROCMultiClass====================="]
        for i, r in enumerate(self._rocs):
            lines.append(f" class {i}: AUROC={r.calculateAUC():.4f} "
                         f"AUPRC={r.calculateAUCPR():.4f}")
        lines.append(f" average AUROC: {self.calculateAverageAUC():.4f}")
        return "\n".join(lines)


class ROCBinary:
    """Per-output-column binary ROC for multi-label problems
    (reference: ROCBinary — labels [N, M] in {0,1}, scores [N, M])."""

    def __init__(self, thresholdSteps: int = 0):
        self._steps = thresholdSteps
        self._rocs = None

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self._rocs = None

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels)
        p = _to_np(predictions)
        if y.ndim == 1:
            y, p = y[:, None], p[:, None]
        keep = None
        if mask is not None:
            m = _to_np(mask)
            if m.shape == y.shape:  # per-output mask
                keep = m > 0
            else:
                m = m.reshape(-1) > 0
                y, p = y[m], p[m]
        if self._rocs is None:
            self._rocs = [ROC(self._steps) for _ in range(y.shape[1])]
        for c in range(y.shape[1]):
            if keep is None:
                self._rocs[c].eval(y[:, c], p[:, c])
            else:
                self._rocs[c].eval(y[keep[:, c], c], p[keep[:, c], c])
        return self

    def calculateAUC(self, outputNum: int = 0) -> float:
        return self._rocs[outputNum].calculateAUC()

    def calculateAUCPR(self, outputNum: int = 0) -> float:
        return self._rocs[outputNum].calculateAUCPR()

    def numLabels(self) -> int:
        return len(self._rocs)

    def stats(self) -> str:
        return "\n".join(f"output {i}: AUROC={r.calculateAUC():.4f}"
                         for i, r in enumerate(self._rocs))

"""Regression evaluation.

Reference: org.nd4j.evaluation.regression.RegressionEvaluation — per-column
MSE, MAE, RMSE, RSE (relative squared error), Pearson correlation, R^2.
Sums accumulate on host across batches; metrics are derived at read time so
the class streams over arbitrarily many minibatches in O(columns) memory.
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.evaluation.evaluation import _to_np


class RegressionEvaluation:
    def __init__(self, nColumns=None, columnNames=None):
        self._names = list(columnNames) if columnNames else None
        if self._names and nColumns is None:
            nColumns = len(self._names)
        self._n_cols = nColumns
        self._initialized = False

    def reset(self):
        """Clear accumulated statistics (reference: IEvaluation.reset())."""
        self._initialized = False
        # drop the accumulators so a read between reset() and the next
        # eval() fails loudly instead of returning the discarded stats
        for a in ("_count", "_sum_err", "_sum_abs_err", "_sum_sq_err",
                  "_sum_label", "_sum_sq_label", "_sum_pred", "_sum_sq_pred",
                  "_sum_label_pred"):
            if hasattr(self, a):
                delattr(self, a)

    def _init(self, n):
        self._n_cols = n
        z = np.zeros(n, np.float64)
        self._count = z.copy()
        self._sum_err = z.copy()          # sum(pred - label)
        self._sum_abs_err = z.copy()      # sum|pred - label|
        self._sum_sq_err = z.copy()       # sum(pred - label)^2
        self._sum_label = z.copy()
        self._sum_sq_label = z.copy()
        self._sum_pred = z.copy()
        self._sum_sq_pred = z.copy()
        self._sum_label_pred = z.copy()   # sum(label * pred)
        self._initialized = True

    def eval(self, labels, predictions, mask=None):
        y = _to_np(labels).astype(np.float64)
        p = _to_np(predictions).astype(np.float64)
        if y.ndim == 3:  # RNN [B, C, T] -> [B*T, C]
            y = np.transpose(y, (0, 2, 1)).reshape(-1, y.shape[1])
            p = np.transpose(p, (0, 2, 1)).reshape(-1, p.shape[1])
        if y.ndim == 1:
            y, p = y[:, None], p[:, None]
        if mask is not None:
            m = _to_np(mask).reshape(-1) > 0
            y, p = y[m], p[m]
        if not self._initialized:
            self._init(y.shape[1])
        err = p - y
        self._count += y.shape[0]
        self._sum_err += err.sum(0)
        self._sum_abs_err += np.abs(err).sum(0)
        self._sum_sq_err += (err ** 2).sum(0)
        self._sum_label += y.sum(0)
        self._sum_sq_label += (y ** 2).sum(0)
        self._sum_pred += p.sum(0)
        self._sum_sq_pred += (p ** 2).sum(0)
        self._sum_label_pred += (y * p).sum(0)
        return self

    # ----- per-column metrics -----------------------------------------
    def meanSquaredError(self, col=0) -> float:
        return float(self._sum_sq_err[col] / max(self._count[col], 1))

    def meanAbsoluteError(self, col=0) -> float:
        return float(self._sum_abs_err[col] / max(self._count[col], 1))

    def rootMeanSquaredError(self, col=0) -> float:
        return float(np.sqrt(self.meanSquaredError(col)))

    def relativeSquaredError(self, col=0) -> float:
        n = max(self._count[col], 1)
        mean_label = self._sum_label[col] / n
        ss_tot = self._sum_sq_label[col] - n * mean_label ** 2
        return float(self._sum_sq_err[col] / max(ss_tot, 1e-12))

    def rSquared(self, col=0) -> float:
        return float(1.0 - self.relativeSquaredError(col))

    def pearsonCorrelation(self, col=0) -> float:
        n = max(self._count[col], 1)
        cov = self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col] / n
        var_l = self._sum_sq_label[col] - self._sum_label[col] ** 2 / n
        var_p = self._sum_sq_pred[col] - self._sum_pred[col] ** 2 / n
        return float(cov / max(np.sqrt(max(var_l * var_p, 0.0)), 1e-12))

    # ----- column averages (reference: average* methods) --------------
    def averageMeanSquaredError(self) -> float:
        return float(np.mean([self.meanSquaredError(i) for i in range(self._n_cols)]))

    def averageMeanAbsoluteError(self) -> float:
        return float(np.mean([self.meanAbsoluteError(i) for i in range(self._n_cols)]))

    def averagerootMeanSquaredError(self) -> float:
        return float(np.mean([self.rootMeanSquaredError(i) for i in range(self._n_cols)]))

    def averageRSquared(self) -> float:
        return float(np.mean([self.rSquared(i) for i in range(self._n_cols)]))

    def averagePearsonCorrelation(self) -> float:
        return float(np.mean([self.pearsonCorrelation(i) for i in range(self._n_cols)]))

    def numColumns(self) -> int:
        return self._n_cols

    def stats(self) -> str:
        name = lambda i: (self._names[i] if self._names else f"col_{i}")
        header = f"{'Column':<16}{'MSE':>12}{'MAE':>12}{'RMSE':>12}{'RSE':>12}{'PC':>12}{'R^2':>12}"
        rows = [f"{name(i):<16}{self.meanSquaredError(i):>12.5f}"
                f"{self.meanAbsoluteError(i):>12.5f}{self.rootMeanSquaredError(i):>12.5f}"
                f"{self.relativeSquaredError(i):>12.5f}{self.pearsonCorrelation(i):>12.5f}"
                f"{self.rSquared(i):>12.5f}"
                for i in range(self._n_cols)]
        return "\n".join(["==================Regression Evaluation==================",
                          header] + rows)

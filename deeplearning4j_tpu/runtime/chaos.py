"""Process-wide deterministic chaos harness for the serving tier.

The training path already proves its failure handling with induced
faults (``FaultInjector``, runtime/resilience.py) — this module
generalizes that discipline to the WHOLE process: a seeded
``ChaosPlan`` schedules faults against named injection seams
(``fault_point("fleet.dispatch")``-style) wired at every dispatch
boundary, so the breaker/quarantine/hedge/brownout machinery in
serving/fleet.py is tested against the failures it exists for — and
the same fault sequence replays from the same seed.

Seam inventory (every caller passes its payload through the seam so a
``corrupt`` rule can mutate it in flight):

========================  ============================================
seam                      dispatch boundary
========================  ============================================
``host.submit``           ServedModel.submit (serving/host.py)
``host.submit_sequence``  ServedSequenceModel.submit (serving/host.py)
``queue.dispatch``        MicroBatcher coalesced dispatch
                          (serving/queue.py, inside the batch-failure
                          try so an injected raise fails the batch the
                          organic way)
``sequence.step``         SequenceScheduler slot-batched decode step
                          (serving/sequence.py)
``fleet.dispatch``        FleetRouter per-replica dispatch attempt
                          (serving/fleet.py, inside the failover try)
``server.request``        the HTTP GET/POST handlers (serving/
                          server.py; ordinals interleave in request
                          order)
``aot.disk_read``         ExecutableCache disk-tier load (runtime/
                          aot.py; payload is the artifact path — a
                          corrupt rule makes the open fail, which the
                          cache must absorb as a miss)
``aot.disk_write``        ExecutableCache disk-tier store
``checkpoint.write``      ResilientFit._save (runtime/resilience.py,
                          inside the retry() lambda)
``checkpoint.restore``    ResilientFit._maybe_resume
========================  ============================================

Fault kinds, per rule: ``raise`` N times, ``wedge`` for T seconds
(blocks on an optional release event — the injectable-clock wedge),
``slow`` by T seconds, and ``corrupt`` (payload transform). Every rule
resolves to an explicit set of per-seam invocation ordinals at
SCHEDULE time — rate-based rules draw those ordinals from the plan's
seeded RNG — so the fired sequence is a pure function of the seed and
each seam's invocation order, never of thread timing. ``plan.events``
records ``(seam, kind, ordinal)`` in fire order; two plans with the
same seed driven through the same traffic produce identical lists.

Zero overhead when nothing is armed: ``fault_point`` is a module-level
read of one global (no lock, no allocation) before returning the
payload unchanged, and an ARMED plan short-circuits the same way for
seams it has no rules for — the armed-vs-disarmed serving overhead
gate (bench `serving_chaos`) holds at <=1.03x because of these two
fast paths. No jax import anywhere in this module, so wiring a seam
into a module can never add an accelerator dependency.

Telemetry: ``dl4j_chaos_injections_total{seam,kind}`` counts every
fired fault (docs/OBSERVABILITY.md); tests separate injected failures
from organic ones by exception type (``ChaosError``).

See docs/RESILIENCE.md "Chaos harness".
"""

from __future__ import annotations

import random
import threading

__all__ = ["ChaosError", "ChaosPlan", "SEAMS", "arm", "armed_plan",
           "disarm", "fault_point", "register_seam", "registered_seams"]

#: the built-in seam inventory; new boundaries add theirs via
#: ``register_seam`` — arming a plan that schedules a name in neither
#: is rejected (a typo'd seam would otherwise silently never fire)
SEAMS = ("host.submit", "host.submit_sequence", "queue.dispatch",
         "sequence.step", "fleet.dispatch", "server.request",
         "aot.disk_read", "aot.disk_write", "checkpoint.write",
         "checkpoint.restore")

#: seams registered at runtime beyond the built-in inventory
_EXTRA_SEAMS = set()


def register_seam(name):
    """Register a seam name beyond the built-in ``SEAMS`` inventory so
    plans scheduling it pass arm-time validation. Idempotent; returns
    the name (handy at module scope: ``SEAM = register_seam("x.y")``)."""
    name = str(name)
    if not name:
        raise ValueError("seam name must be non-empty")
    with _ARM_LOCK:
        if name not in SEAMS:
            _EXTRA_SEAMS.add(name)
    return name


def registered_seams():
    """Every seam a plan may schedule: the built-in inventory plus
    everything ``register_seam``-ed, as a tuple."""
    with _ARM_LOCK:
        return SEAMS + tuple(sorted(_EXTRA_SEAMS))

_KINDS = ("raise", "wedge", "slow", "corrupt")


class ChaosError(RuntimeError):
    """An INJECTED failure. Everything the harness raises derives from
    this (unless a rule overrides ``exc``), so tests can assert "zero
    non-injected errors" by error class."""


#: the module-level fast path: ``fault_point`` reads this one global
#: and returns immediately when no plan is armed
_PLAN = None
_ARM_LOCK = threading.Lock()


def fault_point(seam, payload=None):
    """The seam hook. Disarmed: one global read, payload returned
    unchanged. Armed: the plan fires whatever it scheduled for this
    invocation ordinal of `seam` (raise/wedge/slow) and returns the
    possibly-corrupted payload."""
    plan = _PLAN  # thread-ok[THR01]: atomic reference read; arm/disarm
    # swap the whole plan object, never mutate a live one's rule book
    if plan is None:
        return payload
    return plan._fire(seam, payload)


def arm(plan):
    """Install `plan` process-wide (replacing any armed plan).

    Rejects a plan that schedules rules against a seam that is neither
    in ``SEAMS`` nor ``register_seam``-ed: a typo'd seam name would
    otherwise arm fine and silently never fire — the chaos run reports
    green without having injected anything."""
    global _PLAN
    with _ARM_LOCK:
        unknown = sorted(set(getattr(plan, "_rules", ()) or ())
                         - set(SEAMS) - _EXTRA_SEAMS)
        if unknown:
            raise ValueError(
                "plan schedules unknown seam(s) "
                + ", ".join(repr(s) for s in unknown)
                + " — not in chaos.SEAMS and never register_seam()-ed; "
                "a typo'd seam would silently never fire")
        _PLAN = plan
    return plan


def disarm():
    """Remove the armed plan (restores the zero-overhead fast path).
    Returns the plan that was armed, or None."""
    global _PLAN
    with _ARM_LOCK:
        plan, _PLAN = _PLAN, None
    return plan


def armed_plan():
    return _PLAN


def default_corrupt(payload):
    """The stock payload corruption: numeric arrays get their first
    element poisoned (NaN for floats, flipped max for ints), strings/
    paths get a suffix that breaks them, bytes get a flipped bit.
    Anything else is returned unchanged (a wrapper object would break
    callers in ways no real corruption does)."""
    try:
        import numpy as np
    except ImportError:  # pragma: no cover - numpy is a hard dep in-repo
        np = None
    if np is not None and isinstance(payload, np.ndarray) \
            and payload.size:
        bad = np.array(payload, copy=True)
        flat = bad.reshape(-1)
        if np.issubdtype(bad.dtype, np.floating):
            flat[0] = np.nan
        elif np.issubdtype(bad.dtype, np.integer):
            flat[0] = np.iinfo(bad.dtype).max
        return bad
    if isinstance(payload, str):
        return payload + ".chaos-corrupt"
    if isinstance(payload, bytes):
        return bytes([payload[0] ^ 0xFF]) + payload[1:] if payload \
            else b"\xff"
    return payload


class ChaosPlan:
    """A seeded, replayable fault schedule over the named seams.

    Build rules before arming; each rule binds to explicit invocation
    ordinals of its seam (``at`` = first ordinal, ``times`` =
    consecutive count), or — for ``random_*`` rules — to ordinals drawn
    from the plan's seeded RNG at schedule time. Ordinals count the
    seam's ``fault_point`` invocations from 0 WHILE the plan is armed
    (a seam with no rules is never counted — that is the armed fast
    path).

    clock/sleep are injectable for deterministic tests: ``sleep``
    defaults to ``time.sleep``; pass e.g. ``ManualClock.advance`` to
    make wedge/slow rules advance virtual time instead of blocking.
    """

    def __init__(self, seed=0, sleep=None):
        import time as _time

        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._sleep = sleep if sleep is not None else _time.sleep
        self._lock = threading.Lock()
        self._rules = {}     # seam -> [rule dict]
        self._counts = {}    # seam -> invocations seen while armed
        #: (seam, kind, ordinal) in fire order — the replay record two
        #: equal-seed plans must produce identically
        self.events = []
        self._m_fired = None  # lazy: telemetry registered on first arm

    # -- schedule --------------------------------------------------------
    def _add(self, seam, kind, fires, **kw):
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(one of {_KINDS})")
        rule = {"seam": str(seam), "kind": kind,
                "fires": frozenset(int(i) for i in fires), **kw}
        if not rule["fires"]:
            return self
        with self._lock:
            self._rules.setdefault(str(seam), []).append(rule)
        return self

    def raise_n(self, seam, times=1, at=0, exc=ChaosError,
                message="injected fault"):
        """Raise `exc` on invocations [at, at+times) of `seam`."""
        return self._add(seam, "raise", range(at, at + times),
                         exc=exc, message=str(message))

    def wedge(self, seam, seconds, at=0, times=1, release=None):
        """Block for `seconds` (or until `release` — a
        threading.Event — fires) on invocations [at, at+times): the
        wedged-replica fault."""
        return self._add(seam, "wedge", range(at, at + times),
                         seconds=float(seconds), release=release)

    def slow(self, seam, seconds, at=0, times=1):
        """Sleep `seconds` before proceeding on invocations
        [at, at+times): the slow-replica / slow-disk fault."""
        return self._add(seam, "slow", range(at, at + times),
                         seconds=float(seconds))

    def corrupt(self, seam, at=0, times=1, mutate=None):
        """Pass the seam payload through `mutate` (default:
        ``default_corrupt``) on invocations [at, at+times)."""
        return self._add(seam, "corrupt", range(at, at + times),
                         mutate=mutate or default_corrupt)

    def random_raises(self, seam, rate, window, exc=ChaosError,
                      message="injected fault"):
        """Seeded intermittent failures: each of the first `window`
        invocations of `seam` raises with probability `rate` — the
        ordinals are drawn NOW from the plan RNG, so the same seed
        schedules the same ordinals."""
        fires = [i for i in range(int(window))
                 if self._rng.random() < float(rate)]
        return self._add(seam, "raise", fires, exc=exc,
                         message=str(message))

    def random_slows(self, seam, rate, window, seconds):
        """Seeded intermittent slowness over the first `window`
        invocations of `seam`."""
        fires = [i for i in range(int(window))
                 if self._rng.random() < float(rate)]
        return self._add(seam, "slow", fires, seconds=float(seconds))

    # -- introspection ---------------------------------------------------
    def schedule(self):
        """{seam: sorted fire ordinals per rule} — the replayable
        schedule (a pure function of the seed + rule calls)."""
        with self._lock:
            return {seam: [sorted(r["fires"]) for r in rules]
                    for seam, rules in self._rules.items()}

    def fired(self, seam=None):
        """Count of fired faults (optionally for one seam)."""
        with self._lock:
            if seam is None:
                return len(self.events)
            return sum(1 for s, _, _ in self.events if s == seam)

    # -- runtime ---------------------------------------------------------
    def _metrics(self):
        # lazy so building a plan in a test never touches the registry
        # until the first fault actually fires
        if self._m_fired is None:  # thread-ok[THR01]: double-checked
            # fast path — a stale None just falls through to the lock,
            # where the check repeats before assignment
            with self._lock:
                if self._m_fired is None:
                    from deeplearning4j_tpu.runtime import telemetry

                    self._m_fired = telemetry.get_registry().counter(
                        "dl4j_chaos_injections_total",
                        "chaos faults fired, by seam and kind",
                        labels=("seam", "kind"))
        return self._m_fired  # thread-ok[THR01]: reference read of an
        # assign-once instrument; the registry dedupes by name anyway

    def _fire(self, seam, payload):
        rules = self._rules.get(seam)  # thread-ok[THR01]: rule books
        # are append-only before arming; the armed fast path reads the
        # dict snapshot and misses at worst a rule added mid-traffic
        if not rules:
            return payload  # the armed fast path: seam has no rules
        with self._lock:
            n = self._counts.get(seam, 0)
            self._counts[seam] = n + 1
            due = [r for r in rules if n in r["fires"]]
            for r in due:
                self.events.append((seam, r["kind"], n))
        # act OUTSIDE the lock: wedge/slow block, raise unwinds (a
        # THR03-clean seam can never stall an unrelated seam's fire)
        for r in due:
            self._metrics().labels(seam=seam, kind=r["kind"]).inc()
            kind = r["kind"]
            if kind == "slow":
                self._sleep(r["seconds"])
            elif kind == "wedge":
                ev = r.get("release")
                if ev is not None:
                    ev.wait(r["seconds"])
                else:
                    self._sleep(r["seconds"])
            elif kind == "corrupt":
                payload = r["mutate"](payload)
            elif kind == "raise":
                raise r["exc"](
                    f"chaos[{seam}#{n}]: {r['message']}")
        return payload

    # -- arming ----------------------------------------------------------
    def __enter__(self):
        arm(self)
        return self

    def __exit__(self, *exc):
        disarm()
        return False

"""ctypes binding for the native bulk CSV parser (textparse.cpp).

Built on runtime/_native.py (shared with ringbuffer.py). The binding
returns None whenever the native path cannot serve the request — no
compiler, delimiter the parser can't handle, or content that is not a
clean numeric rectangle — and callers fall back to the Python record
loop, so behavior never changes, only speed.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from deeplearning4j_tpu.runtime._native import NativeLoader

_HERE = os.path.dirname(os.path.abspath(__file__))


def _configure(lib):
    lib.tp_parse_f32.restype = ctypes.c_long
    lib.tp_parse_f32.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char,
        ctypes.c_long, ctypes.POINTER(ctypes.c_float),
        ctypes.c_long, ctypes.POINTER(ctypes.c_long)]


_loader = NativeLoader(os.path.join(_HERE, "textparse.cpp"),
                       os.path.join(_HERE, "build", "libtextparse.so"),
                       _configure)


def native_lib():
    """Load (building if needed) the native library; None if unavailable."""
    return _loader.lib()


def _first_data_line(data, skip_rows):
    """First non-blank, non-skipped line — WITHOUT copying the buffer."""
    i, skipped, n = 0, 0, len(data)
    while i < n:
        j = data.find(b"\n", i)
        if j < 0:
            j = n
        line = data[i:j].strip()
        i = j + 1
        if not line:
            continue
        if skipped < int(skip_rows):
            skipped += 1
            continue
        return line
    return b""


def parse_csv_f32(data, delimiter=",", skip_rows=0):
    """bytes/str -> float32 [rows, cols] matrix, or None to fall back.

    None means: native lib unavailable, unsupported delimiter, or the
    content is not a clean numeric rectangle (ragged rows, non-numeric
    or empty fields) — exactly the cases the Python path handles with
    its richer per-token semantics."""
    lib = native_lib()
    if lib is None:
        return None
    if isinstance(data, str):
        data = data.encode("utf-8")
    d = str(delimiter)
    # whitespace delimiters collide with the parser's field trimming
    if len(d) != 1 or d in (" ", "\t", "\n", "\r"):
        return None
    # capacity: rows over-estimated from newline count (headers/blank
    # lines inflate it harmlessly), columns from the first DATA line —
    # a short header row must not shrink the estimate
    first = _first_data_line(data, skip_rows)
    if not first:
        return None
    cols_est = first.count(d.encode()) + 1
    cap = (data.count(b"\n") + 1) * cols_est
    out = np.empty(cap, np.float32)
    ncols = ctypes.c_long(0)
    rows = lib.tp_parse_f32(
        data, len(data), d.encode()[0], int(skip_rows),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        cap, ctypes.byref(ncols))
    if rows <= 0 or ncols.value == 0:
        return None
    return out[:rows * ncols.value].reshape(rows, ncols.value).copy()

"""Runtime autotuning arbiter: measure once, start tuned forever.

The framework exposes a family of lowering knobs — BN/loss dtype tails,
the BN->activation epilogue, three maxpool backward impls, the flash
attention backward strategy, fitDataSet staging — each shipped at a
default chosen from ONE reference measurement (usually the TPU v5e
round-4 window). The right setting is a function of backend, shapes and
jaxlib version, so any fixed default is wrong somewhere; the EQuARX
pattern (arXiv:2506.17615 — measure the variants, persist the winner,
key by the configuration) applies to every one of these knobs, not just
collectives.

``autotune(net, x_shape)`` is that pattern as a runtime service:

* **sweep** — coordinate descent over the knob registry. Each candidate
  re-lowers the network's canonical train step under the flipped knob;
  candidates whose HLO is byte-identical to the incumbent (the knob
  does not touch this program — e.g. flash_bwd on an attention-free
  CNN) are skipped without compiling.
* **prove** — every adopted candidate must run ``steps`` training steps
  on the live backend and reproduce the incumbent's loss sequence
  (bitwise for impl-swap knobs like maxpool_bwd, tolerance-banded for
  math-changing knobs like the wide tails). A faster-but-wrong
  candidate is rejected, never scored.
* **score** — ``util.hbm_ledger`` attributed bytes of the compiled step
  (the bandwidth bill the round-5 attribution engine audits); when a
  real accelerator is live, measured step wall time becomes the primary
  score with bytes as the tiebreak. A candidate must win by
  ``min_gain`` (default 0.5%) — noise never flips a default.
* **persist** — winners are stored keyed EXACTLY like the AOT
  executable cache (runtime/aot.py): ambient fingerprint x program
  fingerprint x signature — except the knob values themselves are
  excluded from the ambient part (they are the tuning's OUTPUT, not its
  environment). Any later process calling ``autotune``/``warm_start``
  with the same network on the same backend gets the persisted winners
  applied with ZERO re-sweeps and zero compiles.

The knob values live in the AOT ambient fingerprint, so installing a
tuned config can never collide with a stock executable — flipping a
knob IS a different cache key (gated in tests/test_aot_cache.py).

Distinct from the hyperparameter-search ``arbiter/`` package: that
tunes the MODEL (learning rates, layer sizes) by training to
convergence; this tunes the LOWERING (same math, fewer bytes) by
compiling and proving parity. See docs/AUTOTUNE.md.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import time

import numpy as np

__all__ = [
    "KNOBS", "Knob", "current_knobs", "applied", "install",
    "tuning_key", "TuningStore", "enable", "disable", "store",
    "autotune", "autotune_subject", "warm_start", "AutotuneResult",
]

#: bump when the knob inventory or record layout changes — old records
#: become stale (re-swept), never silently misapplied
TUNE_FORMAT = 1

#: env var naming a directory for the persistent tier (shares a
#: directory with the AOT executable cache comfortably: records are
#: ``<key>.tune.json`` next to the ``.aotx`` executables)
TUNE_DIR_ENV = "DL4J_TPU_AUTOTUNE_CACHE"


class Knob:
    """One tunable lowering toggle: where it lives (module attr), what
    values it may take, how to set it, and how strictly a candidate
    must reproduce the incumbent's loss sequence (rtol 0.0 = bitwise —
    the impl-swap knobs are exact-math alternatives; > 0 = the knob
    changes rounding, e.g. the wide tails)."""

    def __init__(self, name, module, attr, candidates, setter=None,
                 parity_rtol=0.0, doc=""):
        self.name = name
        self._module = module
        self._attr = attr
        self.candidates = tuple(candidates)
        self._setter = setter  # name of a validating setter on module
        self.parity_rtol = float(parity_rtol)
        self.doc = doc

    def _mod(self):
        import importlib

        return importlib.import_module(self._module)

    def get(self):
        return getattr(self._mod(), self._attr)

    def set(self, value):
        """Set the knob; returns the previous value."""
        if value not in self.candidates:
            raise ValueError(
                f"knob {self.name}: {value!r} not in {self.candidates}")
        mod = self._mod()
        if self._setter is not None:
            return getattr(mod, self._setter)(value)
        old = getattr(mod, self._attr)
        setattr(mod, self._attr, value)
        return old


#: the knob registry, in sweep order (cheapest-to-prove first). These
#: are exactly the module globals the AOT ambient fingerprint carries —
#: keep the two lists in sync (gated in tests/test_autotune.py).
KNOBS = (
    Knob("maxpool_bwd", "deeplearning4j_tpu.ops.pooling",
         "_BACKWARD_IMPL", ("stock", "indices", "argmax"),
         setter="set_maxpool_bwd",
         doc="max_pool2d gradient: XLA select-and-scatter / saved-int8-"
             "indices single-pass (non-overlapping windows) / argmax "
             "recompute"),
    Knob("global_maxpool_bwd", "deeplearning4j_tpu.ops.pooling",
         "_GLOBAL_MAXPOOL_BWD", ("stock", "indices"),
         setter="set_global_maxpool_bwd",
         doc="global max-pool gradient: jnp.max autodiff / saved-argmax "
             "elementwise pass"),
    Knob("bn_epilogue", "deeplearning4j_tpu.ops.norm",
         "_EPILOGUE", ("fused", "unfused"), setter="set_bn_epilogue",
         parity_rtol=1e-4,  # tanh/sigmoid grad-from-output is ulp-level
         doc="BN -> activation(-> add): one custom-VJP epilogue (no "
             "pre-activation residual) / legacy composition"),
    Knob("flash_bwd", "deeplearning4j_tpu.ops.pallas_attention",
         "_BWD_IMPL", ("kernel", "recompute"), setter="set_flash_bwd",
         parity_rtol=1e-3,
         doc="pallas flash-attention backward: hand-written dq/dkv "
             "kernels / jax.vjp recompute through the blockwise scan"),
    Knob("bn_tail", "deeplearning4j_tpu.ops.norm",
         "_TAIL_MODE", ("compute", "wide"), parity_rtol=0.05,
         doc="BN activation-scale math dtype under a sub-fp32 policy"),
    Knob("loss_tail", "deeplearning4j_tpu.nn.losses",
         "_TAIL_MODE", ("compute", "wide"), parity_rtol=0.05,
         doc="loss-tail activation-scale math dtype"),
    # NOT registered: canon_staging (DL4J_TPU_CANON_STAGING). It only
    # shapes the fitDataSet staging path, never the _train_step program
    # this arbiter lowers and scores — sweeping it would record a dead
    # 'identical' row on every subject. It IS in the AOT ambient
    # fingerprint (flipping it re-keys executables) and bench.py's
    # canon_staging_ab leg measures it on the program it does shape.
)

_KNOBS_BY_NAME = {k.name: k for k in KNOBS}


def current_knobs():
    """{name: live value} for every registered knob."""
    return {k.name: k.get() for k in KNOBS}


class applied:
    """Context manager: set the given {name: value} knobs, restore the
    previous values on exit (exception-safe, reverse order)."""

    def __init__(self, knobs):
        self._target = dict(knobs)
        self._old = []

    def __enter__(self):
        for name, value in self._target.items():
            knob = _KNOBS_BY_NAME[name]
            self._old.append((knob, knob.get()))
            knob.set(value)
        return self

    def __exit__(self, *exc):
        for knob, value in reversed(self._old):
            knob.set(value)
        self._old = []
        return False


def install(knobs):
    """Permanently set {name: value} knobs (the warm-start entry);
    returns {name: previous} so a caller can undo. Callers must not
    reuse jitted steps traced before the install — the AOT key changes
    with the knobs, so cached executables re-key correctly, but a bare
    jax.jit handle traced earlier keeps the old lowering."""
    old = {}
    for name, value in knobs.items():
        old[name] = _KNOBS_BY_NAME[name].set(value)
    return old


# ----------------------------------------------------------------------
# keys and the store
# ----------------------------------------------------------------------

def _ambient_base():
    """The AOT ambient fingerprint MINUS the tuned knobs: the
    environment the tuning is valid FOR, independent of where the
    knobs currently point (a tuned process must look up the same
    record it would have written when stock)."""
    from deeplearning4j_tpu.runtime import aot

    amb = dict(aot.ambient_fingerprint())
    for k in _KNOBS_BY_NAME:
        amb.pop(k, None)
    amb["tune_format"] = TUNE_FORMAT
    # knob inventory: adding a candidate or a knob re-tunes
    amb["knob_inventory"] = tuple(
        (k.name, k.candidates) for k in KNOBS)
    return amb


def tuning_key(net, extra=""):
    """sha256 over (ambient-minus-knobs, program fingerprint) — the AOT
    cache-key anatomy (docs/COMPILE.md) with the knob axis removed and
    no call signature: tuned knobs are properties of the PROGRAM on
    this backend, not of one batch shape, so precompile()/serving can
    recall them for any signature (docs/AUTOTUNE.md 'Key anatomy')."""
    from deeplearning4j_tpu.runtime import aot

    try:
        fp = aot.network_fingerprint(net)
    except Exception:  # fault-ok[FLT01]: the SameDiff-fingerprint fallback IS the handling — the two graph families share one entry point and the except is the dispatch between them
        fp = aot.samediff_fingerprint(net)  # SameDiff graphs
    base = repr(sorted(_ambient_base().items()))
    return hashlib.sha256("|".join(
        [base, fp, extra]).encode()).hexdigest()


class TuningStore:
    """Two-tier {key: record} store mirroring aot.ExecutableCache:
    process memory plus an optional JSON-per-key disk tier written
    atomically (tmp + rename). Records embed the ambient base; a
    version/backend change makes them stale (removed, re-swept), and a
    corrupt file is a miss, never a crash."""

    def __init__(self, directory=None):
        self.directory = os.path.expanduser(str(directory)) \
            if directory else None
        if self.directory:
            os.makedirs(self.directory, mode=0o700, exist_ok=True)
        self._mem = {}
        self.stats = {"hits": 0, "misses": 0, "puts": 0, "stale": 0,
                      "corrupt": 0, "store_errors": 0}

    def _path(self, key):
        return os.path.join(self.directory, key + ".tune.json")

    def get(self, key):
        rec = self._mem.get(key)
        if rec is not None:
            self.stats["hits"] += 1
            return rec
        if self.directory is None:
            self.stats["misses"] += 1
            return None
        path = self._path(key)
        if not os.path.exists(path):
            self.stats["misses"] += 1
            return None
        try:
            with open(path, "r", encoding="utf-8") as fh:  # fault-ok[FLT02]: the tuning store is read once per sweep at startup, off the serving dispatch path — its failure contract (corrupt -> counted miss) is total without an injection seam
                rec = json.load(fh)
        except Exception:
            self.stats["corrupt"] += 1
            self._remove(path)
            return None
        if rec.get("tune_format") != TUNE_FORMAT:
            self.stats["stale"] += 1
            self._remove(path)
            return None
        self.stats["hits"] += 1
        self._mem[key] = rec
        return rec

    def put(self, key, rec):
        rec = dict(rec)
        rec["tune_format"] = TUNE_FORMAT
        self._mem[key] = rec
        self.stats["puts"] += 1
        if self.directory is None:
            return
        try:
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "w", encoding="utf-8") as fh:
                    json.dump(rec, fh, indent=1)
                os.replace(tmp, self._path(key))
            except BaseException:
                self._remove(tmp)
                raise
        except Exception:
            # memory tier still works and the next process re-sweeps,
            # but count the failed store so a read-only tune dir shows
            # up in stats instead of silently re-tuning every process
            self.stats["store_errors"] += 1

    @staticmethod
    def _remove(path):
        try:
            os.remove(path)
        except OSError:
            pass

    def clear_memory(self):
        self._mem.clear()


_STORE = None


def enable(directory=None):
    """Turn on the process-wide tuning store (directory=None falls back
    to $DL4J_TPU_AUTOTUNE_CACHE, memory-only if unset). Idempotent for
    an unchanged directory. Returns the TuningStore."""
    global _STORE
    directory = directory or os.environ.get(TUNE_DIR_ENV) or None
    norm = os.path.expanduser(str(directory)) if directory else None
    if _STORE is not None and _STORE.directory == norm:
        return _STORE
    _STORE = TuningStore(directory)
    return _STORE


def disable():
    global _STORE
    _STORE = None


def store():
    """The active store, auto-enabling from the env var on first use
    (mirrors aot.session_cache); creates a memory-only store when the
    env var is unset so autotune() always has somewhere to persist."""
    if _STORE is None:
        enable()
    return _STORE


# ----------------------------------------------------------------------
# the sweep
# ----------------------------------------------------------------------

class AutotuneResult:
    """What autotune() found (or recalled): ``knobs`` is the winning
    {name: value} config, ``swept`` says whether this call paid the
    sweep or reused a persisted record, ``per_knob`` the candidate-by-
    candidate audit trail (bytes, wall, parity, verdict)."""

    def __init__(self, key, knobs, swept, baseline_bytes=None,
                 tuned_bytes=None, per_knob=None, wall=None):
        self.key = key
        self.knobs = dict(knobs)
        self.swept = swept
        self.baseline_bytes = baseline_bytes
        self.tuned_bytes = tuned_bytes
        self.per_knob = list(per_knob or [])
        self.wall = wall  # {"baseline_s": ..., "tuned_s": ...} | None

    @property
    def changed(self):
        """Knobs the sweep moved off their pre-sweep values."""
        return {k: v for k, v in self.knobs.items()
                if self.per_knob and v != next(
                    (p["from"] for p in self.per_knob
                     if p["knob"] == k), v)}

    def to_record(self):
        return {
            "knobs": self.knobs,
            "baseline_bytes": self.baseline_bytes,
            "tuned_bytes": self.tuned_bytes,
            "per_knob": self.per_knob,
            "wall": self.wall,
        }

    @classmethod
    def from_record(cls, key, rec):
        return cls(key, rec["knobs"], swept=False,
                   baseline_bytes=rec.get("baseline_bytes"),
                   tuned_bytes=rec.get("tuned_bytes"),
                   per_knob=rec.get("per_knob"),
                   wall=rec.get("wall"))

    def format(self):
        lines = [f"key {self.key[:16]}  "
                 f"({'swept' if self.swept else 'recalled'})"]
        for p in self.per_knob:
            lines.append(
                f"  {p['knob']:<20} {p['from']:>9} -> {p['to']:<9} "
                f"{p['verdict']:<10}"
                + (f" {p['bytes']:>12,} B" if p.get("bytes") else "")
                + (f" {p['wall_s'] * 1e3:8.2f} ms"
                   if p.get("wall_s") else ""))
        if self.baseline_bytes and self.tuned_bytes is not None:
            cut = 1.0 - self.tuned_bytes / self.baseline_bytes
            lines.append(
                f"  bytes/step {self.baseline_bytes:,} -> "
                f"{self.tuned_bytes:,}  ({cut:+.1%} cut)")
        return "\n".join(lines)


def _lower_subject(net, x_shape):
    from deeplearning4j_tpu.analysis.hbm import lower_train_step

    return lower_train_step(net, x_shape)


def _compile_subject(net, x_shape, lowered):
    """Compile through the AOT cache: the candidate's knob values are
    in the ambient fingerprint, so every candidate gets its own slot
    and an autotune re-run in a warm process pays zero compiles."""
    from deeplearning4j_tpu.analysis.hbm import compile_train_step

    return compile_train_step(net, x_shape, lowered=lowered)


def _ledger_bytes(compiled):
    from deeplearning4j_tpu.util import hbm_ledger

    return int(hbm_ledger.ledger_for_compiled(compiled)["total_bytes"])


def _device_live():
    import jax

    return jax.default_backend() not in ("cpu",)


def _step_args(net, x_shape, seed=0):
    """Concrete parity-run arguments matching lower_train_step's
    abstract signature (random data — all-ones would give the BN
    pathological zero-variance batch)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    B = x_shape[0]
    x = jnp.asarray(rng.rand(*x_shape).astype("float32"))
    y = jnp.asarray(np.eye(10, dtype="float32")[
        rng.randint(0, 10, B)])
    key = jax.random.key(0)
    it0 = jnp.asarray(0, jnp.int32)
    if hasattr(net, "layers"):
        return (net._params, net._upd_states, net._states, it0, x, y,
                key, None, None)
    inputs = {net.conf.networkInputs[0]: x}
    return (net._params, net._upd_states, net._states, it0, inputs,
            [y], key, None, None)


def _run_steps(compiled, args, steps):
    """Execute the compiled step `steps` times, chaining the carry;
    returns the loss sequence (host floats) and median wall seconds."""
    import jax

    params, upd, states, it0, x, y, key, fm, lm = args
    losses = []
    walls = []
    for i in range(steps):
        t0 = time.perf_counter()
        params, upd, states, loss = compiled(
            params, upd, states, it0 + i, x, y, key, fm, lm)
        jax.block_until_ready(loss)
        walls.append(time.perf_counter() - t0)
        losses.append(float(np.asarray(loss, dtype=np.float64)))
    return losses, float(np.median(walls))


def _parity_ok(base_losses, cand_losses, rtol):
    if any(not np.isfinite(v) for v in cand_losses):
        return False
    a = np.asarray(base_losses)
    b = np.asarray(cand_losses)
    if rtol <= 0.0:
        return bool(np.array_equal(a, b))
    return bool(np.allclose(a, b, rtol=rtol, atol=rtol * 1e-2))


def autotune(net, x_shape, *, knobs=None, store_=None, steps=3,
             force=False, min_gain=0.005, seed=0):
    """Tune the registered knobs for `net`'s canonical train step.

    Warm path: an un-forced call whose key is already in the store
    returns the persisted winners WITHOUT sweeping or compiling (the
    second-process contract — gate it with aot.CompileWatch). Cold
    path: coordinate descent as described in the module docstring.
    The process's knob state is left exactly as found — call
    ``install(result.knobs)`` (or ``warm_start``) to adopt.

    knobs: optional subset of knob names to sweep (default: all).
    """
    st = store_ if store_ is not None else store()
    key = tuning_key(net)
    if not force:
        rec = st.get(key)
        if rec is not None:
            return AutotuneResult.from_record(key, rec)

    names = list(knobs) if knobs else [k.name for k in KNOBS]
    for n in names:
        if n not in _KNOBS_BY_NAME:
            raise ValueError(
                f"unknown knob {n!r}; registry: "
                f"{sorted(_KNOBS_BY_NAME)}")

    best = current_knobs()
    per_knob = []
    # baseline: the current configuration (candidate contexts below
    # restore the process state after every lower/compile/run, so the
    # sweep leaves the knobs exactly as it found them)
    low = _lower_subject(net, x_shape)
    best_hlo = hashlib.sha256(low.as_text().encode()).hexdigest()
    compiled = _compile_subject(net, x_shape, low)
    baseline_bytes = best_bytes = _ledger_bytes(compiled)
    args = _step_args(net, x_shape, seed=seed)
    base_losses, base_wall = _run_steps(compiled, args, steps)
    best_wall = base_wall
    live = _device_live()

    for name in names:
        knob = _KNOBS_BY_NAME[name]
        for cand in knob.candidates:
            if cand == best[name]:
                continue
            entry = {"knob": name, "from": best[name], "to": cand}
            with applied({**best, name: cand}):
                low_c = _lower_subject(net, x_shape)
                hlo_c = hashlib.sha256(
                    low_c.as_text().encode()).hexdigest()
                if hlo_c == best_hlo:
                    entry["verdict"] = "identical"
                    per_knob.append(entry)
                    continue
                comp_c = _compile_subject(net, x_shape, low_c)
                bytes_c = _ledger_bytes(comp_c)
                losses_c, wall_c = _run_steps(comp_c, args, steps)
            entry["bytes"] = bytes_c
            entry["wall_s"] = wall_c
            if not _parity_ok(base_losses, losses_c,
                              knob.parity_rtol):
                entry["verdict"] = "parity-fail"
                per_knob.append(entry)
                continue
            if live:
                wins = wall_c < best_wall * (1.0 - min_gain) or (
                    wall_c <= best_wall
                    and bytes_c < best_bytes * (1.0 - min_gain))
            else:
                wins = bytes_c < best_bytes * (1.0 - min_gain)
            if wins:
                entry["verdict"] = "adopted"
                best = {**best, name: cand}
                best_bytes, best_wall, best_hlo = \
                    bytes_c, wall_c, hlo_c
                # parity is measured against the INCUMBENT: once a
                # math-changing knob is adopted, later bitwise knobs
                # must match the adopted trajectory, not the original
                # baseline (a stale baseline would spuriously
                # parity-fail every exact-impl candidate after a
                # tail-mode adoption)
                base_losses = losses_c
            else:
                entry["verdict"] = "no-gain"
            per_knob.append(entry)

    # wall is RECORDED on every backend (bench A/Bs it); it only enters
    # the SCORE when a real accelerator is live
    result = AutotuneResult(
        key, best, swept=True, baseline_bytes=baseline_bytes,
        tuned_bytes=best_bytes, per_knob=per_knob,
        wall={"baseline_s": base_wall, "tuned_s": best_wall,
              "scored_by": "wall+bytes" if live else "bytes"})
    st.put(key, result.to_record())
    return result


def autotune_subject(subject, batch_size=None, **kw):
    """autotune() over one of the analysis CLI's attribution subjects
    (analysis.hbm.SUBJECTS: canonical batch sizes lenet=64,
    resnet_block=32 — the bytes the tier-1 ceilings gate)."""
    from deeplearning4j_tpu.analysis.hbm import build_subject

    if batch_size is None:
        batch_size = {"lenet": 64, "resnet_block": 32}.get(subject, 32)
    net, x_shape, _slots = build_subject(subject, batch_size=batch_size)
    return autotune(net, x_shape, **kw)


def warm_start(net, store_=None):
    """Look up the persisted winners for (ambient, net) and INSTALL
    them; returns the installed {name: value} or None when no record
    exists. The precompile()/serving warm-start hook: zero sweeps,
    zero compiles, just the tuned point.

    Knobs are PROCESS-GLOBAL (they are module globals read at trace
    time), so in a process hosting several networks the last
    warm-started network's winners govern every later lowering —
    last-writer-wins, and a network whose record disagrees silently
    loses its tuned point. Multi-model processes should either share
    one tuned config (tune the flagship, install once) or scope
    processes per model; see docs/AUTOTUNE.md."""
    st = store_ if store_ is not None else store()
    rec = st.get(tuning_key(net))
    if rec is None:
        return None
    install(rec["knobs"])
    return dict(rec["knobs"])

"""Shared build-on-first-use loader for the native runtime pieces.

One copy of the compile/cache/load logic serving runtime/ringbuffer.py
and runtime/textparse.py: mtime-staleness rebuild, atomic rename so two
processes building concurrently never load a half-written .so, and a
record-the-error singleton so a missing compiler is probed exactly once.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading


class NativeLoader:
    def __init__(self, src, so, configure, extra_flags=()):
        """`configure(lib)` sets restype/argtypes after a successful load."""
        self._src = src
        self._so = so
        self._configure = configure
        self._flags = list(extra_flags)
        self._lib = None
        self._err = None
        self._lock = threading.Lock()

    def _build(self):
        os.makedirs(os.path.dirname(self._so), exist_ok=True)
        tmp = f"{self._so}.tmp.{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               *self._flags, self._src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, self._so)  # atomic: concurrent builders race safely

    def err(self):
        """Why lib() returned None (the load/build exception), or None."""
        with self._lock:
            return self._err

    def lib(self):
        """The loaded library, or None if unavailable (no compiler)."""
        with self._lock:
            if self._lib is not None or self._err is not None:
                return self._lib
            try:
                if not os.path.exists(self._so) or (
                        os.path.getmtime(self._so)
                        < os.path.getmtime(self._src)):
                    self._build()
                lib = ctypes.CDLL(self._so)
                self._configure(lib)
                self._lib = lib
            except Exception as e:  # pragma: no cover - no-compiler envs
                self._err = e
            return self._lib

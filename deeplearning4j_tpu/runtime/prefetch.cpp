// Native prefetch ring buffer.
//
// Reference role: the JVM side of deeplearning4j's AsyncDataSetIterator —
// org.nd4j.linalg.dataset.AsyncDataSetIterator and its
// workspace-backed bounded queue — which keeps the accelerator from ever
// waiting on host-side ETL. Here the bounded handoff is native: fixed
// preallocated byte slots, mutex+condvar backpressure, memcpy in/out while
// the Python caller has dropped the GIL (ctypes releases it for the call),
// so producer (ETL thread) and consumer (device-feed loop) overlap fully.
//
// Protocol: slots carry opaque byte payloads (the Python side packs
// DataSet arrays). push blocks while full, pop blocks while empty;
// close() wakes everyone, after which pops drain the remaining items and
// then return PF_CLOSED. reopen() resets an emptied ring for the next
// epoch without reallocating slots.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Ring {
    std::vector<std::vector<uint8_t>> slots;
    std::vector<size_t> sizes;
    size_t cap;
    size_t head = 0;   // next pop index
    size_t tail = 0;   // next push index
    size_t count = 0;
    bool closed = false;
    std::mutex mu;
    std::condition_variable not_full;
    std::condition_variable not_empty;

    Ring(size_t capacity, size_t slot_bytes)
        : slots(capacity), sizes(capacity, 0), cap(capacity) {
        for (auto& s : slots) s.resize(slot_bytes);
    }
};

constexpr long PF_OK = 0;
constexpr long PF_TIMEOUT = -1;
constexpr long PF_CLOSED = -2;
constexpr long PF_TOO_BIG = -3;

}  // namespace

extern "C" {

void* pf_create(size_t capacity, size_t slot_bytes) {
    if (capacity == 0 || slot_bytes == 0) return nullptr;
    return new Ring(capacity, slot_bytes);
}

void pf_destroy(void* h) { delete static_cast<Ring*>(h); }

size_t pf_capacity(void* h) { return static_cast<Ring*>(h)->cap; }

size_t pf_slot_bytes(void* h) { return static_cast<Ring*>(h)->slots[0].size(); }

size_t pf_count(void* h) {
    Ring* r = static_cast<Ring*>(h);
    std::lock_guard<std::mutex> lk(r->mu);
    return r->count;
}

// Blocking push. timeout_ms < 0 means wait forever.
long pf_push(void* h, const uint8_t* data, size_t n, long timeout_ms) {
    Ring* r = static_cast<Ring*>(h);
    if (n > r->slots[0].size()) return PF_TOO_BIG;
    std::unique_lock<std::mutex> lk(r->mu);
    auto ready = [&] { return r->count < r->cap || r->closed; };
    if (timeout_ms < 0) {
        r->not_full.wait(lk, ready);
    } else if (!r->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready)) {
        return PF_TIMEOUT;
    }
    if (r->closed) return PF_CLOSED;
    std::memcpy(r->slots[r->tail].data(), data, n);
    r->sizes[r->tail] = n;
    r->tail = (r->tail + 1) % r->cap;
    ++r->count;
    r->not_empty.notify_one();
    return PF_OK;
}

// Blocking pop; returns payload size (>= 0) or a PF_* error.
long pf_pop(void* h, uint8_t* out, size_t out_cap, long timeout_ms) {
    Ring* r = static_cast<Ring*>(h);
    std::unique_lock<std::mutex> lk(r->mu);
    auto ready = [&] { return r->count > 0 || r->closed; };
    if (timeout_ms < 0) {
        r->not_empty.wait(lk, ready);
    } else if (!r->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready)) {
        return PF_TIMEOUT;
    }
    if (r->count == 0) return PF_CLOSED;  // closed and drained
    size_t n = r->sizes[r->head];
    if (n > out_cap) return PF_TOO_BIG;
    std::memcpy(out, r->slots[r->head].data(), n);
    r->head = (r->head + 1) % r->cap;
    --r->count;
    r->not_full.notify_one();
    return static_cast<long>(n);
}

void pf_close(void* h) {
    Ring* r = static_cast<Ring*>(h);
    {
        std::lock_guard<std::mutex> lk(r->mu);
        r->closed = true;
    }
    r->not_full.notify_all();
    r->not_empty.notify_all();
}

void pf_reopen(void* h) {
    Ring* r = static_cast<Ring*>(h);
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = false;
    r->head = r->tail = r->count = 0;
}

}  // extern "C"

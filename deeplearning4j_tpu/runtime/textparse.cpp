// Native bulk CSV -> float32 parser for the data-loading path.
//
// Reference: datavec-api's CSVRecordReader parses record-at-a-time on
// the JVM (opencsv + Jackson); the hot path for numeric training CSVs
// is a single buffer sweep. This parser does one pass over the raw
// bytes into a row-major float32 matrix; anything it cannot prove is a
// clean numeric rectangle (ragged rows, non-numeric or empty fields)
// is rejected with a negative code and the caller falls back to the
// Python record loop, so semantics never silently change.
//
// Build: g++ -O2 -shared -fPIC (see runtime/textparse.py, same
// build-on-first-use scheme as runtime/ringbuffer.py).

#include <cstddef>
#include <cstdlib>
#include <cstring>

extern "C" {

// Parse delimited numeric text into float32 row-major.
//  - rows split on '\n'; trailing '\r'/spaces stripped; blank rows skipped
//  - the first `skip_rows` non-blank rows are dropped (headers)
//  - each field must parse COMPLETELY as a float (strtof), spaces trimmed
// Returns the row count and writes the column count to *ncols_out.
// Errors: -1 ragged row, -2 non-numeric/empty/oversized field,
//         -3 output capacity exceeded.
long tp_parse_f32(const char* buf, size_t len, char delim, long skip_rows,
                  float* out, long cap, long* ncols_out) {
    long rows = 0, ncols = -1, written = 0, skipped = 0;
    size_t i = 0;
    while (i < len) {
        size_t eol = i;
        while (eol < len && buf[eol] != '\n') eol++;
        size_t end = eol;
        while (end > i && (buf[end - 1] == '\r' || buf[end - 1] == ' ' ||
                           buf[end - 1] == '\t'))
            end--;
        size_t start = i;
        while (start < end && (buf[start] == ' ' || buf[start] == '\t'))
            start++;
        i = eol + 1;
        if (start == end) continue;  // blank line
        if (skipped < skip_rows) {
            skipped++;
            continue;
        }
        long c = 0;
        size_t p = start;
        while (true) {
            size_t q = p;
            while (q < end && buf[q] != delim) q++;
            size_t fp = p, flen = q - p;
            while (flen > 0 && (buf[fp] == ' ' || buf[fp] == '\t')) {
                fp++;
                flen--;
            }
            while (flen > 0 && (buf[fp + flen - 1] == ' ' ||
                                buf[fp + flen - 1] == '\t'))
                flen--;
            char tmp[64];
            if (flen == 0 || flen >= sizeof(tmp)) return -2;
            // strtof accepts a WIDER grammar than the Python path
            // (hex floats "0x1A", inf/nan, locale decimal commas) —
            // restrict to the plain decimal-float character set so the
            // fast path never parses what the record loop would reject
            for (size_t t = 0; t < flen; t++) {
                char ch = buf[fp + t];
                if (!((ch >= '0' && ch <= '9') || ch == '+' || ch == '-' ||
                      ch == '.' || ch == 'e' || ch == 'E'))
                    return -2;
            }
            memcpy(tmp, buf + fp, flen);
            tmp[flen] = 0;
            char* endp = nullptr;
            float v = strtof(tmp, &endp);
            if (endp != tmp + flen) return -2;
            if (written >= cap) return -3;
            out[written++] = v;
            c++;
            if (q >= end) break;
            p = q + 1;
        }
        if (ncols < 0) {
            ncols = c;
        } else if (c != ncols) {
            return -1;
        }
        rows++;
    }
    if (ncols_out) *ncols_out = ncols < 0 ? 0 : ncols;
    return rows;
}

}  // extern "C"

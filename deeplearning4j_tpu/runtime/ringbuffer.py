"""ctypes binding for the native prefetch ring buffer, with build-on-first-use
and a pure-Python fallback.

Reference: the bounded blocking queue inside
org.nd4j.linalg.dataset.AsyncDataSetIterator. The native ring
(runtime/prefetch.cpp) memcpys payloads outside the GIL so the ETL thread
and the device-feed loop overlap; the Python fallback keeps the same
interface when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading

from deeplearning4j_tpu.runtime._native import NativeLoader

_HERE = os.path.dirname(os.path.abspath(__file__))

PF_OK, PF_TIMEOUT, PF_CLOSED, PF_TOO_BIG = 0, -1, -2, -3


def _configure(lib):
    lib.pf_create.restype = ctypes.c_void_p
    lib.pf_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
    lib.pf_destroy.argtypes = [ctypes.c_void_p]
    lib.pf_capacity.restype = ctypes.c_size_t
    lib.pf_capacity.argtypes = [ctypes.c_void_p]
    lib.pf_slot_bytes.restype = ctypes.c_size_t
    lib.pf_slot_bytes.argtypes = [ctypes.c_void_p]
    lib.pf_count.restype = ctypes.c_size_t
    lib.pf_count.argtypes = [ctypes.c_void_p]
    lib.pf_push.restype = ctypes.c_long
    lib.pf_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_size_t, ctypes.c_long]
    lib.pf_pop.restype = ctypes.c_long
    lib.pf_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                           ctypes.c_size_t, ctypes.c_long]
    lib.pf_close.argtypes = [ctypes.c_void_p]
    lib.pf_reopen.argtypes = [ctypes.c_void_p]


_loader = NativeLoader(os.path.join(_HERE, "prefetch.cpp"),
                       os.path.join(_HERE, "build", "libprefetch.so"),
                       _configure, extra_flags=("-pthread",))


def native_lib():
    """Load (building if needed) the native library; None if unavailable."""
    return _loader.lib()


class NativeRingBuffer:
    """Bounded blocking byte-payload ring over the C++ implementation."""

    def __init__(self, capacity: int, slot_bytes: int):
        lib = native_lib()
        if lib is None:
            raise RuntimeError(
                f"native prefetch unavailable: {_loader.err()!r}")
        self._lib = lib
        self._h = lib.pf_create(capacity, slot_bytes)
        if not self._h:
            raise ValueError("bad ring parameters")
        self.slot_bytes = slot_bytes
        self._out = ctypes.create_string_buffer(slot_bytes)

    def push(self, payload: bytes, timeout_ms: int = -1) -> int:
        return self._lib.pf_push(self._h, payload, len(payload), timeout_ms)

    def pop(self, timeout_ms: int = -1):
        """bytes | PF_TIMEOUT | PF_CLOSED."""
        n = self._lib.pf_pop(self._h, self._out, self.slot_bytes, timeout_ms)
        if n < 0:
            return int(n)
        return self._out.raw[:n]

    def count(self) -> int:
        return int(self._lib.pf_count(self._h))

    def close(self):
        self._lib.pf_close(self._h)

    def reopen(self):
        self._lib.pf_reopen(self._h)

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pf_destroy(self._h)
                self._h = None
        except Exception:
            pass


class PythonRingBuffer:
    """queue.Queue fallback with the same interface/semantics."""

    def __init__(self, capacity: int, slot_bytes: int):
        self.slot_bytes = slot_bytes
        self._cap = capacity
        self._q = queue.Queue(maxsize=capacity)
        self._closed = threading.Event()

    def push(self, payload: bytes, timeout_ms: int = -1) -> int:
        if len(payload) > self.slot_bytes:
            return PF_TOO_BIG
        deadline = None if timeout_ms < 0 else timeout_ms / 1000.0
        while not self._closed.is_set():
            try:
                self._q.put(payload, timeout=0.05 if deadline is None else deadline)
                return PF_OK
            except queue.Full:
                if deadline is not None:
                    return PF_TIMEOUT
        return PF_CLOSED

    def pop(self, timeout_ms: int = -1):
        deadline = None if timeout_ms < 0 else timeout_ms / 1000.0
        while True:
            try:
                return self._q.get(timeout=0.05 if deadline is None else deadline)
            except queue.Empty:
                if self._closed.is_set():
                    return PF_CLOSED
                if deadline is not None:
                    return PF_TIMEOUT

    def count(self) -> int:
        return self._q.qsize()

    def close(self):
        self._closed.set()

    def reopen(self):
        self._closed.clear()
        self._q = queue.Queue(maxsize=self._cap)


def make_ring(capacity: int, slot_bytes: int, force_python: bool = False):
    if not force_python and native_lib() is not None:
        return NativeRingBuffer(capacity, slot_bytes)
    return PythonRingBuffer(capacity, slot_bytes)

"""Unified telemetry: metrics registry + span tracing, off the hot path.

The stack's observability was fragmented — a singleton section timer
(util.profiler.OpProfiler), ad-hoc ``stats`` dicts on the micro-batcher,
loadgen-only percentiles, print-style listeners. A system serving real
traffic needs first-class monitoring the way TensorFlow ships it as part
of the system design (arXiv:1605.08695); under whole-program compilation
(arXiv:1810.09868) the right unit of observation is the DISPATCHED
EXECUTABLE, not the op — which is exactly what lets every instrument in
this module live at dispatch boundaries, on host-side code that already
runs between device dispatches, with zero added device syncs and zero
added compiles (CI-gated: RetraceSentinel + the ≤3% overhead gate in
tests/test_telemetry.py).

Three cooperating pieces:

* ``MetricsRegistry`` — process-wide, thread-safe counters / gauges /
  fixed-bucket histograms (with exact percentile readout over a bounded
  sample reservoir), optional Prometheus-style labels, an injectable
  clock (pair with ``serving.queue.ManualClock`` so tier-1 latency tests
  run with zero sleeps), a JSON ``snapshot()`` and Prometheus
  text-exposition ``prometheus()`` (served on ``GET /metrics`` by
  ``serving.server.InferenceServer``).
* span tracing — ``span()``/``add_span()``/``event()`` record structured
  spans (train step wall, fitDataSet staging vs data-wait, AOT
  compile/deserialize, serving coalesce→dispatch→reply) into a bounded
  ring buffer, exportable as JSONL (``export_jsonl``) and Chrome
  trace-event JSON (``export_chrome_trace``) viewable in Perfetto
  (ui.perfetto.dev → open trace file). docs/OBSERVABILITY.md has the
  span taxonomy and a how-to.
* a process-wide kill switch — ``set_enabled(False)`` (or env
  ``DL4J_TPU_TELEMETRY=off``) turns every instrument write and span
  record into a cheap no-op; the overhead CI gate measures the
  instrumented step against exactly this mode.

This module imports NO jax and performs NO device operations — the
purity linter's PUR02 (host sync inside traced code) is clean over it by
construction, and it is safe to call from trace-time code (e.g. the
RetraceSentinel's compile counter).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "TraceBuffer",
    "get_registry", "set_enabled", "enabled", "percentile",
    "DEFAULT_BUCKETS",
]

# process-wide kill switch (the overhead A/B: instrumented vs disabled)
_ENABLED = os.environ.get("DL4J_TPU_TELEMETRY", "on").lower() \
    not in ("off", "0", "false", "no")


def set_enabled(on: bool) -> bool:
    """Flip the process-wide telemetry switch. Disabled = every
    instrument write and span record is a cheap no-op (reads — snapshot,
    prometheus, export — keep working on whatever was recorded)."""
    global _ENABLED
    _ENABLED = bool(on)
    return _ENABLED


def enabled() -> bool:
    return _ENABLED


# ----------------------------------------------------------------------
# shared percentile math (the ONE implementation: histogram readout and
# serving.loadgen both use it; tested against the numpy oracle)
# ----------------------------------------------------------------------
def percentile(values, q):
    """Linear-interpolated percentile (q in [0, 100]) of a sequence —
    the same 'linear' method numpy defaults to, without requiring the
    input pre-sorted. Returns None for an empty sequence."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    q = float(q)
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    rank = (len(vals) - 1) * q / 100.0
    lo = math.floor(rank)
    hi = math.ceil(rank)
    if lo == hi:
        return vals[int(rank)]
    frac = rank - lo
    return vals[lo] * (1.0 - frac) + vals[hi] * frac


# ----------------------------------------------------------------------
# instruments
# ----------------------------------------------------------------------

#: default latency buckets (seconds) — µs dispatches through multi-second
#: compiles all land in a named bucket
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                   30.0, 60.0)

#: raw samples a histogram retains for exact percentile readout; past
#: this the reservoir is a sliding window of the most recent samples
DEFAULT_SAMPLE_CAP = 8192

_NAME_OK = None  # compiled lazily (module import stays re-importable)


def _check_name(name):
    global _NAME_OK
    if _NAME_OK is None:
        import re

        _NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    if not _NAME_OK.match(name):
        raise ValueError(
            f"invalid metric name {name!r}: Prometheus names match "
            "[a-zA-Z_:][a-zA-Z0-9_:]*")
    return name


def _escape_label(v):
    """Prometheus label-value escaping: backslash, double-quote, LF."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escape_help(v):
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


class _Child:
    """One (instrument, label-values) time series. Counter/gauge state is
    a float; histogram state is bucket counts + sum + a bounded sample
    reservoir. All mutation goes through the parent instrument's lock."""

    __slots__ = ("_parent", "labels", "value", "bucket_counts", "sum",
                 "count", "samples")

    def __init__(self, parent, labels):
        self._parent = parent
        self.labels = labels          # dict, insertion == labelnames order
        self.value = 0.0
        if parent.kind == "histogram":
            self.bucket_counts = [0] * (len(parent.buckets) + 1)
            self.sum = 0.0
            self.count = 0
            self.samples = []         # bounded ring, newest last

    # -- counter / gauge -------------------------------------------------
    def inc(self, n=1.0):
        if not _ENABLED:
            return self
        if self._parent.kind == "counter" and n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._parent._lock:
            self.value += n
        return self

    def dec(self, n=1.0):
        if self._parent.kind != "gauge":
            raise TypeError(f"dec() on a {self._parent.kind}")
        return self.inc(-n)

    def set(self, v):
        if self._parent.kind != "gauge":
            raise TypeError(f"set() on a {self._parent.kind}")
        if not _ENABLED:
            return self
        with self._parent._lock:
            self.value = float(v)
        return self

    # -- histogram ---------------------------------------------------------
    def observe(self, v):
        if self._parent.kind != "histogram":
            raise TypeError(f"observe() on a {self._parent.kind}")
        if not _ENABLED:
            return self
        v = float(v)
        p = self._parent
        with p._lock:
            i = 0
            for i, edge in enumerate(p.buckets):  # noqa: B007
                if v <= edge:
                    break
            else:
                i = len(p.buckets)
            self.bucket_counts[i] += 1
            self.sum += v
            self.count += 1
            self.samples.append(v)
            if len(self.samples) > p.sample_cap:
                del self.samples[:len(self.samples) - p.sample_cap]
        return self

    def percentile(self, q):
        """Exact linear-interpolated percentile over the retained
        samples (exact for the whole series while count <= sample_cap;
        past that, over the most recent sample_cap observations)."""
        with self._parent._lock:
            vals = list(self.samples)
        return percentile(vals, q)

    def mean(self):
        """Mean over ALL observations (sum/count, not the bounded
        reservoir); None before the first observe. The fleet brownout
        controller's measured per-item service estimate
        (serving/fleet.py)."""
        with self._parent._lock:
            return self.sum / self.count if self.count else None

    def reset(self):
        """Zero this series in place (handles cached by callers stay
        attached — MicroBatcher/OpProfiler read-through views rely on
        it)."""
        with self._parent._lock:
            self.value = 0.0
            if self._parent.kind == "histogram":
                self.bucket_counts = [0] * (len(self._parent.buckets) + 1)
                self.sum = 0.0
                self.count = 0
                self.samples = []
        return self


class _Instrument:
    """Base: a named family of label-distinguished children. The
    unlabeled instrument IS its own () child, so `counter.inc()` and
    `counter.labels(x=1).inc()` are the same machinery."""

    kind = None

    def __init__(self, name, help="", labelnames=()):
        self.name = _check_name(name)
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        self._lock = threading.RLock()
        self._children = {}
        if not self.labelnames:
            self._default = self._make_child_locked({})
        else:
            self._default = None

    def _make_child_locked(self, labels):
        # *_locked: caller holds self._lock (construction-time calls
        # trivially satisfy it — the instance is unpublished)
        child = _Child(self, labels)
        self._children[tuple(labels.values())] = child
        return child

    def _label_key(self, kv):
        if tuple(sorted(kv)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name} declares labels {self.labelnames}, "
                f"got {tuple(sorted(kv))}")
        return tuple(str(kv[ln]) for ln in self.labelnames)

    def labels(self, **kv):
        """The child time series for exactly this label set (created on
        first use). Label names must match the declared labelnames."""
        key = self._label_key(kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child_locked(
                    {ln: str(kv[ln]) for ln in self.labelnames})
        return child

    def labels_get(self, **kv):
        """The child for this label set, or None — a READ that never
        creates a series (facade read paths use it so probing an
        unknown label can't grow the registry)."""
        with self._lock:
            return self._children.get(self._label_key(kv))

    def remove(self, **kv):
        """Drop this label set's series from the family (no-op when it
        does not exist). A handle already cached by a caller keeps
        working but is detached — the series no longer appears in
        exposition/snapshot. Lifecycle owners (MicroBatcher.close) use
        it so per-instance series don't accumulate forever."""
        with self._lock:
            self._children.pop(self._label_key(kv), None)
        return self

    def _only(self):
        if self._default is None:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}: address a "
                "series via .labels(...)")
        return self._default

    def children(self):
        with self._lock:
            return list(self._children.values())

    def reset(self):
        for c in self.children():
            c.reset()
        return self


class Counter(_Instrument):
    kind = "counter"

    def inc(self, n=1.0):
        return self._only().inc(n)

    @property
    def value(self):
        return self._only().value


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, v):
        return self._only().set(v)

    def inc(self, n=1.0):
        return self._only().inc(n)

    def dec(self, n=1.0):
        return self._only().dec(n)

    @property
    def value(self):
        return self._only().value


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None,
                 sample_cap=DEFAULT_SAMPLE_CAP):
        buckets = DEFAULT_BUCKETS if buckets is None else tuple(
            sorted(float(b) for b in buckets))
        if not buckets:
            raise ValueError("histogram needs at least one bucket edge")
        self.buckets = buckets
        self.sample_cap = int(sample_cap)
        super().__init__(name, help, labelnames)

    def observe(self, v):
        return self._only().observe(v)

    def percentile(self, q):
        return self._only().percentile(q)

    @property
    def count(self):
        return self._only().count

    @property
    def sum(self):
        return self._only().sum


# ----------------------------------------------------------------------
# span tracing
# ----------------------------------------------------------------------
class TraceBuffer:
    """Bounded ring of structured spans. A span is one dict:
    {name, cat, ts (seconds on the registry clock), dur (seconds),
    ph ('X' complete span / 'i' instant), pid, tid, args} — directly
    mappable to the Chrome trace-event format Perfetto loads."""

    def __init__(self, capacity=8192):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._spans = []
        self.dropped = 0   # spans evicted by the ring bound

    def add(self, name, cat, ts, dur, args=None, ph="X"):
        if not _ENABLED:
            return
        span = {"name": str(name), "cat": str(cat), "ts": float(ts),
                "dur": float(dur), "ph": ph, "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": dict(args) if args else {}}
        with self._lock:
            self._spans.append(span)
            if len(self._spans) > self.capacity:
                drop = len(self._spans) - self.capacity
                del self._spans[:drop]
                self.dropped += drop

    def spans(self):
        with self._lock:
            return [dict(s) for s in self._spans]

    def clear(self):
        with self._lock:
            self._spans = []
            self.dropped = 0


class MetricsRegistry:
    """Process-wide instrument + trace registry (module docstring).

    clock: monotonic seconds callable (default time.perf_counter);
    inject serving.queue.ManualClock for deterministic tests. The clock
    stamps spans; components with their OWN clock (MicroBatcher) record
    spans with explicit timestamps via add_span.
    """

    def __init__(self, clock=None, trace_capacity=8192):
        self.clock = clock if clock is not None else time.perf_counter
        self._lock = threading.RLock()
        self._instruments = {}
        self.trace = TraceBuffer(trace_capacity)

    # -- instrument factories (get-or-create, type-checked) -------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise TypeError(
                        f"{name} already registered as {inst.kind}, "
                        f"requested {cls.kind}")
                if tuple(labelnames) != inst.labelnames:
                    raise ValueError(
                        f"{name} already registered with labels "
                        f"{inst.labelnames}, requested {tuple(labelnames)}")
                return inst
            inst = cls(name, help, labelnames, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=()):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=(), buckets=None,
                  sample_cap=DEFAULT_SAMPLE_CAP):
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets, sample_cap=sample_cap)

    def get(self, name):
        with self._lock:
            return self._instruments.get(name)

    def instruments(self):
        with self._lock:
            return dict(self._instruments)

    def reset(self):
        """Zero every series and clear the trace ring IN PLACE —
        instrument/child handles cached by callers stay attached."""
        for inst in self.instruments().values():
            inst.reset()
        self.trace.clear()
        return self

    # -- tracing ---------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name, cat="", **args):
        """Record the wrapped block as one complete span on this
        registry's clock. No-op (beyond one clock read) when telemetry
        is disabled."""
        if not _ENABLED:
            yield
            return
        t0 = self.clock()
        try:
            yield
        finally:
            self.trace.add(name, cat, t0, self.clock() - t0, args)

    def add_span(self, name, cat, ts, dur, **args):
        """Record a span with explicit start/duration (seconds) — for
        components that own their clock (MicroBatcher's ManualClock)."""
        self.trace.add(name, cat, ts, dur, args)

    def event(self, name, cat="", **args):
        """Record an instant event (Chrome ph 'i') at now."""
        self.trace.add(name, cat, self.clock(), 0.0, args, ph="i")

    # -- export ----------------------------------------------------------
    def snapshot(self):
        """JSON-safe nested view of every instrument: the
        ``host.metrics_snapshot()`` / bench-record surface."""
        out = {}
        for name, inst in sorted(self.instruments().items()):
            series = []
            for c in inst.children():
                with inst._lock:
                    if inst.kind == "histogram":
                        rec = {"labels": dict(c.labels),
                               "count": c.count,
                               "sum": round(c.sum, 9),
                               "p50": percentile(c.samples, 50),
                               "p99": percentile(c.samples, 99),
                               "buckets": dict(zip(
                                   [str(b) for b in inst.buckets]
                                   + ["+Inf"], c.bucket_counts))}
                    else:
                        rec = {"labels": dict(c.labels), "value": c.value}
                series.append(rec)
            out[name] = {"kind": inst.kind, "help": inst.help,
                         "series": series}
        return out

    def prometheus(self):
        """Prometheus text exposition (format version 0.0.4): HELP/TYPE
        lines, label escaping, cumulative histogram buckets with the
        canonical le= edges plus _sum/_count."""
        lines = []
        for name, inst in sorted(self.instruments().items()):
            if inst.help:
                lines.append(f"# HELP {name} {_escape_help(inst.help)}")
            lines.append(f"# TYPE {name} {inst.kind}")
            for c in inst.children():
                base = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in c.labels.items())
                if inst.kind == "histogram":
                    with inst._lock:
                        counts = list(c.bucket_counts)
                        total, csum = c.count, c.sum
                    cum = 0
                    for edge, n in zip(inst.buckets, counts):
                        cum += n
                        lab = (base + "," if base else "") + \
                            f'le="{edge:g}"'
                        lines.append(f"{name}_bucket{{{lab}}} {cum}")
                    lab = (base + "," if base else "") + 'le="+Inf"'
                    lines.append(f"{name}_bucket{{{lab}}} {total}")
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{name}_sum{suffix} {csum:g}")
                    lines.append(f"{name}_count{suffix} {total}")
                else:
                    suffix = f"{{{base}}}" if base else ""
                    lines.append(f"{name}{suffix} {c.value:g}")
        return "\n".join(lines) + "\n"

    def chrome_trace(self):
        """The trace ring as a Chrome trace-event JSON object —
        ui.perfetto.dev opens the dumped file directly. ts/dur are
        microseconds per the trace-event spec."""
        events = []
        for s in self.trace.spans():
            ev = {"name": s["name"], "cat": s["cat"] or "default",
                  "ph": s["ph"], "ts": s["ts"] * 1e6,
                  "pid": s["pid"], "tid": s["tid"], "args": s["args"]}
            if s["ph"] == "X":
                ev["dur"] = s["dur"] * 1e6
            else:
                ev["s"] = "t"   # instant scope: thread
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path):
        """Write chrome_trace() to `path` (atomic tmp+rename); returns
        the path."""
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:  # fault-ok[FLT02]: observability export, off every dispatch path — an export failure raises to the operator who asked for the file; nothing in the serving tier depends on it
            json.dump(self.chrome_trace(), fh)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path):
        """One JSON object per span, oldest first; returns the path."""
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:  # fault-ok[FLT02]: observability export, off every dispatch path — same contract as export_chrome_trace above
            for s in self.trace.spans():
                fh.write(json.dumps(s) + "\n")
        os.replace(tmp, path)
        return path


# ----------------------------------------------------------------------
# the process-wide default registry
# ----------------------------------------------------------------------
_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every built-in instrument lives in.
    Its identity is stable for the process lifetime — cache instrument
    handles freely; registry.reset() zeroes values in place."""
    return _REGISTRY

"""Resilient training runtime: retry, resume, and non-finite guards.

Reference: production TPU-pod training treats host preemption, flaky
data sources and numeric blow-ups as ROUTINE (TensorFlow's distributed
runtime is built around recoverable checkpointed workers — Abadi et
al.; the reference stack's analogues are CheckpointListener,
EarlyStoppingTrainer's exception hooks and FailureTestingListener).
This module is that layer for the jax_graft build, three cooperating
pieces:

* RetryPolicy / retry() — capped exponential backoff with DETERMINISTIC
  seeded jitter, shared by the data path (RetryingDataSetIterator,
  ResilientFit's batch fetch) and checkpoint I/O.
* ResilientFit — wraps MultiLayerNetwork / ParallelWrapper training
  with periodic ATOMIC checkpoints (util.sharded_checkpoint), automatic
  resume-from-latest on restart, and an on-device non-finite step guard:
  a step whose loss or updated parameters contain NaN/Inf is SKIPPED
  (params/updater/state keep their pre-step values — selected inside
  the jitted step, so donation stays safe and no host-side rewind copy
  is ever made) and training aborts with a clear error after K
  consecutive bad steps.
* FaultInjector — a deterministic, seedable fault-injection harness
  (raise-on-Nth-batch IOError, poison-NaN step, kill-after-step
  preemption) that tests and bench.py thread through the data iterators
  and the train step.

The guard's skip decision costs one extra all-finite reduction per
step and rides the loss fetch the training loop already pays — no
additional host sync.
"""

from __future__ import annotations

import os
import random
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util import sharded_checkpoint as _ckpt

_TM = None


def _tm():
    """Lazily-resolved resilience telemetry handles (runtime.telemetry;
    see docs/OBSERVABILITY.md). Event COUNTS (skips, saves, restores)
    are the MetricsListener's job — the direct wiring here carries only
    what the listener chain cannot see: retry fire counts and
    checkpoint I/O durations."""
    global _TM
    if _TM is None:
        from deeplearning4j_tpu.runtime import telemetry

        reg = telemetry.get_registry()
        _TM = {
            "reg": reg,
            "retries": reg.counter(
                "dl4j_retries_total",
                "transient failures retried with backoff (data fetch, "
                "checkpoint I/O)"),
            "ckpt_save_s": reg.histogram(
                "dl4j_checkpoint_save_seconds",
                "atomic checkpoint write wall (ResilientFit._save)"),
            "ckpt_restore_s": reg.histogram(
                "dl4j_checkpoint_restore_seconds",
                "checkpoint restore wall (resume-after-preemption)"),
        }
    return _TM


# ----------------------------------------------------------------------
# retry with capped exponential backoff + deterministic jitter
# ----------------------------------------------------------------------
class RetryPolicy:
    """Capped exponential backoff. attempt k (1-based) sleeps

        base_k = min(maxDelay, initialDelay * multiplier**(k-1))
        delay_k in [base_k * (1 - jitter), base_k]

    with the jitter fraction drawn from random.Random(seed) — the SAME
    seed replays the SAME delay sequence, so backoff behavior is exactly
    testable (no wall-clock flakiness in the fault matrix).
    """

    def __init__(self, maxRetries: int = 3, initialDelay: float = 0.05,
                 maxDelay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, seed: int = 0,
                 retryOn=(IOError, OSError, TimeoutError), sleep=time.sleep):
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.maxRetries = int(maxRetries)
        self.initialDelay = float(initialDelay)
        self.maxDelay = float(maxDelay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.retryOn = tuple(retryOn)
        self.sleep = sleep

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.maxDelay,
                   self.initialDelay * self.multiplier ** (attempt - 1))
        return base * (1.0 - self.jitter * rng.random())

    def delays(self):
        """The full deterministic delay sequence this policy would sleep
        (one fresh rng, as retry() uses) — for tests and capacity math."""
        rng = random.Random(self.seed)
        return [self.delay(k, rng) for k in range(1, self.maxRetries + 1)]


def retry(fn, policy: RetryPolicy = None, on_retry=None):
    """Call fn(); on an exception in policy.retryOn, back off and retry
    up to policy.maxRetries times, then re-raise the last error.
    on_retry(attempt, exc, delay) observes each backoff (listener /
    logging hook)."""
    policy = policy or RetryPolicy()
    rng = random.Random(policy.seed)
    attempt = 0
    while True:
        try:
            return fn()
        except policy.retryOn as e:
            attempt += 1
            if attempt > policy.maxRetries:
                raise
            d = policy.delay(attempt, rng)
            _tm()["retries"].inc()
            if on_retry is not None:
                on_retry(attempt, e, d)
            policy.sleep(d)


# ----------------------------------------------------------------------
# deterministic fault injection
# ----------------------------------------------------------------------
class Preemption(Exception):
    """Simulated host preemption: the 'process' dies here. Emitted by
    FaultInjector.killAfterStep so tests can kill training mid-epoch and
    restart through ResilientFit's resume-from-latest path."""


class FaultInjector:
    """Deterministic, seedable fault schedule threaded through the data
    iterators (wrapIterator) and the train step (ResilientFit hooks).

    Faults are scheduled explicitly — failOnBatch / poisonStep /
    killAfterStep — or drawn reproducibly from the seed
    (randomIOFaults). Every injection is recorded in .events as
    (kind, position) tuples so tests assert on exactly what fired.

    Scope: the TRAINING data path only. The process-wide
    generalization — seeded fault schedules against named seams at
    every SERVING dispatch boundary (and this module's checkpoint
    write/restore) — is ``runtime.chaos.ChaosPlan``
    (docs/RESILIENCE.md "Chaos harness").
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.events = []
        self._io_faults = {}     # global batch index -> [times left, exc]
        self._poison_steps = set()
        self._kill_after = None
        self._killed = False

    # ----- scheduling -------------------------------------------------
    def failOnBatch(self, n: int, times: int = 1, exc=None):
        """Raise `exc` (default IOError) from the wrapped iterator's
        next() for the n-th batch (0-based, counted across epochs),
        `times` consecutive attempts before that fetch succeeds."""
        self._io_faults[int(n)] = [int(times),
                                   exc if exc is not None
                                   else IOError(f"injected data fault at "
                                                f"batch {n}")]
        return self

    def randomIOFaults(self, nBatches: int, rate: float, times: int = 1):
        """Schedule IOErrors on a seed-deterministic subset of the first
        nBatches fetches (~rate of them)."""
        rng = random.Random(self.seed)
        for b in range(int(nBatches)):
            if rng.random() < rate:
                self.failOnBatch(b, times=times)
        return self

    def poisonStep(self, *steps: int):
        """Poison the features feeding the given global iterations with
        NaN — the loss and every gradient of that step go non-finite,
        which is what the step guard must catch and skip."""
        self._poison_steps.update(int(s) for s in steps)
        return self

    def killAfterStep(self, step: int):
        """Raise Preemption once, right after the global iteration
        counter reaches `step` (i.e. after `step` completed steps) —
        after any checkpoint scheduled at that step, like a real
        preemption landing between steps."""
        self._kill_after = int(step)
        return self

    # ----- hooks (called by the training loop / iterator wrapper) -----
    def maybe_poison(self, iteration: int, x):
        if iteration in self._poison_steps:
            self.events.append(("poison", iteration))
            return jnp.full_like(jnp.asarray(x), jnp.nan)
        return x

    def maybe_kill(self, iteration: int):
        if (self._kill_after is not None and not self._killed
                and iteration >= self._kill_after):
            self._killed = True
            self.events.append(("preempt", iteration))
            raise Preemption(f"injected preemption after step {iteration}")

    def wrapIterator(self, iterator):
        """DataSetIterator wrapper raising the scheduled data faults.
        The fault fires BEFORE the underlying fetch, so a retry consumes
        the same batch the failed attempt would have."""
        return _FaultyIterator(iterator, self)

    def _check_fetch(self, global_batch: int):
        fault = self._io_faults.get(global_batch)
        if fault and fault[0] > 0:
            fault[0] -= 1
            self.events.append(("data_fault", global_batch))
            raise fault[1]


class _FaultyIterator:
    """FaultInjector's data-path shim: counts successful fetches across
    epochs (reset() does NOT replay faults) and raises the scheduled
    exception before consuming the underlying batch."""

    def __init__(self, base, injector: FaultInjector):
        self._base = base
        self._injector = injector
        self._fetched = 0

    def reset(self):
        self._base.reset()

    def hasNext(self):
        return self._base.hasNext()

    def next(self, num=None):
        self._injector._check_fetch(self._fetched)
        ds = self._base.next() if num is None else self._base.next(num)
        self._fetched += 1
        return ds

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    def __getattr__(self, name):  # batch()/totalExamples()/preprocessors
        return getattr(self._base, name)


# ----------------------------------------------------------------------
# non-finite step guard
# ----------------------------------------------------------------------
class NonFiniteStepError(FloatingPointError):
    """K consecutive steps produced non-finite loss/params — the run has
    diverged and skipping more steps would only burn accelerator time."""


def non_finite_guard(step_fn):
    """Wrap a `(params, upd, states, it, x, y, key, fm, lm) ->
    (params', upd', states', loss)` train step so that a step whose loss
    or updated parameters contain NaN/Inf returns the UNCHANGED inputs
    instead (plus an `ok` flag). The select happens inside the jitted
    computation, so the wrapped step stays donation-safe and the skip
    costs no host round-trip beyond the loss fetch the loop already
    pays. NaN gradients surface as NaN updated params, so checking loss
    + params covers the whole backward path."""

    def guarded(params, upd_states, states, iteration, x, y, key,
                fmask, lmask):
        new_p, new_u, new_s, loss = step_fn(
            params, upd_states, states, iteration, x, y, key, fmask, lmask)
        ok = jnp.all(jnp.isfinite(loss))
        for leaf in jax.tree_util.tree_leaves(new_p):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                ok = ok & jnp.all(jnp.isfinite(leaf))

        def sel(old, new):
            return jax.tree_util.tree_map(
                lambda o, n: jnp.where(ok, n, o), old, new)

        return (sel(params, new_p), sel(upd_states, new_u),
                sel(states, new_s), loss, ok)

    return guarded


# ----------------------------------------------------------------------
# the resilient training harness
# ----------------------------------------------------------------------
class ResilientFit:
    """Preemption-safe fit() for MultiLayerNetwork / ParallelWrapper.

    * periodic atomic checkpoints every `saveEveryNIterations` steps via
      util.sharded_checkpoint (keep-last-N rotation, resume metadata in
      the manifest so the mid-epoch position commits with the state),
    * automatic resume-from-latest: if `checkpointDir` already holds a
      complete checkpoint, fit() restores it, replays the data iterator
      to the saved batch position and continues — a run killed mid-epoch
      and restarted lands on the BITWISE-identical trajectory (same
      iteration-keyed dropout stream, same updater moments),
    * the non-finite step guard (see non_finite_guard),
    * retry with backoff on the batch fetch and the checkpoint write.

    Usage:
        rf = ResilientFit(net, ckpt_dir, saveEveryNIterations=50)
        rf.fit(iterator, epochs=10)        # crash it; run again: resumes

    Listener events (optimize.listeners.TrainingListener hooks):
    onStepSkipped, onCheckpointSaved, onCheckpointRestored, plus the
    usual iterationDone/onEpochStart/onEpochEnd with fit() parity.
    """

    def __init__(self, net, checkpointDir=None, *,
                 saveEveryNIterations: int = 0, keepLast: int = 2,
                 saveUpdater: bool = True,
                 maxConsecutiveBadSteps: int = 3,
                 retryPolicy: RetryPolicy = None,
                 injector: FaultInjector = None):
        try:
            from deeplearning4j_tpu.parallel.trainer import ParallelWrapper
        except ImportError:  # parallel layer unavailable (jax too old)
            ParallelWrapper = ()
        if ParallelWrapper and isinstance(net, ParallelWrapper):
            self.wrapper, self.net = net, net.net
        else:
            self.wrapper, self.net = None, net
        if getattr(self.net, "_solver", None) is not None:
            raise ValueError(
                "ResilientFit requires optimizationAlgo="
                "STOCHASTIC_GRADIENT_DESCENT: the non-finite guard's "
                "skip semantics are undefined under a line search, whose "
                "internal state already encodes the rejected step")
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        if not isinstance(self.net, MultiLayerNetwork):
            raise TypeError(
                f"ResilientFit wraps MultiLayerNetwork (directly or via "
                f"ParallelWrapper); got {type(self.net).__name__}")
        from deeplearning4j_tpu.nn.conf.builder import BackpropType

        if self.net.conf.backpropType == BackpropType.TruncatedBPTT:
            raise ValueError(
                "ResilientFit does not support truncated BPTT yet: a "
                "mid-sequence skip would desynchronize the carry stream")
        self.checkpointDir = None if checkpointDir is None \
            else os.path.abspath(str(checkpointDir))
        self.saveEvery = int(saveEveryNIterations)
        if self.saveEvery > 0 and self.checkpointDir is None:
            raise ValueError(
                "saveEveryNIterations > 0 needs a checkpointDir")
        self.keepLast = int(keepLast)
        self.saveUpdater = bool(saveUpdater)
        self.maxBad = int(maxConsecutiveBadSteps)
        self.retryPolicy = retryPolicy or RetryPolicy()
        self.injector = injector
        self._jit = None
        self._guarded = None
        self._bad = 0
        self.skippedSteps = 0

    # ----- step construction ------------------------------------------
    def _build_jit(self):
        if self._jit is not None:
            return
        if self.wrapper is not None:
            self.wrapper._place_replicated()
            step = non_finite_guard(self.wrapper.trainStep())
        else:
            step = non_finite_guard(self.net._train_step)
        self._guarded = step
        self._jit = jax.jit(step, donate_argnums=(0, 1, 2))

    def _loop_jit(self, k):
        """Guarded k-block loop for fit(stepsPerSync=k): the non-finite
        guard wraps EVERY step inside the on-device loop (a bad step's
        params/updater/state are rolled back in place, exactly the k=1
        semantics), and the loop returns k-vectors of losses and ok
        flags that the host-side guard accounting consumes at the sync
        boundary. max_bad freezes the carry on device from the step
        where the consecutive-bad count reaches the abort threshold —
        the k=1 path raises before training the next batch, so an
        aborting block's params must not contain later steps either."""
        from deeplearning4j_tpu.nn.multilayer import fit_dataset_jit

        return fit_dataset_jit(self.net, k, step_fn=self._guarded,
                               guarded=True, owner=self,
                               max_bad=self.maxBad)

    # ----- checkpoint / resume ----------------------------------------
    def _fire(self, hook, *args):
        for lst in self.net._listeners:
            getattr(lst, hook, lambda *a: None)(self.net, *args)

    def _save(self, batch_in_epoch: int):
        from deeplearning4j_tpu.util.sharded_checkpoint import \
            ShardedModelSerializer

        net = self.net
        tm = _tm()
        t0 = tm["reg"].clock()
        path = _ckpt.step_path(self.checkpointDir, net._iteration)
        # trainer-owned step state (threshold compression's error-
        # feedback residual + live tau) rides the checkpoint as its own
        # item so a mid-epoch resume replays the exact trajectory; the
        # NET state stays canonical and restores into any mode
        trainer_state = None
        if self.wrapper is not None:
            get = getattr(self.wrapper, "_ckpt_trainer_state", None)
            trainer_state = get() if get is not None else None
        from deeplearning4j_tpu.runtime.chaos import fault_point

        def _write():
            # chaos seam INSIDE the retry lambda: an injected write
            # fault is retried like any transient I/O failure
            # (runtime/chaos.py, seam checkpoint.write)
            fault_point("checkpoint.write")
            return ShardedModelSerializer.writeModel(
                net, path, saveUpdater=self.saveUpdater,
                extra={"iteration": net._iteration, "epoch": net._epoch,
                       "batch_in_epoch": int(batch_in_epoch)},
                trainer_state=trainer_state)

        retry(_write, self.retryPolicy)
        _ckpt.gc_checkpoints(self.checkpointDir, self.keepLast)
        dt = tm["reg"].clock() - t0
        tm["ckpt_save_s"].observe(dt)
        tm["reg"].trace.add("resilience.checkpoint_save", "resilience",
                            t0, dt, {"iteration": net._iteration})
        self._fire("onCheckpointSaved", path, net._iteration)

    def _maybe_resume(self) -> int:
        """Restore the latest complete checkpoint into the wrapped net,
        returning the batch-within-epoch to replay past (0 = fresh or
        epoch-aligned resume). A checkpoint that fails its content
        digest (CheckpointDigestError, util/sharded_checkpoint.py) is
        treated as ABSENT: the walk falls back to the previous
        snapshot instead of restoring silently-corrupt state."""
        if self.checkpointDir is None:
            return 0
        steps = _ckpt.complete_steps(self.checkpointDir)
        if not steps:
            return 0
        from deeplearning4j_tpu.runtime.chaos import fault_point
        from deeplearning4j_tpu.util.sharded_checkpoint import (
            CheckpointDigestError, ShardedModelSerializer,
        )

        tm = _tm()
        t0 = tm["reg"].clock()
        restored = path = None
        for step in reversed(steps):
            path = _ckpt.step_path(self.checkpointDir, step)

            def _restore(p=path):
                # chaos seam INSIDE the retry lambda (runtime/chaos.py,
                # seam checkpoint.restore)
                fault_point("checkpoint.restore")
                return ShardedModelSerializer.restore(p)

            try:
                restored = retry(_restore, self.retryPolicy)
                break
            except CheckpointDigestError:
                tm["reg"].event("resilience.checkpoint_corrupt",
                                "resilience", step=step, path=path)
                continue
        if restored is None:
            return 0    # every snapshot failed its digest: fresh start
        net = self.net
        net._params = restored._params
        net._states = restored._states
        net._upd_states = restored._upd_states
        net._iteration = restored._iteration
        net._epoch = restored._epoch
        manifest = _ckpt.read_manifest(path)
        extra = manifest.get("extra", {})
        if self.wrapper is not None:
            # re-place the restored state onto the mesh: checkpoints
            # hold the CANONICAL full-shape updater-state layout, and
            # under the ZeRO sharded update (weight_update='sharded')
            # the live carry is the 1/dp flat-shard view — re-placement
            # is bitwise (the view is a reshape). Under threshold
            # compression this also re-packs the residual carry (fresh
            # zeros), which the saved trainer state then overwrites.
            self.wrapper._place_replicated()
            if manifest.get("trainerState"):
                tmpl = self.wrapper._ckpt_trainer_state()
                if tmpl is not None:
                    abstract = jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(
                            a.shape, a.dtype, sharding=a.sharding),
                        tmpl)
                    ts = retry(
                        lambda: _ckpt.restore_trainer_state(path,
                                                            abstract),
                        self.retryPolicy)
                    self.wrapper._restore_trainer_state(ts)
        dt = tm["reg"].clock() - t0
        tm["ckpt_restore_s"].observe(dt)
        tm["reg"].trace.add("resilience.checkpoint_restore",
                            "resilience", t0, dt,
                            {"iteration": net._iteration})
        self._fire("onCheckpointRestored", path, net._iteration)
        return int(extra.get("batch_in_epoch", 0))

    # ----- the loop ----------------------------------------------------
    def fit(self, data, epochs: int = 1, stepsPerSync: int = 1):
        """Train until `epochs` epochs are complete, resuming from the
        latest checkpoint when one exists. `data` is a DataSetIterator;
        its order must be replayable (deterministic/seeded) for resumed
        runs to match uninterrupted ones.

        stepsPerSync=k > 1 runs the device-staged k-batch block loop
        (MultiLayerNetwork.fitDataSet mechanics) with the non-finite
        guard inside the loop: one host sync per k fresh batches, the
        guard consuming the block's k-vector of losses/ok flags, and
        checkpoint + injected-preemption points at the k-step sync
        boundaries (a save cadence that lands mid-block commits at the
        block's end). The parameter trajectory — including which steps
        are skipped — is identical to stepsPerSync=1; the ragged final
        block runs through the per-batch guarded step."""
        net = self.net
        net._require_init()
        k = int(stepsPerSync)
        if k < 1:
            raise ValueError(f"stepsPerSync must be >= 1, got {k}")
        replay = self._maybe_resume()
        self._build_jit()
        jloop = self._loop_jit(k) if k > 1 else None
        self._bad = 0
        while net._epoch < int(epochs):
            data.reset()
            skip, replay = replay, 0
            if skip == 0:
                self._fire("onEpochStart")
            b = 0
            buf = []
            while self._has_next(data):
                ds = retry(data.next, self.retryPolicy)
                b += 1
                if b <= skip:
                    continue  # replayed: already folded into the params
                if k == 1:
                    self._step(ds)
                    self._boundary(b, 1)
                else:
                    buf.append(ds)
                    if len(buf) == k:
                        self._block_step(buf, jloop)
                        buf = []
                        self._boundary(b, k)
            for i, ds in enumerate(buf):
                # ragged tail: per-batch guarded step, no k-loop retrace
                self._step(ds)
                self._boundary(b - len(buf) + i + 1, 1)
            self._fire("onEpochEnd")
            net._epoch += 1
        return net

    def _boundary(self, b, steps):
        """Checkpoint/injected-preemption hooks at a sync boundary that
        just advanced the iteration counter by `steps`. A saveEvery
        cadence that fires anywhere inside the block saves once, at the
        block's end (the first host-visible state)."""
        net = self.net
        if self.saveEvery > 0 and \
                net._iteration // self.saveEvery > \
                (net._iteration - steps) // self.saveEvery:
            self._save(b)
        if self.injector is not None:
            self.injector.maybe_kill(net._iteration)

    def _has_next(self, data) -> bool:
        """hasNext with the same backoff as next() — a record-reader-
        backed iterator probes the remote source here. If an error WAS
        retried and the iterator then reports exhausted, the 'end of
        epoch' is really the iterator dying (e.g. an async wrapper that
        latches exhausted after a producer error): re-raise the original
        error instead of silently recording a truncated epoch."""
        errs = []

        def probe():
            try:
                return data.hasNext()
            except self.retryPolicy.retryOn as e:
                errs.append(e)
                raise

        more = retry(probe, self.retryPolicy)
        if not more and errs:
            raise errs[-1]
        return more

    def _step(self, ds):
        from deeplearning4j_tpu.nn.multilayer import _unwrap

        net = self.net
        x = _unwrap(ds.getFeatures())
        y = _unwrap(ds.getLabels())
        fmask = _unwrap(ds.getFeaturesMaskArray())
        lmask = _unwrap(ds.getLabelsMaskArray())
        if self.injector is not None:
            x = self.injector.maybe_poison(net._iteration, x)
        if self.wrapper is not None:
            w = self.wrapper
            # divisibility-checked placement (rejects, never pads)
            x = w._shard_batch(x)
            y = w._shard_batch(y)
            fmask = w._shard_batch(fmask)
            lmask = w._shard_batch(lmask)
        # the exact key stream of MultiLayerNetwork._fit_batch — resumed
        # and uninterrupted runs fold the same iteration into the same
        # seed, which is what makes the trajectories bitwise-identical
        key = jax.random.fold_in(
            jax.random.key(net.conf.seed ^ 0x5EED), net._iteration)
        from deeplearning4j_tpu.nn.multilayer import _tm as _train_tm

        tm = _train_tm()
        t0 = tm["reg"].clock()
        net._params, net._upd_states, net._states, loss, ok = self._jit(
            net._params, net._upd_states, net._states,
            jnp.asarray(net._iteration, jnp.int32), x, y, key, fmask, lmask)
        ok = bool(ok)   # the guarded step's host sync
        dt = tm["reg"].clock() - t0
        tm["step_s"].observe(dt)
        tm["reg"].trace.add("train.step", "train", t0, dt,
                            {"iteration": net._iteration, "ok": ok})
        self._account_step(loss, ok)

    def _account_step(self, loss, ok):
        """Per-step guard accounting, shared by the k=1 path and the
        k-vector replay at a block's sync boundary: score/iteration
        advance, skip events, the consecutive-bad abort. The two paths
        MUST fire identically — tests assert the same skip-event stream
        for stepsPerSync=1 and k>1 on the same faults."""
        net = self.net
        net._score = float(loss)
        net._iteration += 1
        # counted HERE so the k=1 path and the k-vector block replay
        # bill dl4j_train_steps_total identically
        from deeplearning4j_tpu.nn.multilayer import _tm as _train_tm

        _train_tm()["steps"].inc()
        if ok:
            self._bad = 0
        else:
            self._bad += 1
            self.skippedSteps += 1
            self._fire("onStepSkipped", net._iteration, net._epoch,
                       net._score)
        for lst in net._listeners:
            lst.iterationDone(net, net._iteration, net._epoch)
        if not ok and self._bad >= self.maxBad:
            raise NonFiniteStepError(
                f"{self._bad} consecutive non-finite steps (last loss "
                f"{net._score}) at iteration {net._iteration} — aborting "
                f"instead of skipping forever; lower the learning rate "
                f"or enable gradient clipping")

    def _block_step(self, batches, jloop):
        """One stepsPerSync block: stage k batches as a stacked device
        buffer (sharded over the wrapper's mesh when present), run the
        guarded on-device k-loop, then consume the k-vector of
        losses/ok flags in ONE host sync — per-step guard accounting
        (skip events, consecutive-bad abort) replays host-side exactly
        as the k=1 path fires it."""
        from deeplearning4j_tpu.data.iterators import stack_datasets

        net = self.net
        k = len(batches)
        start = net._iteration
        xs, ys, fms, lms = stack_datasets(batches)
        if self.injector is not None:
            for i in range(k):
                xs[i] = np.asarray(
                    self.injector.maybe_poison(start + i, xs[i]))
        staged = (xs, ys, fms, lms)
        if self.wrapper is not None:
            from deeplearning4j_tpu.parallel.sharding import \
                shard_batch_stack

            staged = shard_batch_stack(staged, self.wrapper.mesh,
                                       self.wrapper.batch_axis)
        else:
            staged = jax.device_put(staged)
        xs, ys, fms, lms = staged
        (net._params, net._upd_states, net._states, losses, oks, _bad) = \
            jloop(net._params, net._upd_states, net._states,
                  jnp.asarray(start, jnp.int32), xs, ys, fms, lms,
                  jnp.asarray(self._bad, jnp.int32))
        losses = np.asarray(losses)  # the block's one host sync
        oks = np.asarray(oks)
        for i in range(k):
            # raises at the same step k=1 would; the device loop froze
            # the carry from that step on, so params match bitwise
            self._account_step(losses[i], bool(oks[i]))
        for lst in net._listeners:
            getattr(lst, "onSyncBoundary", lambda *a: None)(
                net, net._iteration, losses)

"""Native runtime: C++ prefetch ring buffer + async iterators.

Reference: the reference's host-side runtime (threaded ETL, async prefetch
queues of org.nd4j.linalg.dataset.Async*DataSetIterator). The compute path
is XLA's; this package covers the host machinery around it.
"""

from deeplearning4j_tpu.runtime.ringbuffer import (
    NativeRingBuffer, PythonRingBuffer, make_ring, native_lib,
    PF_OK, PF_TIMEOUT, PF_CLOSED, PF_TOO_BIG,
)
from deeplearning4j_tpu.runtime.async_iterator import (
    AsyncDataSetIterator, AsyncMultiDataSetIterator, pack_arrays, unpack_arrays,
)
from deeplearning4j_tpu.runtime.resilience import (
    RetryPolicy, retry, FaultInjector, Preemption, ResilientFit,
    NonFiniteStepError, non_finite_guard,
)
from deeplearning4j_tpu.runtime.chaos import (
    ChaosError, ChaosPlan, fault_point,
)

__all__ = [
    "NativeRingBuffer", "PythonRingBuffer", "make_ring", "native_lib",
    "AsyncDataSetIterator", "AsyncMultiDataSetIterator",
    "pack_arrays", "unpack_arrays",
    "PF_OK", "PF_TIMEOUT", "PF_CLOSED", "PF_TOO_BIG",
    "RetryPolicy", "retry", "FaultInjector", "Preemption", "ResilientFit",
    "NonFiniteStepError", "non_finite_guard",
    "ChaosError", "ChaosPlan", "fault_point",
]

"""Async prefetching iterators.

Reference: org.nd4j.linalg.dataset.AsyncDataSetIterator /
AsyncMultiDataSetIterator — a background ETL thread keeps a bounded queue of
ready batches so `fit()` never waits on host-side data work. Here the queue
is the native C++ ring (runtime/prefetch.cpp); batches cross it as packed
bytes, memcpy'd outside the GIL, then unpacked zero-copy with numpy views
on the consumer side and handed to jax.device_put.
"""

from __future__ import annotations

import struct
import threading
import time as _time

import numpy as np

from deeplearning4j_tpu.runtime.ringbuffer import (
    PF_CLOSED, PF_TIMEOUT, PF_TOO_BIG, make_ring,
)

_DTYPES = [np.float32, np.float64, np.int32, np.int64, np.uint8, np.bool_,
           np.float16, np.int16, np.int8, np.uint32, np.uint64]
_DTYPE_CODE = {np.dtype(d): i for i, d in enumerate(_DTYPES)}


def pack_arrays(arrays) -> bytes:
    """[np.ndarray | None, ...] -> bytes. Header: u32 count; per array:
    u8 present, u8 dtype, u8 ndim, u32 dims[ndim]; payloads follow in order."""
    head = [struct.pack("<I", len(arrays))]
    body = []
    for a in arrays:
        if a is None:
            head.append(struct.pack("<B", 0))
            continue
        a = np.ascontiguousarray(a)
        code = _DTYPE_CODE.get(a.dtype)
        if code is None:
            raise TypeError(f"unsupported dtype {a.dtype}")
        head.append(struct.pack(f"<BBB{a.ndim}I", 1, code, a.ndim, *a.shape))
        body.append(a.tobytes())
    return b"".join(head + body)


def unpack_arrays(buf: bytes):
    """Inverse of pack_arrays; array payloads are zero-copy views of buf."""
    (count,) = struct.unpack_from("<I", buf, 0)
    off = 4
    metas = []
    for _ in range(count):
        (present,) = struct.unpack_from("<B", buf, off)
        off += 1
        if not present:
            metas.append(None)
            continue
        code, ndim = struct.unpack_from("<BB", buf, off)
        off += 2
        shape = struct.unpack_from(f"<{ndim}I", buf, off)
        off += 4 * ndim
        metas.append((np.dtype(_DTYPES[code]), tuple(shape)))
    out = []
    for m in metas:
        if m is None:
            out.append(None)
            continue
        dt, shape = m
        n = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
        out.append(np.frombuffer(buf, dt, count=int(np.prod(shape, dtype=np.int64)),
                                 offset=off).reshape(shape))
        off += n
    return out


def _pack_dataset(ds) -> bytes:
    def to_np(a):
        return None if a is None else np.asarray(
            a.toNumpy() if hasattr(a, "toNumpy") else a)

    return pack_arrays([to_np(ds.getFeatures()), to_np(ds.getLabels()),
                        to_np(ds.getFeaturesMaskArray()),
                        to_np(ds.getLabelsMaskArray())])


def _unpack_dataset(buf: bytes):
    from deeplearning4j_tpu.data.dataset import DataSet

    f, l, fm, lm = unpack_arrays(buf)
    return DataSet(f, l, fm, lm)


class AsyncDataSetIterator:
    """Wraps any DataSetIterator with background prefetch
    (reference: AsyncDataSetIterator(backedIterator, queueSize)).

    The producer thread runs the wrapped iterator (record reading,
    normalization, augmentation — arbitrary Python/C++ ETL) and pushes
    packed batches into the ring; the training loop pops ready batches.
    An end-of-epoch sentinel (empty payload) closes each pass.
    """

    _SENTINEL = b""

    def __init__(self, backedIterator, queueSize: int = 4, forcePython: bool = False):
        self._base = backedIterator
        self._queueSize = max(2, int(queueSize))
        self._forcePython = forcePython
        self._ring = None
        self._thread = None
        self._error = None
        self._pending = None
        self._exhausted = False
        self._start_epoch()

    # ----- producer ---------------------------------------------------
    def _producer(self, ring):  # fault-ok[FLT02]: data-layer faults are FaultInjector's domain (runtime/resilience.py wraps the BASE iterator) — the chaos seams cover the serving tier, not the training feed
        try:
            while self._base.hasNext():
                payload = _pack_dataset(self._base.next())
                rc = ring.push(payload)
                if rc == PF_CLOSED:
                    return  # consumer reset/shut down
                if rc == PF_TOO_BIG:
                    raise ValueError(
                        f"batch of {len(payload)} bytes exceeds ring slot "
                        f"{ring.slot_bytes}")
            ring.push(self._SENTINEL)
        except Exception as e:  # surface in the consumer
            if ring is self._ring:
                self._error = e
            # else: this is an abandoned worker from a previous epoch
            # (bounded _shutdown gave up on it) — its failure must not
            # poison the current epoch's fresh ring
            ring.close()

    def _start_epoch(self):
        self._base.reset()
        self._error = None
        self._pending = None
        self._exhausted = False
        if not self._base.hasNext():
            self._exhausted = True
            return
        # size slots from the first batch (uniform batches; the final
        # partial batch is only ever smaller)
        first = _pack_dataset(self._base.next())
        if self._ring is None:
            # 2x + header margin: a padded final minibatch can carry mask
            # arrays the first batch lacks
            self._ring = make_ring(self._queueSize, 2 * len(first) + 1024,  # thread-ok[THR04]: single-consumer contract — _start_epoch only ever runs on the consumer thread; the producer receives the ring as an ARGUMENT precisely so it never races this attribute
                                   force_python=self._forcePython)
        else:
            self._ring.reopen()
        self._ring.push(first)
        self._thread = threading.Thread(target=self._producer,
                                        args=(self._ring,), daemon=True)
        self._thread.start()

    # ----- consumer (DataSetIterator surface) -------------------------
    def _fill(self):
        """Stage the next batch. A producer error propagates on the very
        next consumer call — BEFORE any batches still queued in the ring
        — and never stalls the training loop: the pop runs on a short
        timeout so a raise that a missed close() wakeup would otherwise
        hide is picked up within ~100 ms."""
        if self._pending is not None or self._exhausted:
            return
        while True:
            if self._error is not None:
                self._finish(drain=True)
                raise self._error
            got = self._ring.pop(timeout_ms=100)
            if got == PF_TIMEOUT:
                t = self._thread
                if (t is not None and not t.is_alive()
                        and self._error is None
                        and self._ring.count() == 0):
                    # worker died without sentinel OR error (e.g. killed
                    # by the interpreter) — fail loudly, don't spin
                    self._exhausted = True
                    self._thread = None
                    raise RuntimeError(
                        "async prefetch worker died without signaling "
                        "end-of-epoch or an error")
                continue  # re-check the error flag, then keep waiting
            if isinstance(got, int):  # PF_CLOSED after error/shutdown
                self._finish()
                if self._error is not None:
                    raise self._error
                return
            if got == self._SENTINEL:
                self._finish()
                if self._error is not None:
                    raise self._error
                return
            self._pending = got
            return

    def _finish(self, drain=False):
        """End-of-pass bookkeeping: mark exhausted and JOIN the producer
        so a raising worker never leaks its daemon thread (it has either
        pushed the sentinel or closed the ring, so it is exiting)."""
        self._exhausted = True
        if drain:
            self._ring.close()  # unstick a producer blocked on push
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        if t is not None and not t.is_alive():
            self._thread = None

    def hasNext(self) -> bool:
        self._fill()
        return self._pending is not None

    def next(self, num=None):
        self._fill()
        if self._pending is None:
            if self._error is not None:
                raise self._error
            raise StopIteration("iterator exhausted")
        ds = _unpack_dataset(self._pending)
        self._pending = None
        return ds

    def reset(self):
        self._shutdown()
        self._start_epoch()

    def _shutdown(self):
        if self._ring is not None:
            self._ring.close()
        t = self._thread
        if t is not None and t.is_alive():
            # drain so a blocked producer can observe the close; bounded
            # so a base iterator stuck in I/O can't hang reset()/close()
            # forever (the worker is a daemon thread and cannot keep the
            # process alive)
            deadline = _time.monotonic() + 5.0
            while t.is_alive() and _time.monotonic() < deadline:
                self._ring.pop(timeout_ms=10)
                t.join(timeout=0.05)
            if t.is_alive():
                import warnings

                warnings.warn(
                    "async prefetch worker did not exit within 5s "
                    "(base iterator stuck in I/O?); abandoning the "
                    "daemon thread and its ring — when its blocking "
                    "call returns it may consume one more base batch, "
                    "then sees the closed ring and exits", stacklevel=3)
                # never reuse this ring: the zombie would push a stale
                # batch/sentinel into the NEXT epoch after reopen();
                # left closed, its push gets PF_CLOSED and the thread
                # dies. _start_epoch sizes a fresh ring on demand.
                self._ring = None
        self._thread = None

    def close(self):
        self._shutdown()

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()

    # passthrough metadata
    def batch(self):
        return self._base.batch()

    def totalExamples(self):
        return self._base.totalExamples()

    def inputColumns(self):
        return self._base.inputColumns()

    def totalOutcomes(self):
        return self._base.totalOutcomes()

    def setPreProcessor(self, pp):
        self._base.setPreProcessor(pp)

    def getPreProcessor(self):
        return self._base.getPreProcessor()


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Async wrapper for MultiDataSetIterator (reference:
    AsyncMultiDataSetIterator). Packs the flattened feature/label/mask
    lists instead of the 4-slot DataSet layout."""

    def _producer(self, ring):  # fault-ok[FLT02]: same loop, different pack — data-layer faults are FaultInjector's domain (runtime/resilience.py), not a chaos seam
        try:
            while self._base.hasNext():
                payload = self._pack_mds(self._base.next())
                rc = ring.push(payload)
                if rc == PF_CLOSED:
                    return
                if rc == PF_TOO_BIG:
                    raise ValueError("multidataset exceeds ring slot")
            ring.push(self._SENTINEL)
        except Exception as e:
            if ring is self._ring:  # see AsyncDataSetIterator._producer
                self._error = e
            ring.close()

    @staticmethod
    def _pack_mds(mds) -> bytes:
        def to_np_list(xs):
            return [None if x is None else np.asarray(
                x.toNumpy() if hasattr(x, "toNumpy") else x) for x in (xs or [])]

        feats = to_np_list(mds.getFeatures())
        labs = to_np_list(mds.getLabels())
        fmasks = to_np_list(mds.getFeaturesMaskArrays())
        lmasks = to_np_list(mds.getLabelsMaskArrays())
        # mask lists are positional: pad with None slots to the arity of
        # their array lists so unpacking stays index-aligned
        fmasks += [None] * (len(feats) - len(fmasks))
        lmasks += [None] * (len(labs) - len(lmasks))
        counts = np.array([len(feats), len(labs)], np.uint32)
        return pack_arrays([counts] + feats + labs + fmasks + lmasks)

    def _start_epoch(self):
        # identical to the base, but measure with the MDS packer
        self._base.reset()
        self._error = None
        self._pending = None
        self._exhausted = False
        if not self._base.hasNext():
            self._exhausted = True
            return
        first = self._pack_mds(self._base.next())
        if self._ring is None:
            self._ring = make_ring(self._queueSize, 2 * len(first) + 1024,  # thread-ok[THR04]: single-consumer contract — see AsyncDataSetIterator._start_epoch; the producer gets the ring as an argument
                                   force_python=self._forcePython)
        else:
            self._ring.reopen()
        self._ring.push(first)
        self._thread = threading.Thread(target=self._producer,
                                        args=(self._ring,), daemon=True)
        self._thread.start()

    def next(self, num=None):
        from deeplearning4j_tpu.data.multidataset import MultiDataSet

        self._fill()
        if self._pending is None:
            if self._error is not None:
                raise self._error
            raise StopIteration("iterator exhausted")
        arrays = unpack_arrays(self._pending)
        self._pending = None
        nf, nl = int(arrays[0][0]), int(arrays[0][1])
        feats = arrays[1:1 + nf]
        labs = arrays[1 + nf:1 + nf + nl]
        fmasks = arrays[1 + nf + nl:1 + 2 * nf + nl]
        lmasks = arrays[1 + 2 * nf + nl:1 + 2 * nf + 2 * nl]
        return MultiDataSet(feats, labs,
                            fmasks if any(m is not None for m in fmasks) else None,
                            lmasks if any(m is not None for m in lmasks) else None)

"""AOT compilation + persistent executable cache.

The suite and production cold-start are COMPILE-dominated: every
process (and every fresh network instance) pays XLA seconds-to-minutes
re-compiling programs that are byte-identical to ones already compiled.
jax's own answer (``jax_compilation_cache_dir``) segfaults on this
jaxlib 0.4.36 deserializing donated-buffer executables (see
tests/conftest.py), so this module is our own layer, in the spirit of
whole-program compilation (arXiv:1810.09868 — compile the WHOLE step
once, then reuse the executable):

* ``ExecutableCache`` — a two-level (in-memory + on-disk) store of
  compiled XLA executables keyed by a content hash of everything that
  shapes the traced program: the network configuration JSON, the entry
  point, the abstract call signature (shapes/dtypes/shardings), the
  dtype-policy toggles, the weight-update/sharding mode, and the
  jax/jaxlib/package versions (a version bump invalidates stale
  artifacts; a corrupted or stale file falls back to a fresh compile).

* the donation-segfault workaround — cached executables are compiled
  with donation STRIPPED (``donate_argnums=()``), which is the form
  jaxlib 0.4.36 round-trips safely, and donation is re-applied at call
  time by the wrapper: after the executable returns, the buffers at the
  donated positions are explicitly deleted (guarded against
  input-to-output aliasing), so the caller-visible contract — donated
  inputs are invalid after the call, memory is released promptly — is
  preserved. Stripping donation cannot change math (aliasing is a
  buffer-assignment concern), which is why a warm-started fit is
  bitwise-identical to a cold one.

* ``cached_jit`` — a drop-in ``jax.jit`` replacement the network
  classes build their train/forward/loss steps with. With no cache
  enabled it IS the plain donated jit (zero behavior change); with a
  session cache enabled every first call per signature goes
  key-lookup → deserialize-or-compile, so two networks with equal
  configs share ONE executable instead of compiling twice.

* ``precompile`` warm-start — ``network.precompile(...)`` (all three
  network types), ``ParallelWrapper.precompile(...)`` and
  ``ParallelInference.precompile(...)`` drive ``CachedJit.warm`` with
  example abstract arguments so serving processes and trainers hit the
  first real batch with a hot executable.

* shape-bucket canonicalization — ``bucket_batch`` rounds request
  batch sizes up to a small fixed set of buckets so a serving tier
  compiles one executable per bucket, never one per request size; the
  bucket count is the retrace budget to hand RetraceSentinel
  (``sentinel_budget``).

Scope: single-process jax only (``jax.process_count() > 1`` disables
the cache — multihost executables embed device assignments that do not
round-trip across launches).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
import threading
import time

import jax
import numpy as np

from deeplearning4j_tpu.runtime.chaos import \
    fault_point as _chaos_fault_point

__all__ = [
    "ExecutableCache", "CachedJit", "cached_jit", "compile_lowered",
    "enable", "disable", "session_cache", "ambient_fingerprint",
    "network_fingerprint", "samediff_fingerprint", "abstract_signature",
    "bucket_batch", "pad_batch", "sentinel_budget",
    "DEFAULT_BATCH_BUCKETS", "CompileWatch",
]

#: bump when the on-disk artifact layout changes — old files become
#: stale (fresh compile + overwrite), never a crash
CACHE_FORMAT = 1

#: env var naming a directory for the persistent tier; unset = the
#: session cache (when enabled) is memory-only
CACHE_DIR_ENV = "DL4J_TPU_AOT_CACHE"

#: kill switch: DL4J_TPU_AOT=off ignores enable()/env-dir entirely
AOT_ENV = "DL4J_TPU_AOT"


def _package_version():
    from deeplearning4j_tpu import __version__

    return __version__


_TM = None


def _tm():
    """Lazily-resolved AOT telemetry handles (runtime.telemetry): the
    compile-vs-load split as registry instruments + trace spans, on top
    of the per-cache ``stats``/``seconds`` dicts the CLI reports."""
    global _TM
    if _TM is None:
        from deeplearning4j_tpu.runtime import telemetry

        reg = telemetry.get_registry()
        _TM = {
            "reg": reg,
            "hits_mem": reg.counter(
                "dl4j_aot_cache_hits_total",
                "executable-cache hits by tier",
                labels=("tier",)).labels(tier="memory"),
            "hits_disk": reg.counter(
                "dl4j_aot_cache_hits_total",
                "executable-cache hits by tier",
                labels=("tier",)).labels(tier="disk"),
            "misses": reg.counter(
                "dl4j_aot_cache_misses_total",
                "executable-cache misses (XLA compiles paid)"),
            "compile_s": reg.histogram(
                "dl4j_aot_compile_seconds",
                "XLA compile wall on a cache miss"),
            "load_s": reg.histogram(
                "dl4j_aot_load_seconds",
                "disk-tier deserialize wall on a disk hit"),
        }
    return _TM


def _tm_compile(t0, key=None, entry=None):
    """Record one cache-miss compile that started at perf_counter t0."""
    tm = _tm()
    dt = time.perf_counter() - t0
    tm["misses"].inc()
    tm["compile_s"].observe(dt)
    tm["reg"].trace.add(
        "aot.compile", "compile", t0, dt,
        {"key": (key or "")[:16], "entry": entry or ""})
    return dt


# ----------------------------------------------------------------------
# fingerprints: everything that shapes the traced program
# ----------------------------------------------------------------------

def ambient_fingerprint():
    """Process-level facts that change the compiled program without
    appearing in any argument: versions (stale-cache invalidation),
    backend, device count, x64 mode, and the module-global A/B toggles
    (loss/BN tail modes, pooling backward impl, attention windows) the
    bench flips — a cache hit across two of THESE states would replay
    the wrong program."""
    from deeplearning4j_tpu.nn import losses as _losses
    from deeplearning4j_tpu.nn import multilayer as _ml
    from deeplearning4j_tpu.ops import norm as _norm
    from deeplearning4j_tpu.ops import pallas_attention as _pattn
    from deeplearning4j_tpu.ops import pooling as _pooling

    return {
        "format": CACHE_FORMAT,
        "package": _package_version(),
        "jax": jax.__version__,
        "jaxlib": __import__("jaxlib").__version__,
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "x64": bool(jax.config.jax_enable_x64),
        # the autotune-arbiter knobs (runtime/autotune.py): every value
        # the arbiter can flip lives in the key, so a tuned run and a
        # stock run can NEVER share an executable — flipping a knob is
        # a different program, not a warm hit
        "loss_tail": _losses._TAIL_MODE,
        "bn_tail": _norm._TAIL_MODE,
        "bn_epilogue": _norm._EPILOGUE,
        "maxpool_bwd": _pooling._BACKWARD_IMPL,
        "global_maxpool_bwd": _pooling._GLOBAL_MAXPOOL_BWD,
        "flash_bwd": _pattn._BWD_IMPL,
        "canon_staging": _ml._CANON_STAGING,
        "argmax_bwd_win": _pooling._ARGMAX_BWD_MAX_WINDOW,
        "flash_window": (_pattn._MIN_FLASH_SEQ, _pattn._BLOCKWISE_WINDOW,
                         _pattn._INTERPRET),
    }


def _sha(text):
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def network_fingerprint(net):
    """Stable content hash of a MultiLayerNetwork/ComputationGraph's
    traced-program identity: the config JSON (layers, updaters,
    frozen flags, remat policy, dtype — everything serde serializes)
    plus the pieces that live OUTSIDE the conf: the weight-update hook
    (ZeRO sharding changes the program and its mesh is not conf state)
    and the solver algo. Raises if the conf cannot serialize — callers
    treat that as "not cacheable", never as an error."""
    impl = getattr(net, "_update_impl", None)
    impl_desc = "none" if impl is None else (
        f"{type(impl).__name__}:{getattr(impl, 'axis', None)}:"
        f"{getattr(impl, 'min_shard_size', None)}:"
        f"{tuple(sorted(dict(getattr(impl, 'mesh', None).shape).items())) if getattr(impl, 'mesh', None) is not None else None}")
    return _sha("|".join([
        type(net).__name__,
        net.conf.toJson(),
        impl_desc,
        "solver" if getattr(net, "_solver", None) is not None else "sgd",
    ]))


def samediff_fingerprint(sd):
    """Structural hash of a SameDiff graph + its TrainingConfig: op
    list (names/inputs/outputs/attrs), variable table (name, type,
    dtype/shape of stored arrays — values ride as runtime arguments and
    do not bake into the program), loss variables, and the training
    config (updater + regularization) when set."""
    parts = [f"{o.opName}({','.join(o.inputs)})->"
             f"({','.join(o.outputs)}){sorted(o.kwargs.items())!r}"
             for o in sd._ops]
    for n in sorted(sd._vars):
        v = sd._vars[n]
        a = sd._arrays.get(n)
        parts.append(
            f"{n}:{v.variableType}:"
            f"{None if a is None else (tuple(a.shape), str(a.dtype))}:"
            f"{getattr(v, '_ph_shape', None)}:{getattr(v, '_ph_dtype', None)}")
    parts.append(f"loss={sd._loss_vars}")
    tc = sd._tc
    if tc is not None:
        from deeplearning4j_tpu.util import serde

        try:
            upd = serde.to_json(tc.updater)
        except Exception:  # fault-ok[FLT01]: the repr fallback IS the handling — any stable string works as a cache-key component, a serde failure only changes the key, never correctness
            upd = repr(vars(tc.updater)) if hasattr(tc.updater, "__dict__") \
                else repr(tc.updater)
        parts.append(f"tc:{upd}:{tc.l1}:{tc.l2}:{tc.weightDecay}:"
                     f"{tc.dataSetFeatureMapping}:{tc.dataSetLabelMapping}:"
                     f"{tc.lossVariables}")
    impl = getattr(sd, "_update_impl", None)
    parts.append("zero" if impl is not None else "dense")
    return _sha("|".join(parts))


def _leaf_sig(leaf):
    """Hashable per-leaf signature — (aval, sharding) OBJECT pairs for
    jax arrays (both hash/compare by value; no string building on the
    per-call hot path — stringification happens once per first-seen
    signature in _sig_repr). np/python leaves carry no sharding."""
    if isinstance(leaf, jax.Array):
        return (leaf.aval, leaf.sharding)
    if isinstance(leaf, np.ndarray):
        return (tuple(leaf.shape), str(leaf.dtype), None)
    if isinstance(leaf, jax.ShapeDtypeStruct):
        # normalize to the signature an equivalent CONCRETE array would
        # produce, so warm(ShapeDtypeStruct(...)) primes the same table/
        # cache entry the real call looks up (an SDS without an explicit
        # sharding matches the default single-device placement)
        from jax.core import ShapedArray
        from jax.sharding import SingleDeviceSharding

        sh = getattr(leaf, "sharding", None)
        if sh is None:
            sh = SingleDeviceSharding(jax.devices()[0])
        return (ShapedArray(leaf.shape, leaf.dtype), sh)
    # python scalar: jit would trace it weak-typed; keep the type in
    # the key so int/float streams don't collide
    return ("py", type(leaf).__name__)


def abstract_signature(args):
    """Hashable signature of a call's positional args: pytree structure
    + per-leaf (aval, sharding). The same function at the same
    signature lowers to the same program."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (treedef, tuple(_leaf_sig(leaf) for leaf in leaves))


def _sig_repr(sig):
    """Stable string form of a signature for the sha256 disk key —
    computed once per first-seen signature, never on the dispatch hot
    path. Aval/sharding objects repr deterministically across
    processes (device ids, mesh axes, dtype names)."""
    if isinstance(sig, str):
        return sig
    treedef, leaf_sigs = sig
    parts = []
    for ls in leaf_sigs:
        parts.append(",".join(repr(c) for c in ls))
    return f"{treedef}|{';'.join(parts)}"


def cache_key(base_fp, entry, sig, ambient=None):
    """The on-disk cache key: sha256 over (ambient fingerprint, program
    fingerprint, entry-point name, abstract signature)."""
    amb = ambient if ambient is not None else ambient_fingerprint()
    return _sha("|".join([repr(sorted(amb.items())), base_fp, entry,
                          _sig_repr(sig)]))


# ----------------------------------------------------------------------
# the cache
# ----------------------------------------------------------------------

class ExecutableCache:
    """Two-level executable store.

    Memory tier: key -> jax.stages.Compiled, shared by every network in
    the process (the tier-1 win: N identical configs, 1 compile).
    Disk tier (optional ``directory``): pickled
    (meta, payload, in_tree, out_tree) per key, written atomically
    (tmp + rename); ``meta`` embeds the ambient fingerprint so a
    package/jax/jaxlib version bump or toggle flip makes the artifact
    stale (removed + recompiled) instead of silently wrong. A file that
    fails to unpickle or deserialize is removed and treated as a miss —
    a corrupted cache can cost a compile, never correctness.
    """

    #: per-artifact disk ceiling: a single serialized executable larger
    #: than this stays memory-only (keeps a shared cache dir bounded;
    #: the XLA:CPU artifacts measured so far are ~0.05-1 MB)
    max_artifact_bytes = 64 * 1024 * 1024

    def __init__(self, directory=None):
        self.directory = os.path.expanduser(str(directory)) \
            if directory else None
        if self.directory:
            # artifacts are pickles: loading one executes whatever it
            # encodes, so the directory must be writable ONLY by the
            # trusting user — created 0700, files land 0600 (mkstemp)
            os.makedirs(self.directory, mode=0o700, exist_ok=True)
        # serving threads drive get/put concurrently (every BATCHED
        # dispatch and every handler-thread first request lands here);
        # the stats counters are read-modify-write and the memory tier
        # is check-then-insert, so both live under one lock (the THR01
        # audit, ISSUE 14). Reentrant: note_miss can fire under get.
        self._lock = threading.RLock()
        self._mem = {}
        self.stats = {"mem_hits": 0, "disk_hits": 0, "misses": 0,
                      "puts": 0, "stale": 0, "corrupt": 0,
                      "oversize": 0, "store_errors": 0}
        #: key -> seconds of the compile (miss) or load (disk hit);
        #: the CLI --precompile report reads this
        self.seconds = {}

    def note_miss(self, key=None, seconds=None):
        """Count one compile-path miss (and optionally its wall) — the
        lock-safe increment every caller that pays a compile uses
        (CachedJit, compile_lowered); bare `stats["misses"] += 1` from
        another thread would lose counts and CompileWatch proofs with
        them."""
        with self._lock:
            self.stats["misses"] += 1
            if key is not None and seconds is not None:
                self.seconds[key] = float(seconds)

    # -- paths ----------------------------------------------------------
    def _path(self, key):
        return os.path.join(self.directory, key + ".aotx")

    def __contains__(self, key):
        with self._lock:
            if key in self._mem:
                return True
        return self.directory is not None \
            and os.path.exists(self._path(key))

    # -- read -----------------------------------------------------------
    def get(self, key, ambient=None):
        """-> Compiled or None. Memory first; then disk (deserialize +
        promote to memory). Stale/corrupted disk entries are removed.
        The disk load itself runs unlocked — two threads racing the
        same cold key can both deserialize (a benign duplicate load);
        the memory tier and counters stay consistent either way."""
        with self._lock:
            hit = self._mem.get(key)
            if hit is not None:
                self.stats["mem_hits"] += 1
        if hit is not None:
            _tm()["hits_mem"].inc()
            return hit
        if self.directory is None:
            return None
        path = self._path(key)
        if not os.path.exists(path):
            return None
        t0 = time.perf_counter()
        try:
            # chaos seam INSIDE the corrupt-handling try: an injected
            # raise or a corrupted path must be absorbed exactly like
            # organic disk rot — a miss, never an error
            # (runtime/chaos.py, seam aot.disk_read)
            path = _chaos_fault_point("aot.disk_read", path)
            with open(path, "rb") as fh:
                meta, payload, in_tree, out_tree = pickle.load(fh)
        except Exception:
            with self._lock:
                self.stats["corrupt"] += 1
            self._remove(path)
            return None
        amb = ambient if ambient is not None else ambient_fingerprint()
        if meta.get("ambient") != amb:
            with self._lock:
                self.stats["stale"] += 1
            self._remove(path)
            return None
        try:
            from jax.experimental import serialize_executable as _se

            compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception:
            with self._lock:
                self.stats["corrupt"] += 1
            self._remove(path)
            return None
        dt = time.perf_counter() - t0
        with self._lock:
            self.seconds[key] = dt
            self.stats["disk_hits"] += 1
            self._mem[key] = compiled
        tm = _tm()
        tm["hits_disk"].inc()
        tm["load_s"].observe(dt)
        tm["reg"].trace.add("aot.deserialize", "compile", t0, dt,
                            {"key": key[:16]})
        return compiled

    @staticmethod
    def _remove(path):
        try:
            os.remove(path)
        except OSError:
            pass

    # -- write ----------------------------------------------------------
    def put(self, key, compiled, ambient=None, entry=None):
        """Store in memory and (when a directory is configured)
        serialize to disk atomically. Serialization failures are
        swallowed — the memory tier still works and the next process
        simply recompiles."""
        with self._lock:
            self._mem[key] = compiled
            self.stats["puts"] += 1
        if self.directory is None:
            return
        try:
            from jax.experimental import serialize_executable as _se

            # chaos seam inside the swallow-everything try: an injected
            # disk-write fault costs the artifact, never the process
            # (runtime/chaos.py, seam aot.disk_write)
            _chaos_fault_point("aot.disk_write")
            payload, in_tree, out_tree = _se.serialize(compiled)
            if len(payload) > self.max_artifact_bytes:
                with self._lock:
                    self.stats["oversize"] += 1
                return
            meta = {"ambient":
                    ambient if ambient is not None else ambient_fingerprint(),
                    "entry": entry}
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump((meta, payload, in_tree, out_tree), fh)
                os.replace(tmp, self._path(key))
            except BaseException:
                self._remove(tmp)
                raise
        except Exception:
            # disk store is best-effort (the memory tier already holds
            # the executable), but a silently failing store looks like
            # a working cache that never warms across processes — count
            # it so operators can tell "cold by design" from "broken"
            with self._lock:
                self.stats["store_errors"] += 1

    def clear_memory(self):
        """Drop the in-process tier (tests simulate a second process by
        clearing memory and re-reading disk)."""
        with self._lock:
            self._mem.clear()

    def clear(self):
        self.clear_memory()
        if self.directory:
            for name in os.listdir(self.directory):
                if name.endswith(".aotx"):
                    self._remove(os.path.join(self.directory, name))


# ----------------------------------------------------------------------
# session cache
# ----------------------------------------------------------------------

_SESSION = None
_SESSION_INIT = False


def enable(directory=None):
    """Turn on the process-wide session cache. directory=None falls
    back to $DL4J_TPU_AOT_CACHE (memory-only if unset); directory=False
    forces memory-only even when the env var is set (the test suite
    uses this — see tests/conftest.py on why the suite must never
    deserialize). Idempotent — re-enabling with the same directory
    keeps the existing cache. Returns the ExecutableCache."""
    global _SESSION, _SESSION_INIT
    if directory is False:
        directory = None
    else:
        directory = directory or os.environ.get(CACHE_DIR_ENV) or None
    # compare in the same (expanduser'd) form ExecutableCache stores,
    # or re-enabling with a '~' path would discard the live cache
    norm = os.path.expanduser(str(directory)) if directory else None
    if _SESSION is not None and _SESSION.directory == norm:
        _SESSION_INIT = True
        return _SESSION
    _SESSION = ExecutableCache(directory)
    _SESSION_INIT = True
    return _SESSION


def disable():
    """Turn the session cache off (networks fall back to plain jit)."""
    global _SESSION, _SESSION_INIT
    _SESSION = None
    _SESSION_INIT = True


def session_cache():
    """The active session cache or None. First call auto-enables a
    disk-backed cache iff DL4J_TPU_AOT_CACHE is set (so a warm-started
    process needs no code change); DL4J_TPU_AOT=off vetoes everything;
    multihost always disables (device assignments in serialized
    executables do not survive across launches)."""
    global _SESSION_INIT
    if os.environ.get(AOT_ENV, "").lower() in ("off", "0", "false"):
        return None
    if not _SESSION_INIT:
        _SESSION_INIT = True
        if os.environ.get(CACHE_DIR_ENV):
            enable()
    if _SESSION is not None and jax.process_count() > 1:
        return None
    return _SESSION


# ----------------------------------------------------------------------
# donation emulation
# ----------------------------------------------------------------------

class _AotCall:
    """A cached (donation-stripped) executable + call-time re-donation:
    after the call, delete the array leaves at the donated argument
    positions — the same "this buffer is dead now" contract the donated
    jit gives callers, minus XLA's in-place aliasing (peak memory
    during the step is higher; see docs/COMPILE.md). Leaves that alias
    an output object are skipped, and deletion failures are ignored —
    deletion is a memory hint, never a correctness step."""

    __slots__ = ("compiled", "donate_argnums")

    def __init__(self, compiled, donate_argnums=()):
        self.compiled = compiled
        self.donate_argnums = tuple(donate_argnums)

    def __call__(self, *args):
        out = self.compiled(*args)
        if self.donate_argnums:
            out_ids = {id(leaf) for leaf in jax.tree_util.tree_leaves(out)}
            for i in self.donate_argnums:
                if i >= len(args):
                    continue
                for leaf in jax.tree_util.tree_leaves(args[i]):
                    if isinstance(leaf, jax.Array) \
                            and id(leaf) not in out_ids:
                        try:
                            if not leaf.is_deleted():
                                leaf.delete()
                        except Exception:  # fault-ok[FLT01]: deletion is a memory hint, never a correctness step (class docstring) — there is nothing to classify when the runtime declines it
                            pass
        return out


def compile_lowered(lowered, key=None, cache=None, entry=None,
                    donate_argnums=()):
    """Compile a jax.stages.Lowered through a cache: warm hit returns
    the deserialized executable (wrapped for re-donation when
    donate_argnums is given), miss pays lowered.compile() and stores
    it. With no cache this is exactly ``lowered.compile()``. The
    lowering itself must have donation STRIPPED — a donated lowering
    would produce the artifact class jaxlib 0.4.36 cannot deserialize."""
    cache = cache if cache is not None else session_cache()
    if cache is None or key is None:
        compiled = lowered.compile()
    else:
        compiled = cache.get(key)
        if compiled is None:
            t0 = time.perf_counter()
            compiled = lowered.compile()
            cache.note_miss(key, _tm_compile(t0, key, entry))
            cache.put(key, compiled, entry=entry)
    if donate_argnums:
        return _AotCall(compiled, donate_argnums)
    return compiled


# ----------------------------------------------------------------------
# CachedJit — the drop-in jit the network classes build steps with
# ----------------------------------------------------------------------

#: table sentinel: this signature failed through the AOT path once —
#: the plain jit owns it permanently (see CachedJit.__call__)
_BAD_ENTRY = object()


class CachedJit:
    """jit wrapper with an AOT fast path.

    Call behavior per invocation:
      * no session/pinned cache, or keyword args (static-arg paths), or
        an unfingerprintable owner -> the plain fallback jit, donation
        and all (exactly the pre-AOT behavior);
      * cache active -> signature lookup in the per-instance table; a
        first-seen signature computes the content key and goes through
        the cache (deserialize or compile-without-donation + store),
        then dispatches to the cached executable with call-time
        re-donation.

    ``owner`` supplies the program fingerprint lazily (the conf JSON
    hash); ``extra`` folds caller context the fingerprint cannot see
    (e.g. a ParallelWrapper's mesh/compression mode) into the key.
    """

    def __init__(self, fn, owner=None, entry="step", extra="",
                 donate_argnums=(), fingerprint=None, **jit_kwargs):
        self._fn = fn
        self._owner = owner
        self._entry = entry
        self._extra = extra
        self._donate = tuple(donate_argnums or ())
        self._jit_kwargs = dict(jit_kwargs)
        self._fallback = jax.jit(fn, donate_argnums=self._donate,
                                 **jit_kwargs)
        # donation-stripped twin: the ONLY jit the AOT path lowers
        # through, so every cached artifact is the serialization-safe
        # form (the conftest segfault workaround)
        self._bare = jax.jit(fn, **jit_kwargs)
        self._table = {}
        self._fingerprint = fingerprint  # explicit > owner-derived
        self._fp_failed = False
        self._pinned_cache = None
        # identity of the owner's weight-update hook when the
        # fingerprint was derived: installing/removing the ZeRO hook
        # changes the traced program, so a change invalidates the
        # derived fingerprint + table (checked per call, id() cheap)
        self._seen_impl = object()
        # serving handler threads dispatch through ONE CachedJit
        # concurrently; the signature table is check-then-insert and a
        # first-seen signature pays an XLA compile, so the entry path
        # is single-flight PER SIGNATURE (the THR01/THR04 audit,
        # ISSUE 14): the table holds a threading.Event while a
        # signature's compile is in flight — a racing thread with the
        # SAME signature waits on it instead of duplicating the
        # compile, while warm traffic for other signatures keeps
        # flowing (the lock itself only guards table metadata, never
        # the compile). RLock: invalidate() may fire inside the locked
        # metadata path via the impl-change check.
        self._lock = threading.RLock()

    # -- key plumbing ----------------------------------------------------
    def pin_cache(self, cache):
        """Use this cache regardless of the session cache (precompile
        with an explicit cache pins it so later fit() calls keep
        hitting the same store)."""
        self._pinned_cache = cache
        return self

    def _cache(self):
        return self._pinned_cache if self._pinned_cache is not None \
            else session_cache()

    def _base_fp_locked(self):
        if self._fp_failed:
            return None
        if self._fingerprint is None:
            if self._owner is None:
                self._fp_failed = True
                return None
            try:
                self._fingerprint = network_fingerprint(self._owner)
            except Exception:  # fault-ok[FLT01]: _fp_failed IS the classification — dispatch consults it and routes every call to the plain-jit fallback instead of the cache
                self._fp_failed = True
                return None
        return self._fingerprint

    def invalidate(self):
        """Forget the derived fingerprint + signature table (the owner's
        program identity changed, e.g. a weight-update hook was
        installed)."""
        with self._lock:
            self._invalidate_locked()
        return self

    def _invalidate_locked(self):
        if self._owner is not None:
            self._fingerprint = None
        self._fp_failed = False
        self._table.clear()

    def _check_impl_locked(self):
        if self._owner is None:
            return
        cur = id(getattr(self._owner, "_update_impl", None))
        if cur != self._seen_impl:
            self._seen_impl = cur
            self._invalidate_locked()

    # -- dispatch --------------------------------------------------------
    def _entry_for(self, args, cache):
        sig = abstract_signature(args)
        while True:
            with self._lock:
                self._check_impl_locked()
                ent = self._table.get(sig)
                if ent is None:
                    fp = self._base_fp_locked()
                    if fp is None:
                        return None, None
                    marker = threading.Event()
                    self._table[sig] = marker   # we own this compile
                    break
                if not isinstance(ent, threading.Event):
                    return ent
                in_flight = ent
            # another thread is compiling THIS signature: wait outside
            # the lock, then re-read (its entry, or ownership if it
            # failed / the table was invalidated mid-compile). Bounded:
            # the owner's finally guarantees marker.set(), but a 1s
            # cap means a thread killed mid-compile (or a marker that
            # leaked through invalidate) degrades to a slow re-read
            # loop instead of a permanent wedge
            in_flight.wait(1.0)
        try:
            # the compile runs outside the lock — warm dispatches of
            # OTHER signatures are never stalled behind it
            key = cache_key(fp, self._entry + self._extra, sig)
            compiled = cache.get(key)
            if compiled is None:
                t0 = time.perf_counter()
                compiled = self._bare.lower(*args).compile()
                cache.note_miss(key, _tm_compile(t0, key, self._entry))
                cache.put(key, compiled, entry=self._entry)
            ent = (_AotCall(compiled, self._donate), key)
            with self._lock:
                if self._table.get(sig) is marker:
                    self._table[sig] = ent
            return ent
        except BaseException:
            with self._lock:
                if self._table.get(sig) is marker:
                    del self._table[sig]
            raise
        finally:
            marker.set()   # wake waiters either way; they re-read

    def __call__(self, *args, **kwargs):
        cache = self._cache()
        if cache is None or kwargs:
            return self._fallback(*args, **kwargs)
        ent, _key = self._entry_for(args, cache)
        if ent is None or ent is _BAD_ENTRY:
            return self._fallback(*args)
        try:
            return ent(*args)
        except TypeError:
            # aval disagreement the signature didn't capture —
            # blacklist the entry so the plain jit owns this call
            # pattern from here on (no retry-per-call)
            with self._lock:
                self._table[abstract_signature(args)] = (_BAD_ENTRY, None)
            return self._fallback(*args)

    def warm(self, *args, cache=None):
        """Populate the cache + dispatch table for this signature
        WITHOUT executing (args may be ShapeDtypeStructs). Returns
        (key, status, seconds): status "warm" = served from cache,
        "cold" = compiled now, None = not cacheable."""
        if cache is not None:
            self.pin_cache(cache)
        c = self._cache()
        if c is None:
            c = self.pin_cache(enable())._cache()
        before = dict(c.stats)
        ent, key = self._entry_for(args, c)
        if ent is None or ent is _BAD_ENTRY:
            return None, None, 0.0
        status = "cold" if c.stats["misses"] > before["misses"] else "warm"
        return key, status, c.seconds.get(key, 0.0)

    # -- jit API passthrough --------------------------------------------
    def lower(self, *args, **kwargs):
        return self._fallback.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return self._fallback.eval_shape(*args, **kwargs)

    @property
    def __wrapped__(self):
        return self._fn


def cached_jit(fn, owner=None, entry="step", extra="", donate_argnums=(),
               fingerprint=None, **jit_kwargs):
    """Build a CachedJit (see class docstring). Drop-in for
    ``jax.jit(fn, donate_argnums=..., **jit_kwargs)``."""
    return CachedJit(fn, owner=owner, entry=entry, extra=extra,
                     donate_argnums=donate_argnums,
                     fingerprint=fingerprint, **jit_kwargs)


# ----------------------------------------------------------------------
# warm-path proof
# ----------------------------------------------------------------------

class CompileWatch:
    """Context manager proving a region of code compiled nothing.

    Snapshots the cache's miss counter on entry and exposes the delta
    as ``.misses`` on exit — the warm-swap / serving-soak gate is built
    on it: after ``precompile()``, "zero request-path compiles" is
    ``CompileWatch().misses == 0`` over the whole serving window.
    Counts CACHE misses, i.e. every compile the AOT layer paid; code
    running outside the cache (fallback jit) is the RetraceSentinel's
    jurisdiction — use both for a complete proof (docs/SERVING.md).
    """

    def __init__(self, cache=None):
        self._explicit = cache
        self.misses = None

    def __enter__(self):
        self._cache = self._explicit if self._explicit is not None \
            else session_cache()
        if self._cache is None:
            raise RuntimeError(
                "CompileWatch needs an active executable cache "
                "(aot.enable() or an explicit cache) — with no cache "
                "there is no miss counter to prove warmth against")
        self._before = self._cache.stats["misses"]
        return self

    def __exit__(self, *exc):
        self.misses = self._cache.stats["misses"] - self._before
        return False

    def assert_no_compiles(self, context="watched region"):
        if self.misses is None:
            raise RuntimeError("assert_no_compiles before __exit__")
        if self.misses:
            raise RuntimeError(
                f"{context} paid {self.misses} compile(s) that a warm "
                "cache should have served — a cold executable reached "
                "the hot path (precompile the signature, or the key "
                "changed: see docs/COMPILE.md key anatomy)")
        return self


# ----------------------------------------------------------------------
# shape buckets
# ----------------------------------------------------------------------

#: serving-tier batch buckets: one executable per bucket, never one
#: per request size
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def bucket_batch(n, buckets=DEFAULT_BATCH_BUCKETS):
    """Smallest bucket >= n; past the largest bucket, the next multiple
    of it (so compiles stay bounded: len(buckets) + overflow sizes)."""
    n = int(n)
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    for b in buckets:
        if n <= b:
            return b
    top = max(buckets)
    return ((n + top - 1) // top) * top


def pad_batch(arr, bucket):
    """Zero-pad arr's leading (batch) axis up to `bucket` (host-side,
    numpy). Caller slices the surplus rows off the output."""
    arr = np.asarray(arr)
    pad = bucket - arr.shape[0]
    if pad < 0:
        raise ValueError(
            f"batch {arr.shape[0]} exceeds bucket {bucket}")
    if pad == 0:
        return arr
    return np.concatenate(
        [arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)], axis=0)


def sentinel_budget(buckets=DEFAULT_BATCH_BUCKETS, entries=1):
    """The retrace budget a bucketized call site is allowed: one
    compile per bucket per entry point — hand to
    RetraceSentinel(max_compiles=...)."""
    return len(tuple(buckets)) * int(entries)

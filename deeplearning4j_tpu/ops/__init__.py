"""Functional TPU op kernels (conv, pooling, rnn scans, norm, attention).

Reference: libnd4j op implementations + cuDNN helper classes; here each is
a lax/pallas composition that XLA fuses.
"""

"""Recurrent cells as fused scans.

Reference: the reference's LSTM forward is LSTMHelpers.activateHelper
(hand-rolled per-timestep GEMMs) or the cuDNN LSTM helper (CudnnLSTMHelper)
on GPU. TPU design: the input projection x_t @ W for ALL timesteps is one
large [T*B, nIn] x [nIn, 4H] matmul executed on the MXU before the scan;
the lax.scan body then carries only the recurrent h_t @ U matmul. This is
the standard XLA RNN recipe — it keeps the MXU busy with one big GEMM
instead of T skinny ones, which is where cuDNN's fused LSTM gets its speed
on GPU.

Data layout here is time-major [T, B, F]; the nn layer wrappers convert
from the API's NCW [B, F, T] at the layer boundary.

Gate order in the packed weights: [input i, forget f, output o, cell g]
(reference LSTMParamInitializer packs [i, f, o, g] as well).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lstm_scan(x_tbf, w, u, b, h0=None, c0=None, peephole=None,
              activation=jnp.tanh, gate_activation=jax.nn.sigmoid):
    """LSTM over time-major input.

    x_tbf: [T, B, nIn]; w: [nIn, 4H]; u: [H, 4H]; b: [4H]
    peephole: None or (p_i, p_f, p_o) each [H] (GravesLSTM variant).
    Returns (outputs [T, B, H], (h_T, c_T)).
    """
    T, B, _ = x_tbf.shape
    H = u.shape[0]
    # one big MXU matmul for all timesteps' input projections
    xw = (x_tbf.reshape(T * B, -1) @ w + b).reshape(T, B, 4 * H)

    if h0 is None:
        h0 = jnp.zeros((B, H), dtype=x_tbf.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), dtype=x_tbf.dtype)

    def step(carry, xw_t):
        h, c = carry
        gates = xw_t + h @ u
        i, f, o, g = jnp.split(gates, 4, axis=-1)
        if peephole is not None:
            p_i, p_f, p_o = peephole
            i = i + c * p_i
            f = f + c * p_f
        i = gate_activation(i)
        f = gate_activation(f)
        g = activation(g)
        c_new = f * c + i * g
        if peephole is not None:
            o = o + c_new * p_o
        o = gate_activation(o)
        h_new = o * activation(c_new)
        return (h_new, c_new), h_new

    (h_t, c_t), ys = lax.scan(step, (h0, c0), xw)
    return ys, (h_t, c_t)


def simple_rnn_scan(x_tbf, w, u, b, h0=None, activation=jnp.tanh):
    """Elman RNN (reference: SimpleRnn). Same big-matmul-then-scan shape."""
    T, B, _ = x_tbf.shape
    H = u.shape[0]
    xw = (x_tbf.reshape(T * B, -1) @ w + b).reshape(T, B, H)
    if h0 is None:
        h0 = jnp.zeros((B, H), dtype=x_tbf.dtype)

    def step(h, xw_t):
        h_new = activation(xw_t + h @ u)
        return h_new, h_new

    h_t, ys = lax.scan(step, h0, xw)
    return ys, h_t


def gru_scan(x_tbf, w, u, b, h0=None, activation=jnp.tanh,
             gate_activation=jax.nn.sigmoid):
    """GRU. w: [nIn, 3H] (r, z, n), u: [H, 3H], b: [3H]."""
    T, B, _ = x_tbf.shape
    H = u.shape[0]
    xw = (x_tbf.reshape(T * B, -1) @ w + b).reshape(T, B, 3 * H)
    if h0 is None:
        h0 = jnp.zeros((B, H), dtype=x_tbf.dtype)
    u_rz, u_n = u[:, : 2 * H], u[:, 2 * H:]

    def step(h, xw_t):
        x_rz, x_n = xw_t[:, : 2 * H], xw_t[:, 2 * H:]
        rz = gate_activation(x_rz + h @ u_rz)
        r, z = jnp.split(rz, 2, axis=-1)
        n = activation(x_n + (r * h) @ u_n)
        h_new = (1 - z) * n + z * h
        return h_new, h_new

    h_t, ys = lax.scan(step, h0, xw)
    return ys, h_t

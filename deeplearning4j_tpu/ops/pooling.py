"""Pooling primitives (NHWC).

Reference: libnd4j maxpool2d/avgpool2d/pnormpool2d (SubsamplingLayer) and
global pooling reductions (GlobalPoolingLayer). lax.reduce_window is the
single underlying primitive; XLA fuses the divisor correction for avg
pooling.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.conv import _pair


def max_pool2d(x, kernel, stride, padding):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )

def avg_pool2d(x, kernel, stride, padding, count_include_pad=True):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    if count_include_pad and padding != "SAME":
        return summed / (k[0] * k[1])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return summed / counts


def pnorm_pool2d(x, kernel, stride, padding, p=2):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        jnp.power(jnp.abs(x), p), 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return jnp.power(summed, 1.0 / p)


def upsample2d(x, size):
    """Nearest-neighbour upsampling [B,H,W,C] (reference: Upsampling2D)."""
    sh, sw = _pair(size)
    x = jnp.repeat(x, sh, axis=1)
    return jnp.repeat(x, sw, axis=2)


def global_pool(x, pooling_type, axes, mask=None, pnorm=2):
    """Global pooling over `axes` with optional mask over those axes.

    Reference: GlobalPoolingLayer (used for masked RNN sequence pooling and
    CNN global pooling).
    """
    t = str(pooling_type).lower()
    if mask is not None:
        # mask must already be broadcastable to x (callers reshape, e.g.
        # [B,T] -> [B,1,T] for NCW recurrent data)
        m = jnp.broadcast_to(mask, x.shape)
        if t == "max":
            x = jnp.where(m > 0, x, -jnp.inf)
        else:
            x = x * m
        denom = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
    else:
        denom = None
    if t == "max":
        return jnp.max(x, axis=axes)
    if t == "sum":
        return jnp.sum(x, axis=axes)
    if t == "avg":
        if denom is not None:
            return jnp.sum(x, axis=axes) / denom
        return jnp.mean(x, axis=axes)
    if t == "pnorm":
        s = jnp.sum(jnp.power(jnp.abs(x), pnorm), axis=axes)
        return jnp.power(s, 1.0 / pnorm)
    raise ValueError(f"Unknown pooling type {pooling_type}")

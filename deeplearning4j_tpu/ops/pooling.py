"""Pooling primitives (NHWC).

Reference: libnd4j maxpool2d/avgpool2d/pnormpool2d (SubsamplingLayer) and
global pooling reductions (GlobalPoolingLayer). lax.reduce_window is the
single underlying primitive; XLA fuses the divisor correction for avg
pooling.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.conv import _pair

# Windows larger than this get the stock select-and-scatter gradient: the
# unrolled argmax backward emits k*k pad/where terms, which stops paying for
# itself (HLO bloat) well before 6x6.
_ARGMAX_BWD_MAX_WINDOW = 36

# Backward implementation switch. The argmax rewrite was built for TPU,
# where XLA's select-and-scatter materializes a single 206 MB op in the
# ResNet stem (BENCH_NOTES.md) — but the live-TPU A/B landed the OTHER
# way: on TPU v5e the stock gradient measures ~1.9x faster than the
# argmax form (8.99 vs 15.60 ms fwd+bwd at the stem-pool shape,
# BENCH_LIVE_r04.json), and on CPU it is ~5x faster (XLA-CPU rewrites
# select-and-scatter into a vectorized scatter). Stock is therefore the
# default on every backend; the argmax path stays available
# (DL4J_TPU_MAXPOOL_BWD=argmax) and gradient-parity-pinned for backends
# where the trade may differ. bench.py still A/Bs both per run.
_BACKWARD_IMPL = os.environ.get("DL4J_TPU_MAXPOOL_BWD", "stock").lower()
if _BACKWARD_IMPL not in ("argmax", "stock"):
    raise ValueError(
        f"DL4J_TPU_MAXPOOL_BWD must be 'argmax' or 'stock', got "
        f"{os.environ['DL4J_TPU_MAXPOOL_BWD']!r}")


def max_pool2d_reference(x, kernel, stride, padding):
    """Stock maxpool whose JAX gradient lowers to XLA select-and-scatter.

    Kept as the numerical oracle for `max_pool2d`'s custom backward (see
    tests/test_pooling_backward.py). Reference: libnd4j maxpool2d +
    cudnnPoolingBackward (CudnnSubsamplingHelper) — upstream likewise
    special-cases this backward off the generic path.
    """
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )


def _pool_pads(H, W, k, s, padding):
    """Resolve padding to explicit ((lo,hi),(lo,hi)) plus output dims."""
    if padding == "SAME":
        Ho = -(-H // s[0])
        Wo = -(-W // s[1])
        th = max((Ho - 1) * s[0] + k[0] - H, 0)
        tw = max((Wo - 1) * s[1] + k[1] - W, 0)
        pads = ((th // 2, th - th // 2), (tw // 2, tw - tw // 2))
    else:
        pads = (tuple(padding[0]), tuple(padding[1]))
        Ho = (H + pads[0][0] + pads[0][1] - k[0]) // s[0] + 1
        Wo = (W + pads[1][0] + pads[1][1] - k[1]) // s[1] + 1
    return pads, Ho, Wo


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool2d_argmax(x, k, s, padding):
    return max_pool2d_reference(x, k, s, padding)


def _max_pool2d_argmax_fwd(x, k, s, padding):
    return max_pool2d_reference(x, k, s, padding), x


def _max_pool2d_argmax_bwd(k, s, padding, x, dy):
    # select-and-scatter is unfusable and HBM-heavy on TPU (206 MB
    # materialized for the ResNet-50 stem pool at batch 128). Instead:
    # recompute the per-window argmax (first-match, matching XLA's
    # ge-select tie rule) from the saved input with k*k strided slices,
    # then route dy back with k*k interior-padded adds — all fusable
    # elementwise/pad HLOs.
    B, H, W, C = x.shape
    pads, Ho, Wo = _pool_pads(H, W, k, s, padding)
    Hp = H + pads[0][0] + pads[0][1]
    Wp = W + pads[1][0] + pads[1][1]
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)),
                 constant_values=-jnp.inf)
    best = None
    besti = None
    j = 0
    for dh in range(k[0]):
        for dw in range(k[1]):
            v = lax.slice(xp, (0, dh, dw, 0),
                          (B, dh + (Ho - 1) * s[0] + 1,
                           dw + (Wo - 1) * s[1] + 1, C),
                          (1, s[0], s[1], 1))
            if best is None:
                best = v
                besti = jnp.zeros(v.shape, jnp.int32)
            else:
                take = v > best  # strict >: first (lowest-index) tie wins
                best = jnp.where(take, v, best)
                besti = jnp.where(take, j, besti)
            j += 1
    zero = jnp.zeros((), dy.dtype)
    dxp = jnp.zeros((B, Hp, Wp, C), dy.dtype)
    j = 0
    for dh in range(k[0]):
        for dw in range(k[1]):
            contrib = jnp.where(besti == j, dy, zero)
            dxp = dxp + lax.pad(
                contrib, zero,
                ((0, 0, 0),
                 (dh, Hp - dh - ((Ho - 1) * s[0] + 1), s[0] - 1),
                 (dw, Wp - dw - ((Wo - 1) * s[1] + 1), s[1] - 1),
                 (0, 0, 0)))
            j += 1
    dx = lax.slice(dxp, (0, pads[0][0], pads[1][0], 0),
                   (B, pads[0][0] + H, pads[1][0] + W, C))
    return (dx,)


_max_pool2d_argmax.defvjp(_max_pool2d_argmax_fwd, _max_pool2d_argmax_bwd)


def max_pool2d(x, kernel, stride, padding):
    """Max pooling with an argmax-routed custom backward.

    Known tradeoff: the custom_vjp blocks FORWARD-mode autodiff —
    jax.jvp/jacfwd through windows <= _ARGMAX_BWD_MAX_WINDOW raise
    TypeError (larger windows fall back to the stock path and still
    support it). Nothing in this framework differentiates pooling
    forward-mode (training and gradchecks are reverse-mode); the vjp
    form is kept because it controls the residual exactly — save x
    only, recompute the argmax in the backward — where a custom_jvp
    formulation would leave k*k window masks as residuals. Use
    max_pool2d_reference if you need jacfwd.
    """
    k, s = _pair(kernel), _pair(stride)
    if isinstance(padding, str):
        if padding != "SAME":
            raise ValueError(
                f"string padding must be 'SAME', got {padding!r} "
                "(use explicit ((lo,hi),(lo,hi)) pairs otherwise)")
        pad = "SAME"
    else:
        pad = (tuple(padding[0]), tuple(padding[1]))
    if _BACKWARD_IMPL == "stock" or k[0] * k[1] > _ARGMAX_BWD_MAX_WINDOW:
        return max_pool2d_reference(x, k, s, pad)
    return _max_pool2d_argmax(x, k, s, pad)

def avg_pool2d(x, kernel, stride, padding, count_include_pad=True):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    if count_include_pad and padding != "SAME":
        return summed / (k[0] * k[1])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return summed / counts


def pnorm_pool2d(x, kernel, stride, padding, p=2):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        jnp.power(jnp.abs(x), p), 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return jnp.power(summed, 1.0 / p)


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def max_pool3d(x, kernel, stride, padding):
    """[B,D,H,W,C] max pooling (reference: Subsampling3DLayer). Stock
    gradient — 3D pooling is not on the flagship hot path."""
    k, s = _triple(kernel), _triple(stride)
    pad = padding if padding == "SAME" else \
        ((0, 0),) + tuple(padding) + ((0, 0),)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )


def avg_pool3d(x, kernel, stride, padding, count_include_pad=True):
    k, s = _triple(kernel), _triple(stride)
    pad = padding if padding == "SAME" else \
        ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    if count_include_pad and padding != "SAME":
        return summed / (k[0] * k[1] * k[2])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return summed / counts


def upsample2d(x, size):
    """Nearest-neighbour upsampling [B,H,W,C] (reference: Upsampling2D)."""
    sh, sw = _pair(size)
    x = jnp.repeat(x, sh, axis=1)
    return jnp.repeat(x, sw, axis=2)


def global_pool(x, pooling_type, axes, mask=None, pnorm=2):
    """Global pooling over `axes` with optional mask over those axes.

    Reference: GlobalPoolingLayer (used for masked RNN sequence pooling and
    CNN global pooling).
    """
    t = str(pooling_type).lower()
    if mask is not None:
        # mask must already be broadcastable to x (callers reshape, e.g.
        # [B,T] -> [B,1,T] for NCW recurrent data)
        m = jnp.broadcast_to(mask, x.shape)
        if t == "max":
            x = jnp.where(m > 0, x, -jnp.inf)
        else:
            x = x * m
        denom = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
    else:
        denom = None
    if t == "max":
        return jnp.max(x, axis=axes)
    if t == "sum":
        return jnp.sum(x, axis=axes)
    if t == "avg":
        if denom is not None:
            return jnp.sum(x, axis=axes) / denom
        return jnp.mean(x, axis=axes)
    if t == "pnorm":
        s = jnp.sum(jnp.power(jnp.abs(x), pnorm), axis=axes)
        return jnp.power(s, 1.0 / pnorm)
    raise ValueError(f"Unknown pooling type {pooling_type}")

"""Pooling primitives (NHWC).

Reference: libnd4j maxpool2d/avgpool2d/pnormpool2d (SubsamplingLayer) and
global pooling reductions (GlobalPoolingLayer). lax.reduce_window is the
single underlying primitive; XLA fuses the divisor correction for avg
pooling.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.conv import _pair

# Windows larger than this get the stock select-and-scatter gradient: the
# unrolled argmax backward emits k*k pad/where terms, which stops paying for
# itself (HLO bloat) well before 6x6.
_ARGMAX_BWD_MAX_WINDOW = 36

# Backward implementation switch. The argmax rewrite was built for TPU,
# where XLA's select-and-scatter materializes a single 206 MB op in the
# ResNet stem (BENCH_NOTES.md) — but the live-TPU A/B landed the OTHER
# way: on TPU v5e the stock gradient measures ~1.9x faster than the
# argmax form (8.99 vs 15.60 ms fwd+bwd at the stem-pool shape,
# BENCH_LIVE_r04.json), and on CPU it is ~5x faster (XLA-CPU rewrites
# select-and-scatter into a vectorized scatter). Stock is therefore the
# default on every backend; the argmax path stays available
# (DL4J_TPU_MAXPOOL_BWD=argmax) and gradient-parity-pinned for backends
# where the trade may differ. bench.py still A/Bs both per run.
#
# Round 12 adds a third impl, "indices": the forward computes max AND
# the per-window argmax in one fused pass of k*k strided slices and
# saves the winner index as an INT8 residual (k*k <= 36 fits), so the
# backward never re-reads x and never lowers to select-and-scatter.
# For NON-OVERLAPPING windows (stride >= kernel — every pool in the
# zoo flagships) the backward is ONE elementwise pass: upsample dy,
# compare the saved index against a static in-window offset pattern.
# Measured on XLA:CPU it cuts the LeNet b64 train step from 129.1 MB
# to 69.2 MB (-46%) with BITWISE-equal gradients (first-match tie
# rule, same as select-and-scatter's ge-select). Overlapping windows
# keep the stock gradient under "indices" (the interior-padded
# scatter-add form measured WORSE than select-and-scatter on CPU:
# 131.3 vs 129.1 MB). Not the default — the runtime autotune arbiter
# (runtime/autotune.py, docs/AUTOTUNE.md) picks it per backend from
# measurement instead of taste.
_BACKWARD_IMPLS = ("stock", "argmax", "indices")
_BACKWARD_IMPL = os.environ.get("DL4J_TPU_MAXPOOL_BWD", "stock").lower()
if _BACKWARD_IMPL not in _BACKWARD_IMPLS:
    raise ValueError(
        f"DL4J_TPU_MAXPOOL_BWD must be one of {_BACKWARD_IMPLS}, got "
        f"{os.environ['DL4J_TPU_MAXPOOL_BWD']!r}")

#: global max-pool backward: "stock" = jnp.max autodiff (re-reads x in
#: the backward to rebuild the winner mask; ties each receive the full
#: cotangent), "indices" = save the int32 argmax in the forward, the
#: backward is one elementwise pass with no x re-read (first-match tie
#: rule). Tunable per backend by the autotune arbiter.
_GLOBAL_IMPLS = ("stock", "indices")
_GLOBAL_MAXPOOL_BWD = os.environ.get(
    "DL4J_TPU_GLOBAL_MAXPOOL_BWD", "stock").lower()
if _GLOBAL_MAXPOOL_BWD not in _GLOBAL_IMPLS:
    raise ValueError(
        f"DL4J_TPU_GLOBAL_MAXPOOL_BWD must be one of {_GLOBAL_IMPLS}, "
        f"got {os.environ['DL4J_TPU_GLOBAL_MAXPOOL_BWD']!r}")


def set_maxpool_bwd(impl):
    """Set the max_pool2d backward impl (the autotune arbiter's entry;
    DL4J_TPU_MAXPOOL_BWD seeds the initial value). Returns the previous
    impl. Callers must re-jit (the AOT ambient fingerprint carries the
    value, so cached executables never cross impls)."""
    global _BACKWARD_IMPL
    impl = str(impl).lower()
    if impl not in _BACKWARD_IMPLS:
        raise ValueError(
            f"maxpool_bwd must be one of {_BACKWARD_IMPLS}, got {impl!r}")
    old, _BACKWARD_IMPL = _BACKWARD_IMPL, impl
    return old


def set_global_maxpool_bwd(impl):
    """Set the global_pool max backward impl; returns the previous."""
    global _GLOBAL_MAXPOOL_BWD
    impl = str(impl).lower()
    if impl not in _GLOBAL_IMPLS:
        raise ValueError(
            f"global_maxpool_bwd must be one of {_GLOBAL_IMPLS}, "
            f"got {impl!r}")
    old, _GLOBAL_MAXPOOL_BWD = _GLOBAL_MAXPOOL_BWD, impl
    return old


def max_pool2d_reference(x, kernel, stride, padding):
    """Stock maxpool whose JAX gradient lowers to XLA select-and-scatter.

    Kept as the numerical oracle for `max_pool2d`'s custom backward (see
    tests/test_pooling_backward.py). Reference: libnd4j maxpool2d +
    cudnnPoolingBackward (CudnnSubsamplingHelper) — upstream likewise
    special-cases this backward off the generic path.
    """
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )


def _pool_pads(H, W, k, s, padding):
    """Resolve padding to explicit ((lo,hi),(lo,hi)) plus output dims."""
    if padding == "SAME":
        Ho = -(-H // s[0])
        Wo = -(-W // s[1])
        th = max((Ho - 1) * s[0] + k[0] - H, 0)
        tw = max((Wo - 1) * s[1] + k[1] - W, 0)
        pads = ((th // 2, th - th // 2), (tw // 2, tw - tw // 2))
    else:
        pads = (tuple(padding[0]), tuple(padding[1]))
        Ho = (H + pads[0][0] + pads[0][1] - k[0]) // s[0] + 1
        Wo = (W + pads[1][0] + pads[1][1] - k[1]) // s[1] + 1
    return pads, Ho, Wo


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool2d_argmax(x, k, s, padding):
    return max_pool2d_reference(x, k, s, padding)


def _max_pool2d_argmax_fwd(x, k, s, padding):
    return max_pool2d_reference(x, k, s, padding), x


def _max_pool2d_argmax_bwd(k, s, padding, x, dy):
    # select-and-scatter is unfusable and HBM-heavy on TPU (206 MB
    # materialized for the ResNet-50 stem pool at batch 128). Instead:
    # recompute the per-window argmax (first-match, matching XLA's
    # ge-select tie rule) from the saved input with k*k strided slices,
    # then route dy back with k*k interior-padded adds — all fusable
    # elementwise/pad HLOs.
    B, H, W, C = x.shape
    pads, Ho, Wo = _pool_pads(H, W, k, s, padding)
    Hp = H + pads[0][0] + pads[0][1]
    Wp = W + pads[1][0] + pads[1][1]
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)),
                 constant_values=-jnp.inf)
    best = None
    besti = None
    j = 0
    for dh in range(k[0]):
        for dw in range(k[1]):
            v = lax.slice(xp, (0, dh, dw, 0),
                          (B, dh + (Ho - 1) * s[0] + 1,
                           dw + (Wo - 1) * s[1] + 1, C),
                          (1, s[0], s[1], 1))
            if best is None:
                best = v
                besti = jnp.zeros(v.shape, jnp.int32)
            else:
                take = v > best  # strict >: first (lowest-index) tie wins
                best = jnp.where(take, v, best)
                besti = jnp.where(take, j, besti)
            j += 1
    zero = jnp.zeros((), dy.dtype)
    dxp = jnp.zeros((B, Hp, Wp, C), dy.dtype)
    j = 0
    for dh in range(k[0]):
        for dw in range(k[1]):
            contrib = jnp.where(besti == j, dy, zero)
            dxp = dxp + lax.pad(
                contrib, zero,
                ((0, 0, 0),
                 (dh, Hp - dh - ((Ho - 1) * s[0] + 1), s[0] - 1),
                 (dw, Wp - dw - ((Wo - 1) * s[1] + 1), s[1] - 1),
                 (0, 0, 0)))
            j += 1
    dx = lax.slice(dxp, (0, pads[0][0], pads[1][0], 0),
                   (B, pads[0][0] + H, pads[1][0] + W, C))
    return (dx,)


_max_pool2d_argmax.defvjp(_max_pool2d_argmax_fwd, _max_pool2d_argmax_bwd)


def _max_pool2d_indices_fwd_math(x, k, s, padding):
    """Fused max + per-window argmax in one pass of k*k strided slices.
    Returns (y, besti int8) — strict > keeps the FIRST (lowest-index)
    tie, the same rule as XLA select-and-scatter's ge-select and the
    argmax path, so all three impls are bitwise-interchangeable."""
    B, H, W, C = x.shape
    pads, Ho, Wo = _pool_pads(H, W, k, s, padding)
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)),
                 constant_values=-jnp.inf)
    best = None
    besti = None
    j = 0
    for dh in range(k[0]):
        for dw in range(k[1]):
            v = lax.slice(xp, (0, dh, dw, 0),
                          (B, dh + (Ho - 1) * s[0] + 1,
                           dw + (Wo - 1) * s[1] + 1, C),
                          (1, s[0], s[1], 1))
            if best is None:
                best = v
                besti = jnp.zeros(v.shape, jnp.int8)
            else:
                take = v > best
                best = jnp.where(take, v, best)
                besti = jnp.where(take, jnp.int8(j), besti)
            j += 1
    return best, besti


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool2d_indices(x, k, s, padding):
    return _max_pool2d_indices_fwd_math(x, k, s, padding)[0]


def _max_pool2d_indices_fwd(x, k, s, padding):
    y, besti = _max_pool2d_indices_fwd_math(x, k, s, padding)
    # residuals: the int8 winner table (pooled scale) plus a ZERO-BYTE
    # carrier whose aval remembers the input's H,W (custom_vjp residuals
    # must be jax types; the shape rides the aval, no data moves)
    return y, (besti, jnp.zeros((x.shape[1], x.shape[2], 0), jnp.int8))


def _max_pool2d_indices_bwd(k, s, padding, res, dy):
    # non-overlapping windows only (stride >= kernel; max_pool2d routes
    # overlapping windows to the stock path): every padded input
    # position lands in AT MOST one window, so dy routes back in ONE
    # elementwise pass — upsample dy/besti by the stride and keep the
    # positions whose in-window offset matches the saved winner. No
    # scatter, no select-and-scatter, no re-read of x.
    besti, hw = res
    H, W = hw.shape[0], hw.shape[1]
    B, Ho, Wo, C = dy.shape
    pads, _, _ = _pool_pads(H, W, k, s, padding)
    dy_up = jnp.repeat(jnp.repeat(dy, s[0], axis=1), s[1], axis=2)
    bi_up = jnp.repeat(jnp.repeat(besti, s[0], axis=1), s[1], axis=2)
    Hc, Wc = Ho * s[0], Wo * s[1]  # padded coords covered by windows
    hp = jnp.arange(Hc) % s[0]     # in-window row/col offsets
    wp = jnp.arange(Wc) % s[1]
    jpat = (hp[:, None] * k[1] + wp[None, :]).astype(jnp.int8)
    covered = (hp[:, None] < k[0]) & (wp[None, :] < k[1])
    m = (bi_up == jpat[None, :, :, None]) & covered[None, :, :, None]
    dxp = jnp.where(m, dy_up, jnp.zeros((), dy.dtype))
    # padded coords [p_lo, p_lo + extent); window coverage can stop
    # short of the input extent (truncation) — pad the tail with zeros
    need_h, need_w = pads[0][0] + H, pads[1][0] + W
    if need_h > Hc or need_w > Wc:
        dxp = jnp.pad(dxp, ((0, 0), (0, max(0, need_h - Hc)),
                            (0, max(0, need_w - Wc)), (0, 0)))
    dx = lax.slice(dxp, (0, pads[0][0], pads[1][0], 0),
                   (B, need_h, need_w, C))
    return (dx,)


_max_pool2d_indices.defvjp(_max_pool2d_indices_fwd, _max_pool2d_indices_bwd)


def _flatten_pool_spec(shape, axes):
    """(pre, pool, post) sizes for a CONTIGUOUS run of pooled axes."""
    a0, a1 = axes[0], axes[-1]
    pre = 1
    for d in shape[:a0]:
        pre *= d
    pool = 1
    for d in shape[a0:a1 + 1]:
        pool *= d
    post = 1
    for d in shape[a1 + 1:]:
        post *= d
    return pre, pool, post


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _global_max_indices(x, axes):
    return jnp.max(x, axis=axes)


def _global_max_indices_fwd(x, axes):
    pre, pool, post = _flatten_pool_spec(x.shape, axes)
    xr = x.reshape(pre, pool, post)
    y = jnp.max(xr, axis=1)
    idx = jnp.argmax(xr, axis=1).astype(jnp.int32)
    out_shape = tuple(d for i, d in enumerate(x.shape) if i not in axes)
    # zero-byte carrier: pooled dims ride the aval, everything else is 0
    carrier = jnp.zeros(tuple(d if i in axes else 0
                              for i, d in enumerate(x.shape)), jnp.int8)
    return y.reshape(out_shape), (idx, carrier)


def _global_max_indices_bwd(axes, res, dy):
    idx, carrier = res
    out_dims = iter(dy.shape)
    full_shape = tuple(carrier.shape[i] if i in axes else next(out_dims)
                       for i in range(carrier.ndim))
    pre, pool, post = _flatten_pool_spec(full_shape, axes)
    dyr = dy.reshape(pre, post)
    # first-match winner only (stock jnp.max autodiff hands EVERY tied
    # maximum the full cotangent; see tests/test_pooling_backward.py)
    mask = lax.broadcasted_iota(jnp.int32, (pre, pool, post), 1) \
        == idx[:, None, :]
    dxr = jnp.where(mask, dyr[:, None, :], jnp.zeros((), dy.dtype))
    return (dxr.reshape(full_shape),)


_global_max_indices.defvjp(_global_max_indices_fwd, _global_max_indices_bwd)


def max_pool2d(x, kernel, stride, padding):
    """Max pooling with an argmax-routed custom backward.

    Known tradeoff: the custom_vjp blocks FORWARD-mode autodiff —
    jax.jvp/jacfwd through windows <= _ARGMAX_BWD_MAX_WINDOW raise
    TypeError (larger windows fall back to the stock path and still
    support it). Nothing in this framework differentiates pooling
    forward-mode (training and gradchecks are reverse-mode); the vjp
    form is kept because it controls the residual exactly — save x
    only, recompute the argmax in the backward — where a custom_jvp
    formulation would leave k*k window masks as residuals. Use
    max_pool2d_reference if you need jacfwd.
    """
    k, s = _pair(kernel), _pair(stride)
    if isinstance(padding, str):
        if padding != "SAME":
            raise ValueError(
                f"string padding must be 'SAME', got {padding!r} "
                "(use explicit ((lo,hi),(lo,hi)) pairs otherwise)")
        pad = "SAME"
    else:
        pad = (tuple(padding[0]), tuple(padding[1]))
    impl = _choose_pool_bwd(k, s, impl=_BACKWARD_IMPL)
    if impl == "indices":
        return _max_pool2d_indices(x, k, s, pad)
    if impl == "argmax":
        return _max_pool2d_argmax(x, k, s, pad)
    return max_pool2d_reference(x, k, s, pad)


def _choose_pool_bwd(k, s, *, impl):
    """Pure dispatch decision -> 'stock' | 'argmax' | 'indices' for a
    (kernel, stride) pair under the configured impl — split out so
    tests pin the routing without running a kernel (the _choose_impl
    pattern from ops/pallas_attention.py). 'indices' requires
    non-overlapping windows (stride >= kernel): overlapping pools would
    need the interior-padded scatter-add backward, which measured WORSE
    than select-and-scatter on XLA:CPU — they keep the stock gradient."""
    if impl == "indices":
        if s[0] >= k[0] and s[1] >= k[1] \
                and k[0] * k[1] <= _ARGMAX_BWD_MAX_WINDOW:
            return "indices"
        return "stock"
    if impl == "argmax":
        if k[0] * k[1] > _ARGMAX_BWD_MAX_WINDOW:
            return "stock"
        return "argmax"
    return "stock"

def avg_pool2d(x, kernel, stride, padding, count_include_pad=True):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    if count_include_pad and padding != "SAME":
        return summed / (k[0] * k[1])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return summed / counts


def pnorm_pool2d(x, kernel, stride, padding, p=2):
    k, s = _pair(kernel), _pair(stride)
    pad = padding if padding == "SAME" else ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        jnp.power(jnp.abs(x), p), 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], 1),
        window_strides=(1, s[0], s[1], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return jnp.power(summed, 1.0 / p)


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(v)


def max_pool3d(x, kernel, stride, padding):
    """[B,D,H,W,C] max pooling (reference: Subsampling3DLayer). Stock
    gradient — 3D pooling is not on the flagship hot path."""
    k, s = _triple(kernel), _triple(stride)
    pad = padding if padding == "SAME" else \
        ((0, 0),) + tuple(padding) + ((0, 0),)
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )


def avg_pool3d(x, kernel, stride, padding, count_include_pad=True):
    k, s = _triple(kernel), _triple(stride)
    pad = padding if padding == "SAME" else \
        ((0, 0),) + tuple(padding) + ((0, 0),)
    summed = lax.reduce_window(
        x, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    if count_include_pad and padding != "SAME":
        return summed / (k[0] * k[1] * k[2])
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(
        ones, 0.0, lax.add,
        window_dimensions=(1, k[0], k[1], k[2], 1),
        window_strides=(1, s[0], s[1], s[2], 1),
        padding=pad if padding != "SAME" else "SAME",
    )
    return summed / counts


def upsample2d(x, size):
    """Nearest-neighbour upsampling [B,H,W,C] (reference: Upsampling2D)."""
    sh, sw = _pair(size)
    x = jnp.repeat(x, sh, axis=1)
    return jnp.repeat(x, sw, axis=2)


def global_pool(x, pooling_type, axes, mask=None, pnorm=2):
    """Global pooling over `axes` with optional mask over those axes.

    Reference: GlobalPoolingLayer (used for masked RNN sequence pooling and
    CNN global pooling).
    """
    t = str(pooling_type).lower()
    # normalize negative axes up front: the indices route's flatten
    # arithmetic and membership tests assume positive indices (a
    # caller passing (-2, -1) — valid for jnp.max — must not crash
    # only once the arbiter selects "indices")
    axes = tuple(sorted(a % x.ndim for a in axes))
    if (t == "max" and mask is None and _GLOBAL_MAXPOOL_BWD == "indices"
            and axes == tuple(range(axes[0], axes[-1] + 1))):
        # saved-indices backward (arbiter-selected): one elementwise
        # pass, no x re-read. Contiguous pooled axes only (every call
        # site: (1,2) NHWC, (1,2,3) NDHWC, (2,) NCW) — anything else
        # keeps the stock gradient below.
        return _global_max_indices(x, axes)
    if mask is not None:
        # mask must already be broadcastable to x (callers reshape, e.g.
        # [B,T] -> [B,1,T] for NCW recurrent data)
        m = jnp.broadcast_to(mask, x.shape)
        if t == "max":
            x = jnp.where(m > 0, x, -jnp.inf)
        else:
            x = x * m
        denom = jnp.maximum(jnp.sum(m, axis=axes), 1.0)
    else:
        denom = None
    if t == "max":
        return jnp.max(x, axis=axes)
    if t == "sum":
        return jnp.sum(x, axis=axes)
    if t == "avg":
        if denom is not None:
            return jnp.sum(x, axis=axes) / denom
        return jnp.mean(x, axis=axes)
    if t == "pnorm":
        s = jnp.sum(jnp.power(jnp.abs(x), pnorm), axis=axes)
        return jnp.power(s, 1.0 / pnorm)
    raise ValueError(f"Unknown pooling type {pooling_type}")

"""Flash attention as a hand-written Pallas TPU kernel.

Reference: the upstream attention layers (SelfAttentionLayer et al.) run
through cuDNN-era fused kernels on GPU; SURVEY.md row 21 commits this repo
to a flash-style Pallas kernel for the TPU hot path, with the lax.scan
blockwise form (ops/attention.py) as the portable fallback.

Design: one grid step per (batch*heads, q-block); the kernel streams KV
blocks through VMEM with an online-softmax recurrence (Rabe & Staats /
FlashAttention), so the [T, T] score matrix never materialises in HBM.
Score matmuls hit the MXU with fp32 accumulation regardless of the input
dtype (bf16 inputs stay bf16 in HBM/VMEM).

Backward: hand-written flash backward kernels (default, round 12) — the
forward additionally emits the per-row logsumexp, and two Pallas kernels
rebuild the probabilities blockwise from (q, k, lse) to produce dq and
dk/dv with fp32 accumulators, O(T) memory, and no [T, T] score
materialisation (the FlashAttention-2 backward recurrence). The previous
strategy — recompute the blockwise forward under jax.vjp and let XLA
differentiate it — stays available as DL4J_TPU_FLASH_BWD=recompute (and
as the autotune arbiter's alternative candidate); it costs extra
activation-scale HBM traffic for the scan carries, which is exactly the
bill the round-5 attribution named.

`flash_attention` transparently falls back to `blockwise_attention` when
Pallas/TPU is unavailable (CPU tests, masks, tiny shapes), so callers can
use it unconditionally.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.attention import blockwise_attention

_NEG_INF = -1e30

#: logsumexp sentinel for rows with NO valid key (fully padded): large
#: POSITIVE, so the backward's exp(s - lse) underflows to exactly 0 for
#: every key instead of overflowing (a -inf lse would give exp(+inf))
_LSE_EMPTY = 1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None, *,
                block_k: int, Tk: int, causal: bool, block_q: int,
                scale: float):
    """One (bh, q-block) program. Refs carry a leading singleton bh axis:
    q_ref [1, bq, D], k_ref/v_ref [1, Tk_pad, D]. Emits the output
    block and — only when the caller requested it (the kernel-backward
    path; inference and the recompute backward skip the extra HBM
    write) — the per-row logsumexp."""
    from jax.experimental import pallas as pl

    _, bq, D = q_ref.shape
    Tk_pad = k_ref.shape[1]
    n_kb = Tk_pad // block_k
    iq = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale

    acc0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        acc, m, l = carry
        kj = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = k_pos < Tk
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        # explicit zero where invalid: a fully-masked block's sentinel
        # otherwise normalises itself away (exp(s - m) == 1)
        p = jnp.where(valid, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    if causal:
        # skip KV blocks entirely above the diagonal for this q block
        n_used = jnp.minimum(
            (iq + 1) * block_q + block_k - 1, Tk_pad) // block_k
    else:
        n_used = n_kb
    acc, m, l = jax.lax.fori_loop(0, n_used, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.where(l == 0, 1.0, l)[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        lse_ref[0] = jnp.where(l > 0, m + jnp.log(l), _LSE_EMPTY)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, block_k: int, Tk: int, causal: bool,
                   block_q: int, scale: float):
    """dq for one (bh, q-block): stream KV blocks, rebuild p from the
    saved logsumexp (no second online softmax), accumulate
    dq += (p * (dp - delta)) @ k in fp32. delta = rowsum(do * o) is
    precomputed outside (one elementwise pass)."""
    from jax.experimental import pallas as pl

    _, bq, D = q_ref.shape
    Tk_pad = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]
    delta = delta_ref[0]
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, dq):
        kj = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = k_pos < Tk
        if causal:
            valid = valid & (q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(s - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(do, vj, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(ds, kj, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    if causal:
        n_used = jnp.minimum(
            (iq + 1) * block_q + block_k - 1, Tk_pad) // block_k
    else:
        n_used = Tk_pad // block_k
    dq = jax.lax.fori_loop(0, n_used, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, block_q: int, Tk: int,
                    causal: bool, block_k: int, scale: float):
    """dk and dv for one (bh, kv-block): stream q blocks (causal skips
    the blocks fully above this kv block's diagonal), accumulate
    dv += p^T @ do and dk += (p * (dp - delta))^T @ (q * scale)."""
    from jax.experimental import pallas as pl

    _, bk, D = k_ref.shape
    Tq_pad = q_ref.shape[1]
    jk = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, bk), 1)
    k_valid = (jk * block_k
               + jax.lax.broadcasted_iota(jnp.int32, (bk,), 0)) < Tk

    def body(i, carry):
        dk, dv = carry
        qi = q_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32) * scale
        doi = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lsei = lse_ref[0, pl.ds(i * block_q, block_q)]
        deltai = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(qi, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        valid = k_pos < Tk
        if causal:
            valid = valid & (q_pos >= k_pos)
        p = jnp.where(valid, jnp.exp(s - lsei[:, None]), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p, doi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(doi, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - deltai[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds, qi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    i0 = (jk * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        i0, Tq_pad // block_q, body,
        (jnp.zeros((bk, D), jnp.float32), jnp.zeros((bk, D), jnp.float32)))
    # zero the KV padding rows so the slice-off can't leak garbage
    dk_ref[0] = jnp.where(k_valid[:, None], dk, 0.0).astype(dk_ref.dtype)
    dv_ref[0] = jnp.where(k_valid[:, None], dv, 0.0).astype(dv_ref.dtype)


# test hook: when True, pallas_call runs in interpreter mode (works on CPU)
# and flash_attention always takes the kernel path regardless of backend
# (tests/test_attention.py::TestFlashKernel sets this to check the kernel
# against the fused reference, forward and backward)
_INTERPRET = False

#: backward strategy for the pallas kernel path: "kernel" (default) =
#: the hand-written flash backward kernels (_bwd_dq_kernel /
#: _bwd_dkv_kernel; probabilities rebuilt from the saved logsumexp);
#: "recompute" = jax.vjp through the blockwise scan (the pre-round-12
#: behavior). Tunable via the autotune arbiter; part of the AOT
#: ambient fingerprint.
_BWD_IMPLS = ("kernel", "recompute")
_BWD_IMPL = os.environ.get("DL4J_TPU_FLASH_BWD", "kernel").lower()
if _BWD_IMPL not in _BWD_IMPLS:
    raise ValueError(
        f"DL4J_TPU_FLASH_BWD must be one of {_BWD_IMPLS}, got "
        f"{os.environ['DL4J_TPU_FLASH_BWD']!r}")


def set_flash_bwd(impl):
    """Set the flash-attention backward impl; returns the previous
    value (the autotune arbiter's entry)."""
    global _BWD_IMPL
    impl = str(impl).lower()
    if impl not in _BWD_IMPLS:
        raise ValueError(
            f"flash_bwd must be one of {_BWD_IMPLS}, got {impl!r}")
    old, _BWD_IMPL = _BWD_IMPL, impl
    return old


def _pad_flat(x, T, pad):
    """[B,H,T,D] -> [B*H, T+pad, D] (zero row padding)."""
    B, H, _, D = x.shape
    xf = x.reshape(B * H, T, D)
    if pad:
        xf = jnp.pad(xf, ((0, 0), (0, pad), (0, 0)))
    return xf


def _flash_fwd_impl(q, k, v, causal, block_q, block_k, need_lse=True):
    """q [B,H,Tq,D], k/v [B,H,Tk,D] -> ([B,H,Tq,D], lse [B*H,Tq_pad]
    or None) via pallas_call. The logsumexp (padded flat form — the
    backward kernels reuse it without reshaping) is only materialised
    when requested: inference and the recompute backward skip the
    extra (B*H, Tq) fp32 HBM write entirely."""
    from jax.experimental import pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    pq = (-Tq) % bq
    pk = (-Tk) % bk
    qf = _pad_flat(q, Tq, pq)
    kf = _pad_flat(k, Tk, pk)
    vf = _pad_flat(v, Tk, pk)
    Tqp, Tkp = Tq + pq, Tk + pk

    kernel = functools.partial(
        _fwd_kernel, block_k=bk, Tk=Tk, causal=causal, block_q=bq,
        scale=1.0 / (D ** 0.5))
    out_specs = [pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0))]
    out_shape = [jax.ShapeDtypeStruct((B * H, Tqp, D), q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((1, bq), lambda bh, i: (bh, i)))
        out_shape.append(jax.ShapeDtypeStruct((B * H, Tqp),
                                              jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(B * H, Tqp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=_INTERPRET,
    )(qf, kf, vf)
    out, lse = (res if need_lse else (res[0], None))
    return out[:, :Tq].reshape(B, H, Tq, D), lse


def _flash_bwd_impl(q, k, v, o, lse, do, causal, block_q, block_k):
    """The flash backward: dq kernel over q blocks, dk/dv kernel over
    KV blocks. delta = rowsum(do * o) is one elementwise pass; p is
    rebuilt blockwise from the saved logsumexp — no [T,T] buffer, no
    second online softmax, fp32 accumulators throughout."""
    from jax.experimental import pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    pq = (-Tq) % bq
    pk = (-Tk) % bk
    Tqp, Tkp = Tq + pq, Tk + pk
    qf = _pad_flat(q, Tq, pq)
    dof = _pad_flat(do, Tq, pq)
    of = _pad_flat(o, Tq, pq)
    kf = _pad_flat(k, Tk, pk)
    vf = _pad_flat(v, Tk, pk)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)
    scale = 1.0 / (D ** 0.5)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=bk, Tk=Tk,
                          causal=causal, block_q=bq, scale=scale),
        grid=(B * H, Tqp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, bq), lambda bh, i: (bh, i)),
            pl.BlockSpec((1, bq), lambda bh, i: (bh, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tqp, D), q.dtype),
        interpret=_INTERPRET,
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=bq, Tk=Tk,
                          causal=causal, block_k=bk, scale=scale),
        grid=(B * H, Tkp // bk),
        in_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, Tqp, D), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, Tqp, D), lambda bh, j: (bh, 0, 0)),
            pl.BlockSpec((1, Tqp), lambda bh, j: (bh, 0)),
            pl.BlockSpec((1, Tqp), lambda bh, j: (bh, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda bh, j: (bh, j, 0)),
            pl.BlockSpec((1, bk, D), lambda bh, j: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tkp, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tkp, D), v.dtype),
        ],
        interpret=_INTERPRET,
    )(kf, vf, qf, dof, lse, delta)
    return (dq[:, :Tq].reshape(B, H, Tq, D),
            dk[:, :Tk].reshape(B, H, Tk, D),
            dv[:, :Tk].reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    # primal (no differentiation): never materialise the lse
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                           need_lse=False)[0]


def _flash_fwd(q, k, v, causal, block_q, block_k):
    # the bwd strategy decides the residuals at trace time: the kernel
    # backward needs (o, lse); the recompute backward re-runs the
    # blockwise forward from (q, k, v) alone and must not pay the lse
    # write or carry dead residuals
    need = _BWD_IMPL == "kernel"
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               need_lse=need)
    # o rides as a residual UNPADDED: it is the primal output, so the
    # buffer is shared with whatever the caller keeps alive anyway
    return out, (q, k, v, out if need else None, lse)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if lse is not None:
        # (checking the RESIDUALS, not _BWD_IMPL again: a knob flip
        # between the fwd and bwd trace must not mismatch them)
        return _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q,
                               block_k)
    # recompute-VJP through the O(T)-memory blockwise reference
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, block_size=block_k,
                                               causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# below this sequence length the fused XLA attention wins: the [T,T]
# score tile fits comfortably on-chip and pallas_call launch overhead
# isn't amortised
_MIN_FLASH_SEQ = 512

# Mid-T window where the lax.scan blockwise form measured FASTEST on the
# TPU v5e (BENCH_LIVE_r04 / BENCH_NOTES.md attention table, bf16
# B4 H8 D64: T=512 flash 5.00 ms beats blockwise 16.29; T=2048 blockwise
# 7.92 ms beats flash 13.45 AND fused 12.67; T=8192 flash 13.93 beats
# blockwise 23.84). A single min-T threshold cannot encode that
# win-lose-win pattern, so the dispatcher carries the measured window
# explicitly. Boundaries sit at the geometric midpoints of the measured
# grid (1024, 4096) pending a finer sweep — bench_attention's block-size
# sweep exists to move them from measurement, not taste.
_BLOCKWISE_WINDOW = (1024, 4096)


def _choose_impl(T, *, on_tpu, force_streaming=False, has_mask=False,
                 interpret=False):
    """Pure dispatch decision -> 'flash' | 'fused' | 'blockwise'.

    Split out of flash_attention so tests can pin the choice per (T,
    backend) against the banked hardware table without running a kernel
    (tests/test_attention.py::TestDispatchTable)."""
    if has_mask:
        # the pallas kernel carries no mask; below the fused/flash
        # crossover the fused form (key_mask support in
        # dot_product_attention, round 6) beats the blockwise scan —
        # the [T,T] score tile fits on-chip and masking is one
        # jnp.where. Longer masked T keeps the O(T)-memory scan, as
        # does an explicit bounded-memory request.
        if T < _MIN_FLASH_SEQ and not force_streaming:
            return "fused"
        return "blockwise"
    if interpret:
        return "flash"
    if not on_tpu:
        if not force_streaming and T <= 2048:
            return "fused"
        return "blockwise"
    if T < _MIN_FLASH_SEQ:
        return "blockwise" if force_streaming else "fused"
    lo, hi = _BLOCKWISE_WINDOW
    if lo <= T < hi:
        return "blockwise"
    return "flash"


# ----------------------------------------------------------------------
# paged KV attention: block-table decode + chunked prefill (serving)
# ----------------------------------------------------------------------
# The serving tier (serving/kvcache.py) stores KV in fixed-size pages
# inside a device-resident pool [P, page, H, Dh]; a per-slot block
# table maps logical KV block j -> physical page bt[s, j] (the
# vLLM/PagedAttention shape). The kernels below index K/V through that
# table instead of a contiguous [T, Dh] buffer; page_size doubles as
# the kernel's block_k, so the online-softmax accumulation order is
# IDENTICAL to the dense flash kernel's block order and the outputs
# are bitwise equal to _fwd_kernel on the same tokens
# (tests/test_paged_attention.py gates it across aligned/padded/bf16
# grids). Trailing pages past a slot's seq_len are fully masked and
# are bitwise no-ops on the (acc, m, l) carry — the grid can always
# run the full static block-table width.


def _paged_decode_kernel(bt_ref, sl_ref, q_ref, k_ref, v_ref, *refs,
                         page: int, scale: float):
    """One (slot, head, page) program of the block-table decode grid.

    Scalar-prefetch refs: bt_ref [S, MP] block table, sl_ref [S] live
    KV length per slot. q_ref [1, 1, Dh] is the slot's single query
    row; k_ref/v_ref [1, page, 1, Dh] are the page the index map
    gathered through the block table. The online-softmax carry (acc,
    m, l) lives in VMEM scratch across the page axis (innermost grid
    dim); p == 0 initialises it, the last page normalises and writes
    the output row. A padded slot (sl == 0) masks every key, so l
    stays 0 and the l == 0 guard emits exact zeros — with the
    _LSE_EMPTY (+1e30) sentinel on the lse output, exactly like the
    dense kernel's fully-padded rows."""
    from jax.experimental import pallas as pl

    if len(refs) == 5:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
        lse_ref = None
    s_i = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = sl_ref[s_i]
    q = q_ref[0].astype(jnp.float32) * scale            # [1, Dh]
    kj = k_ref[0, :, 0, :].astype(jnp.float32)          # [page, Dh]
    vj = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    k_pos = p * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)
    # the decode query sits at position length-1, so the causal mask
    # q_pos >= k_pos coincides with the length mask k_pos < length —
    # causal by construction, one comparison
    valid = k_pos < length
    s = jnp.where(valid, s, _NEG_INF)
    m, l, acc = m_ref[...], l_ref[...], acc_ref[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    corr = jnp.exp(m - m_new)
    pr = jnp.exp(s - m_new[:, None])
    pr = jnp.where(valid, pr, 0.0)
    m_ref[...] = m_new
    l_ref[...] = l * corr + jnp.sum(pr, axis=1)
    acc_ref[...] = acc * corr[:, None] + jax.lax.dot_general(
        pr, vj, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(p == n_pages - 1)
    def _finalize():
        l_f = l_ref[...]
        o_ref[0] = (acc_ref[...]
                    / jnp.where(l_f == 0, 1.0, l_f)[:, None]
                    ).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = jnp.where(l_f > 0, m_ref[...] + jnp.log(l_f),
                                   _LSE_EMPTY)


def paged_flash_decode(q, k_pool, v_pool, block_tables, seq_lens,
                       need_lse=False, interpret=None):
    """Block-table flash decode: one query row per slot, K/V gathered
    through the slot's block table.

    q [S, H, Dh]; k_pool/v_pool [P, page, H, Dh]; block_tables
    [S, MP] int32 (physical page per logical block — padded slots
    point at the pool's null page); seq_lens [S] int32 (live KV
    tokens per slot; 0 = padded slot -> zero output row + _LSE_EMPTY
    sentinel). Returns [S, H, Dh] (and lse [S, H] fp32 when
    need_lse). Bitwise-equal to the dense flash kernel on the same
    tokens when page == the dense kernel's block_k."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    S, H, Dh = q.shape
    page = k_pool.shape[1]
    MP = block_tables.shape[1]
    interp = _INTERPRET if interpret is None else interpret
    kernel = functools.partial(_paged_decode_kernel, page=page,
                               scale=1.0 / (Dh ** 0.5))
    out_shape = [jax.ShapeDtypeStruct((S, H, Dh), q.dtype)]
    out_specs = [pl.BlockSpec((1, 1, Dh),
                              lambda s, h, p, bt, sl: (s, h, 0))]
    if need_lse:
        out_shape.append(jax.ShapeDtypeStruct((S, H), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1),
                                      lambda s, h, p, bt, sl: (s, h)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, H, MP),
        in_specs=[
            pl.BlockSpec((1, 1, Dh), lambda s, h, p, bt, sl: (s, h, 0)),
            pl.BlockSpec((1, page, 1, Dh),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
            pl.BlockSpec((1, page, 1, Dh),
                         lambda s, h, p, bt, sl: (bt[s, p], 0, h, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=[pltpu.VMEM((1, Dh), jnp.float32),
                        pltpu.VMEM((1,), jnp.float32),
                        pltpu.VMEM((1,), jnp.float32)],
    )
    res = pl.pallas_call(kernel, grid_spec=grid_spec,
                         out_shape=out_shape, interpret=interp)(
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(seq_lens, jnp.int32), q, k_pool, v_pool)
    return (res[0], res[1]) if need_lse else res[0]


def _paged_prefill_kernel(bt_ref, prm_ref, q_ref, k_ref, v_ref, o_ref,
                          acc_ref, m_ref, l_ref, *, page: int,
                          chunk: int, scale: float):
    """One (head, page) program of the chunked-prefill grid: the
    chunk's C query rows (positions t0..t0+C-1) against every page of
    ONE slot's block table — its own freshly written page included, so
    in-chunk attention is causal by the q_pos >= k_pos mask. prm_ref
    carries (t0, L) where L = t0 + valid chunk rows; padded chunk rows
    (q_pos >= L) emit garbage the caller slices off, and their zeroed
    KV rows are masked from every valid query by k_pos < L."""
    from jax.experimental import pallas as pl

    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    t0 = prm_ref[0]
    L = prm_ref[1]
    q = q_ref[0].astype(jnp.float32) * scale            # [C, Dh]
    kj = k_ref[0, :, 0, :].astype(jnp.float32)          # [page, Dh]
    vj = v_ref[0, :, 0, :].astype(jnp.float32)
    s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    q_pos = t0 + jax.lax.broadcasted_iota(jnp.int32, (chunk, page), 0)
    k_pos = p * page + jax.lax.broadcasted_iota(jnp.int32,
                                                (chunk, page), 1)
    valid = (k_pos < L) & (q_pos >= k_pos)
    s = jnp.where(valid, s, _NEG_INF)
    m, l, acc = m_ref[...], l_ref[...], acc_ref[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=1))
    corr = jnp.exp(m - m_new)
    pr = jnp.exp(s - m_new[:, None])
    pr = jnp.where(valid, pr, 0.0)
    m_ref[...] = m_new
    l_ref[...] = l * corr + jnp.sum(pr, axis=1)
    acc_ref[...] = acc * corr[:, None] + jax.lax.dot_general(
        pr, vj, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(p == n_pages - 1)
    def _finalize():
        l_f = l_ref[...]
        o_ref[0] = (acc_ref[...]
                    / jnp.where(l_f == 0, 1.0, l_f)[:, None]
                    ).astype(o_ref.dtype)


def paged_flash_prefill(q_chunk, k_pool, v_pool, block_table, t0,
                        n_valid, interpret=None):
    """Chunked-prefill attention for ONE slot: the prompt chunk's
    queries (C rows at offset t0, C == page_size) against the slot's
    whole block table — the chunk's own KV page must already be
    written into the pool (kvcache append, then this kernel; causal
    in-chunk by construction).

    q_chunk [C, H, Dh]; k_pool/v_pool [P, page, H, Dh]; block_table
    [MP] int32; t0 = chunk offset (multiple of page_size); n_valid =
    live rows in this chunk (< C only for the prompt's tail chunk).
    Returns [C, H, Dh]; rows past n_valid are padding garbage the
    caller slices off."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, H, Dh = q_chunk.shape
    page = k_pool.shape[1]
    MP = block_table.shape[0]
    interp = _INTERPRET if interpret is None else interpret
    kernel = functools.partial(_paged_prefill_kernel, page=page,
                               chunk=C, scale=1.0 / (Dh ** 0.5))
    t0 = jnp.asarray(t0, jnp.int32)
    prm = jnp.stack([t0, t0 + jnp.asarray(n_valid, jnp.int32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(H, MP),
        in_specs=[
            pl.BlockSpec((1, C, Dh), lambda h, p, bt, prm_: (h, 0, 0)),
            pl.BlockSpec((1, page, 1, Dh),
                         lambda h, p, bt, prm_: (bt[p], 0, h, 0)),
            pl.BlockSpec((1, page, 1, Dh),
                         lambda h, p, bt, prm_: (bt[p], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, Dh),
                               lambda h, p, bt, prm_: (h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((C, Dh), jnp.float32),
                        pltpu.VMEM((C,), jnp.float32),
                        pltpu.VMEM((C,), jnp.float32)],
    )
    out = pl.pallas_call(kernel, grid_spec=grid_spec,
                         out_shape=jax.ShapeDtypeStruct((H, C, Dh),
                                                        q_chunk.dtype),
                         interpret=interp)(
        jnp.asarray(block_table, jnp.int32), prm,
        jnp.moveaxis(q_chunk, 1, 0), k_pool, v_pool)
    return jnp.moveaxis(out, 0, 1)


def _paged_attend_core(q, k_pages, v_pages, length, q0):
    """Portable twin of the paged kernels for ONE (slot, head): q
    [R, Dh] raw queries at positions q0..q0+R-1, k_pages/v_pages
    [MP, page, Dh] gathered pages, length = live KV tokens. Page-
    sequential online softmax — the SAME accumulation order and ops
    as the kernels (and, page == block_k, as the dense flash kernel),
    so the serving hot path on CPU and the pallas path on TPU agree
    bitwise per page-block reduction."""
    R, Dh = q.shape
    MP, page, _ = k_pages.shape
    qs = q.astype(jnp.float32) * (1.0 / (Dh ** 0.5))
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (R, page), 0)

    def body(j, carry):
        acc, m, l = carry
        kj = jax.lax.dynamic_index_in_dim(
            k_pages, j, 0, keepdims=False).astype(jnp.float32)
        vj = jax.lax.dynamic_index_in_dim(
            v_pages, j, 0, keepdims=False).astype(jnp.float32)
        s = jax.lax.dot_general(qs, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = j * page + jax.lax.broadcasted_iota(jnp.int32,
                                                    (R, page), 1)
        valid = (k_pos < length) & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        pr = jnp.exp(s - m_new[:, None])
        pr = jnp.where(valid, pr, 0.0)
        l_new = l * corr + jnp.sum(pr, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            pr, vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0, MP, body,
        (jnp.zeros((R, Dh), jnp.float32),
         jnp.full((R,), _NEG_INF, jnp.float32),
         jnp.zeros((R,), jnp.float32)))
    return (acc / jnp.where(l == 0, 1.0, l)[:, None]).astype(q.dtype)


def paged_attend(q, k_pages, v_pages, lengths, q_starts):
    """Batched portable paged attention (the serving hot path's form,
    jit-safe): q [S, R, H, Dh] (R = 1 for decode, R = chunk for
    prefill), k_pages/v_pages [S, MP, page, H, Dh] (pool pages already
    gathered through each slot's block table — on CPU one jnp take;
    the pallas kernels do this gather per-page in VMEM instead),
    lengths [S] live KV tokens, q_starts [S] position of q row 0.
    Returns [S, R, H, Dh]; a length-0 slot yields exact zero rows."""
    qt = jnp.moveaxis(q, 2, 1)                # [S, H, R, Dh]
    kt = jnp.moveaxis(k_pages, 3, 1)          # [S, H, MP, page, Dh]
    vt = jnp.moveaxis(v_pages, 3, 1)
    per_head = jax.vmap(_paged_attend_core,
                        in_axes=(0, 0, 0, None, None))
    per_slot = jax.vmap(per_head, in_axes=(0, 0, 0, 0, 0))
    out = per_slot(qt, kt, vt, lengths, q_starts)
    return jnp.moveaxis(out, 1, 2)


def flash_attention(q, k, v, causal=False, key_mask=None,
                    block_q=512, block_k=512, force_streaming=False):
    """Attention [B,H,T,D] with automatic kernel dispatch.

    The dispatch obeys the measured winner-per-T table (see
    _BLOCKWISE_WINDOW): fused XLA below 512 (scores fit on-chip), the
    Pallas flash kernel at long T, and the lax.scan blockwise form in
    the measured mid-T window where it beats both. Ragged masks and
    non-TPU backends use the blockwise form (same online-softmax math,
    same O(T) memory).

    force_streaming=True (set when the caller passed an explicit
    block_size, i.e. asked for bounded memory) never takes the fused
    O(T^2)-score path — only the pallas kernel or the blockwise scan.
    """
    from deeplearning4j_tpu.ops.attention import dot_product_attention

    T = max(q.shape[2], k.shape[2])
    impl = _choose_impl(T, on_tpu=_on_tpu(), force_streaming=force_streaming,
                        has_mask=key_mask is not None, interpret=_INTERPRET)
    if impl == "fused":
        return dot_product_attention(q, k, v, causal=causal,
                                     key_mask=key_mask)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, block_size=block_k, causal=causal,
                                   key_mask=key_mask)
    if _INTERPRET:
        # interpreter-mode tests exist to catch kernel regressions — the
        # silent fallback below would hand them blockwise output that
        # matches the reference by construction
        return _flash(q, k, v, causal, block_q, block_k)
    try:
        return _flash(q, k, v, causal, block_q, block_k)
    except Exception:
        # pallas lowering can fail for exotic shapes/dtypes; never take the
        # model down for a fast path
        return blockwise_attention(q, k, v, block_size=block_k, causal=causal)

"""Flash attention as a hand-written Pallas TPU kernel.

Reference: the upstream attention layers (SelfAttentionLayer et al.) run
through cuDNN-era fused kernels on GPU; SURVEY.md row 21 commits this repo
to a flash-style Pallas kernel for the TPU hot path, with the lax.scan
blockwise form (ops/attention.py) as the portable fallback.

Design: one grid step per (batch*heads, q-block); the kernel streams KV
blocks through VMEM with an online-softmax recurrence (Rabe & Staats /
FlashAttention), so the [T, T] score matrix never materialises in HBM.
Score matmuls hit the MXU with fp32 accumulation regardless of the input
dtype (bf16 inputs stay bf16 in HBM/VMEM).

Backward: recompute strategy — the VJP re-runs the blockwise forward under
jax.vjp, which is also O(T) memory. This is the standard flash-attention
trade (FLOPs for HBM), and XLA fuses the recompute with the rest of the
backward.

`flash_attention` transparently falls back to `blockwise_attention` when
Pallas/TPU is unavailable (CPU tests, masks, tiny shapes), so callers can
use it unconditionally.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.attention import blockwise_attention

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, Tk: int,
                causal: bool, block_q: int, scale: float):
    """One (bh, q-block) program. Refs carry a leading singleton bh axis:
    q_ref [1, bq, D], k_ref/v_ref [1, Tk_pad, D]."""
    from jax.experimental import pallas as pl

    _, bq, D = q_ref.shape
    Tk_pad = k_ref.shape[1]
    n_kb = Tk_pad // block_k
    iq = pl.program_id(1)

    q = q_ref[0].astype(jnp.float32) * scale

    acc0 = jnp.zeros((bq, D), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)

    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    def body(j, carry):
        acc, m, l = carry
        kj = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vj = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        valid = k_pos < Tk
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    if causal:
        # skip KV blocks entirely above the diagonal for this q block
        n_used = jnp.minimum(
            (iq + 1) * block_q + block_k - 1, Tk_pad) // block_k
    else:
        n_used = n_kb
    acc, m, l = jax.lax.fori_loop(0, n_used, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.where(l == 0, 1.0, l)[:, None]).astype(o_ref.dtype)


# test hook: when True, pallas_call runs in interpreter mode (works on CPU)
# and flash_attention always takes the kernel path regardless of backend
# (tests/test_attention.py::TestFlashKernel sets this to check the kernel
# against the fused reference, forward and backward)
_INTERPRET = False


def _flash_fwd_impl(q, k, v, causal, block_q, block_k):
    """q [B,H,Tq,D], k/v [B,H,Tk,D] -> [B,H,Tq,D] via pallas_call."""
    from jax.experimental import pallas as pl

    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    pq = (-Tq) % bq
    pk = (-Tk) % bk
    qf = q.reshape(B * H, Tq, D)
    kf = k.reshape(B * H, Tk, D)
    vf = v.reshape(B * H, Tk, D)
    if pq:
        qf = jnp.pad(qf, ((0, 0), (0, pq), (0, 0)))
    if pk:
        kf = jnp.pad(kf, ((0, 0), (0, pk), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pk), (0, 0)))
    Tqp, Tkp = Tq + pq, Tk + pk

    kernel = functools.partial(
        _fwd_kernel, block_k=bk, Tk=Tk, causal=causal, block_q=bq,
        scale=1.0 / (D ** 0.5))
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Tqp // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, Tkp, D), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tqp, D), q.dtype),
        interpret=_INTERPRET,
    )(qf, kf, vf)
    return out[:, :Tq].reshape(B, H, Tq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v = res
    # recompute-VJP through the O(T)-memory blockwise reference
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, block_size=block_k,
                                               causal=causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


# below this sequence length the fused XLA attention wins: the [T,T]
# score tile fits comfortably on-chip and pallas_call launch overhead
# isn't amortised
_MIN_FLASH_SEQ = 512

# Mid-T window where the lax.scan blockwise form measured FASTEST on the
# TPU v5e (BENCH_LIVE_r04 / BENCH_NOTES.md attention table, bf16
# B4 H8 D64: T=512 flash 5.00 ms beats blockwise 16.29; T=2048 blockwise
# 7.92 ms beats flash 13.45 AND fused 12.67; T=8192 flash 13.93 beats
# blockwise 23.84). A single min-T threshold cannot encode that
# win-lose-win pattern, so the dispatcher carries the measured window
# explicitly. Boundaries sit at the geometric midpoints of the measured
# grid (1024, 4096) pending a finer sweep — bench_attention's block-size
# sweep exists to move them from measurement, not taste.
_BLOCKWISE_WINDOW = (1024, 4096)


def _choose_impl(T, *, on_tpu, force_streaming=False, has_mask=False,
                 interpret=False):
    """Pure dispatch decision -> 'flash' | 'fused' | 'blockwise'.

    Split out of flash_attention so tests can pin the choice per (T,
    backend) against the banked hardware table without running a kernel
    (tests/test_attention.py::TestDispatchTable)."""
    if has_mask:
        # the pallas kernel carries no mask; below the fused/flash
        # crossover the fused form (key_mask support in
        # dot_product_attention, round 6) beats the blockwise scan —
        # the [T,T] score tile fits on-chip and masking is one
        # jnp.where. Longer masked T keeps the O(T)-memory scan, as
        # does an explicit bounded-memory request.
        if T < _MIN_FLASH_SEQ and not force_streaming:
            return "fused"
        return "blockwise"
    if interpret:
        return "flash"
    if not on_tpu:
        if not force_streaming and T <= 2048:
            return "fused"
        return "blockwise"
    if T < _MIN_FLASH_SEQ:
        return "blockwise" if force_streaming else "fused"
    lo, hi = _BLOCKWISE_WINDOW
    if lo <= T < hi:
        return "blockwise"
    return "flash"


def flash_attention(q, k, v, causal=False, key_mask=None,
                    block_q=512, block_k=512, force_streaming=False):
    """Attention [B,H,T,D] with automatic kernel dispatch.

    The dispatch obeys the measured winner-per-T table (see
    _BLOCKWISE_WINDOW): fused XLA below 512 (scores fit on-chip), the
    Pallas flash kernel at long T, and the lax.scan blockwise form in
    the measured mid-T window where it beats both. Ragged masks and
    non-TPU backends use the blockwise form (same online-softmax math,
    same O(T) memory).

    force_streaming=True (set when the caller passed an explicit
    block_size, i.e. asked for bounded memory) never takes the fused
    O(T^2)-score path — only the pallas kernel or the blockwise scan.
    """
    from deeplearning4j_tpu.ops.attention import dot_product_attention

    T = max(q.shape[2], k.shape[2])
    impl = _choose_impl(T, on_tpu=_on_tpu(), force_streaming=force_streaming,
                        has_mask=key_mask is not None, interpret=_INTERPRET)
    if impl == "fused":
        return dot_product_attention(q, k, v, causal=causal,
                                     key_mask=key_mask)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, block_size=block_k, causal=causal,
                                   key_mask=key_mask)
    if _INTERPRET:
        # interpreter-mode tests exist to catch kernel regressions — the
        # silent fallback below would hand them blockwise output that
        # matches the reference by construction
        return _flash(q, k, v, causal, block_q, block_k)
    try:
        return _flash(q, k, v, causal, block_q, block_k)
    except Exception:
        # pallas lowering can fail for exotic shapes/dtypes; never take the
        # model down for a fast path
        return blockwise_attention(q, k, v, block_size=block_k, causal=causal)

"""Convolution primitives (NHWC, MXU-targeted).

Reference: libnd4j conv2d/deconv2d/depthwise ops and the cuDNN helper
(CudnnConvolutionHelper) that the reference's ConvolutionLayer prefers on
GPU. On TPU all variants are one primitive — lax.conv_general_dilated —
which XLA tiles onto the MXU and fuses with surrounding elementwise work,
so there is no helper/fallback split to port.

Layout: NHWC activations, HWIO weights (the TPU-native layouts). The nn
layer API remains NCHW like the reference; conversion happens once at the
network input boundary, not per-op.
"""

from __future__ import annotations

from jax import lax
import numpy as np


def _pair(v):
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def explicit_padding(mode, padding, kernel, stride, dilation):
    """Resolve a ConvolutionMode + explicit padding config to lax padding.

    Reference: org.deeplearning4j.nn.conf.ConvolutionMode — Same computes
    TF-style same-padding; Truncate/Strict use the configured pad values.
    """
    if str(mode).lower() == "same":
        return "SAME"
    ph, pw = _pair(padding)
    return ((ph, ph), (pw, pw))


def _deconv_pads(mode, padding, kernel, dilation):
    """ConvolutionMode + configured pad -> lax.conv_transpose padding
    that reproduces the reference's deconv output size
    s*(in-1) + k_eff - 2*pad. lax's explicit (lo, hi) pairs ADD to the
    output relative to a (k_eff-1)-padded baseline, so the mapping is
    lo = hi = k_eff - 1 - pad (NOT the forward-conv (pad, pad)).
    n-dimensional: padding/kernel/dilation are equal-length tuples."""
    if str(mode).lower() == "same":
        return "SAME"
    pads = []
    for p, k, d in zip(padding, kernel, dilation):
        k_eff = (k - 1) * d + 1
        pads.append((k_eff - 1 - p, k_eff - 1 - p))
    return tuple(pads)


def deconv_explicit_padding(mode, padding, kernel, dilation):
    return _deconv_pads(mode, _pair(padding), _pair(kernel), _pair(dilation))


def deconv3d_explicit_padding(mode, padding, kernel, dilation):
    return _deconv_pads(mode, padding, kernel, dilation)


def conv2d(x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)), dilation=(1, 1),
           groups=1):
    """x: [B,H,W,Cin], w: [kh,kw,Cin/groups,Cout] -> [B,H',W',Cout]."""
    out = lax.conv_general_dilated(
        x, w,
        window_strides=_pair(stride),
        padding=padding,
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )
    if b is not None:
        out = out + b
    return out


def deconv2d(x, w, b=None, stride=(1, 1), padding=((0, 0), (0, 0)), dilation=(1, 1)):
    """Transposed convolution. w: [kh,kw,Cout,Cin] stored IO-swapped so
    fan semantics match the forward conv it inverts."""
    out = lax.conv_transpose(
        x, w,
        strides=_pair(stride),
        padding=padding,
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if b is not None:
        out = out + b
    return out


def conv1d(x, w, b=None, stride=1, padding=((0, 0),), dilation=1):
    """x: [B,T,Cin], w: [k,Cin,Cout] -> [B,T',Cout]."""
    out = lax.conv_general_dilated(
        x, w,
        window_strides=(int(stride),),
        padding=padding if padding == "SAME" else tuple(padding),
        rhs_dilation=(int(dilation),),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if b is not None:
        out = out + b
    return out


def conv3d(x, w, b=None, stride=(1, 1, 1), padding=((0, 0),) * 3,
           dilation=(1, 1, 1)):
    """x: [B,D,H,W,Cin], w: [kd,kh,kw,Cin,Cout] -> [B,D',H',W',Cout].
    Reference: Convolution3D (NDHWC internal, like the 2D NHWC path)."""
    out = lax.conv_general_dilated(
        x, w,
        window_strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    if b is not None:
        out = out + b
    return out


def deconv3d(x, w, b=None, stride=(1, 1, 1), padding=((0, 0),) * 3,
             dilation=(1, 1, 1)):
    """Transposed 3D convolution. w: [kd,kh,kw,Cin,Cout] — the forward
    layout; conv_transpose reads I against its own input channels
    (reference: Deconvolution3D)."""
    out = lax.conv_transpose(
        x, w,
        strides=tuple(stride),
        padding=padding,
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    if b is not None:
        out = out + b
    return out


def conv_output_size(size, kernel, stride, pad, dilation, mode):
    """Spatial shape inference, matching the reference's
    ConvolutionUtils.getOutputSize."""
    k_eff = (kernel - 1) * dilation + 1
    if str(mode).lower() == "same":
        return int(np.ceil(size / stride))
    return (size + 2 * pad - k_eff) // stride + 1


def deconv_output_size(size, kernel, stride, pad, dilation, mode):
    k_eff = (kernel - 1) * dilation + 1
    if str(mode).lower() == "same":
        return size * stride
    return stride * (size - 1) + k_eff - 2 * pad

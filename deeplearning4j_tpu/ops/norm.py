"""Normalization primitives.

Reference: BatchNormalization (+ CudnnBatchNormalizationHelper) and
LocalResponseNormalization layer impls. On TPU both are bandwidth-bound
elementwise/reduction patterns that XLA fuses; no helper split needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    """Fused training-mode BN core: (y, mean, var) with a hand-written
    backward (the cuDNN-batchnorm-backward formulas). Residuals are
    (x, mean, inv, gamma) — x in its HBM dtype, no fp32 xhat
    materialisation — so the backward is exactly two passes over x
    (dgamma/dbeta reduction + dx), where autodiff through mean/var
    generates more intermediate traffic. The mean/var outputs are
    carry-only (running-stat updates); their cotangents are treated as
    zero."""
    y, mean, var, _ = _bn_train_fwd_math(x, gamma, beta, eps)
    return y, mean, var


def _bn_train_fwd_math(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(ft)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    inv = lax.rsqrt(var + eps)
    y = (xf - mean) * inv * gamma.astype(ft) + beta.astype(ft)
    return y.astype(x.dtype), mean, var, inv


def _bn_train_fwd(x, gamma, beta, eps):
    y, mean, var, inv = _bn_train_fwd_math(x, gamma, beta, eps)
    return (y, mean, var), (x, mean, inv, gamma)


def _bn_train_bwd(eps, res, cts):
    dy, _dmean, _dvar = cts  # stats outputs are carry-only: zero cotangent
    x, mean, inv, gamma = res
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    dyf = dy.astype(ft)
    xhat = (x.astype(ft) - mean) * inv
    dbeta = jnp.sum(dyf, axis=axes)
    dgamma = jnp.sum(dyf * xhat, axis=axes)
    dx = (gamma.astype(ft) * inv / n) * (n * dyf - dbeta - xhat * dgamma)
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def batch_norm(x, gamma, beta, running_mean, running_var, *, train: bool,
               decay: float = 0.9, eps: float = 1e-5, use_stats: bool = True):
    """Channels-last batch norm over all leading axes.

    Returns (y, new_running_mean, new_running_var). `decay` matches the
    reference's decay semantics: running = decay*running + (1-decay)*batch.
    Training mode runs the fused custom-VJP core (_bn_train); eval mode is
    a plain affine transform XLA fuses into neighbours.
    """
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if train:
        # locked gamma/beta become constants; grads exist but are unused
        g = jnp.ones(x.shape[-1], ft) if gamma is None else gamma
        b = jnp.zeros(x.shape[-1], ft) if beta is None else beta
        y, mean, var = _bn_train(x, g, b, float(eps))
        new_rm = (decay * running_mean.astype(ft)
                  + (1.0 - decay) * mean).astype(running_mean.dtype)
        new_rv = (decay * running_var.astype(ft)
                  + (1.0 - decay) * var).astype(running_var.dtype)
        return y, new_rm, new_rv
    mean = running_mean.astype(ft)
    var = running_var.astype(ft)
    inv = lax.rsqrt(var + eps)
    y = (x.astype(ft) - mean) * inv
    if gamma is not None:
        y = y * gamma.astype(ft)
    if beta is not None:
        y = y + beta.astype(ft)
    return y.astype(x.dtype), running_mean, running_var


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (NHWC).

    Reference: LocalResponseNormalization (AlexNet-era). Implemented as an
    average pool over the channel axis.
    """
    sq = jnp.square(x)
    half = n // 2
    # pad channels and sum a sliding window over the channel dim
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    summed = lax.reduce_window(
        padded, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    return x / jnp.power(k + alpha * summed, beta)

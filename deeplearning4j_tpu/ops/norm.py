"""Normalization primitives.

Reference: BatchNormalization (+ CudnnBatchNormalizationHelper) and
LocalResponseNormalization layer impls. On TPU both are bandwidth-bound
elementwise/reduction patterns that XLA fuses; no helper split needed.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def batch_norm(x, gamma, beta, running_mean, running_var, *, train: bool,
               decay: float = 0.9, eps: float = 1e-5, use_stats: bool = True):
    """Channels-last batch norm over all leading axes.

    Returns (y, new_running_mean, new_running_var). `decay` matches the
    reference's decay semantics: running = decay*running + (1-decay)*batch.
    """
    axes = tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        new_rm = decay * running_mean + (1.0 - decay) * mean
        new_rv = decay * running_var + (1.0 - decay) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    y = (x - mean) * inv
    if gamma is not None:
        y = y * gamma
    if beta is not None:
        y = y + beta
    return y, new_rm, new_rv


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (NHWC).

    Reference: LocalResponseNormalization (AlexNet-era). Implemented as an
    average pool over the channel axis.
    """
    sq = jnp.square(x)
    half = n // 2
    # pad channels and sum a sliding window over the channel dim
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    summed = lax.reduce_window(
        padded, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    return x / jnp.power(k + alpha * summed, beta)

"""Normalization primitives.

Reference: BatchNormalization (+ CudnnBatchNormalizationHelper) and
LocalResponseNormalization layer impls. On TPU both are bandwidth-bound
elementwise/reduction patterns that XLA fuses; no helper split needed.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def batch_norm(x, gamma, beta, running_mean, running_var, *, train: bool,
               decay: float = 0.9, eps: float = 1e-5, use_stats: bool = True):
    """Channels-last batch norm over all leading axes.

    Returns (y, new_running_mean, new_running_var). `decay` matches the
    reference's decay semantics: running = decay*running + (1-decay)*batch.
    """
    axes = tuple(range(x.ndim - 1))
    # stats and normalisation math in fp32 (bf16 squares underflow); the
    # result is cast back so the activation dtype is stable through the net
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        # keep the carried stats in their own dtype (donated/scan carries
        # must be dtype-stable)
        new_rm = (decay * running_mean.astype(jnp.float32)
                  + (1.0 - decay) * mean).astype(running_mean.dtype)
        new_rv = (decay * running_var.astype(jnp.float32)
                  + (1.0 - decay) * var).astype(running_var.dtype)
    else:
        mean, var = running_mean.astype(jnp.float32), running_var.astype(jnp.float32)
        new_rm, new_rv = running_mean, running_var
    inv = lax.rsqrt(var + eps)
    y = (xf - mean) * inv
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    return y.astype(x.dtype), new_rm, new_rv


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (NHWC).

    Reference: LocalResponseNormalization (AlexNet-era). Implemented as an
    average pool over the channel axis.
    """
    sq = jnp.square(x)
    half = n // 2
    # pad channels and sum a sliding window over the channel dim
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    summed = lax.reduce_window(
        padded, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    return x / jnp.power(k + alpha * summed, beta)

"""Normalization primitives.

Reference: BatchNormalization (+ CudnnBatchNormalizationHelper) and
LocalResponseNormalization layer impls. On TPU both are bandwidth-bound
elementwise/reduction patterns that XLA fuses; no helper split needed.

Dtype policy (round 6, the BN tail fix): under a sub-fp32 compute dtype
(bf16/fp16) the default "compute" tail keeps every ACTIVATION-SCALE
tensor in the compute dtype — fp32 appears only in the vector-scale
statistics (mean/var/inv/dgamma/dbeta) and inside reduction
accumulators, where XLA fuses the widening convert into the reduce and
no fp32 buffer ever reaches HBM. The round-5 attribution named fp32
activation-scale buffers in the BN tails as a dtype_widening bin; this
removes the source. The previous math (all BN arithmetic in fp32,
cast at the layer edge) stays available as mode "wide" — module global
`_TAIL_MODE`, initial value from DL4J_TPU_BN_TAIL — so bench.py can A/B
the two lowerings instead of trusting the analysis
(tests/test_hbm_attribution.py pins that "compute" passes the
activation-dtype audit and "wide" fails it).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

#: "compute" (default) = activation-scale BN math in the compute dtype,
#: fp32 only for vector-scale stats + fused reduce accumulators;
#: "wide" = the pre-round-6 all-fp32 tail. Read at TRACE time.
_TAIL_MODE = os.environ.get("DL4J_TPU_BN_TAIL", "compute")

#: "fused" (default) = BN -> activation (-> residual add) runs as ONE
#: custom-VJP epilogue whose backward derives the activation gradient
#: FROM THE OUTPUT (relu mask = out > 0, tanh' = 1 - out^2, ...), so
#: the pre-activation BN output is never kept as a residual — the
#: round-5 attribution billed exactly that buffer's extra touch to
#: grad_double_touch. "unfused" = the legacy composition (BN custom
#: VJP, then the activation with its own autodiff residual). Routed in
#: nn/conf/layers.BatchNormalization; tunable by the autotune arbiter
#: (runtime/autotune.py) and carried in the AOT ambient fingerprint.
_EPILOGUE = os.environ.get("DL4J_TPU_BN_EPILOGUE", "fused").lower()
if _EPILOGUE not in ("fused", "unfused"):
    raise ValueError(
        f"DL4J_TPU_BN_EPILOGUE must be 'fused' or 'unfused', got "
        f"{os.environ['DL4J_TPU_BN_EPILOGUE']!r}")


def set_bn_epilogue(mode):
    """Set the BN epilogue mode ('fused'/'unfused'); returns the
    previous value (the autotune arbiter's entry)."""
    global _EPILOGUE
    mode = str(mode).lower()
    if mode not in ("fused", "unfused"):
        raise ValueError(
            f"bn_epilogue must be 'fused' or 'unfused', got {mode!r}")
    old, _EPILOGUE = _EPILOGUE, mode
    return old


#: activations whose gradient is an exact function of the OUTPUT — the
#: set the fused epilogue supports. relu: out>0 iff pre>0 (bitwise-equal
#: mask); leakyrelu (slope a>0) preserves sign; tanh' = 1-out^2;
#: sigmoid' = out*(1-out); identity' = 1.
EPILOGUE_ACTIVATIONS = ("identity", "relu", "leakyrelu", "tanh", "sigmoid")


def bn_act_supported(activation):
    """True when the fused epilogue can take this activation name."""
    return str(activation).lower() in EPILOGUE_ACTIVATIONS


def _wide_tail(x):
    """True when BN should run its activation-scale math in fp32: the
    legacy mode, or a compute dtype that is already >= fp32."""
    ft = jnp.promote_types(x.dtype, jnp.float32)
    return _TAIL_MODE == "wide" or x.dtype == ft


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    """Fused training-mode BN core: (y, mean, var) with a hand-written
    backward (the cuDNN-batchnorm-backward formulas). Residuals are
    (x, mean, inv, gamma) — x in its HBM dtype, no fp32 xhat
    materialisation — so the backward is exactly two passes over x
    (dgamma/dbeta reduction + dx), where autodiff through mean/var
    generates more intermediate traffic. The mean/var outputs are
    carry-only (running-stat updates); their cotangents are treated as
    zero."""
    y, mean, var, _ = _bn_train_fwd_math(x, gamma, beta, eps)
    return y, mean, var


def _bn_train_fwd_math(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if _wide_tail(x):
        xf = x.astype(ft)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        inv = lax.rsqrt(var + eps)
        y = (xf - mean) * inv * gamma.astype(ft) + beta.astype(ft)
        return y.astype(x.dtype), mean, var, inv
    # compute-dtype tail: stats accumulate in fp32 INSIDE the reduces
    # (jnp.mean(..., dtype=ft) — the convert fuses, nothing fp32 at
    # activation scale materialises); the normalisation itself runs in
    # the compute dtype with the fp32 vector statistics cast down once.
    # var is E[(x - round(mean))^2]: the (mean - round(mean))^2 bias is
    # below the compute dtype's own resolution.
    mean = jnp.mean(x, axis=axes, dtype=ft)
    xc = x - mean.astype(x.dtype)
    var = jnp.mean(jnp.square(xc), axis=axes, dtype=ft)
    inv = lax.rsqrt(var + eps)
    scale = (inv * gamma.astype(ft)).astype(x.dtype)
    y = xc * scale + beta.astype(x.dtype)
    return y, mean, var, inv


def _bn_train_fwd(x, gamma, beta, eps):
    y, mean, var, inv = _bn_train_fwd_math(x, gamma, beta, eps)
    return (y, mean, var), (x, mean, inv, gamma)


def _bn_train_bwd(eps, res, cts):
    dy, _dmean, _dvar = cts  # stats outputs are carry-only: zero cotangent
    x, mean, inv, gamma = res
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    if _wide_tail(x):
        dyf = dy.astype(ft)
        xhat = (x.astype(ft) - mean) * inv
        dbeta = jnp.sum(dyf, axis=axes)
        dgamma = jnp.sum(dyf * xhat, axis=axes)
        dx = (gamma.astype(ft) * inv / n) * (n * dyf - dbeta
                                             - xhat * dgamma)
        return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
                dbeta.astype(gamma.dtype))
    # compute-dtype tail: dy/xhat stay in the compute dtype; the dbeta/
    # dgamma accumulators widen inside their reduces (fused), and the
    # fp32 vector terms are cast down once for the elementwise dx pass
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dbeta = jnp.sum(dy, axis=axes, dtype=ft)
    dgamma = jnp.sum(dy * xhat, axis=axes, dtype=ft)
    k = (gamma.astype(ft) * inv / n).astype(x.dtype)
    dx = k * (n * dy - dbeta.astype(x.dtype)
              - xhat * dgamma.astype(x.dtype))
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


# ----------------------------------------------------------------------
# fused BN -> activation (-> residual add) epilogue
# ----------------------------------------------------------------------

def _registry_act(act):
    """The nn/activations registry function for `act` (deferred import:
    nn.__init__ -> conf.layers -> this module would cycle at import
    time). The epilogue applies the REGISTRY functions directly —
    value AND kink conventions are the legacy layer path's by
    construction (a dead conv channel + zero beta puts a whole channel
    AT the relu kink at init, so the subgradient there is NOT
    measure-zero; a re-implementation drifted once already)."""
    from deeplearning4j_tpu.nn import activations as _act

    return _act.get(act)


#: leakyrelu negative slope, derived lazily FROM the registry function
#: itself (leaky(-1) == -alpha) so the two can never drift
_LEAKY_ALPHA = None


def _leaky_alpha():
    global _LEAKY_ALPHA  # purity-ok[PUR04]: deterministic memo of a module constant — same float every process, trace-time write is benign
    if _LEAKY_ALPHA is None:
        # the registry function is jitted: a first call that lands
        # inside an outer trace would hand float() a tracer
        with jax.ensure_compile_time_eval():
            _LEAKY_ALPHA = float(-_registry_act("leakyrelu")(-1.0))
    return _LEAKY_ALPHA


def _epilogue_apply(y, act):
    if act == "identity":
        return y
    return _registry_act(act)(y)


def _epilogue_grad_from_out(out, act):
    """d(act)/d(pre) as a function of the OUTPUT. None = identity (1).
    relu/leakyrelu masks are BITWISE the pre-activation masks INCLUDING
    the kink: jax.nn.relu's grad at exactly 0 is 0 (out > 0 strict);
    jax.nn.leaky_relu's is 1 (where(x >= 0) — out >= 0 here, exact
    since leaky_relu preserves sign for alpha > 0). tanh/sigmoid are
    the textbook output-space forms (ulp-level vs autodiff through the
    input)."""
    if act == "relu":
        return (out > 0).astype(out.dtype)
    if act == "leakyrelu":
        return jnp.where(out >= 0, jnp.ones((), out.dtype),
                         jnp.asarray(_leaky_alpha(), out.dtype))
    if act == "tanh":
        return 1 - out * out
    if act == "sigmoid":
        return out * (1 - out)
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_act_train(x, gamma, beta, eps, act):
    """Fused training-mode BN -> activation.

    Forward math is EXACTLY _bn_train's (same tail-mode handling) with
    the activation applied in the same fusion; the hand-written
    backward turns the output cotangent into the pre-activation
    cotangent via _epilogue_grad_from_out and reuses _bn_train_bwd, so
    the BN output is never a residual — the backward touches only x,
    the final output (shared with the next layer's own residual) and
    the vector-scale stats."""
    y, mean, var, _ = _bn_train_fwd_math(x, gamma, beta, eps)
    return _epilogue_apply(y, act), mean, var


def _bn_act_train_fwd(x, gamma, beta, eps, act):
    y, mean, var, inv = _bn_train_fwd_math(x, gamma, beta, eps)
    out = _epilogue_apply(y, act)
    return (out, mean, var), (x, mean, inv, gamma, out)


def _bn_act_train_bwd(eps, act, res, cts):
    dout, _dm, _dv = cts  # stats outputs are carry-only (as _bn_train)
    x, mean, inv, gamma, out = res
    g = _epilogue_grad_from_out(out, act)
    dy = dout if g is None else dout * g
    return _bn_train_bwd(eps, (x, mean, inv, gamma), (dy, None, None))


_bn_act_train.defvjp(_bn_act_train_fwd, _bn_act_train_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _bn_act_add_train(x, gamma, beta, residual, eps, act):
    """_bn_act_train with a skip-connection add fused BEFORE the
    activation (the ResNet block tail: BN -> add -> relu)."""
    y, mean, var, _ = _bn_train_fwd_math(x, gamma, beta, eps)
    return _epilogue_apply(y + residual, act), mean, var


def _bn_act_add_train_fwd(x, gamma, beta, residual, eps, act):
    y, mean, var, inv = _bn_train_fwd_math(x, gamma, beta, eps)
    out = _epilogue_apply(y + residual, act)
    return (out, mean, var), (x, mean, inv, gamma, out)


def _bn_act_add_train_bwd(eps, act, res, cts):
    dout, _dm, _dv = cts
    x, mean, inv, gamma, out = res
    g = _epilogue_grad_from_out(out, act)
    dy = dout if g is None else dout * g
    dx, dgamma, dbeta = _bn_train_bwd(eps, (x, mean, inv, gamma),
                                      (dy, None, None))
    return (dx, dgamma, dbeta, dy)


_bn_act_add_train.defvjp(_bn_act_add_train_fwd, _bn_act_add_train_bwd)


def _locked_gamma_beta(x, gamma, beta, ft):
    """Locked gamma/beta become constants (grads exist but are unused)
    — ONE definition shared by batch_norm and batch_norm_act."""
    g = jnp.ones(x.shape[-1], ft) if gamma is None else gamma
    b = jnp.zeros(x.shape[-1], ft) if beta is None else beta
    return g, b


def _ema(running, batch_stat, decay, ft):
    """running = decay*running + (1-decay)*batch, accumulated in ft and
    cast back — the reference's decay semantics, shared by both BN
    entry points so fused and unfused layers track IDENTICAL stats."""
    return (decay * running.astype(ft)
            + (1.0 - decay) * batch_stat).astype(running.dtype)


def batch_norm_act(x, gamma, beta, running_mean, running_var, *,
                   train: bool, activation: str, decay: float = 0.9,
                   eps: float = 1e-5, residual=None):
    """batch_norm with the activation (and an optional pre-activation
    residual add) fused into one epilogue. Same contract/returns as
    batch_norm; activation must satisfy bn_act_supported. With
    _EPILOGUE == "unfused" this IS batch_norm + add + activation (the
    stock composition the parity tests pin the fused path against)."""
    act = str(activation).lower()
    if not bn_act_supported(act):
        raise ValueError(
            f"activation {activation!r} is not epilogue-fusable; "
            f"supported: {EPILOGUE_ACTIVATIONS}")
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if _EPILOGUE != "fused" or not train:
        y, rm, rv = batch_norm(x, gamma, beta, running_mean, running_var,
                               train=train, decay=decay, eps=eps)
        if residual is not None:
            y = y + residual
        # eval mode: the affine+add+activation is one elementwise chain
        # XLA fuses on its own; no residual-buffer concern without grads
        return _epilogue_apply(y, act), rm, rv
    g, b = _locked_gamma_beta(x, gamma, beta, ft)
    if residual is None:
        y, mean, var = _bn_act_train(x, g, b, float(eps), act)
    else:
        y, mean, var = _bn_act_add_train(x, g, b, residual,
                                         float(eps), act)
    return (y, _ema(running_mean, mean, decay, ft),
            _ema(running_var, var, decay, ft))


def batch_norm(x, gamma, beta, running_mean, running_var, *, train: bool,
               decay: float = 0.9, eps: float = 1e-5, use_stats: bool = True):
    """Channels-last batch norm over all leading axes.

    Returns (y, new_running_mean, new_running_var). `decay` matches the
    reference's decay semantics: running = decay*running + (1-decay)*batch.
    Training mode runs the fused custom-VJP core (_bn_train); eval mode is
    a plain affine transform XLA fuses into neighbours.
    """
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if train:
        g, b = _locked_gamma_beta(x, gamma, beta, ft)
        y, mean, var = _bn_train(x, g, b, float(eps))
        return (y, _ema(running_mean, mean, decay, ft),
                _ema(running_var, var, decay, ft))
    mean = running_mean.astype(ft)
    var = running_var.astype(ft)
    inv = lax.rsqrt(var + eps)
    if _wide_tail(x):
        y = (x.astype(ft) - mean) * inv
        if gamma is not None:
            y = y * gamma.astype(ft)
        if beta is not None:
            y = y + beta.astype(ft)
        return y.astype(x.dtype), running_mean, running_var
    # compute-dtype tail: fold the whole affine into two fp32 VECTORS
    # (scale, shift) computed once, cast down once — y = x*a + b with no
    # activation-scale widening
    a = inv if gamma is None else inv * gamma.astype(ft)
    b = -mean * a
    if beta is not None:
        b = b + beta.astype(ft)
    y = x * a.astype(x.dtype) + b.astype(x.dtype)
    return y, running_mean, running_var


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (NHWC).

    Reference: LocalResponseNormalization (AlexNet-era). Implemented as an
    average pool over the channel axis.
    """
    sq = jnp.square(x)
    half = n // 2
    # pad channels and sum a sliding window over the channel dim
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    summed = lax.reduce_window(
        padded, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    return x / jnp.power(k + alpha * summed, beta)

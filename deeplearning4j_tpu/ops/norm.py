"""Normalization primitives.

Reference: BatchNormalization (+ CudnnBatchNormalizationHelper) and
LocalResponseNormalization layer impls. On TPU both are bandwidth-bound
elementwise/reduction patterns that XLA fuses; no helper split needed.

Dtype policy (round 6, the BN tail fix): under a sub-fp32 compute dtype
(bf16/fp16) the default "compute" tail keeps every ACTIVATION-SCALE
tensor in the compute dtype — fp32 appears only in the vector-scale
statistics (mean/var/inv/dgamma/dbeta) and inside reduction
accumulators, where XLA fuses the widening convert into the reduce and
no fp32 buffer ever reaches HBM. The round-5 attribution named fp32
activation-scale buffers in the BN tails as a dtype_widening bin; this
removes the source. The previous math (all BN arithmetic in fp32,
cast at the layer edge) stays available as mode "wide" — module global
`_TAIL_MODE`, initial value from DL4J_TPU_BN_TAIL — so bench.py can A/B
the two lowerings instead of trusting the analysis
(tests/test_hbm_attribution.py pins that "compute" passes the
activation-dtype audit and "wide" fails it).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

#: "compute" (default) = activation-scale BN math in the compute dtype,
#: fp32 only for vector-scale stats + fused reduce accumulators;
#: "wide" = the pre-round-6 all-fp32 tail. Read at TRACE time.
_TAIL_MODE = os.environ.get("DL4J_TPU_BN_TAIL", "compute")


def _wide_tail(x):
    """True when BN should run its activation-scale math in fp32: the
    legacy mode, or a compute dtype that is already >= fp32."""
    ft = jnp.promote_types(x.dtype, jnp.float32)
    return _TAIL_MODE == "wide" or x.dtype == ft


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train(x, gamma, beta, eps):
    """Fused training-mode BN core: (y, mean, var) with a hand-written
    backward (the cuDNN-batchnorm-backward formulas). Residuals are
    (x, mean, inv, gamma) — x in its HBM dtype, no fp32 xhat
    materialisation — so the backward is exactly two passes over x
    (dgamma/dbeta reduction + dx), where autodiff through mean/var
    generates more intermediate traffic. The mean/var outputs are
    carry-only (running-stat updates); their cotangents are treated as
    zero."""
    y, mean, var, _ = _bn_train_fwd_math(x, gamma, beta, eps)
    return y, mean, var


def _bn_train_fwd_math(x, gamma, beta, eps):
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if _wide_tail(x):
        xf = x.astype(ft)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.var(xf, axis=axes)
        inv = lax.rsqrt(var + eps)
        y = (xf - mean) * inv * gamma.astype(ft) + beta.astype(ft)
        return y.astype(x.dtype), mean, var, inv
    # compute-dtype tail: stats accumulate in fp32 INSIDE the reduces
    # (jnp.mean(..., dtype=ft) — the convert fuses, nothing fp32 at
    # activation scale materialises); the normalisation itself runs in
    # the compute dtype with the fp32 vector statistics cast down once.
    # var is E[(x - round(mean))^2]: the (mean - round(mean))^2 bias is
    # below the compute dtype's own resolution.
    mean = jnp.mean(x, axis=axes, dtype=ft)
    xc = x - mean.astype(x.dtype)
    var = jnp.mean(jnp.square(xc), axis=axes, dtype=ft)
    inv = lax.rsqrt(var + eps)
    scale = (inv * gamma.astype(ft)).astype(x.dtype)
    y = xc * scale + beta.astype(x.dtype)
    return y, mean, var, inv


def _bn_train_fwd(x, gamma, beta, eps):
    y, mean, var, inv = _bn_train_fwd_math(x, gamma, beta, eps)
    return (y, mean, var), (x, mean, inv, gamma)


def _bn_train_bwd(eps, res, cts):
    dy, _dmean, _dvar = cts  # stats outputs are carry-only: zero cotangent
    x, mean, inv, gamma = res
    axes = tuple(range(x.ndim - 1))
    ft = jnp.promote_types(x.dtype, jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    if _wide_tail(x):
        dyf = dy.astype(ft)
        xhat = (x.astype(ft) - mean) * inv
        dbeta = jnp.sum(dyf, axis=axes)
        dgamma = jnp.sum(dyf * xhat, axis=axes)
        dx = (gamma.astype(ft) * inv / n) * (n * dyf - dbeta
                                             - xhat * dgamma)
        return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
                dbeta.astype(gamma.dtype))
    # compute-dtype tail: dy/xhat stay in the compute dtype; the dbeta/
    # dgamma accumulators widen inside their reduces (fused), and the
    # fp32 vector terms are cast down once for the elementwise dx pass
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dbeta = jnp.sum(dy, axis=axes, dtype=ft)
    dgamma = jnp.sum(dy * xhat, axis=axes, dtype=ft)
    k = (gamma.astype(ft) * inv / n).astype(x.dtype)
    dx = k * (n * dy - dbeta.astype(x.dtype)
              - xhat * dgamma.astype(x.dtype))
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def batch_norm(x, gamma, beta, running_mean, running_var, *, train: bool,
               decay: float = 0.9, eps: float = 1e-5, use_stats: bool = True):
    """Channels-last batch norm over all leading axes.

    Returns (y, new_running_mean, new_running_var). `decay` matches the
    reference's decay semantics: running = decay*running + (1-decay)*batch.
    Training mode runs the fused custom-VJP core (_bn_train); eval mode is
    a plain affine transform XLA fuses into neighbours.
    """
    ft = jnp.promote_types(x.dtype, jnp.float32)
    if train:
        # locked gamma/beta become constants; grads exist but are unused
        g = jnp.ones(x.shape[-1], ft) if gamma is None else gamma
        b = jnp.zeros(x.shape[-1], ft) if beta is None else beta
        y, mean, var = _bn_train(x, g, b, float(eps))
        new_rm = (decay * running_mean.astype(ft)
                  + (1.0 - decay) * mean).astype(running_mean.dtype)
        new_rv = (decay * running_var.astype(ft)
                  + (1.0 - decay) * var).astype(running_var.dtype)
        return y, new_rm, new_rv
    mean = running_mean.astype(ft)
    var = running_var.astype(ft)
    inv = lax.rsqrt(var + eps)
    if _wide_tail(x):
        y = (x.astype(ft) - mean) * inv
        if gamma is not None:
            y = y * gamma.astype(ft)
        if beta is not None:
            y = y + beta.astype(ft)
        return y.astype(x.dtype), running_mean, running_var
    # compute-dtype tail: fold the whole affine into two fp32 VECTORS
    # (scale, shift) computed once, cast down once — y = x*a + b with no
    # activation-scale widening
    a = inv if gamma is None else inv * gamma.astype(ft)
    b = -mean * a
    if beta is not None:
        b = b + beta.astype(ft)
    y = x * a.astype(x.dtype) + b.astype(x.dtype)
    return y, running_mean, running_var


def lrn(x, k=2.0, n=5, alpha=1e-4, beta=0.75):
    """Local response normalization across channels (NHWC).

    Reference: LocalResponseNormalization (AlexNet-era). Implemented as an
    average pool over the channel axis.
    """
    sq = jnp.square(x)
    half = n // 2
    # pad channels and sum a sliding window over the channel dim
    padded = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    summed = lax.reduce_window(
        padded, 0.0, lax.add,
        window_dimensions=(1, 1, 1, n),
        window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    return x / jnp.power(k + alpha * summed, beta)

"""Attention primitives.

Reference: org.deeplearning4j.nn.conf.layers.SelfAttentionLayer /
LearnedSelfAttentionLayer / RecurrentAttentionLayer and AttentionVertex,
implemented upstream via SameDiff's sd.nn.multiHeadDotProductAttention.

TPU design: a blockwise (flash-style) attention computed with lax.scan
over KV blocks — O(T) memory instead of materialising the [T,T] score
matrix — with the block matmuls on the MXU in bf16. XLA also has a fused
attention path; the explicit blockwise form here is the building block the
ring-attention sequence parallelism (parallel/sequence.py) extends across
chips.

Layout: [B, H, T, D] (batch, heads, time, head_dim).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, carry, mask_value=-1e30, mask=None):
    """One flash block: q [B,H,Tq,D] against k/v [B,H,Tk,D].

    carry = (acc [B,H,Tq,D], row_max m [B,H,Tq], row_sum l [B,H,Tq]).
    Returns updated carry (online softmax, Rabe & Staats / flash-attention
    recurrence).
    """
    acc, m, l = carry
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    if mask is not None:
        scores = jnp.where(mask, scores, mask_value)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if mask is not None:
        # zero masked probabilities EXPLICITLY: when a whole block (or
        # row) is masked, m_new itself is mask_value and exp(scores -
        # m_new) == 1 — the finite sentinel normalises itself away and
        # a fully-masked row would silently attend uniformly. With the
        # hard zero, l stays 0 there and the l==0 guard below emits 0.
        p = jnp.where(mask, p, jnp.zeros((), p.dtype))
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return acc_new, m_new, l_new


def blockwise_attention(q, k, v, block_size=512, causal=False, key_mask=None):
    """Flash-style attention over KV blocks. q,k,v: [B,H,T,D].
    key_mask: optional [B,Tk] bool validity of key positions."""
    B, H, T, D = q.shape
    Tk = k.shape[2]
    bs = min(block_size, Tk)
    n_blocks = (Tk + bs - 1) // bs
    pad = n_blocks * bs - Tk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, n_blocks, bs, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, n_blocks, bs, D).transpose(2, 0, 1, 3, 4)
    kmb = None
    if key_mask is not None:
        km = key_mask if pad == 0 else jnp.pad(key_mask, ((0, 0), (0, pad)))
        kmb = km.reshape(B, n_blocks, bs).transpose(1, 0, 2)  # [nb,B,bs]

    q_pos = jnp.arange(T)[:, None]

    def scan_fn(carry, blk):
        kj, vj, j, kmj = blk
        mask = None
        k_pos = j * bs + jnp.arange(bs)[None, :]
        valid = k_pos < Tk
        if causal:
            mask = (q_pos >= k_pos) & valid
        elif pad:
            mask = jnp.broadcast_to(valid, (T, bs))
        if mask is not None:
            mask = mask[None, None]
        if kmj is not None:
            km4 = kmj[:, None, None, :]  # [B,1,1,bs]
            mask = km4 if mask is None else mask & km4
        return _block_attn(q, kj, vj, carry, mask=mask), None

    acc0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, T), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, T), q.dtype)
    xs = (kb, vb, jnp.arange(n_blocks)) if kmb is None \
        else (kb, vb, jnp.arange(n_blocks), kmb)
    if kmb is None:
        (acc, m, l), _ = lax.scan(
            lambda c, b: scan_fn(c, (b[0], b[1], b[2], None)), (acc0, m0, l0), xs)
    else:
        (acc, m, l), _ = lax.scan(scan_fn, (acc0, m0, l0), xs)
    # fully-masked rows have l == 0; emit 0 instead of NaN
    return acc / jnp.where(l == 0, 1.0, l)[..., None]


def dot_product_attention(q, k, v, mask=None, causal=False, key_mask=None):
    """Plain fused attention (XLA materialises and fuses the scores).
    Fine for short T; blockwise_attention for long T.

    key_mask: optional [B, Tk] bool validity of key positions — the
    ragged-batch mask the blockwise path has always taken. Semantics
    match blockwise_attention exactly: masked keys get no weight, and a
    row whose keys are ALL masked emits 0 (softmax alone would emit the
    uniform average of v, a silent garbage read)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    valid = None
    if causal:
        T, Tk = q.shape[2], k.shape[2]
        cm = jnp.arange(T)[:, None] >= jnp.arange(Tk)[None, :]
        scores = jnp.where(cm[None, None], scores, -1e30)
        valid = cm[None, None]
    if key_mask is not None:
        kmb = key_mask[:, None, None, :]
        scores = jnp.where(kmb, scores, -1e30)
        valid = kmb if valid is None else valid & kmb
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
        if valid is not None and key_mask is not None:
            valid = valid & mask
    p = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    if key_mask is not None:
        # a row with NO valid key under the COMBINED causal+key_mask
        # (+mask) constraint has all scores -1e30 — softmax would emit
        # the uniform average of v, silently reading masked positions.
        # Per-row validity (not just any(key_mask)) matches blockwise's
        # l == 0 guard exactly.
        any_valid = jnp.any(valid, axis=-1)[..., None]
        o = jnp.where(any_valid, o, jnp.zeros((), o.dtype))
    return o


def multi_head_attention(x, Wq, Wk, Wv, Wo, nHeads, causal=False,
                         block_size=None, kv=None):
    """Full MHA: x [B, T, E]; Wq/Wk/Wv [E, H*D]; Wo [H*D, E].

    The attention core goes through flash_attention's dispatcher: Pallas
    flash kernel on TPU for long T, fused XLA for short T, blockwise scan
    elsewhere. An explicit block_size forces the blockwise form (and sets
    the flash KV block on TPU)."""
    from deeplearning4j_tpu.ops.pallas_attention import flash_attention

    B, T, E = x.shape
    src = x if kv is None else kv
    q = (x @ Wq).reshape(B, T, nHeads, -1).transpose(0, 2, 1, 3)
    k = (src @ Wk).reshape(B, src.shape[1], nHeads, -1).transpose(0, 2, 1, 3)
    v = (src @ Wv).reshape(B, src.shape[1], nHeads, -1).transpose(0, 2, 1, 3)
    if block_size:
        # explicit block_size = the caller bounded attention memory; never
        # fall back to the O(T^2) fused form
        o = flash_attention(q, k, v, causal=causal, block_k=block_size,
                            force_streaming=True)
    else:
        o = flash_attention(q, k, v, causal=causal)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, -1)
    return o @ Wo

"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up rebuild of the deeplearning4j stack (ND4J arrays, SameDiff
autodiff, the DL4J layer/configuration API, model zoo, and distributed
gradient sharing) designed for TPU hardware: arrays are XLA device buffers,
ops lower to jax.numpy/lax and fuse under jit, networks compile to single
XLA computations, and scaling rides jax.sharding meshes with ICI
collectives instead of parameter servers / Aeron UDP.

Top-level convenience re-exports mirror the reference's most-used entry
points (reference: org.nd4j.linalg.factory.Nd4j,
org.deeplearning4j.nn.multilayer.MultiLayerNetwork, ...).
"""

from deeplearning4j_tpu.ndarray import INDArray, Nd4j, DataType

__version__ = "0.1.0"

__all__ = ["INDArray", "Nd4j", "DataType", "__version__"]

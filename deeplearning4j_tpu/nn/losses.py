"""Loss functions.

Reference: org.nd4j.linalg.lossfunctions.LossFunctions.LossFunction and the
ILossFunction impls. Each loss here is
``loss(labels, preactivations, activation_name, mask) -> scalar mean loss``
computed from *pre-activation* outputs so that softmax+xent /
sigmoid+binary-xent fuse into numerically-stable logsumexp forms (the
reference pairs separate activation and loss kernels and special-cases
"softmax+mcxent" for stability; jax.nn gives us the stable forms directly).
Masking matches the reference's per-timestep mask semantics: masked
elements contribute zero loss and the mean is over unmasked elements.

Dtype policy (round 6, the loss-tail fix): under a sub-fp32 compute
dtype the default "compute" tail keeps every ACTIVATION-SCALE tensor
(preactivations, per-element losses, log-probabilities) in the compute
dtype — fp32 appears only in reduction accumulators (``jnp.sum(...,
dtype=f32)``, where XLA fuses the widening convert into the reduce) and
in vector-scale terms like the per-row logsumexp. The round-5 HBM
attribution named fp32 activation-scale buffers in the loss/softmax
tails as a ``dtype_widening`` bin; trainers used to cast the whole
preact to fp32 before calling in here, which materialised exactly those
buffers. The legacy all-fp32 tail stays available as mode "wide"
(module global `_TAIL_MODE`, initial value from DL4J_TPU_LOSS_TAIL) so
bench.py can A/B the two lowerings. `tail_dtype(dtype)` is the policy
switch the trainers consult before casting.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn import activations as _act

#: "compute" (default) = activation-scale loss math in the compute
#: dtype with fp32 accumulators; "wide" = the pre-round-6 all-fp32
#: tail. Read at TRACE time.
_TAIL_MODE = os.environ.get("DL4J_TPU_LOSS_TAIL", "compute")


def tail_dtype(dtype):
    """The dtype a trainer should cast preact/labels to before the loss
    tail: fp32(+) in "wide" mode or when the compute dtype is already
    >= fp32 (fp64 gradient-check oracles keep fp64); the compute dtype
    itself otherwise — the fp32 accumulation then happens INSIDE the
    reduces here, where it never materialises at activation scale."""
    wide = jnp.promote_types(dtype, jnp.float32)
    if _TAIL_MODE == "wide" or dtype == wide:
        return wide
    return dtype


def _log_softmax(preact):
    """log_softmax whose fp32 appears only at vector scale: max and the
    logsumexp accumulate in fp32 (fused into the reduces), the [.., O]
    tensors stay in the input dtype. In "wide" mode (fp32 input) this
    is exactly jax.nn.log_softmax."""
    ft = jnp.promote_types(preact.dtype, jnp.float32)
    if preact.dtype == ft:
        return jax.nn.log_softmax(preact, axis=-1)
    m = jnp.max(preact, axis=-1, keepdims=True)
    s = preact - m
    lse = jnp.log(jnp.sum(jnp.exp(s), axis=-1, keepdims=True, dtype=ft))
    return s - lse.astype(preact.dtype)


class LossFunctions:
    class LossFunction:
        MSE = "mse"
        L2 = "l2"
        MAE = "mae"
        L1 = "l1"
        MCXENT = "mcxent"
        NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
        XENT = "xent"  # binary cross-entropy
        HINGE = "hinge"
        SQUARED_HINGE = "squared_hinge"
        KL_DIVERGENCE = "kl_divergence"
        POISSON = "poisson"
        COSINE_PROXIMITY = "cosine_proximity"
        SPARSE_MCXENT = "sparse_mcxent"
        MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mape"
        MEAN_SQUARED_LOGARITHMIC_ERROR = "msle"
        WASSERSTEIN = "wasserstein"
        RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"


def _apply_mask_mean(per_elem, mask):
    """Mean over unmasked elements. per_elem has shape [batch, ...].
    Reductions accumulate in fp32 (`dtype=ft` fuses the widening convert
    into the reduce — nothing fp32 materialises at activation scale);
    the returned scalar is always >= fp32."""
    ft = jnp.promote_types(per_elem.dtype, jnp.float32)
    if mask is None:
        return jnp.mean(jnp.sum(per_elem, axis=tuple(range(1, per_elem.ndim)),
                                dtype=ft))
    # mask is per example/timestep ([batch] or [batch, time]); broadcast over
    # the output dim and normalise by the unmasked count, like the reference.
    # Cast the mask DOWN to the loss dtype first: a fp32 mask would promote
    # the whole per-element product back to activation-scale fp32.
    mask = mask.astype(per_elem.dtype)
    n_unmasked = jnp.maximum(jnp.sum(mask, dtype=ft), 1.0)
    while mask.ndim < per_elem.ndim:
        mask = mask[..., None]
    return jnp.sum(per_elem * mask, dtype=ft) / n_unmasked


def compute(loss_name, labels, preact, activation="identity", mask=None, weights=None):
    """Mean loss over the batch (reference: ILossFunction.computeScore)."""
    name = str(loss_name).lower()
    if name == "reconstruction_crossentropy":
        # alias: identical math to XENT, and the sigmoid-logits form is
        # numerically stable where the clipped-log path saturates
        name = "xent"
    act = _act.get(activation)

    if name in ("mcxent", "negativeloglikelihood"):
        if activation == "softmax":
            logp = _log_softmax(preact)
        else:
            logp = jnp.log(jnp.clip(act(preact), 1e-10, 1.0))
        per = -labels * logp
        if weights is not None:
            per = per * jnp.asarray(weights, per.dtype)
        return _apply_mask_mean(per, mask)

    if name == "sparse_mcxent":
        # labels are CLASS INDICES — [B], [B,1], or [B,T,1] for
        # recurrent heads (reference: LossSparseMCXENT)
        idx = labels.astype(jnp.int32)
        if idx.ndim == preact.ndim and idx.shape[-1] == 1:
            idx = idx[..., 0]
        if activation == "softmax":
            logp = _log_softmax(preact)
        else:
            logp = jnp.log(jnp.clip(act(preact), 1e-10, 1.0))
        per = -jnp.take_along_axis(logp, idx[..., None], axis=-1)
        if weights is not None:
            # per-CLASS weights gather by each example's own class;
            # cast DOWN to the loss dtype — fp32 weights would promote
            # the activation-scale product back to fp32
            per = per * jnp.asarray(weights, per.dtype)[idx][..., None]
        return _apply_mask_mean(per, mask)

    if name == "xent":
        if activation == "sigmoid":
            # stable sigmoid BCE from logits
            per = jnp.maximum(preact, 0) - preact * labels + jnp.log1p(jnp.exp(-jnp.abs(preact)))
        else:
            p = jnp.clip(act(preact), 1e-10, 1.0 - 1e-10)
            per = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
        if weights is not None:
            per = per * jnp.asarray(weights, per.dtype)
        return _apply_mask_mean(per, mask)

    out = act(preact)
    if name in ("mse", "l2"):
        per = jnp.square(out - labels)
        if name == "mse":
            per = per  # reference L2 = sum sq; MSE = mean over output dim
    elif name in ("mae", "l1"):
        per = jnp.abs(out - labels)
    elif name == "hinge":
        per = jnp.maximum(0.0, 1.0 - labels * out)
    elif name == "squared_hinge":
        per = jnp.square(jnp.maximum(0.0, 1.0 - labels * out))
    elif name == "kl_divergence":
        p = jnp.clip(labels, 1e-10, 1.0)
        q = jnp.clip(out, 1e-10, 1.0)
        per = p * (jnp.log(p) - jnp.log(q))
    elif name == "poisson":
        per = out - labels * jnp.log(jnp.clip(out, 1e-10, None))
    elif name == "cosine_proximity":
        ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + 1e-10)
        on = out / (jnp.linalg.norm(out, axis=-1, keepdims=True) + 1e-10)
        per = -ln * on
    elif name == "mape":
        # reference LossMAPE: 100 * |y - yhat| / |y|
        per = 100.0 * jnp.abs(out - labels) / jnp.clip(jnp.abs(labels),
                                                       1e-10, None)
    elif name == "msle":
        # reference LossMSLE: (log((y+1)/(yhat+1)))^2
        per = jnp.square(jnp.log1p(labels) - jnp.log1p(out))
    elif name == "wasserstein":
        # reference LossWasserstein (WGAN critic): mean(labels * yhat),
        # labels in {-1, +1} marking real/generated
        per = labels * out
    elif name == "reconstruction_crossentropy":
        # reference LossReconstructionCrossEntropy over activated output
        p = jnp.clip(out, 1e-10, 1.0 - 1e-10)
        per = -(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p))
    else:
        raise ValueError(f"Unknown loss function '{loss_name}'")

    if weights is not None:
        per = per * jnp.asarray(weights, per.dtype)
    if name in ("mse", "mape", "msle"):
        # mean over the output dim as well (reference LossMSE/LossMAPE/
        # LossMSLE all divide by labels.size(1))
        per = per / per.shape[-1]
    return _apply_mask_mean(per, mask)

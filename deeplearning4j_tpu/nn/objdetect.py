"""Object detection: YOLOv2 output layer and inference utilities.

Reference: org.deeplearning4j.nn.layers.objdetect —
Yolo2OutputLayer (conf.layers.objdetect.Yolo2OutputLayer.Builder),
DetectedObject, YoloUtils (getPredictedObjects / non-max suppression).

Label format matches the reference: [minibatch, 4+C, H, W] where the 4 are
(x1, y1, x2, y2) corner coordinates in GRID units and C is a per-cell
one-hot class map; a cell contains an object iff its class vector is
non-zero. Network output is a conv map with A*(5+C) channels for A anchors.

TPU design: the whole loss — responsible-anchor IOU matching, coordinate /
confidence / class terms — is one vectorized jnp expression over
[B,H,W,A,...]; no per-box host loops, so it fuses into the same XLA
computation as the backbone's forward+backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.layers import LossLayer


class Yolo2OutputLayer(LossLayer):
    """YOLOv2 detection loss head (reference:
    conf.layers.objdetect.Yolo2OutputLayer).

    boundingBoxes: [A, 2] anchor priors (w, h) in grid units.
    """

    def __init__(self, boundingBoxes=None, lambdaCoord=5.0, lambdaNoObj=0.5,
                 **kw):
        super().__init__(lossFunction="yolo2", **kw)
        if boundingBoxes is None:
            raise ValueError("Yolo2OutputLayer requires anchor boundingBoxes")
        self.anchors = np.asarray(boundingBoxes, np.float32).reshape(-1, 2)
        self.lambdaCoord = float(lambdaCoord)
        self.lambdaNoObj = float(lambdaNoObj)

    class Builder:
        def __init__(self):
            self._kw = {}

        def boundingBoxePriors(self, priors):
            self._kw["boundingBoxes"] = (
                priors.toNumpy() if hasattr(priors, "toNumpy") else priors)
            return self

        def lambdaCoord(self, v):
            self._kw["lambdaCoord"] = v
            return self

        def lambdaNoObj(self, v):
            self._kw["lambdaNoObj"] = v
            return self

        def build(self):
            return Yolo2OutputLayer(**self._kw)

    # ----- geometry ---------------------------------------------------
    def _decode(self, pre):
        """Raw conv map [B,H,W,A*(5+C)] -> (xy in grid units, wh in grid
        units, conf, class logits), each [B,H,W,A,...]."""
        B, H, W, D = pre.shape
        A = self.anchors.shape[0]
        p = pre.reshape(B, H, W, A, D // A)
        cx = jnp.arange(W, dtype=p.dtype)[None, None, :, None]
        cy = jnp.arange(H, dtype=p.dtype)[None, :, None, None]
        xy = jnp.stack([jax.nn.sigmoid(p[..., 0]) + cx,
                        jax.nn.sigmoid(p[..., 1]) + cy], axis=-1)
        anchors = jnp.asarray(self.anchors, p.dtype)
        wh = anchors * jnp.exp(jnp.clip(p[..., 2:4], -10.0, 10.0))
        conf = jax.nn.sigmoid(p[..., 4])
        cls = p[..., 5:]
        return xy, wh, conf, cls

    @staticmethod
    def _iou_wh(wh_a, wh_b):
        """IOU of boxes sharing a center; shapes broadcast to [..., 2]."""
        inter = jnp.minimum(wh_a[..., 0], wh_b[..., 0]) * \
            jnp.minimum(wh_a[..., 1], wh_b[..., 1])
        union = wh_a[..., 0] * wh_a[..., 1] + wh_b[..., 0] * wh_b[..., 1] - inter
        return inter / jnp.maximum(union, 1e-9)

    @staticmethod
    def _iou_boxes(xy_a, wh_a, xy_b, wh_b):
        lo = jnp.maximum(xy_a - wh_a / 2, xy_b - wh_b / 2)
        hi = jnp.minimum(xy_a + wh_a / 2, xy_b + wh_b / 2)
        inter = jnp.prod(jnp.clip(hi - lo, 0.0), axis=-1)
        union = jnp.prod(wh_a, -1) + jnp.prod(wh_b, -1) - inter
        return inter / jnp.maximum(union, 1e-9)

    # ----- loss -------------------------------------------------------
    def computeLoss(self, pre, labels, mask=None):
        """labels NCHW [B, 4+C, H, W] (reference layout); pre NHWC."""
        lab = jnp.transpose(labels, (0, 2, 3, 1)).astype(pre.dtype)  # [B,H,W,4+C]
        box, cls_lab = lab[..., :4], lab[..., 4:]
        obj = (jnp.sum(cls_lab, -1) > 0).astype(pre.dtype)  # [B,H,W]

        xy_p, wh_p, conf, cls_logits = self._decode(pre)

        # label geometry (grid units)
        xy_l = jnp.stack([(box[..., 0] + box[..., 2]) / 2,
                          (box[..., 1] + box[..., 3]) / 2], -1)   # [B,H,W,2]
        wh_l = jnp.stack([box[..., 2] - box[..., 0],
                          box[..., 3] - box[..., 1]], -1)

        # responsible anchor per labelled cell: best shape-IOU prior
        anchors = jnp.asarray(self.anchors, pre.dtype)              # [A,2]
        prior_iou = self._iou_wh(wh_l[..., None, :], anchors)       # [B,H,W,A]
        resp = jax.nn.one_hot(jnp.argmax(prior_iou, -1),
                              anchors.shape[0], dtype=pre.dtype)    # [B,H,W,A]
        resp = resp * obj[..., None]

        n_obj = jnp.maximum(jnp.sum(obj), 1.0)

        # coordinate loss (sqrt-wh, as in the paper / reference)
        d_xy = jnp.sum(jnp.square(xy_p - xy_l[..., None, :]), -1)
        d_wh = jnp.sum(jnp.square(jnp.sqrt(jnp.maximum(wh_p, 1e-9)) -
                                  jnp.sqrt(jnp.maximum(wh_l[..., None, :], 1e-9))), -1)
        loss_coord = self.lambdaCoord * jnp.sum(resp * (d_xy + d_wh)) / n_obj

        # confidence: responsible -> IOU target (stop-grad), others -> 0
        iou = self._iou_boxes(xy_p, wh_p, xy_l[..., None, :], wh_l[..., None, :])
        iou = jax.lax.stop_gradient(iou)
        loss_obj = jnp.sum(resp * jnp.square(conf - iou)) / n_obj
        loss_noobj = self.lambdaNoObj * \
            jnp.sum((1.0 - resp) * jnp.square(conf)) / jnp.maximum(
                jnp.sum(1.0 - resp), 1.0)

        # class loss: softmax cross-entropy at responsible predictors
        logp = jax.nn.log_softmax(cls_logits, -1)
        ce = -jnp.sum(cls_lab[..., None, :] * logp, -1)             # [B,H,W,A]
        loss_cls = jnp.sum(resp * ce) / n_obj

        return loss_coord + loss_obj + loss_noobj + loss_cls

    def forward(self, params, state, x, train, key, mask=None):
        return x, state  # raw map; decoding happens in YoloUtils


class DetectedObject:
    """One detection (reference: objdetect.DetectedObject); coordinates in
    grid units, like the reference."""

    def __init__(self, exampleNumber, centerX, centerY, width, height,
                 predictedClass, classPredictions, confidence):
        self.exampleNumber = exampleNumber
        self.centerX, self.centerY = centerX, centerY
        self.width, self.height = width, height
        self.predictedClass = predictedClass
        self.classPredictions = classPredictions
        self.confidence = confidence

    def getTopLeftXY(self):
        return (self.centerX - self.width / 2, self.centerY - self.height / 2)

    def getBottomRightXY(self):
        return (self.centerX + self.width / 2, self.centerY + self.height / 2)

    def getPredictedClass(self):
        return self.predictedClass

    def getConfidence(self):
        return self.confidence

    def __repr__(self):
        return (f"DetectedObject(ex={self.exampleNumber}, cls={self.predictedClass}, "
                f"conf={self.confidence:.3f}, xywh=({self.centerX:.2f}, "
                f"{self.centerY:.2f}, {self.width:.2f}, {self.height:.2f}))")


class YoloUtils:
    """Host-side decode + NMS (reference: objdetect.YoloUtils)."""

    @staticmethod
    def getPredictedObjects(layer: Yolo2OutputLayer, networkOutput,
                            threshold: float = 0.5, nmsThreshold: float = 0.4):
        """networkOutput: raw map [B,H,W,A*(5+C)] (the net's output for a
        Yolo2 head). Returns a list of DetectedObject over all examples."""
        out = np.asarray(networkOutput.toNumpy()
                         if hasattr(networkOutput, "toNumpy") else networkOutput)
        xy, wh, conf, cls_logits = (np.asarray(v) for v in
                                    layer._decode(jnp.asarray(out)))
        cls_prob = np.asarray(jax.nn.softmax(jnp.asarray(cls_logits), -1))
        B = out.shape[0]
        dets = []
        for b in range(B):
            mask = conf[b] >= threshold               # [H,W,A]
            idxs = np.argwhere(mask)
            cand = []
            for (i, j, a) in idxs:
                cand.append(DetectedObject(
                    b, float(xy[b, i, j, a, 0]), float(xy[b, i, j, a, 1]),
                    float(wh[b, i, j, a, 0]), float(wh[b, i, j, a, 1]),
                    int(np.argmax(cls_prob[b, i, j, a])),
                    cls_prob[b, i, j, a], float(conf[b, i, j, a])))
            dets.extend(YoloUtils.nonMaxSuppression(cand, nmsThreshold))
        return dets

    @staticmethod
    def iou(d1: DetectedObject, d2: DetectedObject) -> float:
        x1, y1 = d1.getTopLeftXY()
        x2, y2 = d1.getBottomRightXY()
        u1, v1 = d2.getTopLeftXY()
        u2, v2 = d2.getBottomRightXY()
        iw = max(0.0, min(x2, u2) - max(x1, u1))
        ih = max(0.0, min(y2, v2) - max(y1, v1))
        inter = iw * ih
        union = d1.width * d1.height + d2.width * d2.height - inter
        return inter / union if union > 0 else 0.0

    @staticmethod
    def nonMaxSuppression(dets, nmsThreshold: float = 0.4):
        """Greedy per-class NMS (reference: YoloUtils.nms)."""
        keep = []
        by_class = {}
        for d in dets:
            by_class.setdefault(d.predictedClass, []).append(d)
        for ds in by_class.values():
            ds = sorted(ds, key=lambda d: -d.confidence)
            while ds:
                best = ds.pop(0)
                keep.append(best)
                ds = [d for d in ds if YoloUtils.iou(best, d) < nmsThreshold]
        return keep

"""Parameter constraints, applied after each update step.

Reference: org.deeplearning4j.nn.conf.constraint.{MaxNormConstraint,
MinMaxNormConstraint, NonNegativeConstraint, UnitNormConstraint}
(BaseConstraint.applyConstraint, run by BaseMultiLayerUpdater after the
updater step). Here the projection happens INSIDE the jitted train step,
right after the parameter update, so it fuses with the updater math.

Each constraint projects a single parameter tensor. Norms are computed
over all axes except the OUTPUT axis (last), matching the reference's
per-output-neuron norms with default dimensions."""

from __future__ import annotations

import jax.numpy as jnp


class BaseConstraint:
    """params to touch: weights ("W"-like) by default, like the reference's
    constrainWeights; set via applyToWeights/applyToBiases."""

    def __init__(self, applyToWeights=True, applyToBiases=False):
        self.applyToWeights = applyToWeights
        self.applyToBiases = applyToBiases

    def appliesTo(self, name: str) -> bool:
        if name in ("centers", "alpha"):
            # class centers / PReLU alpha: neither weight nor bias —
            # projecting them would corrupt their own dynamics
            return False
        is_bias = name in ("b", "beta", "vb")  # vb: AutoEncoder visible bias
        return self.applyToBiases if is_bias else self.applyToWeights

    def apply(self, p):
        raise NotImplementedError

    def _norms(self, p):
        axes = tuple(range(p.ndim - 1)) if p.ndim > 1 else ()
        return jnp.sqrt(jnp.sum(jnp.square(p), axis=axes, keepdims=True)
                        + 1e-12)


class MaxNormConstraint(BaseConstraint):
    def __init__(self, maxNorm=2.0, **kw):
        super().__init__(**kw)
        self.maxNorm = float(maxNorm)

    def apply(self, p):
        n = self._norms(p)
        return p * jnp.minimum(1.0, self.maxNorm / n).astype(p.dtype)


class MinMaxNormConstraint(BaseConstraint):
    """Clamp per-output norms into [min, max] with interpolation rate
    (reference: MinMaxNormConstraint; rate=1 snaps hard)."""

    def __init__(self, minNorm=0.0, maxNorm=2.0, rate=1.0, **kw):
        super().__init__(**kw)
        self.minNorm, self.maxNorm = float(minNorm), float(maxNorm)
        self.rate = float(rate)

    def apply(self, p):
        n = self._norms(p)
        target = jnp.clip(n, self.minNorm, self.maxNorm)
        scale = 1.0 + self.rate * (target / n - 1.0)
        return (p * scale).astype(p.dtype)


class NonNegativeConstraint(BaseConstraint):
    def apply(self, p):
        return jnp.maximum(p, 0.0)


class UnitNormConstraint(BaseConstraint):
    def apply(self, p):
        return (p / self._norms(p)).astype(p.dtype)


def apply_constraints(constraints, params):
    """Project a layer's param dict through its constraint list."""
    if not constraints or not params:
        return params
    out = dict(params)
    for c in constraints:
        for name, p in out.items():
            if c.appliesTo(name):
                out[name] = c.apply(p)
    return out

"""Network configuration builders.

Reference: org.deeplearning4j.nn.conf.NeuralNetConfiguration.Builder →
ListBuilder → MultiLayerConfiguration. The fluent surface matches the
reference (seed/updater/weightInit/activation/l2/list/layer/setInputType/
build); build() performs the same shape-inference walk the reference's
ListBuilder does — inferring each layer's nIn from the propagated
InputType and auto-inserting input preprocessors between layer families.
"""

from __future__ import annotations

from deeplearning4j_tpu.ndarray.dtype import DataType
from deeplearning4j_tpu.nn import updaters as _upd
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf import recurrent as R
from deeplearning4j_tpu.nn.conf import preprocessors as PP


class BackpropType:
    Standard = "standard"
    TruncatedBPTT = "tbptt"


class GradientNormalization:
    NoNormalization = None
    RenormalizeL2PerLayer = "renormalize_l2_per_layer"
    RenormalizeL2PerParamType = "renormalize_l2_per_param_type"
    ClipElementWiseAbsoluteValue = "clip_elementwise"
    ClipL2PerLayer = "clip_l2_per_layer"
    ClipL2PerParamType = "clip_l2_per_param_type"


class MultiLayerConfiguration:
    def __init__(self, layers, defaults, seed, dataType, inputType,
                 preprocessors, backpropType, tbpttFwdLength, tbpttBackLength,
                 gradientNormalization=None, gradientNormalizationThreshold=1.0):
        self.layers = layers
        self.defaults = defaults
        self.seed = seed
        self.dataType = dataType
        self.inputType = inputType
        self.preprocessors = preprocessors  # {layer_index: InputPreProcessor}
        self.backpropType = backpropType
        self.tbpttFwdLength = tbpttFwdLength
        self.tbpttBackLength = tbpttBackLength
        self.gradientNormalization = gradientNormalization
        self.gradientNormalizationThreshold = gradientNormalizationThreshold
        self.activationCheckpointing = defaults.get(
            "activationCheckpointing", False)
        self.checkpointPolicy = defaults.get("checkpointPolicy")
        self.optimizationAlgo = defaults.get(
            "optimizationAlgo", "STOCHASTIC_GRADIENT_DESCENT")
        self.maxNumLineSearchIterations = defaults.get(
            "maxNumLineSearchIterations", 20)
        # resolved per-layer input types (set during shape inference)
        self.layerInputTypes = []

    def toJson(self) -> str:
        """Config-only JSON round trip (reference:
        MultiLayerConfiguration.toJson)."""
        from deeplearning4j_tpu.util import serde

        return serde.to_json(self)

    @staticmethod
    def fromJson(text: str) -> "MultiLayerConfiguration":
        from deeplearning4j_tpu.util import serde

        return serde.from_json(text, MultiLayerConfiguration)

    def inferShapes(self):
        """Propagate InputType through layers; auto-insert preprocessors.

        Mirrors MultiLayerConfiguration.Builder.build()'s use of
        getOutputType/getPreProcessorForInputType in the reference.
        """
        if self.inputType is None:
            raise ValueError(
                "setInputType(...) is required (or set nIn on every layer)")
        cur = self.inputType
        if cur.kind == InputType.CNN_FLAT:
            first = self.layers[0]
            if isinstance(first, (L.ConvolutionLayer, L.SubsamplingLayer, L.BatchNormalization)):
                # reshape flat input to CNN at the entry (reference:
                # FeedForwardToCnnPreProcessor for convolutionalFlat)
                self.preprocessors.setdefault(0, PP.FeedForwardToCnnPreProcessor(
                    cur.height, cur.width, cur.channels))
                cur = InputType.convolutional(cur.height, cur.width, cur.channels)
            else:
                cur = InputType.feedForward(cur.arrayElementsPerExample())
        self.layerInputTypes = []
        for i, layer in enumerate(self.layers):
            layer.mergeGlobals(self.defaults)
            if i in self.preprocessors:
                cur = self.preprocessors[i].getOutputType(cur)
            else:
                pp, cur2 = self._auto_preprocessor(layer, cur)
                if pp is not None:
                    self.preprocessors[i] = pp
                    cur = cur2
            if hasattr(layer, "inferNIn"):
                layer.inferNIn(cur)
            self.layerInputTypes.append(cur)
            cur = layer.getOutputType(cur)
        self.outputType = cur
        return self

    @staticmethod
    def _wants(layer):
        layer = _unwrap_layer(layer)
        if isinstance(layer, (R.BaseRecurrentLayer, R.Bidirectional, R.LastTimeStep,
                              L.RnnOutputLayer, L.Convolution1DLayer, L.EmbeddingSequenceLayer)):
            return InputType.RNN
        if isinstance(layer, (L.ConvolutionLayer, L.SubsamplingLayer, L.Upsampling2D,
                              L.ZeroPaddingLayer, L.Cropping2D, L.LocalResponseNormalization)) \
                and not isinstance(layer, L.Convolution1DLayer):
            return InputType.CNN
        if isinstance(layer, (L.DenseLayer, L.BaseOutputLayer, L.EmbeddingLayer)):
            return InputType.FF
        return None  # format-agnostic (BN, activation, dropout, global pool...)

    def _auto_preprocessor(self, layer, cur):
        return auto_preprocessor(layer, cur)


def _unwrap_layer(layer):
    """Look through delegating wrappers (MaskZeroLayer.underlying,
    FrozenLayerWithBackprop.layer, ...) for isinstance-based format and
    nIn inference."""
    seen = 0
    while seen < 8:  # cycle guard
        inner = layer.__dict__.get("underlying") or layer.__dict__.get("layer")
        if inner is None or isinstance(layer, R.Bidirectional):
            # Bidirectional declares its own RNN format; don't unwrap it
            return layer
        layer = inner
        seen += 1
    return layer


def input_type_from_first_layer(layers):
    """InputType derived from an explicit first-layer nIn when no
    setInputType(...) was given — shared by ListBuilder.build() and the
    static validator so the two can never diverge. None when the first
    layer has no nIn to derive from."""
    first = _unwrap_layer(layers[0])
    if getattr(first, "nIn", None) is None:
        return None
    return InputType.feedForward(first.nIn) \
        if not isinstance(first, (R.BaseRecurrentLayer, R.Bidirectional,
                                  L.RnnOutputLayer)) \
        else InputType.recurrent(first.nIn)


def auto_preprocessor(layer, cur):
    """Auto-insert a format preprocessor for a layer given the incoming
    InputType (shared by sequential and graph shape inference)."""
    wants = MultiLayerConfiguration._wants(layer)
    if wants is None or cur.kind == wants:
        return None, cur
    if cur.kind == InputType.CNN and wants == InputType.FF:
        pp = PP.CnnToFeedForwardPreProcessor(cur.height, cur.width, cur.channels)
        return pp, pp.getOutputType(cur)
    if cur.kind == InputType.RNN and wants == InputType.FF:
        pp = PP.RnnToFeedForwardPreProcessor()
        return pp, pp.getOutputType(cur)
    if cur.kind == InputType.FF and wants == InputType.RNN:
        pp = PP.FeedForwardToRnnPreProcessor()
        return pp, pp.getOutputType(cur)
    if cur.kind == InputType.CNN and wants == InputType.RNN:
        pp = PP.CnnToRnnPreProcessor(cur.height, cur.width, cur.channels)
        return pp, pp.getOutputType(cur)
    raise ValueError(
        f"No preprocessor for {cur.kind} -> {wants} (layer {type(layer).__name__})")


class ListBuilder:
    def __init__(self, defaults):
        self._defaults = defaults
        self._layers = []
        self._preprocessors = {}
        self._inputType = None
        self._backpropType = BackpropType.Standard
        self._tbpttFwd = self._tbpttBack = 20

    def layer(self, *args):
        """layer(l) or layer(index, l) like the reference."""
        if len(args) == 2:
            idx, l = args
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = l
        else:
            self._layers.append(args[0])
        return self

    def setInputType(self, it: InputType):
        self._inputType = it
        return self

    def inputPreProcessor(self, idx: int, pp):
        self._preprocessors[idx] = pp
        return self

    def backpropType(self, bp):
        self._backpropType = bp
        return self

    def tBPTTForwardLength(self, n: int):
        self._tbpttFwd = n
        return self

    def tBPTTBackwardLength(self, n: int):
        self._tbpttBack = n
        return self

    def tBPTTLength(self, n: int):
        self._tbpttFwd = self._tbpttBack = n
        return self

    def build(self) -> MultiLayerConfiguration:
        if any(l is None for l in self._layers):
            raise ValueError("Gap in layer indices")
        d = self._defaults
        conf = MultiLayerConfiguration(
            layers=self._layers,
            defaults=d,
            seed=d.get("seed", 12345),
            dataType=d.get("dataType", DataType.FLOAT),
            inputType=self._inputType,
            preprocessors=dict(self._preprocessors),
            backpropType=self._backpropType,
            tbpttFwdLength=self._tbpttFwd,
            tbpttBackLength=self._tbpttBack,
            gradientNormalization=d.get("gradientNormalization"),
            gradientNormalizationThreshold=d.get("gradientNormalizationThreshold", 1.0),
        )
        if self._inputType is not None:
            conf.inferShapes()
        else:
            # all nIn set explicitly: derive input type from first layer
            # (looking through wrapper layers for both nIn and format)
            conf.inputType = input_type_from_first_layer(self._layers)
            if conf.inputType is None:
                raise ValueError("Either setInputType(...) or nIn on the first layer")
            conf.inferShapes()
        return conf


class NeuralNetConfiguration:
    class Builder:
        def __init__(self):
            self._d = {}

        # fluent setters, mirroring the reference builder
        def optimizationAlgo(self, algo):
            """Reference: NeuralNetConfiguration.Builder.optimizationAlgo
            (OptimizationAlgorithm enum): STOCHASTIC_GRADIENT_DESCENT
            (default, per-layer updaters), LINE_GRADIENT_DESCENT,
            CONJUGATE_GRADIENT, or LBFGS (nn/solvers.py — whole-pytree
            optax step with jitted line search)."""
            from deeplearning4j_tpu.nn.solvers import OptimizationAlgorithm

            self._d["optimizationAlgo"] = OptimizationAlgorithm.resolve(algo)
            return self

        def maxNumLineSearchIterations(self, n):
            """Line-search iteration cap for the non-SGD algorithms
            (reference: Builder.maxNumLineSearchIterations)."""
            self._d["maxNumLineSearchIterations"] = int(n)
            return self

        def seed(self, s):
            self._d["seed"] = int(s)
            return self

        def updater(self, u):
            self._d["updater"] = _upd.resolve(u) if not isinstance(u, _upd.IUpdater) else u
            return self

        def checkpointPolicy(self, policy):
            """Named rematerialization policy for the whole train step
            (jax.checkpoint with save_only_these_names). Currently:

            - "save_conv_outputs": save ONLY conv/dense (MXU) outputs as
              backward residuals; recompute the elementwise tails
              (BN/activation/add) from them during the backward pass.
              On bandwidth-bound steps this trades cheap recompute FLOPs
              for the write+read of every elementwise intermediate —
              the remaining HBM lever named in BENCH_NOTES.md round 4.
            - None: store whatever autodiff needs (default).

            Differs from activationCheckpointing (per-layer remat, a
            capacity lever): this is a BANDWIDTH lever with a policy
            boundary around the whole loss. ComputationGraph only."""
            if policy not in (None, "save_conv_outputs"):
                raise ValueError(f"unknown checkpointPolicy {policy!r}")
            self._d["checkpointPolicy"] = policy
            return self

        def activationCheckpointing(self, flag=True):
            """Rematerialize layer activations in the backward pass
            (jax.checkpoint): activations are recomputed instead of
            stored, trading ~1 extra forward of FLOPs for O(depth) ->
            O(1) activation memory. TPU-first feature (no upstream
            analog; the reference's workspaces manage allocator reuse,
            not recomputation). Most useful for deep nets / long
            sequences that overflow HBM."""
            self._d["activationCheckpointing"] = bool(flag)
            return self

        def biasUpdater(self, u):
            self._d["biasUpdater"] = u
            return self

        def weightInit(self, w):
            self._d["weightInit"] = w
            return self

        def dist(self, distribution):
            self._d["distribution"] = distribution
            self._d["weightInit"] = "distribution"
            return self

        def activation(self, a):
            self._d["activation"] = a
            return self

        def l1(self, v):
            self._d["l1"] = float(v)
            return self

        def l2(self, v):
            self._d["l2"] = float(v)
            return self

        def l1Bias(self, v):
            self._d["l1Bias"] = float(v)
            return self

        def l2Bias(self, v):
            self._d["l2Bias"] = float(v)
            return self

        def weightDecay(self, v):
            self._d["weightDecay"] = float(v)
            return self

        def dropOut(self, v):
            # float (retain prob) or an nn.conf.dropout.IDropout strategy
            self._d["dropOut"] = v if not isinstance(v, (int, float)) else float(v)
            return self

        def weightNoise(self, wn):
            """Per-step weight perturbation during training (reference:
            NeuralNetConfiguration.Builder.weightNoise — DropConnect or
            WeightNoise from nn.conf.weightnoise)."""
            self._d["weightNoise"] = wn
            return self

        def _add_constraints(self, constraints, weights, biases):
            import copy

            # configured COPIES: mutating the caller's instances would
            # corrupt a constraint object shared between builders
            cs = []
            for c in constraints:
                c = copy.copy(c)
                c.applyToWeights, c.applyToBiases = weights, biases
                cs.append(c)
            self._d["constraints"] = (self._d.get("constraints") or []) + cs
            return self

        def constrainWeights(self, *constraints):
            """Apply constraints to every layer's weights after each update
            (reference: NeuralNetConfiguration.Builder.constrainWeights)."""
            return self._add_constraints(constraints, True, False)

        def constrainBias(self, *constraints):
            return self._add_constraints(constraints, False, True)

        def constrainAllParameters(self, *constraints):
            return self._add_constraints(constraints, True, True)

        def dataType(self, dt):
            self._d["dataType"] = DataType.from_dtype(dt) if not isinstance(dt, DataType) else dt
            return self

        def gradientNormalization(self, gn):
            self._d["gradientNormalization"] = gn
            return self

        def gradientNormalizationThreshold(self, t):
            self._d["gradientNormalizationThreshold"] = float(t)
            return self

        def convolutionMode(self, m):
            self._d["convolutionMode"] = m
            return self

        def miniBatch(self, flag):
            self._d["miniBatch"] = bool(flag)
            return self

        def trainingWorkspaceMode(self, *_):
            return self  # workspaces are XLA's job; accepted for parity

        def inferenceWorkspaceMode(self, *_):
            return self

        def cudnnAlgoMode(self, *_):
            return self  # no cuDNN on TPU; accepted for parity

        def list(self) -> ListBuilder:
            return ListBuilder(dict(self._d))

        def graphBuilder(self):
            try:
                from deeplearning4j_tpu.nn.conf.graph import GraphBuilder
            except ImportError as e:
                raise NotImplementedError(
                    "ComputationGraph configuration (nn.conf.graph) is not "
                    "available in this build; use .list() for sequential "
                    "networks") from e
            return GraphBuilder(dict(self._d))

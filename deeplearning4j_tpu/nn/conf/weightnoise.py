"""Weight noise — DropConnect and additive/multiplicative noise.

Reference: org.deeplearning4j.nn.conf.weightnoise.{DropConnect,
WeightNoise} (IWeightNoise): perturb a layer's WEIGHTS (not its
activations) during training forward passes; inference uses the clean
weights. Applied functionally inside the jitted train step — the noisy
weights are a pure function of (params, step key), so gradients flow
through the perturbation exactly like upstream's backprop-through-
masked-weights, and runs remain bit-reproducible from the step key.

By default only weight matrices are perturbed ('W'-keyed entries and
friends); biases opt in via applyToBias, matching upstream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _weight_leaves(params):
    """Walk an arbitrarily-nested layer param dict (wrapper layers like
    Bidirectional store {'fwd': {...}, 'bwd': {...}}). Yields
    ((path tuple), leaf key, array). The 'is this a weight' question
    reuses Layer._NON_WEIGHT_PARAMS — the codebase's single param
    classification (bias/beta/centers/alpha/vb) — instead of a parallel
    hand-written set."""
    def walk(d, path):
        for k in sorted(d):
            v = d[k]
            if isinstance(v, dict):
                yield from walk(v, path + (k,))
            else:
                yield path + (k,), k, v

    yield from walk(params, ())


def _rebuild(params, replacements):
    """replacements: {path tuple: new array} -> new nested dict."""
    def build(d, path):
        out = {}
        for k, v in d.items():
            p = path + (k,)
            out[k] = build(v, p) if isinstance(v, dict) \
                else replacements.get(p, v)
        return out

    return build(params, ())


# actual bias vectors within _NON_WEIGHT_PARAMS; the remainder
# ('centers', 'alpha') are parameters with their own dynamics that
# weight noise must NEVER touch, applyToBias or not
_TRUE_BIASES = frozenset({"b", "beta", "vb"})


class IWeightNoise:
    def apply(self, params: dict, key) -> dict:
        """params: one layer's (possibly nested) param dict, already
        cast to compute dtype. Returns the perturbed dict; trace-safe."""
        raise NotImplementedError

    def _perturb(self, params, key, fn):
        from deeplearning4j_tpu.nn.conf.layers import Layer

        repl = {}
        for i, (path, leaf, v) in enumerate(_weight_leaves(params)):
            if not jnp.issubdtype(v.dtype, jnp.inexact):
                continue
            if leaf in Layer._NON_WEIGHT_PARAMS:
                if not (self.applyToBias and leaf in _TRUE_BIASES):
                    continue
            repl[path] = fn(jax.random.fold_in(key, i), v)
        return _rebuild(params, repl)


class DropConnect(IWeightNoise):
    """Zero each weight independently with prob 1-p, scaling kept
    weights by 1/p (inverted dropout on WEIGHTS — reference:
    weightnoise.DropConnect(weightRetainProb))."""

    def __init__(self, weightRetainProb, applyToBias=False):
        if not (0.0 < weightRetainProb <= 1.0):
            raise ValueError(
                f"weightRetainProb must be in (0,1], got {weightRetainProb}")
        self.p = float(weightRetainProb)
        self.applyToBias = bool(applyToBias)

    def apply(self, params, key):
        if self.p == 1.0:
            return params

        def drop(k, v):
            keep = jax.random.bernoulli(k, self.p, v.shape)
            return jnp.where(keep, v / self.p, 0.0).astype(v.dtype)

        return self._perturb(params, key, drop)


class WeightNoise(IWeightNoise):
    """Add (or multiply in) noise drawn from a distribution
    (reference: weightnoise.WeightNoise(Distribution, applyToBias,
    additive)). `distribution` is a nn.weights distribution
    (NormalDistribution/UniformDistribution)."""

    def __init__(self, distribution, applyToBias=False, additive=True):
        self.distribution = distribution
        self.applyToBias = bool(applyToBias)
        self.additive = bool(additive)

    def apply(self, params, key):
        def noise(k, v):
            n = self.distribution.sample(k, v.shape, v.dtype)
            return (v + n if self.additive else v * n).astype(v.dtype)

        return self._perturb(params, key, noise)
